"""CIFAR-10 binary loader (parity: loaders/CifarLoader.scala — 1 label byte +
3×32×32 channel-planar pixel bytes per record; the reference wraps records as
RowColumnMajorByteArrayVectorizedImage, here they land directly in the
canonical (n, X, Y, C) batch array)."""

from __future__ import annotations

import os

import numpy as np

from ..data.dataset import Dataset
from .csv_loader import LabeledData

NROW, NCOL, NCHAN = 32, 32, 3
RECORD = 1 + NROW * NCOL * NCHAN


def load_cifar(path: str) -> LabeledData:
    """Load one CIFAR-10 binary file (or a directory of them)."""
    files = (
        sorted(
            os.path.join(path, f)
            for f in os.listdir(path)
            if f.endswith(".bin")
        )
        if os.path.isdir(path)
        else [path]
    )
    raws = [np.fromfile(f, dtype=np.uint8) for f in files]
    raw = np.concatenate(raws)
    if raw.size % RECORD != 0:
        raise ValueError(f"{path}: not a whole number of CIFAR records")
    rec = raw.reshape(-1, RECORD)
    labels = rec[:, 0].astype(np.int32)
    # channel-planar bytes → (n, X=row, Y=col, C)
    imgs = (
        rec[:, 1:]
        .reshape(-1, NCHAN, NROW, NCOL)
        .transpose(0, 2, 3, 1)
        .astype(np.float32)
    )
    return LabeledData(labels, imgs)


def synthetic_cifar(n: int, seed: int = 0, num_classes: int = 10) -> LabeledData:
    """Class-structured synthetic CIFAR-shaped data for tests/benchmarks in
    this no-download environment. Class signal lives in *local texture*
    (class-specific spatial frequency + orientation), not absolute pixel
    levels — patch-normalized convolutional features deliberately discard
    means/contrast, so level-coded classes would be invisible to the
    RandomPatchCifar featurizer.

    A second, *position-fixed* low-frequency level pattern per class (shared
    across channels, so it survives grayscale conversion) makes the classes
    also visible to raw-pixel linear maps (LinearPixels); patch
    normalization subtracts patch means, so it leaves the texture signal as
    the dominant one for convolutional featurizers."""
    rng = np.random.default_rng(seed)
    xx, yy = np.meshgrid(np.arange(NROW), np.arange(NCOL), indexing="ij")
    protos = np.zeros((num_classes, NROW, NCOL, NCHAN), dtype=np.float32)
    for k in range(num_classes):
        freq = 0.25 + 0.3 * (k % 5)  # cycles/pixel
        theta = np.pi * k / num_classes
        wave = np.sin(
            2 * np.pi * freq * (np.cos(theta) * xx + np.sin(theta) * yy)
        )
        for c in range(NCHAN):
            protos[k, :, :, c] = 128 + 80 * np.cos(c * 1.1) * wave
    # position-fixed smooth per-class level code (constant RNG: identical
    # across differently-seeded train/test draws)
    level_rng = np.random.default_rng(99)
    coarse = level_rng.standard_normal((num_classes, 4, 4)).astype(np.float32)
    levels = np.repeat(np.repeat(coarse, NROW // 4, axis=1), NCOL // 4, axis=2)
    y = rng.integers(0, num_classes, size=n).astype(np.int32)
    phase_x = rng.integers(0, NROW, size=n)
    phase_y = rng.integers(0, NCOL, size=n)
    X = np.stack(
        [
            np.roll(protos[y[i]], (phase_x[i], phase_y[i]), axis=(0, 1))
            for i in range(n)
        ]
    )
    X = X + 30.0 * levels[y][..., None]
    X = X + 16.0 * rng.standard_normal(X.shape).astype(np.float32)
    return LabeledData(y, np.clip(X, 0, 255).astype(np.float32))
