"""The traceability lattice: a STATIC verdict per operator.

KeystoneML learns "can this node compile?" by attempting traces; here the
verdict is derived from static evidence — the operator class registry,
the ``trace_batch`` attribute, and inspection of the function's code
objects (closure cells and nested functions included) for host-callback
markers and Python-side state mutation. The dynamic paths
(``FittedPipeline.untraceable_nodes``, strict compile, AOT export) assert
against this verdict instead of discovering it.

Verdicts, worst-first::

    opaque        no trace_batch at all: host per-item work (text
                  featurizers, ragged image loaders). Cannot jit, cannot
                  export; blocks whole-chain compilation.
    stateful      trace_batch mutates Python-side state (self.x = ...):
                  jit would freeze or silently fork that state.
    host_callback trace_batch routes through jax.pure_callback /
                  io_callback: it jits (the callback stays on host) but
                  can NOT export to a serialized StableHLO artifact.
    batch_coupled trace_batch couples rows (whole-batch statistics):
                  compiles AND exports, but must never be served through
                  any pad-and-slice path and must not stream per-chunk.
    traceable     pure jax over the stacked array: compiles, exports,
                  fuses, shards.

Classification is evidence-based and conservative in the directions that
matter: a marker we cannot rule out (callback name referenced anywhere in
the function's code graph) downgrades the verdict, and an operator class
can pin its verdict explicitly (``check_verdict = "stateful"`` or
:func:`register_verdict`) when inspection cannot see the truth.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, Iterable, Optional, Set

logger = logging.getLogger(__name__)

# -- the lattice ------------------------------------------------------------

TRACEABLE = "traceable"
BATCH_COUPLED = "batch_coupled"
HOST_CALLBACK = "host_callback"
STATEFUL = "stateful"
OPAQUE = "opaque"

#: worst-first severity order (index = badness rank)
SEVERITY = (OPAQUE, STATEFUL, HOST_CALLBACK, BATCH_COUPLED, TRACEABLE)

VERDICTS = frozenset(SEVERITY)


def worst(verdicts: Iterable[str]) -> str:
    """The lattice meet: the worst verdict present (traceable if empty)."""
    best = len(SEVERITY) - 1
    for v in verdicts:
        best = min(best, SEVERITY.index(v))
    return SEVERITY[best]


def blocks_jit(verdict: str) -> bool:
    """Does this verdict block building the whole-chain jitted function?
    (the NotTraceableError criterion)"""
    return verdict in (OPAQUE, STATEFUL)


def blocks_export(verdict: str) -> bool:
    """Does this verdict block AOT export (serialized StableHLO)?
    Host callbacks jit fine but cannot cross the export boundary."""
    return verdict in (OPAQUE, STATEFUL, HOST_CALLBACK)


# -- explicit registry ------------------------------------------------------

_VERDICT_OVERRIDES: Dict[type, str] = {}


def register_verdict(op_class: type, verdict: str) -> None:
    """Pin the verdict for every node of ``op_class`` — the escape hatch
    for operators whose code inspection cannot see the truth (native
    extensions, generated wrappers)."""
    if verdict not in VERDICTS:
        raise ValueError(f"unknown verdict {verdict!r}")
    _VERDICT_OVERRIDES[op_class] = verdict


# -- code inspection --------------------------------------------------------

#: names whose presence anywhere in a trace function's code graph marks it
#: as host-callback-routed
_CALLBACK_MARKERS = frozenset({
    "pure_callback",
    "io_callback",
    "host_callback",
    "call_tf",
    "debug_callback",
})


def _iter_code_graph(fn: Any, max_depth: int = 6):
    """Yield the code objects reachable from ``fn``: its own code, nested
    code constants (comprehensions, local defs), closure-cell functions,
    and — bounded to this package — global functions it references by
    name. Global chasing stops at the keystone_tpu boundary so inspecting
    a node never walks into jax/numpy internals."""
    seen: Set[int] = set()
    stack = [(fn, 0)]
    while stack:
        obj, depth = stack.pop()
        code = getattr(obj, "__code__", None)
        if code is None or id(code) in seen or depth > max_depth:
            continue
        seen.add(id(code))
        yield code
        # nested code objects (lambdas, comprehensions, inner defs)
        for const in code.co_consts:
            if hasattr(const, "co_names"):
                # wrap a bare code object so the stack stays uniform
                stack.append((_CodeHolder(const), depth + 1))
        # closure cells holding functions
        for cell in getattr(obj, "__closure__", None) or ():
            try:
                cv = cell.cell_contents
            except ValueError:  # empty cell
                continue
            if callable(cv):
                stack.append((cv, depth + 1))
        # referenced globals that are keystone-local functions
        g = getattr(obj, "__globals__", None)
        if g is not None:
            for name in code.co_names:
                target = g.get(name)
                if (
                    callable(target)
                    and getattr(target, "__module__", "").startswith(
                        "keystone_tpu"
                    )
                    and hasattr(target, "__code__")
                ):
                    stack.append((target, depth + 1))


class _CodeHolder:
    """Adapter presenting a bare code object with the function surface
    ``_iter_code_graph`` walks."""

    __slots__ = ("__code__",)

    def __init__(self, code):
        self.__code__ = code


def _mentions_callback(fn: Any) -> bool:
    for code in _iter_code_graph(fn):
        if _CALLBACK_MARKERS & set(code.co_names):
            return True
    return False


def _mutates_self(fn: Any) -> bool:
    """Does ``fn``'s OWN code assign attributes on its first positional
    argument (``self.x = ...``)? Source-level AST when available; absent
    source (built/frozen), no evidence ⇒ not stateful."""
    import ast
    import inspect
    import textwrap

    raw = getattr(fn, "__func__", fn)
    code = getattr(raw, "__code__", None)
    if code is None or not code.co_varnames:
        return False
    self_name = code.co_varnames[0]
    if self_name not in ("self", "cls"):
        return False
    try:
        tree = ast.parse(textwrap.dedent(inspect.getsource(raw)))
    except (OSError, SyntaxError, TypeError):
        return False
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == self_name
            ):
                return True
    return False


# -- classification ---------------------------------------------------------

#: bounded memo keyed on (op class, trace_batch CODE OBJECT, coupling) —
#: classification is pure in those inputs, and a pipeline instantiates
#: many nodes per class. The code object itself is the key (not its id):
#: holding the reference prevents a GC'd function's recycled id from
#: serving a stale verdict to an unrelated new function.
from collections import OrderedDict

_CLASS_MEMO: "OrderedDict[tuple, str]" = OrderedDict()
_CLASS_MEMO_MAX = 256


def classify(op: Any) -> str:
    """The static verdict for one operator instance."""
    from ..workflow.operators import GatherTransformerOperator

    cls = type(op)
    if cls in _VERDICT_OVERRIDES:
        return _VERDICT_OVERRIDES[cls]
    declared = getattr(op, "check_verdict", None)
    if declared is not None:
        if declared not in VERDICTS:
            raise ValueError(
                f"{cls.__name__}.check_verdict={declared!r} is not a "
                f"lattice verdict {sorted(VERDICTS)}"
            )
        return declared

    # fused chains: the composite is exactly as good as its worst step
    steps = getattr(op, "steps", None)
    if steps is not None and cls.__name__ == "FusedTransformerOperator":
        return worst(classify(s) for s, _ in steps)

    if isinstance(op, GatherTransformerOperator):
        return TRACEABLE  # structural zip: identity inside a traced fn

    fn = getattr(op, "trace_batch", None)
    if fn is None:
        return OPAQUE

    # memoize ONLY closure-free functions: classification walks closure
    # cells, so two functions sharing one code object but closing over
    # different helpers (a factory-made batch_fn wrapping a pure-jax vs a
    # callback-routed f) can have DIFFERENT true verdicts — a closure is
    # exactly the part the code-object key cannot see
    if getattr(fn, "__closure__", None):
        memo_key = cached = None
    else:
        memo_key = (cls, getattr(fn, "__code__", None), bool(
            getattr(op, "batch_coupled", False)
        ))
        try:
            cached = _CLASS_MEMO.get(memo_key)
        except TypeError:  # unhashable exotic callable
            memo_key = cached = None
    if cached is not None:
        _CLASS_MEMO.move_to_end(memo_key)
        return cached

    if _mutates_self(fn):
        verdict = STATEFUL
    elif _mentions_callback(fn):
        verdict = HOST_CALLBACK
    elif getattr(op, "batch_coupled", False):
        verdict = BATCH_COUPLED
    else:
        verdict = TRACEABLE
    if memo_key is not None:
        _CLASS_MEMO[memo_key] = verdict
        while len(_CLASS_MEMO) > _CLASS_MEMO_MAX:
            _CLASS_MEMO.popitem(last=False)
    return verdict
