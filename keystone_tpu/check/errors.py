"""Typed errors for the static pipeline checker."""

from __future__ import annotations

from typing import Any, Optional


class PipelineCheckError(ValueError):
    """A statically-proven pipeline defect: a shape/dtype/rank mismatch, a
    declared-spec rejection, or a chunk-boundary-incompatible composition
    — raised at ``and_then``/``fit()``/``check()`` entry, BEFORE any chunk
    is produced or sample executed.

    Carries the offending node's id and label so callers (and humans) see
    exactly which stage is wrong, not a traceback from the middle of a
    scan. Subclasses :class:`ValueError` so pre-existing broad callers
    keep working.
    """

    def __init__(
        self,
        message: str,
        *,
        node: Any = None,
        label: Optional[str] = None,
    ):
        self.node = node
        self.label = label
        parts = [str(p) for p in (node, label) if p is not None]
        where = f" [at {' '.join(parts)}]" if parts else ""
        super().__init__(message + where)
        self._message = message

    def __reduce__(self):
        # default exception reduction would re-call __init__ with the
        # already-decorated message, doubling the node suffix
        return (
            _rebuild_check_error,
            (type(self), self._message, self.node, self.label),
        )


def _rebuild_check_error(cls, message, node, label):
    return cls(message, node=node, label=label)


class ContractMismatchError(PipelineCheckError):
    """A pipeline's statically-derived serving contract (datum shape,
    dtype, batch-coupling) does not match what a live engine/fleet/worker
    requires — raised by swap/boot validation from
    :meth:`CheckReport.require_contract`."""


class CheckOnlyExit(Exception):
    """Control-flow exception for the ``--check`` CLI mode: raised by
    ``Pipeline.fit()`` after the static check ran so the pipeline main
    unwinds without executing anything; ``__main__`` catches it and
    reports the check outcome. Deliberately NOT a ValueError — nothing
    should accidentally swallow it."""

    def __init__(self, report):
        self.report = report
        super().__init__("static check complete (check-only mode)")
