"""Abstract interpretation of a pipeline graph: shape/dtype propagation
with ZERO executions.

Specs originate at data leaves (materialized arrays report their shape;
chunked sources report the per-item spec recorded by their constructor;
the pipeline's unbound source is seeded from the fit-time datum hint) and
flow through every node:

* pure-jax / callback-backed nodes are pushed through ``jax.eval_shape``
  over their ``trace_batch`` — tracing with abstract values only, nothing
  computes;
* operators whose apply is NOT abstractly evaluable declare an
  ``out_spec(*in_item_specs)`` instead (host featurizers, per-item nodes,
  ragged-chunk ops) — see :data:`OUT_SPEC_PROTOCOL`;
* estimators declare ``fitted_out_spec(*in_item_specs)``: the per-item
  spec of their fitted transformer's output, which the delegating node
  applies to the serve path.

A node whose inputs are KNOWN and whose evaluation/declaration REJECTS
them raises a node-attributed :class:`PipelineCheckError` — the whole
point: a dtype mismatch surfaces at construction/fit entry, not minutes
into a featurization scan. Unknown inputs propagate as unknown; the
checker never guesses, so it has no false positives by construction.

The leading (batch) dimension is symbolic: specs seeded per-item get the
:data:`SYMBOLIC_LEAD` placeholder, and outputs whose lead equals the
placeholder stay symbolic. All mismatch power lives in the trailing
(per-item) dims, which is exactly what composition can get wrong.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .errors import PipelineCheckError
from . import lattice

logger = logging.getLogger(__name__)

#: placeholder extent for the symbolic batch/lead dimension — an unlikely
#: prime so a real dim is never confused with it in reports
SYMBOLIC_LEAD = 11939

#: protocol documentation anchor: operators may define
#: ``out_spec(*in_item_specs) -> item_spec`` where an item spec is
#: ``(shape_tuple, dtype_str)`` (or a tuple of item specs for multi-array
#: values, or None for unknown); estimators analogously define
#: ``fitted_out_spec(fit_item_specs, apply_item_specs) -> item_spec`` —
#: the per-item spec of the FITTED transformer's output, given the specs
#: of the estimator's fit inputs and of the serve-path input. All
#: declarations must tolerate None entries (unknown inputs) by returning
#: None; raising means "these KNOWN inputs are incompatible" and becomes
#: a node-attributed PipelineCheckError.
OUT_SPEC_PROTOCOL = "out_spec"
FITTED_OUT_SPEC_PROTOCOL = "fitted_out_spec"


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Spec:
    """One array-shaped abstract value: full shape (lead included) +
    dtype. ``sym`` marks the lead dim as the symbolic batch placeholder.
    ``chunked`` marks the value as flowing chunk-by-chunk from an
    out-of-core scan (a materialization-barrier property, not a shape)."""

    shape: Tuple[int, ...]
    dtype: str
    sym: bool = False
    chunked: bool = False

    @property
    def item_shape(self) -> Tuple[int, ...]:
        return self.shape[1:]

    @property
    def item(self) -> Tuple[Tuple[int, ...], str]:
        return (self.item_shape, self.dtype)

    def item_bytes(self) -> Optional[int]:
        """Bytes of ONE item of this value, or None when not derivable."""
        import numpy as np

        try:
            n = 1
            for d in self.item_shape:
                n *= int(d)
            return n * np.dtype(self.dtype).itemsize
        except TypeError:
            logger.debug("item_bytes failed for %s", self, exc_info=True)
            return None

    def display_shape(self) -> Tuple[Optional[int], ...]:
        """The shape with a symbolic lead rendered as None."""
        if self.sym and self.shape:
            return (None, *self.shape[1:])
        return self.shape


@dataclass(frozen=True)
class SpecTuple:
    """A tuple-of-arrays abstract value (gather output, split blocks)."""

    elems: Tuple[Any, ...]  # Spec | SpecTuple | None

    @property
    def chunked(self) -> bool:
        return any(getattr(e, "chunked", False) for e in self.elems)


@dataclass(frozen=True)
class EstimatorSpec:
    """The abstract value of an estimator node: a transformer-to-be. The
    operator rides along so the delegating node can consult its
    ``fitted_out_spec`` declaration."""

    op: Any


AbstractValue = Any  # Spec | SpecTuple | EstimatorSpec | None (unknown)


# ---------------------------------------------------------------------------
# spec construction helpers
# ---------------------------------------------------------------------------


def spec_of_array(value: Any, *, chunked: bool = False) -> Optional[Spec]:
    """Spec of an in-memory array-like, or None. Reads ONLY ``shape`` and
    ``dtype`` attributes — never forces computation."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        return Spec(
            tuple(int(d) for d in shape), str(dtype), chunked=chunked
        )
    except TypeError:
        logger.debug("unspecable array-like %r", type(value), exc_info=True)
        return None


def spec_from_item(
    item: Any, *, chunked: bool = False
) -> Optional[AbstractValue]:
    """Lift a per-item declaration ``(shape, dtype)`` (or a tuple of them,
    or None) into a batched abstract value with a symbolic lead."""
    if item is None:
        return None
    if isinstance(item, Spec):
        return item
    if (
        isinstance(item, tuple)
        and len(item) == 2
        and isinstance(item[1], str)
        and isinstance(item[0], (tuple, list))
        and all(isinstance(d, int) for d in item[0])
    ):
        return Spec(
            (SYMBOLIC_LEAD, *tuple(item[0])), item[1],
            sym=True, chunked=chunked,
        )
    if isinstance(item, (tuple, list)):
        return SpecTuple(
            tuple(spec_from_item(e, chunked=chunked) for e in item)
        )
    return None


def _to_item(av: AbstractValue) -> Any:
    """Project an abstract value down to the per-item declaration form
    handed to out_spec/fitted_out_spec implementations."""
    if isinstance(av, Spec):
        return av.item
    if isinstance(av, SpecTuple):
        return tuple(_to_item(e) for e in av.elems)
    return None


def _to_struct(av: AbstractValue) -> Any:
    """Materialize ShapeDtypeStructs for jax.eval_shape."""
    import jax

    if isinstance(av, Spec):
        return jax.ShapeDtypeStruct(av.shape, av.dtype)
    if isinstance(av, SpecTuple):
        return tuple(_to_struct(e) for e in av.elems)
    raise TypeError(f"not a concrete spec: {av!r}")


def _fully_known(av: AbstractValue) -> bool:
    if isinstance(av, Spec):
        return True
    if isinstance(av, SpecTuple):
        return all(_fully_known(e) for e in av.elems)
    return False


def _from_struct(out: Any, sym_lead: bool, chunked: bool) -> AbstractValue:
    """Lift eval_shape's result pytree back into abstract values."""
    if hasattr(out, "shape") and hasattr(out, "dtype"):
        shape = tuple(int(d) for d in out.shape)
        sym = bool(sym_lead and shape and shape[0] == SYMBOLIC_LEAD)
        return Spec(shape, str(out.dtype), sym=sym, chunked=chunked)
    if isinstance(out, (tuple, list)):
        return SpecTuple(
            tuple(_from_struct(e, sym_lead, chunked) for e in out)
        )
    return None


# ---------------------------------------------------------------------------
# leaves
# ---------------------------------------------------------------------------


def _leaf_value(op: Any) -> AbstractValue:
    """Abstract value of a data leaf, by CHEAP inspection only."""
    from ..data.chunked import ChunkedDataset
    from ..data.dataset import Dataset
    from ..workflow.operators import DatasetOperator, DatumOperator

    if isinstance(op, DatasetOperator):
        ds = op.dataset
        if isinstance(ds, ChunkedDataset):
            item = getattr(ds, "item_spec", None)
            if item is not None:
                return spec_from_item(item, chunked=True)
            # chunked stream of unknown element spec: the shape is
            # unknown; the chunked-flow property rides in chunked_flow
            return None
        if isinstance(ds, Dataset):
            if ds.is_batched:
                payload = ds.payload
                if isinstance(payload, (tuple, list)):
                    return SpecTuple(
                        tuple(spec_of_array(p) for p in payload)
                    )
                return spec_of_array(payload)
            payload = ds.payload
            if isinstance(payload, list) and payload:
                # materialized item list: peeking index 0's metadata is
                # free (no compute); ragged lists simply yield item 0's
                # shape which downstream may or may not hold — so item
                # lists contribute an UNKNOWN spec unless homogeneous is
                # provable; stay conservative
                return None
        return None
    if isinstance(op, DatumOperator):
        # single-datum graphs go through single_transform, not
        # trace_batch — stay unknown rather than guess the batch form
        return None
    return None


def leaf_is_chunked(op: Any) -> bool:
    from ..data.chunked import ChunkedDataset
    from ..workflow.operators import DatasetOperator

    return isinstance(op, DatasetOperator) and isinstance(
        op.dataset, ChunkedDataset
    )


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------


def _concretization_errors() -> tuple:
    import jax

    errs = []
    for name in (
        "TracerArrayConversionError",
        "ConcretizationTypeError",
        "TracerBoolConversionError",
        "TracerIntegerConversionError",
    ):
        e = getattr(jax.errors, name, None)
        if e is not None:
            errs.append(e)
    return tuple(errs)


def infer_specs(
    graph: Any,
    source_specs: Optional[Dict[Any, AbstractValue]] = None,
    verdicts: Optional[Dict[Any, str]] = None,
) -> Tuple[Dict[Any, AbstractValue], Dict[Any, str]]:
    """Propagate abstract values through ``graph`` in topological order.

    Returns ``(values, verdicts)`` — per-graph-id abstract values and the
    (possibly downgraded) per-node verdicts. Raises
    :class:`PipelineCheckError` on a PROVEN incompatibility: a node whose
    inputs are fully known rejecting them, or a batch-coupled node fed an
    unmaterialized chunked stream.
    """
    from ..workflow import analysis
    from ..workflow.graph import NodeId, SourceId
    from ..workflow.operators import (
        DatasetOperator,
        DatumOperator,
        DelegatingOperator,
        EstimatorOperator,
        ExpressionOperator,
        GatherTransformerOperator,
    )

    values: Dict[Any, AbstractValue] = {}
    chunked_flow: Dict[Any, bool] = {}
    verdicts = dict(verdicts or {})
    for src, av in (source_specs or {}).items():
        values[src] = av
        chunked_flow[src] = bool(getattr(av, "chunked", False))

    conc_errors = _concretization_errors()

    for gid in analysis.linearize(graph):
        if isinstance(gid, SourceId):
            values.setdefault(gid, None)
            chunked_flow.setdefault(gid, False)
            continue
        if not isinstance(gid, NodeId) or gid not in graph.operators:
            continue
        op = graph.get_operator(gid)
        deps = graph.get_dependencies(gid)
        dep_vals = [values.get(d) for d in deps]
        dep_chunked = any(chunked_flow.get(d, False) for d in deps)
        label = getattr(op, "label", type(op).__name__)

        if gid not in verdicts:
            verdicts[gid] = lattice.classify(op)
        verdict = verdicts[gid]

        # data leaves
        if not deps and isinstance(op, (DatasetOperator, DatumOperator)):
            values[gid] = _leaf_value(op)
            chunked_flow[gid] = leaf_is_chunked(op)
            continue

        # a Cacher is the materialization point: the stream stops being
        # chunk-at-a-time below it
        is_cacher = type(op).__name__ == "Cacher"
        out_chunked = dep_chunked and not is_cacher

        # chunk-boundary incompatibility: a batch-coupled node consuming
        # an out-of-core stream computes its whole-batch statistics per
        # CHUNK — runtime refuses this mid-scan; refuse it here instead.
        # Coupling is read from the ATTRIBUTE, not the verdict: a coupled
        # node carrying a worse lattice trait is still coupled.
        if getattr(op, "batch_coupled", False) and dep_chunked:
            raise PipelineCheckError(
                "batch-coupled node consumes an out-of-core chunked "
                "stream: its whole-batch statistics would be computed "
                "per chunk — materialize upstream (e.g. .cache()) first",
                node=gid, label=label,
            )

        if isinstance(op, EstimatorOperator) and not isinstance(
            op, DelegatingOperator
        ):
            values[gid] = EstimatorSpec(op)
            chunked_flow[gid] = False
            continue

        if isinstance(op, DelegatingOperator):
            est, data_vals = dep_vals[0], dep_vals[1:]
            out = None
            if isinstance(est, EstimatorSpec):
                decl = getattr(est.op, FITTED_OUT_SPEC_PROTOCOL, None)
                if decl is not None:
                    est_node = deps[0]
                    fit_in = [
                        _to_item(values.get(d))
                        for d in graph.get_dependencies(est_node)
                    ]
                    apply_in = [_to_item(v) for v in data_vals]
                    try:
                        out = spec_from_item(
                            decl(fit_in, apply_in), chunked=out_chunked
                        )
                    except PipelineCheckError:
                        raise
                    except Exception as e:
                        raise PipelineCheckError(
                            f"declared fitted_out_spec of "
                            f"{type(est.op).__name__} rejects the input "
                            f"spec: {e}",
                            node=gid, label=label,
                        ) from e
            values[gid] = out
            chunked_flow[gid] = out_chunked
            continue

        if isinstance(op, ExpressionOperator):
            expr = op.expression
            value = expr._value if getattr(expr, "computed", False) else None
            av = spec_of_array(value) if value is not None else None
            values[gid] = av
            chunked_flow[gid] = False
            continue

        if isinstance(op, GatherTransformerOperator):
            values[gid] = (
                SpecTuple(tuple(dep_vals))
                if all(v is not None for v in dep_vals)
                else None
            )
            chunked_flow[gid] = out_chunked
            continue

        # declared spec wins for nodes whose apply is not abstractly
        # evaluable — and is honored even when inputs are partially
        # unknown (the declaration may not need them)
        decl = getattr(op, OUT_SPEC_PROTOCOL, None)
        if decl is not None:
            try:
                out = decl(*[_to_item(v) for v in dep_vals])
            except PipelineCheckError:
                raise
            except Exception as e:
                raise PipelineCheckError(
                    f"declared out_spec rejects the input spec: {e}",
                    node=gid, label=label,
                ) from e
            values[gid] = spec_from_item(out, chunked=out_chunked)
            chunked_flow[gid] = out_chunked
            continue

        fn = getattr(op, "trace_batch", None)
        if fn is None or not all(_fully_known(v) for v in dep_vals):
            values[gid] = None
            chunked_flow[gid] = out_chunked
            continue

        import jax

        sym_lead = any(
            getattr(v, "sym", False)
            or (
                isinstance(v, SpecTuple)
                and any(getattr(e, "sym", False) for e in v.elems)
            )
            for v in dep_vals
        )
        try:
            out_struct = jax.eval_shape(
                fn, *[_to_struct(v) for v in dep_vals]
            )
        except conc_errors:
            # the "pure jax" classification was optimistic: this
            # trace_batch needs concrete values. It cannot jit either —
            # downgrade so the compile path agrees with reality.
            logger.warning(
                "check: %s claimed traceable but cannot be abstractly "
                "evaluated; downgrading to opaque", label, exc_info=True,
            )
            verdicts[gid] = lattice.OPAQUE
            values[gid] = None
            chunked_flow[gid] = out_chunked
            continue
        except Exception as e:
            if verdict in (lattice.TRACEABLE, lattice.BATCH_COUPLED):
                in_desc = ", ".join(
                    str(
                        v.display_shape()
                        if isinstance(v, Spec) else _to_item(v)
                    )
                    + (f":{v.dtype}" if isinstance(v, Spec) else "")
                    for v in dep_vals
                )
                raise PipelineCheckError(
                    f"node rejects input spec [{in_desc}]: {e}",
                    node=gid, label=label,
                ) from e
            # callback-backed/stateful nodes: abstract evaluation is
            # best-effort evidence, not a contract — unknown, not an error
            logger.debug(
                "check: abstract eval of %s (%s) failed; spec unknown",
                label, verdict, exc_info=True,
            )
            values[gid] = None
            chunked_flow[gid] = out_chunked
            continue
        values[gid] = _from_struct(out_struct, sym_lead, out_chunked)
        chunked_flow[gid] = out_chunked

    # sinks mirror their dependency
    for sink, dep in graph.sink_dependencies.items():
        values[sink] = values.get(dep)
        chunked_flow[sink] = chunked_flow.get(dep, False)

    return values, verdicts
