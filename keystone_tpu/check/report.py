"""CheckReport: the product of one static pipeline check.

``check_graph`` runs the three layers — abstract spec interpretation,
traceability classification, segment planning — over an (optimized or
raw) pipeline graph in milliseconds, executing ZERO chunks and ZERO
samples, and returns a :class:`CheckReport` that every downstream
consumer reads:

* ``Pipeline.check()`` / ``FittedPipeline.check()`` surface it (and emit
  a ``check.report`` trace span);
* ``FittedPipeline.compile`` takes its verdicts as the strict-compile
  truth (and skips doomed AOT exports);
* ``ServingEngine.swap`` / ``ServingFleet.swap`` / cluster worker boot
  validate replacements via :meth:`CheckReport.require_contract`;
* the ``--check`` CLI mode renders :meth:`CheckReport.render`.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import lattice
from .abstract import (
    Spec,
    SpecTuple,
    infer_specs,
    spec_from_item,
)
from .errors import ContractMismatchError, PipelineCheckError
from .segments import Segment, plan_segments

logger = logging.getLogger(__name__)


@dataclass
class CheckReport:
    """Everything the static checker proved about one pipeline graph."""

    #: per-node abstract output value (Spec | SpecTuple | None=unknown)
    specs: Dict[Any, Any]
    #: per-node lattice verdict (see :mod:`keystone_tpu.check.lattice`)
    verdicts: Dict[Any, str]
    #: per-node operator label (for attribution without the graph)
    labels: Dict[Any, str]
    #: maximal traceable segments between materialization barriers
    segments: List[Segment]
    #: barrier node -> reason
    barriers: Dict[Any, str]
    #: nodes whose operator couples rows (the raw ``batch_coupled``
    #: attribute, fused steps included) — ORTHOGONAL to the verdict: a
    #: coupled node that also routes through a host callback classifies
    #: ``host_callback`` in the lattice but still must never be served
    #: through any pad-and-slice path
    coupled_nodes: List[Any] = field(default_factory=list)
    #: the graph's serving input contract: per-item shape/dtype at the
    #: unbound source (None when not statically known)
    datum_shape: Optional[Tuple[int, ...]] = None
    datum_dtype: Optional[str] = None
    #: spec of the sink value, when derivable
    sink_spec: Any = None
    #: node ids in topological order (reporting convenience)
    order: List[Any] = field(default_factory=list)

    # -- verdict projections -------------------------------------------

    @property
    def segment_count(self) -> int:
        return len(self.segments)

    def nodes_with_verdict(self, *verdicts: str) -> List[Any]:
        return [
            n for n in self.order if self.verdicts.get(n) in verdicts
        ]

    def untraceable_nodes(self) -> List[Any]:
        """Nodes that block building the whole-chain jitted function —
        the static replacement for try-trace discovery."""
        return [
            n for n in self.order
            if lattice.blocks_jit(self.verdicts.get(n, lattice.OPAQUE))
        ]

    def untraceable_labels(self) -> List[str]:
        return [self.labels[n] for n in self.untraceable_nodes()]

    def batch_coupled_labels(self) -> List[str]:
        return [self.labels[n] for n in self.coupled_nodes]

    @property
    def jit_compilable(self) -> bool:
        return not self.untraceable_nodes()

    @property
    def exportable(self) -> bool:
        """Can the whole chain AOT-export (serialized StableHLO)? Host
        callbacks jit but cannot cross the export boundary."""
        return not any(
            lattice.blocks_export(v) for v in self.verdicts.values()
        )

    def verdict_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.verdicts.values():
            out[v] = out.get(v, 0) + 1
        return out

    # -- serving-contract validation ------------------------------------

    def require_contract(
        self,
        datum_shape: Optional[Sequence[int]],
        dtype: Any,
        *,
        verb: str = "serve",
    ) -> None:
        """Validate this pipeline against a live serving contract.

        Raises a node-attributed :class:`ContractMismatchError` when the
        pipeline is batch-coupled (bucket padding would corrupt its
        whole-batch statistics) or its statically-known datum shape/dtype
        disagrees with the live engine's. Unknown facts never fail —
        the checker has no false positives by construction."""
        import numpy as np

        coupled = self.coupled_nodes
        if coupled:
            n = coupled[0]
            raise ContractMismatchError(
                f"cannot {verb} a batch-coupled chain: bucket padding "
                "would corrupt its whole-batch statistics — use "
                "FittedPipeline.apply() instead",
                node=n, label=self.labels.get(n),
            )
        if (
            self.datum_shape is not None
            and datum_shape is not None
            and tuple(self.datum_shape) != tuple(datum_shape)
        ):
            raise ContractMismatchError(
                f"datum shape {tuple(self.datum_shape)} does not match "
                f"the live contract {tuple(datum_shape)} — a re-shaped "
                f"model needs a new engine, not a {verb}",
                label="source",
            )
        if (
            self.datum_dtype is not None
            and dtype is not None
            and np.dtype(self.datum_dtype) != np.dtype(dtype)
        ):
            raise ContractMismatchError(
                f"datum dtype {np.dtype(self.datum_dtype)} does not "
                f"match the live contract {np.dtype(dtype)} — batches "
                f"would silently cast; a re-typed model needs a new "
                f"engine, not a {verb}",
                label="source",
            )

    # -- rendering ------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        counts = self.verdict_counts()
        return {
            "nodes": len(self.order),
            "segments": self.segment_count,
            "barriers": len(self.barriers),
            "verdicts": counts,
            "jit_compilable": self.jit_compilable,
            "exportable": self.exportable,
            "datum_shape": (
                list(self.datum_shape)
                if self.datum_shape is not None else None
            ),
            "datum_dtype": self.datum_dtype,
        }

    def render(self) -> str:
        """Human-readable multi-line report (the --check CLI output)."""
        lines = ["static check report", "===================="]
        s = self.summary()
        lines.append(
            f"nodes: {s['nodes']}  segments: {s['segments']}  "
            f"barriers: {s['barriers']}  "
            f"jit: {'yes' if s['jit_compilable'] else 'NO'}  "
            f"export: {'yes' if s['exportable'] else 'NO'}"
        )
        if self.datum_shape is not None:
            lines.append(
                f"datum contract: {tuple(self.datum_shape)} "
                f"{self.datum_dtype or '?'}"
            )
        lines.append("")
        for n in self.order:
            spec = self.specs.get(n)
            if isinstance(spec, Spec):
                sdesc = f"{spec.display_shape()} {spec.dtype}"
            elif isinstance(spec, SpecTuple):
                sdesc = f"tuple[{len(spec.elems)}]"
            elif spec is None:
                sdesc = "?"
            else:
                sdesc = type(spec).__name__
            verdict = self.verdicts.get(n, "-")
            barrier = self.barriers.get(n)
            extra = f"  BARRIER({barrier})" if barrier else ""
            lines.append(
                f"  {str(n):<12} {self.labels.get(n, '?')[:48]:<48} "
                f"{verdict:<14} {sdesc}{extra}"
            )
        lines.append("")
        for seg in self.segments:
            size = (
                f"{seg.est_item_bytes}B/item"
                if seg.est_item_bytes is not None else "?B/item"
            )
            lines.append(
                f"  segment {seg.index}: {len(seg)} node(s), "
                f"{len(seg.inputs)} input(s), "
                f"{len(seg.outputs)} output(s), {size}"
            )
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------


def check_graph(
    graph: Any,
    *,
    source: Any = None,
    datum_spec: Optional[tuple] = None,
    cost_estimator: Any = None,
) -> CheckReport:
    """Run the full static check over ``graph``.

    ``datum_spec`` is the per-item ``(shape, dtype)`` of data fed at the
    graph's unbound ``source`` (the fit-time hint); None leaves the
    source spec unknown. Raises :class:`PipelineCheckError` on any
    statically-proven defect; returns the report otherwise. Executes
    nothing: no chunks, no samples, no compiles."""
    from ..workflow import analysis
    from ..workflow.graph import NodeId

    source_specs = {}
    if source is not None and datum_spec is not None:
        source_specs[source] = spec_from_item(tuple(datum_spec))

    values, verdicts = infer_specs(graph, source_specs)
    order = [
        n for n in analysis.linearize(graph)
        if isinstance(n, NodeId) and n in graph.operators
    ]
    labels = {
        n: getattr(graph.get_operator(n), "label", type(
            graph.get_operator(n)
        ).__name__)
        for n in order
    }
    segments, barriers = plan_segments(
        graph, verdicts, values, cost_estimator=cost_estimator
    )
    # coupling by ATTRIBUTE, not verdict — a coupled node carrying a
    # worse lattice trait (host callback, stateful) must still be
    # refused by every pad-and-slice serving path
    coupled_nodes = [
        n for n in order
        if getattr(graph.get_operator(n), "batch_coupled", False)
    ]

    sink_spec = None
    for sink in sorted(graph.sinks):
        sink_spec = values.get(sink)
        break

    datum_shape = datum_dtype = None
    if datum_spec is not None:
        datum_shape = tuple(datum_spec[0])
        datum_dtype = str(datum_spec[1])

    return CheckReport(
        specs=values,
        verdicts=verdicts,
        labels=labels,
        segments=segments,
        barriers=barriers,
        coupled_nodes=coupled_nodes,
        datum_shape=datum_shape,
        datum_dtype=datum_dtype,
        sink_spec=sink_spec,
        order=order,
    )
