"""Segment planning: partition the DAG into maximal traceable segments
between materialization barriers.

This is the compilation-unit plan the ROADMAP's whole-DAG native
compilation item needs: each segment is a connected sub-DAG every node of
which could lower into ONE fused XLA program, and each barrier is a point
where data must materialize — a Cacher (the result must hit the state
table / HBM pin), an out-of-core scan seam (chunked leaves produce data
chunk-at-a-time), a host-side node (opaque / callback / stateful), an
estimator boundary (fit-time solve), or a gather join (N branch programs
meet in one zip — today's trace fusion also treats the join's consumers
as a fresh group root).

Today the plan is consumed for *validation and reporting*
(``Pipeline.check()``, ``--check``); tomorrow the per-segment lowering
starts from exactly these boundaries.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from . import lattice
from .abstract import Spec, SpecTuple

logger = logging.getLogger(__name__)

#: barrier reasons
BARRIER_CACHER = "cacher"
BARRIER_SCAN_SEAM = "scan_seam"
BARRIER_HOST = "host"
BARRIER_ESTIMATOR = "estimator"
BARRIER_GATHER = "gather_join"
BARRIER_SAVED = "saved_state"
BARRIER_DATA = "data_leaf"


@dataclass
class Segment:
    """One maximal traceable sub-DAG between barriers."""

    index: int
    nodes: List[Any] = field(default_factory=list)  # topo order
    #: external inputs (barrier nodes / sources) this segment reads, in
    #: topological (linearization) order of the producing node — a PINNED
    #: contract: segment fingerprints and lowered-function signatures are
    #: positional over this list, so the order must be stable across
    #: processes (insertion order over members was not, since member
    #: iteration depends on union-find grouping)
    inputs: List[Any] = field(default_factory=list)
    #: nodes whose value leaves the segment (consumed outside / by a sink)
    outputs: List[Any] = field(default_factory=list)
    #: estimated bytes ONE item generates across this segment's node
    #: outputs (per-item pricing: specs first, cost-model evidence where
    #: the spec is unknown); None when nothing was estimable
    est_item_bytes: Optional[int] = None

    def __len__(self) -> int:
        return len(self.nodes)


def barrier_reason(
    op: Any, verdict: str, *, is_chunked_leaf: bool = False
) -> Optional[str]:
    """Why ``op`` is a materialization barrier, or None (segment-eligible).

    Barrier-ness is orthogonal to the verdict for Cachers (their traced
    form is identity — traceable — but their *purpose* is to
    materialize)."""
    from ..workflow.operators import (
        DatasetOperator,
        DatumOperator,
        DelegatingOperator,
        EstimatorOperator,
        ExpressionOperator,
        GatherTransformerOperator,
    )

    if isinstance(op, (DatasetOperator, DatumOperator)):
        return BARRIER_SCAN_SEAM if is_chunked_leaf else BARRIER_DATA
    if isinstance(op, ExpressionOperator):
        return BARRIER_SAVED
    if isinstance(op, (DelegatingOperator, EstimatorOperator)):
        return BARRIER_ESTIMATOR
    if isinstance(op, GatherTransformerOperator):
        return BARRIER_GATHER
    if type(op).__name__ == "Cacher":
        return BARRIER_CACHER
    if lattice.blocks_jit(verdict) or verdict == lattice.HOST_CALLBACK:
        return BARRIER_HOST
    return None


def _spec_item_bytes(av: Any) -> Optional[int]:
    if isinstance(av, Spec):
        return av.item_bytes()
    if isinstance(av, SpecTuple):
        parts = [_spec_item_bytes(e) for e in av.elems]
        known = [p for p in parts if p is not None]
        return sum(known) if known else None
    return None


def plan_segments(
    graph: Any,
    verdicts: Dict[Any, str],
    specs: Dict[Any, Any],
    *,
    cost_estimator: Any = None,
) -> Tuple[List[Segment], Dict[Any, str]]:
    """Partition ``graph`` into maximal traceable segments.

    Returns ``(segments, barriers)`` where ``barriers`` maps each
    non-segment node to its reason. Segments are connected components of
    the segment-eligible node set under graph edges, numbered in
    topological order of their first node.
    """
    from ..workflow import analysis
    from ..workflow.graph import NodeId

    full_order = list(analysis.linearize(graph))
    #: covers sources too — segment inputs may be SourceIds and their
    #: ordering contract (see :class:`Segment`) needs a position for every
    #: graph id a member can depend on
    full_pos = {gid: i for i, gid in enumerate(full_order)}
    order = [
        n for n in full_order
        if isinstance(n, NodeId) and n in graph.operators
    ]
    barriers: Dict[Any, str] = {}
    eligible = set()
    from .abstract import leaf_is_chunked

    for n in order:
        op = graph.get_operator(n)
        reason = barrier_reason(
            op, verdicts.get(n, lattice.OPAQUE),
            is_chunked_leaf=leaf_is_chunked(op),
        )
        if reason is None:
            eligible.add(n)
        else:
            barriers[n] = reason

    # union-find over edges between eligible nodes
    parent: Dict[Any, Any] = {n: n for n in eligible}

    def find(x):
        while parent[x] is not x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def union(a, b):
        ra, rb = find(a), find(b)
        if ra is not rb:
            parent[ra] = rb

    for n in eligible:
        for d in graph.get_dependencies(n):
            if d in eligible:
                union(n, d)

    groups: Dict[Any, List[Any]] = {}
    for n in order:
        if n in eligible:
            groups.setdefault(find(n), []).append(n)

    consumers: Dict[Any, set] = {}
    for n in order:
        for d in graph.get_dependencies(n):
            consumers.setdefault(d, set()).add(n)
    sink_deps = set(graph.sink_dependencies.values())

    topo_pos = {n: i for i, n in enumerate(order)}
    segments: List[Segment] = []
    for i, members in enumerate(
        sorted(groups.values(), key=lambda ms: topo_pos[ms[0]])
    ):
        mset = set(members)
        seen = set()
        inputs: List[Any] = []
        for n in members:
            for d in graph.get_dependencies(n):
                if d not in mset and d not in seen:
                    seen.add(d)
                    inputs.append(d)
        # the pinned inputs contract: topological order of the producer,
        # NOT insertion order over members (which varies with grouping)
        inputs.sort(key=lambda d: full_pos[d])
        outputs = [
            n for n in members
            if n in sink_deps or (consumers.get(n, set()) - mset)
        ]
        seg = Segment(
            index=i, nodes=list(members), inputs=inputs, outputs=outputs
        )
        seg.est_item_bytes = _estimate_item_bytes(
            graph, members, specs, cost_estimator
        )
        segments.append(seg)
    return segments, barriers


def _estimate_item_bytes(
    graph, members, specs, cost_estimator
) -> Optional[int]:
    total = 0
    any_known = False
    for n in members:
        b = _spec_item_bytes(specs.get(n))
        if b is None and cost_estimator is not None:
            priced = cost_estimator.node_profile_ns(
                type(graph.get_operator(n)).__name__, 1
            )
            if priced is not None:
                b = int(priced[1])
        if b is not None:
            total += b
            any_known = True
    return total if any_known else None
