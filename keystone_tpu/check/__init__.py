"""Static pipeline checker: whole-DAG shape/dtype/traceability inference
and compilation-segment planning, before anything executes.

Three layers (see the module docstrings):

* :mod:`~keystone_tpu.check.abstract` — an abstract interpreter
  propagating ``jax.ShapeDtypeStruct`` specs from data leaves through
  every node via ``jax.eval_shape`` (with an ``out_spec`` declaration
  protocol for nodes whose apply is not abstractly evaluable), so
  shape/dtype/rank mismatches raise a typed, node-attributed
  :class:`PipelineCheckError` at ``and_then``/``fit()`` entry instead of
  mid-scan;
* :mod:`~keystone_tpu.check.lattice` — a traceability lattice
  (``traceable | host_callback | batch_coupled | stateful | opaque``)
  classifying every node from static evidence, which the dynamic
  compile/export paths assert against;
* :mod:`~keystone_tpu.check.segments` — a segment planner partitioning
  the DAG into maximal traceable segments between materialization
  barriers: the future whole-DAG compilation-unit plan.

Front doors: ``Pipeline.check()`` / ``FittedPipeline.check()``, the
``--check`` CLI mode, and :func:`check_graph` for raw graphs. The whole
check runs in milliseconds with ZERO chunk executions and ZERO sampled
executions (``cost.count_sampling`` stays untouched — smoke-asserted).

``KEYSTONE_STATIC_CHECK=0`` disables the implicit fit-entry/and_then
checks (explicit ``check()`` calls always run).
"""

from __future__ import annotations

from .abstract import (
    SYMBOLIC_LEAD,
    EstimatorSpec,
    Spec,
    SpecTuple,
    infer_specs,
    spec_from_item,
    spec_of_array,
)
from .errors import CheckOnlyExit, ContractMismatchError, PipelineCheckError
from .lattice import (
    BATCH_COUPLED,
    HOST_CALLBACK,
    OPAQUE,
    STATEFUL,
    TRACEABLE,
    blocks_export,
    blocks_jit,
    classify,
    register_verdict,
)
from .report import CheckReport, check_graph
from .segments import Segment, plan_segments

__all__ = [
    "BATCH_COUPLED",
    "CheckOnlyExit",
    "CheckReport",
    "ContractMismatchError",
    "EstimatorSpec",
    "HOST_CALLBACK",
    "OPAQUE",
    "PipelineCheckError",
    "STATEFUL",
    "SYMBOLIC_LEAD",
    "Segment",
    "Spec",
    "SpecTuple",
    "TRACEABLE",
    "blocks_export",
    "blocks_jit",
    "check_enabled",
    "check_graph",
    "check_only_mode",
    "classify",
    "infer_specs",
    "plan_segments",
    "register_verdict",
    "set_check_only",
    "spec_from_item",
    "spec_of_array",
]


def check_enabled() -> bool:
    """Are the implicit construction/fit-entry checks on?
    (``KEYSTONE_STATIC_CHECK=0`` is the kill switch.)"""
    from ..utils import env_flag

    return env_flag("KEYSTONE_STATIC_CHECK", True)


# -- --check CLI mode -------------------------------------------------------

_check_only = False


def set_check_only(on: bool) -> None:
    """Arm/disarm check-only mode: the next ``Pipeline.fit()`` runs the
    static check, prints the report, and raises :class:`CheckOnlyExit`
    instead of fitting (the ``--check`` CLI flag)."""
    global _check_only
    _check_only = bool(on)


def check_only_mode() -> bool:
    return _check_only
