"""Profile-guided-caching feedback loop: estimate vs. observed.

The reference's AutoCacheRule plans caching from EXTRAPOLATED per-node
profiles (linear time/memory-vs-scale fits) and then never checks whether
the estimates held — a mis-extrapolated node silently skews every future
plan. Here the planner records its per-node estimated seconds/bytes into
the tracer (``AutoCacheRule.apply``), the executor's spans record what
each node actually cost, and :func:`cache_audit` joins the two: one row
per estimated node with estimate, observation, and the ratio between
them. ``observed=False`` rows are themselves a finding — the node never
executed under its planned identity (typically trace-fusion absorbed it,
which also voids its Cacher).
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from .tracer import Tracer

logger = logging.getLogger(__name__)


def observed_by_node(tracer: Tracer, start: int = 0) -> Dict[str, dict]:
    """Aggregate executor spans per DAG node id: observed EXCLUSIVE compute
    seconds, max materialized bytes, and hit/miss counts.

    Exclusive matters: evaluation is lazy, so a node's span contains the
    child spans of every upstream thunk it forced — but the planner's
    estimates are per-node. Comparing inclusive observations against
    exclusive estimates would flag every downstream node as
    mis-extrapolated, so each span's direct-children time is subtracted
    first.

    ``start`` restricts the join to spans recorded at index >= start —
    a long-lived process tracer holds every fit's spans, and NodeIds are
    small per-graph ints, so an unwindowed join would merge observations
    across fits and pipelines."""
    spans = tracer.spans()[start:]
    child_seconds: Dict[int, float] = {}
    for sp in spans:
        if sp.parent_id is not None:
            child_seconds[sp.parent_id] = (
                child_seconds.get(sp.parent_id, 0.0) + sp.seconds
            )
    out: Dict[str, dict] = {}
    for sp in spans:
        if sp.node_id is None:
            continue
        row = out.setdefault(
            sp.node_id,
            {
                "label": sp.name,
                "seconds": 0.0,
                "bytes": None,
                "computes": 0,
                "hits": 0,
            },
        )
        if sp.cache == "hit":
            row["hits"] += 1
            continue
        row["seconds"] += max(
            sp.seconds - child_seconds.get(sp.span_id, 0.0), 0.0
        )
        row["computes"] += 1
        if sp.output_bytes is not None:
            row["bytes"] = max(row["bytes"] or 0, sp.output_bytes)
    return out


def segment_member_ids(tracer: Tracer, start: int = 0) -> set:
    """Node ids dispatched INSIDE a compiled segment (``exec.segment``
    spans carry their member ``node_ids``): these nodes never emit their
    own executor span, by design — the audit must not report them as
    mis-planned just because segment dispatch subsumed them."""
    out: set = set()
    for sp in tracer.spans()[start:]:
        if sp.name != "exec.segment":
            continue
        for nid in sp.attrs.get("node_ids") or ():
            out.add(str(nid))
    return out


def _ratio(observed: Optional[float], estimated: Optional[float]) -> Optional[float]:
    if observed is None or not estimated:
        return None
    return round(observed / estimated, 3)


def cache_audit(tracer: Optional[Tracer] = None) -> List[dict]:
    """One row per node the cache planner estimated: estimated vs observed
    seconds/bytes, plus whether the node got a Cacher and whether it was
    observed executing at all. Rows are sorted Cacher-annotated first,
    then by estimated seconds descending."""
    if tracer is None:
        from . import tracer as tracer_mod

        tracer = tracer_mod.current()
    if tracer is None:
        return []
    observed = observed_by_node(tracer)
    in_segment = segment_member_ids(tracer)
    rows = []
    for node_id, est in tracer.estimates.items():
        obs = observed.get(node_id)
        row = {
            "node": node_id,
            "label": est["label"],
            "cacher": est["cacher"],
            # "node" rows come from the cache planner; "solver" rows from
            # the cost-model chooser (solver/estimator nodes are audited
            # too — their estimate is the chooser's predicted fit time)
            "kind": est.get("kind", "node"),
            "est_seconds": est["est_seconds"],
            "obs_seconds": None if obs is None else round(obs["seconds"], 4),
            "seconds_ratio": _ratio(
                None if obs is None else obs["seconds"], est["est_seconds"]
            ),
            "est_bytes": est["est_bytes"],
            "obs_bytes": None if obs is None else obs["bytes"],
            "bytes_ratio": _ratio(
                None if obs is None else obs["bytes"], est["est_bytes"]
            ),
            "cache_hits": 0 if obs is None else obs["hits"],
            "observed": obs is not None,
            # unobserved because a whole-segment dispatch subsumed it —
            # an expected outcome of segment compilation, not a finding
            "segment": obs is None and node_id in in_segment,
        }
        if est.get("kind") == "solver":
            row["solver"] = est.get("solver")
            row["source"] = est.get("source")
            row["alternatives"] = est.get("alternatives")
            solver_est = est.get("solver_est_seconds")
            row["solver_est_seconds"] = solver_est
            row["solver_seconds_ratio"] = _ratio(
                None if obs is None else obs["seconds"], solver_est
            )
        rows.append(row)
    rows.sort(
        key=lambda r: (not r["cacher"], -(r["est_seconds"] or 0.0))
    )
    return rows


def log_cache_audit(tracer: Optional[Tracer] = None) -> List[dict]:
    """Emit the audit at INFO, one line per row; returns the rows."""
    rows = cache_audit(tracer)
    if not rows:
        return rows
    logger.info(
        "autocache audit: %d estimated node(s), %d Cacher-annotated",
        len(rows),
        sum(1 for r in rows if r["cacher"]),
    )
    for r in rows:
        fmt = lambda v, suffix="": "?" if v is None else f"{v:.4g}{suffix}"
        logger.info(
            "  node %-4s %-40s %s est %ss/%sB observed %ss/%sB "
            "(ratio t=%s mem=%s, hits=%d)%s",
            r["node"],
            (
                f"[solver:{r.get('source', '?')}] {r['label']}"
                if r["kind"] == "solver" else r["label"]
            )[:40],
            "[cached]" if r["cacher"] else "        ",
            fmt(r["est_seconds"]),
            fmt(r["est_bytes"]),
            fmt(r["obs_seconds"]),
            fmt(r["obs_bytes"]),
            fmt(r["seconds_ratio"]),
            fmt(r["bytes_ratio"]),
            r["cache_hits"],
            "" if r["observed"] else (
                " SUBSUMED BY SEGMENT (dispatched inside a compiled segment)"
                if r.get("segment")
                else " NEVER OBSERVED (fused away or unexecuted)"
            ),
        )
    return rows
