"""Scan-pipeline spans: tracer schema for pipelined out-of-core scans.

One ``scan.pipeline`` span per :func:`~keystone_tpu.data.pipeline_scan.
scan_pipeline` scan, covering the whole iteration (first chunk requested
to exhaustion or early close), with the pipeline's counters as span
attrs: host production seconds inside the producer thread, producer-stall
(buffer full — consumer-bound) vs consumer-stall (buffer empty —
producer-bound) seconds, staged H2D bytes, peak buffer occupancy, and
chunk count. The overlap a scan achieved is readable straight off the
span: ``seconds`` ≈ max(producer, consumer) work rather than their sum
when the pipeline is doing its job, and the stall counters say which side
bounded it. ``bench.py``'s ``chunk_pipeline`` extra and
``bin/trace-smoke.sh`` consume these spans.
"""

from __future__ import annotations

from .span import Span
from .tracer import current

#: the span name every pipelined scan records
SCAN_SPAN = "scan.pipeline"


def record_scan_span(stats) -> None:
    """Record one finished scan's counters as a complete span. No-op when
    tracing is off (the usual single ``current() is None`` check)."""
    tracer = current()
    if tracer is None:
        return
    sp = Span(
        name=SCAN_SPAN,
        start=stats.start,
        end=stats.end,
        op_type="ScanPipeline",
        attrs={
            "label": stats.label,
            "chunks": stats.chunks,
            "depth": stats.depth,
            "producer_seconds": round(stats.producer_seconds, 6),
            "producer_stall_seconds": round(stats.producer_stall_seconds, 6),
            "consumer_stall_seconds": round(stats.consumer_stall_seconds, 6),
            "staged_bytes": stats.staged_bytes,
            "occupancy_max": stats.occupancy_max,
        },
    )
    tracer.record_complete(sp)
