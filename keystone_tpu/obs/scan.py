"""Scan-pipeline spans: tracer schema for pipelined out-of-core scans.

One ``scan.pipeline`` span per :func:`~keystone_tpu.data.pipeline_scan.
scan_pipeline` scan, covering the whole iteration (first chunk requested
to exhaustion or early close), with the pipeline's counters as span
attrs: host production seconds inside the producer thread, producer-stall
(buffer full — consumer-bound) vs consumer-stall (buffer empty —
producer-bound) seconds, staged H2D bytes, peak buffer occupancy, and
chunk count. The overlap a scan achieved is readable straight off the
span: ``seconds`` ≈ max(producer, consumer) work rather than their sum
when the pipeline is doing its job, and the stall counters say which side
bounded it. ``bench.py``'s ``chunk_pipeline`` extra and
``bin/trace-smoke.sh`` consume these spans.

Mesh-distributed scans (``lanes > 1``) additionally carry the sharding
schedule: ``lanes``, per-lane chunk/byte totals (``lane_chunks`` /
``lane_bytes`` — skew here is the straggler signal, summarized as
``lane_imbalance`` = max/mean staged bytes), the per-lane ``devices``,
and ``collectives`` — the consumer-reported count of cross-mesh
accumulator reductions and model broadcasts attributed to the scan (the
PAPERS.md #3 gate: O(blocks), never O(chunks); finalize-time reductions
are stamped onto the span after it is recorded). One ``scan.pipeline.lane``
child span per lane nests under the scan span with that lane's device
attribution, so a straggling lane is visible in the trace tree."""

from __future__ import annotations

from .span import Span
from .tracer import current

#: the span name every pipelined scan records
SCAN_SPAN = "scan.pipeline"
#: per-lane child spans of a mesh-distributed scan
SCAN_LANE_SPAN = "scan.pipeline.lane"


def record_scan_span(stats):
    """Record one finished scan's counters as a complete span (plus one
    child span per lane on sharded scans). Returns the scan span so the
    pipeline can stamp late collective counts, or None when tracing is
    off (the usual single ``current() is None`` check)."""
    # scan completion is an allocation peak (staged chunks + accumulator
    # state all live): the memory-watermark seam samples here whether or
    # not tracing is on
    from . import resource as _resource

    _resource.sample_memory()
    tracer = current()
    if tracer is None:
        return None
    attrs = {
        "label": stats.label,
        "chunks": stats.chunks,
        "depth": stats.depth,
        "producer_seconds": round(stats.producer_seconds, 6),
        "producer_stall_seconds": round(stats.producer_stall_seconds, 6),
        "consumer_stall_seconds": round(stats.consumer_stall_seconds, 6),
        "staged_bytes": stats.staged_bytes,
        "occupancy_max": stats.occupancy_max,
    }
    if getattr(stats, "retries", 0):
        # transient-failure retries the scan's budget absorbed — stamped
        # only when nonzero so fault-free traces keep their schema
        attrs["retries"] = stats.retries
    if getattr(stats, "shards", 1) > 1:
        # producer shards (host-side production split over the chunk
        # index space, data/shards.py); per-shard chunk counts are the
        # production-skew signal, same role lane_bytes plays for staging
        attrs["shards"] = stats.shards
        attrs["shard_chunks"] = list(stats.shard_chunks)
    if stats.lanes > 1:
        attrs.update(
            lanes=stats.lanes,
            collectives=stats.collectives,
            lane_chunks=list(stats.lane_chunks),
            lane_bytes=list(stats.lane_bytes),
            devices=list(stats.lane_devices),
        )
        total = sum(stats.lane_bytes)
        if total > 0:
            attrs["lane_imbalance"] = round(
                max(stats.lane_bytes) * stats.lanes / total, 3
            )
    sp = Span(
        name=SCAN_SPAN,
        start=stats.start,
        end=stats.end,
        op_type="ScanPipeline",
        attrs=attrs,
    )
    tracer.record_complete(sp)
    if stats.lanes > 1:
        for lane in range(stats.lanes):
            child = Span(
                name=SCAN_LANE_SPAN,
                start=stats.start,
                end=stats.end,
                parent_id=sp.span_id,
                depth=sp.depth + 1,
                op_type="ScanPipeline",
                attrs={
                    "label": stats.label,
                    "lane": lane,
                    "device": (
                        stats.lane_devices[lane]
                        if lane < len(stats.lane_devices)
                        else ""
                    ),
                    "chunks": (
                        stats.lane_chunks[lane]
                        if lane < len(stats.lane_chunks)
                        else 0
                    ),
                    "staged_bytes": (
                        stats.lane_bytes[lane]
                        if lane < len(stats.lane_bytes)
                        else 0
                    ),
                },
            )
            tracer.record_complete(child)
    return sp
