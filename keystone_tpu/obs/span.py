"""The span record: one timed region of pipeline execution.

A :class:`Span` is what the :class:`~keystone_tpu.obs.tracer.Tracer`
collects — name, DAG node identity, operator type, wall-clock interval,
device-sync time, materialized output bytes, cache hit/miss, and the
XLA-compile count delta across the region. Spans form a tree per thread
(``parent_id``/``depth`` come from the tracer's thread-local stack).

The helpers here size and synchronize values WITHOUT side effects: sizing
never forces a lazy dataset to materialize, and syncing only blocks on
device-resident arrays (host values pass through untouched).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


@dataclass
class Span:
    """One traced region. ``start``/``end`` are ``time.perf_counter``
    readings; the exporter rebases them onto the tracer's epoch."""

    name: str
    start: float
    end: float = 0.0
    span_id: int = 0
    parent_id: Optional[int] = None
    depth: int = 0
    tid: int = 0
    thread_name: str = ""
    #: DAG node identity (stringified NodeId.id), None for non-node spans
    node_id: Optional[str] = None
    #: operator class name (Cacher, FusedTransformerOperator, ...)
    op_type: Optional[str] = None
    #: "hit" (memoized result returned) | "miss" (computed this pull) | None
    cache: Optional[str] = None
    #: seconds spent blocking on the device stream at span exit
    sync_seconds: float = 0.0
    #: materialized result size, when cheaply knowable (see cheap_nbytes)
    output_bytes: Optional[int] = None
    #: XLA backend compiles that happened inside this span
    compiles: int = 0
    instant: bool = False
    #: free-form attributes; the concurrent executor adds
    #: ``queue_wait_seconds`` (ready-to-started scheduler latency) and
    #: ``worker`` (pool thread name) to node spans it forced
    attrs: Dict[str, Any] = field(default_factory=dict)
    #: value to block on at span exit (cleared once synced); not exported
    sync_target: Any = field(default=None, repr=False)

    @property
    def seconds(self) -> float:
        return max(self.end - self.start, 0.0)

    def sync_on(self, value: Any) -> None:
        """Ask the tracer to block on ``value`` when this span closes, so
        asynchronously-dispatched device work is attributed here and not to
        whichever later span first synchronizes."""
        self.sync_target = value


def _device_payload(value: Any) -> Any:
    """What to block on for ``value`` — device arrays / batched payloads.
    Returns None when syncing would force work (item lists, chunked
    datasets) or there is nothing device-resident to wait for."""
    from ..data.dataset import Dataset

    if isinstance(value, Dataset):
        # batched payloads are (pytrees of) arrays already dispatched;
        # item-list / chunked datasets would have to MATERIALIZE to sync
        return value.payload if value.is_batched else None
    return value


def sync_value(value: Any) -> bool:
    """``jax.block_until_ready`` on the device-resident part of ``value``.

    Returns True when a sync was attempted. Missing jax or non-blockable
    values are expected (ImportError/TypeError pass silently); anything
    else is a REAL device error and is logged at WARNING rather than
    swallowed."""
    target = _device_payload(value)
    if target is None:
        return False
    try:
        import jax

        jax.block_until_ready(target)
        return True
    except (ImportError, TypeError):
        return False
    except Exception:
        logger.warning("span sync: block_until_ready failed", exc_info=True)
        return False


def cheap_nbytes(value: Any) -> Optional[int]:
    """Best-effort materialized size of ``value`` in bytes, WITHOUT forcing
    computation, host transfer, or chunk materialization. None when the
    size is not cheaply knowable."""
    import numpy as np

    try:
        from ..data.dataset import Dataset

        if isinstance(value, Dataset):
            if not value.is_batched:
                return None  # sizing would force collect()
            import jax

            return int(
                sum(
                    int(np.prod(a.shape)) * a.dtype.itemsize
                    for a in jax.tree_util.tree_leaves(value.payload)
                    if hasattr(a, "shape") and hasattr(a, "dtype")
                )
            )
        nbytes = getattr(value, "nbytes", None)
        if nbytes is not None:
            return int(nbytes)
        if hasattr(value, "shape") and hasattr(value, "dtype"):
            return int(np.prod(value.shape)) * value.dtype.itemsize
    except Exception:
        # sizing is best-effort by contract: a value that cannot report
        # its bytes must never break the span that carries it
        logger.debug("cheap_nbytes probe failed", exc_info=True)
        return None
    return None
