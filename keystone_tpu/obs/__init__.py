"""Pipeline tracing & per-node profiling.

The observability subsystem the source paper's optimizer implies but
never ships: a :class:`~keystone_tpu.obs.tracer.Tracer` collecting a span
tree across the three execution layers (graph executor pulls, autocache
planning, serving micro-batches), Chrome-trace/Perfetto export, a
plain-text top-N summary, and the estimate-vs-observed autocache audit.

Enable with ``KEYSTONE_TRACE=/path/trace.json`` (or the CLI's
``--trace PATH``); disabled, every instrumentation point is a single
``current() is None`` check.
"""

from .audit import cache_audit, log_cache_audit
from .context import Sampler, TraceContext, new_trace_id, sample_rate
from .export import (
    format_top_spans,
    stitch_chrome_trace,
    to_chrome_trace,
    wire_spans,
    write_chrome_trace,
    write_stitched_trace,
)
from .flight import FlightRecorder, SITE_INSTANTS
from .flight import recorder as flight_recorder
from .scan import SCAN_LANE_SPAN, SCAN_SPAN, record_scan_span
from .span import Span, cheap_nbytes
from .tracer import Tracer, current, export, install, reset, start, stop, suspended

__all__ = [
    "SCAN_LANE_SPAN",
    "SCAN_SPAN",
    "SITE_INSTANTS",
    "FlightRecorder",
    "Sampler",
    "Span",
    "TraceContext",
    "Tracer",
    "cache_audit",
    "cheap_nbytes",
    "current",
    "flight_recorder",
    "new_trace_id",
    "record_scan_span",
    "export",
    "format_top_spans",
    "install",
    "log_cache_audit",
    "reset",
    "sample_rate",
    "start",
    "stitch_chrome_trace",
    "stop",
    "suspended",
    "to_chrome_trace",
    "wire_spans",
    "write_chrome_trace",
    "write_stitched_trace",
]
