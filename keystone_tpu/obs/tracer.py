"""The tracer: thread-safe span collection with a process-global switch.

Parity motivation: KeystoneML's optimizer is profile-guided but its
EXECUTION is blind — per-stage attribution lives in the Spark UI, outside
the system. Here the :class:`Tracer` is that attribution layer: every DAG
node pull, autocache decision, and serving micro-batch lands in one span
registry, exportable as Chrome-trace JSON (``obs/export.py``) and audited
against the cache planner's estimates (``obs/audit.py``).

Overhead contract: tracing is OFF unless a tracer is installed —
:func:`current` returns None and every instrumentation site is a single
``is None`` check with NO span allocation. Installed, each span costs one
dataclass + two clock reads (+ an optional device sync at exit, which is
the point: accurate attribution).

Wiring: ``utils/obs.configure`` installs the global tracer from
``KEYSTONE_TRACE=path`` (or the CLI's ``--trace PATH``) and registers an
atexit export; library code only ever calls :func:`current`.
"""

from __future__ import annotations

import atexit
import contextlib
import itertools
import logging
import threading
import time
from typing import Dict, Iterator, List, Optional

from .span import Span, cheap_nbytes, sync_value

logger = logging.getLogger(__name__)

# -- XLA compile counting ---------------------------------------------------

#: process-wide count of XLA backend compiles, fed by jax.monitoring.
#: Listeners cannot be unregistered individually, so this installs once
#: (lazily, on first Tracer construction) and stays for the process life;
#: the increment is negligible and only spans read the counter.
_compiles = itertools.count()
_compiles_seen = 0
_compile_listener_lock = threading.Lock()
_compile_listener_installed = False


def _compile_count() -> int:
    return _compiles_seen


def _install_compile_listener() -> None:
    global _compile_listener_installed
    with _compile_listener_lock:
        if _compile_listener_installed:
            return
        _compile_listener_installed = True
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            # one /jax/core/compile/backend_compile_duration per real
            # XLA compile (cache hits emit cache events instead)
            if event.endswith("backend_compile_duration"):
                global _compiles_seen
                _compiles_seen = next(_compiles) + 1

        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        logger.debug("jax compile-event listener unavailable", exc_info=True)


# -- the tracer -------------------------------------------------------------


class Tracer:
    """Collects a span tree per thread; thread-safe for concurrent writers
    (the serving worker and N pipeline threads trace into one registry)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._ids = itertools.count(1)
        self._local = threading.local()
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()
        #: node_id -> estimate row recorded by the autocache planner
        #: (see obs/audit.py for the estimate-vs-observed feedback loop)
        self._estimates: Dict[str, dict] = {}
        #: bumped at each optimizer pass (RuleExecutor.execute): NodeIds
        #: are small per-graph ints, so a long-lived tracer must not merge
        #: a NEW pass's estimate for id "3" into a PREVIOUS pipeline's row
        self._plan_epoch = 0
        #: spans discarded below _spans[0] (discard_through): cursors
        #: from spans_since stay valid GLOBAL indices across compaction
        self._span_offset = 0
        _install_compile_listener()

    # -- span recording -------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span(self) -> Optional[Span]:
        """This thread's innermost open span, or None. The concurrent
        executor captures it as the explicit parent for worker threads."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def adopt(self, parent: Optional[Span]) -> Iterator[None]:
        """Explicit cross-thread parent linking: make ``parent`` (a span
        opened on ANOTHER thread) the current parent on THIS thread. The
        per-thread stacks give a correct tree only for same-thread nesting;
        a scheduler worker forcing a DAG node starts with an empty stack,
        so without adoption its node spans would all be roots. ``parent``
        is pushed but never recorded here — its opener owns its exit."""
        if parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        try:
            yield
        finally:
            stack.pop()

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        node_id: Optional[str] = None,
        op_type: Optional[str] = None,
        cache: Optional[str] = None,
        **attrs,
    ) -> Iterator[Span]:
        """Open a span; the yielded handle takes extra attrs and an
        optional ``sync_on(value)`` target blocked on at exit."""
        stack = self._stack()
        thread = threading.current_thread()
        sp = Span(
            name=name,
            start=time.perf_counter(),
            span_id=next(self._ids),
            parent_id=stack[-1].span_id if stack else None,
            depth=len(stack),
            tid=thread.ident or 0,
            thread_name=thread.name,
            node_id=node_id,
            op_type=op_type,
            cache=cache,
            attrs=dict(attrs),
        )
        compiles_at_start = _compile_count()
        stack.append(sp)
        try:
            yield sp
        finally:
            stack.pop()
            target = sp.sync_target
            if target is not None:
                sp.sync_target = None
                t0 = time.perf_counter()
                if sync_value(target):
                    sp.sync_seconds = time.perf_counter() - t0
                if sp.output_bytes is None:
                    sp.output_bytes = cheap_nbytes(target)
            sp.end = time.perf_counter()
            sp.compiles = _compile_count() - compiles_at_start
            with self._lock:
                self._spans.append(sp)

    def instant(
        self,
        name: str,
        *,
        node_id: Optional[str] = None,
        op_type: Optional[str] = None,
        cache: Optional[str] = None,
        **attrs,
    ) -> Span:
        """A zero-duration event (e.g. a memo-cache hit)."""
        stack = self._stack()
        thread = threading.current_thread()
        now = time.perf_counter()
        sp = Span(
            name=name,
            start=now,
            end=now,
            span_id=next(self._ids),
            parent_id=stack[-1].span_id if stack else None,
            depth=len(stack),
            tid=thread.ident or 0,
            thread_name=thread.name,
            node_id=node_id,
            op_type=op_type,
            cache=cache,
            instant=True,
            attrs=dict(attrs),
        )
        with self._lock:
            self._spans.append(sp)
        return sp

    def record_complete(self, sp: Span) -> None:
        """Append an externally-built, already-finished span (used by the
        executor for eagerly-computed expressions). Fills in identity and
        tree position from the calling thread's open span, if any."""
        stack = self._stack()
        thread = threading.current_thread()
        sp.span_id = next(self._ids)
        if sp.parent_id is None and stack:
            sp.parent_id = stack[-1].span_id
            sp.depth = len(stack)
        sp.tid = thread.ident or 0
        sp.thread_name = thread.name
        with self._lock:
            self._spans.append(sp)

    # -- reads ----------------------------------------------------------

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def spans_since(self, cursor: int):
        """``(spans[cursor:], new_cursor)`` — the incremental read the
        cluster worker uses to ship each recorded span back to the
        router exactly once. Cursors are GLOBAL indices (monotonic
        across :meth:`discard_through` compaction), so a bookmark taken
        before a discard still resolves to only-unshipped spans."""
        with self._lock:
            n = self._span_offset + len(self._spans)
            start = max(cursor - self._span_offset, 0)
            return self._spans[start:], n

    def discard_through(self, cursor: int) -> int:
        """Drop spans below global index ``cursor`` (they were shipped to
        another process that now owns them). This is what keeps a
        long-lived ALWAYS-ON traced worker bounded: without it the
        append-only registry grows one Span per hop forever. Returns the
        count discarded. Local reads (``spans()``/``span_summary``) see
        only the retained window afterwards — the shipper is the
        archive."""
        with self._lock:
            k = min(max(cursor - self._span_offset, 0), len(self._spans))
            if k:
                del self._spans[:k]
                self._span_offset += k
            return k

    def span_summary(
        self, prefix: Optional[str] = None
    ) -> Dict[str, Dict[str, object]]:
        """``{name: {"seconds", "calls", ...}}`` — the SAME shape as
        ``utils.timing.snapshot`` and ``MetricsRegistry.snapshot()["phases"]``
        so span, phase, and metrics exports concatenate without schema
        mismatches. ``prefix`` filters to one subsystem (e.g. ``"serve."``)."""
        agg: Dict[str, dict] = {}
        for sp in self.spans():
            if prefix is not None and not sp.name.startswith(prefix):
                continue
            row = agg.setdefault(
                sp.name,
                {
                    "seconds": 0.0,
                    "calls": 0,
                    "sync_seconds": 0.0,
                    "bytes": 0,
                    "compiles": 0,
                    "cache_hits": 0,
                    "cache_misses": 0,
                },
            )
            row["calls"] += 1
            if sp.cache == "hit":
                row["cache_hits"] += 1
                continue
            if sp.cache == "miss":
                row["cache_misses"] += 1
            row["seconds"] += sp.seconds
            row["sync_seconds"] += sp.sync_seconds
            row["compiles"] += sp.compiles
            if sp.output_bytes:
                row["bytes"] = max(row["bytes"], sp.output_bytes)
        for row in agg.values():
            row["seconds"] = round(row["seconds"], 4)
            row["sync_seconds"] = round(row["sync_seconds"], 4)
        return dict(sorted(agg.items()))

    # -- autocache estimates (see obs/audit.py) -------------------------

    def record_node_estimate(
        self,
        node_id: str,
        label: str,
        est_seconds: Optional[float] = None,
        est_bytes: Optional[float] = None,
        cacher: bool = False,
        **extras,
    ) -> None:
        """Record one planner estimate for a DAG node. ``extras`` carry
        planner-specific context into the audit rows verbatim — e.g. the
        solver chooser's ``kind="solver"``, chosen class, pricing
        ``source``, and per-option ``alternatives``. Re-recording the
        same node id within ONE planning pass overwrites (last planner
        wins), preserving any prior extras the new record doesn't name;
        a row left over from an EARLIER pass (same small-int node id,
        different graph) is replaced wholesale so stale solver extras
        can't leak into the new pipeline's audit."""
        with self._lock:
            row = self._estimates.get(str(node_id), {})
            if row.get("_epoch") != self._plan_epoch:
                row = {}
            row.update(
                {
                    "label": label,
                    "est_seconds": est_seconds,
                    "est_bytes": est_bytes,
                    "cacher": bool(cacher),
                    "_epoch": self._plan_epoch,
                    **extras,
                }
            )
            self._estimates[str(node_id)] = row

    def begin_plan_epoch(self) -> None:
        """Mark the start of a new optimizer planning pass (see
        :meth:`record_node_estimate`)."""
        with self._lock:
            self._plan_epoch += 1

    @property
    def estimates(self) -> Dict[str, dict]:
        with self._lock:
            return {
                k: {kk: vv for kk, vv in row.items() if kk != "_epoch"}
                for k, row in self._estimates.items()
            }


# -- process-global wiring --------------------------------------------------

_current: Optional[Tracer] = None
_export_path: Optional[str] = None
_atexit_registered = False
#: spans already written by an explicit export — lets the atexit backstop
#: skip the rewrite (and the duplicate summary/audit logs) when nothing
#: new was recorded since
_exported_span_count: Optional[int] = None
_suspend = threading.local()


def current() -> Optional[Tracer]:
    """The installed tracer, or None (tracing disabled — the fast path).
    Thread-locally None inside a :func:`suspended` block."""
    if getattr(_suspend, "depth", 0):
        return None
    return _current


def install(tracer: Tracer) -> Tracer:
    global _current
    _current = tracer
    return tracer


_install_lock = threading.Lock()


def install_if_absent(tracer: Tracer) -> Optional[Tracer]:
    """Install ``tracer`` only if no tracer is currently installed;
    returns it if installed, None if another tracer already holds the
    slot. Lets concurrent fit-local observation windows (Pipeline.fit
    with a profile store) race safely: exactly one wins the slot."""
    global _current
    with _install_lock:
        if _current is not None:
            return None
        _current = tracer
        return tracer


def uninstall(tracer: Tracer) -> bool:
    """Remove ``tracer`` only if it is still the installed one; returns
    whether it was removed. The safe inverse of :func:`install_if_absent`
    — never tears down a tracer some other thread installed later."""
    global _current
    with _install_lock:
        if _current is not tracer:
            return False
        _current = None
        return True


def start(path: Optional[str] = None) -> Tracer:
    """Install a process tracer (idempotent: an existing tracer is kept so
    repeated ``configure`` calls don't drop collected spans). ``path``
    arms the atexit Chrome-trace export."""
    global _current, _export_path, _atexit_registered
    if _current is None:
        _current = Tracer()
    if path:
        _export_path = path
        if not _atexit_registered:
            _atexit_registered = True
            atexit.register(_atexit_export)
    return _current


def stop() -> Optional[Tracer]:
    """Uninstall and return the tracer (spans stay readable on the
    returned object)."""
    global _current
    tracer, _current = _current, None
    return tracer


def reset() -> None:
    """Drop the installed tracer AND the export path (test hygiene)."""
    global _current, _export_path, _exported_span_count
    _current = None
    _export_path = None
    _exported_span_count = None


@contextlib.contextmanager
def suspended() -> Iterator[None]:
    """Temporarily disable tracing ON THIS THREAD — used around the
    autocache PROFILING runs so sampled-scale executions don't pollute
    the real trace (their node ids would collide with the production
    pull's). Thread-local so a serving worker tracing micro-batches is
    unaffected by a concurrent fit's profiling window."""
    _suspend.depth = getattr(_suspend, "depth", 0) + 1
    try:
        yield
    finally:
        _suspend.depth -= 1


def _atexit_export() -> None:
    """The exit backstop: write only if spans arrived since the last
    explicit export — a CLI run that already exported in its ``finally``
    must not rewrite the file and double-log the summary + audit."""
    if _current is None:
        return
    if _exported_span_count == len(_current.spans()):
        return
    export()


def export(path: Optional[str] = None) -> Optional[str]:
    """Write the Chrome trace for the installed tracer to ``path`` (or the
    path ``start`` armed), log the top-N span summary and the autocache
    estimate-vs-observed audit. No-op (returns None) when tracing is off
    or no path is configured. Safe under atexit: IO failures log a
    warning instead of raising into interpreter shutdown."""
    global _exported_span_count
    tracer = _current
    path = path or _export_path
    if tracer is None or path is None:
        return None
    _exported_span_count = len(tracer.spans())
    from .audit import log_cache_audit
    from .export import format_top_spans, write_chrome_trace

    try:
        write_chrome_trace(tracer, path)
    except OSError:
        logger.warning("trace export to %s failed", path, exc_info=True)
        return None
    logger.info(
        "trace: %d spans -> %s\n%s",
        len(tracer.spans()),
        path,
        format_top_spans(tracer),
    )
    log_cache_audit(tracer)
    return path
