"""Trace exporters: Chrome-trace JSON, cross-process stitching, and a
plain-text top-N summary.

The JSON form is the ``chrome://tracing`` / Perfetto "Trace Event Format"
(https://ui.perfetto.dev opens it directly): one ``"X"`` complete event
per span (``ts``/``dur`` in microseconds, rebased to the tracer's epoch),
``"i"`` instant events for cache hits, and ``"M"`` metadata events naming
the process and its threads — every export carries a ``process_name``
metadata event and its real ``pid``, so multi-process traces render as
DISTINCT process tracks instead of flattening into one. Events are
sorted by ``ts`` so consumers that stream (and ``bin/trace-smoke.sh``'s
monotonicity check) see ordered time.

Cross-process stitching (:func:`stitch_chrome_trace`): each process
serializes its spans with :func:`wire_spans` — rebased onto the shared
unix clock, because perf_counter epochs are process-local — and the
router merges N processes' span sets into ONE document with per-pid
process tracks. Span identity never collides across the merge: events
carry no raw span ids, and the ``trace_id`` attr that ties one request's
hops together is already namespaced by the originating pid
(``obs/context.py``).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .tracer import Tracer


def _json_safe(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


def _span_args(sp) -> dict:
    """One span's exported args dict (typed fields + free-form attrs)."""
    args = {
        k: _json_safe(v)
        for k, v in (
            ("node", sp.node_id),
            ("op_type", sp.op_type),
            ("cache", sp.cache),
            ("sync_ms", round(sp.sync_seconds * 1e3, 3) or None),
            ("output_bytes", sp.output_bytes),
            ("compiles", sp.compiles or None),
        )
        if v is not None
    }
    args.update({k: _json_safe(v) for k, v in sp.attrs.items()})
    return args


def _process_meta(pid: int, process_name: Optional[str]) -> List[dict]:
    if not process_name:
        return []
    return [
        {
            "name": "process_name",
            "ph": "M",
            "ts": 0.0,
            "pid": pid,
            "tid": 0,
            "args": {"name": process_name},
        }
    ]


def default_process_name() -> str:
    """``keystone:<argv0-basename>/<pid>`` — distinct per process even
    when every tier runs the same entry point."""
    import sys

    base = os.path.basename(sys.argv[0] or "python") or "python"
    return f"keystone:{base}/{os.getpid()}"


def to_chrome_trace(
    tracer: Tracer, process_name: Optional[str] = None
) -> Dict[str, object]:
    """The trace as a Chrome-trace dict: ``{"traceEvents": [...], ...}``."""
    pid = os.getpid()
    events: List[dict] = []
    thread_names = {}
    for sp in tracer.spans():
        ev = {
            "name": sp.name,
            "cat": "keystone",
            "ph": "i" if sp.instant else "X",
            "ts": round((sp.start - tracer.epoch) * 1e6, 3),
            "pid": pid,
            "tid": sp.tid,
            "args": _span_args(sp),
        }
        if sp.instant:
            ev["s"] = "t"  # thread-scoped instant marker
        else:
            ev["dur"] = round(sp.seconds * 1e6, 3)
        events.append(ev)
        thread_names.setdefault(sp.tid, sp.thread_name)
    events.sort(key=lambda e: e["ts"])
    meta = _process_meta(pid, process_name or default_process_name()) + [
        {
            "name": "thread_name",
            "ph": "M",
            "ts": 0.0,
            "pid": pid,
            "tid": tid,
            "args": {"name": name},
        }
        for tid, name in sorted(thread_names.items())
    ]
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "keystone_tpu.obs",
            "epoch_unix_seconds": tracer.epoch_unix,
        },
    }


def write_chrome_trace(tracer: Tracer, path: str) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(tracer), f)
    return path


# -- cross-process stitching --------------------------------------------------


def wire_spans(
    spans: Iterable, epoch: float, epoch_unix: float,
    pid: Optional[int] = None, process_name: Optional[str] = None,
) -> List[dict]:
    """Serialize spans for shipping across a process boundary: start
    times rebased from the process-local perf_counter epoch onto the
    HOST-shared unix clock (``epoch_unix + (start - epoch)``), plus the
    pid/thread identity the stitcher needs for per-process tracks. The
    wire form is plain JSON-safe dicts (they ride pickled stats replies
    today, but nothing in them requires pickle)."""
    pid = os.getpid() if pid is None else pid
    out = []
    for sp in spans:
        out.append({
            "name": sp.name,
            "start_unix": epoch_unix + (sp.start - epoch),
            "dur_s": sp.seconds,
            "instant": bool(sp.instant),
            "pid": pid,
            "tid": sp.tid,
            "thread_name": sp.thread_name,
            "process_name": process_name,
            "args": _span_args(sp),
        })
    return out


def stitch_chrome_trace(
    span_sets: Sequence[List[dict]],
    base_unix: Optional[float] = None,
) -> Dict[str, object]:
    """Merge N processes' :func:`wire_spans` outputs into ONE
    Chrome-trace document with real per-pid process tracks.

    ``ts`` is rebased to ``base_unix`` (default: the earliest span seen)
    so the merged timeline starts near 0. Each distinct pid contributes
    its own ``process_name``/``thread_name`` metadata events — the fix
    for the flattened single-process rendering the in-process exporter
    used to produce for multi-process runs."""
    all_spans = [s for spans in span_sets for s in spans]
    if base_unix is None:
        base_unix = min(
            (s["start_unix"] for s in all_spans), default=0.0
        )
    events: List[dict] = []
    proc_names: Dict[int, str] = {}
    thread_names: Dict[Tuple[int, int], str] = {}
    for s in all_spans:
        pid = int(s.get("pid") or 0)
        ev = {
            "name": s["name"],
            "cat": "keystone",
            "ph": "i" if s.get("instant") else "X",
            "ts": round((s["start_unix"] - base_unix) * 1e6, 3),
            "pid": pid,
            "tid": s.get("tid", 0),
            "args": dict(s.get("args") or {}),
        }
        if s.get("instant"):
            ev["s"] = "t"
        else:
            ev["dur"] = round(float(s.get("dur_s") or 0.0) * 1e6, 3)
        events.append(ev)
        if s.get("process_name"):
            proc_names.setdefault(pid, str(s["process_name"]))
        if s.get("thread_name"):
            thread_names.setdefault(
                (pid, s.get("tid", 0)), str(s["thread_name"])
            )
    events.sort(key=lambda e: e["ts"])
    meta: List[dict] = []
    for pid, name in sorted(proc_names.items()):
        meta.extend(_process_meta(pid, name))
    meta.extend(
        {
            "name": "thread_name",
            "ph": "M",
            "ts": 0.0,
            "pid": pid,
            "tid": tid,
            "args": {"name": name},
        }
        for (pid, tid), name in sorted(thread_names.items())
    )
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "keystone_tpu.obs (stitched)",
            "epoch_unix_seconds": base_unix,
            "processes": sorted(proc_names.values()),
        },
    }


def write_stitched_trace(
    span_sets: Sequence[List[dict]], path: str
) -> str:
    with open(path, "w") as f:
        json.dump(stitch_chrome_trace(span_sets), f)
    return path


def format_top_spans(tracer: Tracer, n: int = 10, prefix: Optional[str] = None) -> str:
    """Plain-text top-``n`` span names by total seconds — the quick look
    that doesn't need a trace viewer."""
    summary = tracer.span_summary(prefix=prefix)
    rows = sorted(
        summary.items(), key=lambda kv: kv[1]["seconds"], reverse=True
    )[:n]
    if not rows:
        return "(no spans)"
    width = min(max(len(name) for name, _ in rows), 64)
    lines = [
        f"{'span':<{width}} {'seconds':>9} {'calls':>6} {'sync_s':>8} "
        f"{'hits':>5} {'MB':>9} {'compiles':>8}"
    ]
    for name, row in rows:
        mb = (row["bytes"] or 0) / 2**20
        lines.append(
            f"{name[:width]:<{width}} {row['seconds']:>9.4f} "
            f"{row['calls']:>6} {row['sync_seconds']:>8.4f} "
            f"{row['cache_hits']:>5} {mb:>9.2f} {row['compiles']:>8}"
        )
    return "\n".join(lines)
