"""Trace exporters: Chrome-trace JSON and a plain-text top-N summary.

The JSON form is the ``chrome://tracing`` / Perfetto "Trace Event Format"
(https://ui.perfetto.dev opens it directly): one ``"X"`` complete event
per span (``ts``/``dur`` in microseconds, rebased to the tracer's epoch),
``"i"`` instant events for cache hits, and ``"M"`` metadata events naming
threads. Events are sorted by ``ts`` so consumers that stream (and
``bin/trace-smoke.sh``'s monotonicity check) see ordered time.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from .tracer import Tracer


def _json_safe(v):
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    return str(v)


def to_chrome_trace(tracer: Tracer) -> Dict[str, object]:
    """The trace as a Chrome-trace dict: ``{"traceEvents": [...], ...}``."""
    pid = os.getpid()
    events: List[dict] = []
    thread_names = {}
    for sp in tracer.spans():
        args = {
            k: _json_safe(v)
            for k, v in (
                ("node", sp.node_id),
                ("op_type", sp.op_type),
                ("cache", sp.cache),
                ("sync_ms", round(sp.sync_seconds * 1e3, 3) or None),
                ("output_bytes", sp.output_bytes),
                ("compiles", sp.compiles or None),
            )
            if v is not None
        }
        args.update({k: _json_safe(v) for k, v in sp.attrs.items()})
        ev = {
            "name": sp.name,
            "cat": "keystone",
            "ph": "i" if sp.instant else "X",
            "ts": round((sp.start - tracer.epoch) * 1e6, 3),
            "pid": pid,
            "tid": sp.tid,
            "args": args,
        }
        if sp.instant:
            ev["s"] = "t"  # thread-scoped instant marker
        else:
            ev["dur"] = round(sp.seconds * 1e6, 3)
        events.append(ev)
        thread_names.setdefault(sp.tid, sp.thread_name)
    events.sort(key=lambda e: e["ts"])
    meta = [
        {
            "name": "thread_name",
            "ph": "M",
            "ts": 0.0,
            "pid": pid,
            "tid": tid,
            "args": {"name": name},
        }
        for tid, name in sorted(thread_names.items())
    ]
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "producer": "keystone_tpu.obs",
            "epoch_unix_seconds": tracer.epoch_unix,
        },
    }


def write_chrome_trace(tracer: Tracer, path: str) -> str:
    with open(path, "w") as f:
        json.dump(to_chrome_trace(tracer), f)
    return path


def format_top_spans(tracer: Tracer, n: int = 10, prefix: Optional[str] = None) -> str:
    """Plain-text top-``n`` span names by total seconds — the quick look
    that doesn't need a trace viewer."""
    summary = tracer.span_summary(prefix=prefix)
    rows = sorted(
        summary.items(), key=lambda kv: kv[1]["seconds"], reverse=True
    )[:n]
    if not rows:
        return "(no spans)"
    width = min(max(len(name) for name, _ in rows), 64)
    lines = [
        f"{'span':<{width}} {'seconds':>9} {'calls':>6} {'sync_s':>8} "
        f"{'hits':>5} {'MB':>9} {'compiles':>8}"
    ]
    for name, row in rows:
        mb = (row["bytes"] or 0) / 2**20
        lines.append(
            f"{name[:width]:<{width}} {row['seconds']:>9.4f} "
            f"{row['calls']:>6} {row['sync_seconds']:>8.4f} "
            f"{row['cache_hits']:>5} {mb:>9.2f} {row['compiles']:>8}"
        )
    return "\n".join(lines)
