"""Resource accounting: batch cost attribution + device-memory watermarks.

Attribution (:func:`split_batch_cost`) is the arithmetic behind the
per-tenant cost tables: one replica batch's measured device-seconds are
split EQUALLY across its coalesced members — coalescing means every
member's answer came out of the same compiled program invocation, so an
equal split is the unique charge whose per-tenant sums reconstruct the
replica's true busy time. Queue-seconds (enqueue → dispatch wait) and
payload bytes are charged per member. The replica folds the result into
``MetricsRegistry.observe_cost`` under each request's (tenant, priority)
identity.

Memory (:class:`MemoryWatermark`) samples live device bytes on the three
seams where allocations peak — scan materialization, fit/absorb, and
batch execution — via ``Device.memory_stats()`` where the backend
provides it (TPU/GPU) and a ``jax.live_arrays()`` byte-sum fallback on
CPU. :func:`install_memory_gauges` registers the readings as gauges with
honest merge modes: live bytes SUM across worker processes (distinct
device sets), the peak watermark takes the MAX, the utilization fraction
averages.

The whole plane is gated by ``KEYSTONE_ACCOUNTING`` (default on): the
bench's overhead gate proves attribution-on serves within 10% of
attribution-off, but a deployment that wants the last microsecond can
still turn the charging off.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional, Sequence, Tuple

from ..utils import env_flag

logger = logging.getLogger(__name__)

_enabled: Optional[bool] = None
_enabled_lock = threading.Lock()


def accounting_enabled() -> bool:
    """``KEYSTONE_ACCOUNTING`` (default on), resolved once per process."""
    global _enabled
    if _enabled is None:
        with _enabled_lock:
            if _enabled is None:
                _enabled = env_flag("KEYSTONE_ACCOUNTING", True)
    return _enabled


def reset() -> None:
    """Re-read the env gate and forget watermarks (test hygiene)."""
    global _enabled, _watermark
    with _enabled_lock:
        _enabled = None
    with _watermark_lock:
        _watermark = None


def payload_nbytes(datum: object) -> int:
    """Best-effort byte size of one request payload."""
    n = getattr(datum, "nbytes", None)
    if isinstance(n, (int, float)):
        return int(n)
    if isinstance(datum, (bytes, bytearray, memoryview)):
        return len(datum)
    return 0


def split_batch_cost(
    requests: Sequence[object],
    device_seconds: float,
    now: float,
    payloads: Optional[Sequence[object]] = None,
) -> Dict[Tuple[str, str], Dict[str, object]]:
    """Split one batch's cost across its members, keyed by (tenant,
    priority).

    ``device_seconds`` splits equally per member (see module docstring);
    ``queue_s`` is each member's enqueue→dispatch wait against ``now``
    (the dispatch timestamp, same clock as ``request.enqueued``);
    ``payload_bytes`` comes from ``payloads[i]`` when given (the
    validated ndarray rows) else from each request's ``datum``."""
    if not requests:
        return {}
    per = float(device_seconds) / len(requests)
    out: Dict[Tuple[str, str], Dict[str, object]] = {}
    for i, req in enumerate(requests):
        key = (
            str(getattr(req, "tenant", None) or "default"),
            str(getattr(req, "priority", None) or "normal"),
        )
        row = out.setdefault(
            key,
            {"device_s": 0.0, "queue_s": 0.0, "payload_bytes": 0, "items": 0},
        )
        row["device_s"] += per
        enq = getattr(req, "enqueued", None)
        if isinstance(enq, (int, float)):
            row["queue_s"] += max(0.0, float(now) - float(enq))
        payload = (
            payloads[i]
            if payloads is not None and i < len(payloads)
            else getattr(req, "datum", None)
        )
        row["payload_bytes"] += payload_nbytes(payload)
        row["items"] += 1
    return out


# -- device memory ------------------------------------------------------


def device_memory_bytes() -> Tuple[int, int]:
    """``(live_bytes, limit_bytes)`` summed across local devices.

    Prefers the backend allocator's ``memory_stats()`` (TPU/GPU report
    ``bytes_in_use``/``bytes_limit``); CPU backends expose no allocator
    stats, so the fallback sums ``jax.live_arrays()`` — coarser (host
    copies of committed arrays) but monotone with real footprint, which
    is all a watermark gauge needs. Returns ``(0, 0)`` when jax itself
    is unavailable; never raises."""
    try:
        import jax

        total = limit = 0
        saw_stats = False
        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:  # lint: allow-silent -- probed every sample; backends without allocator stats raise Unimplemented and the live_arrays fallback below IS the handling
                stats = None
            if stats:
                total += int(stats.get("bytes_in_use", 0) or 0)
                limit += int(stats.get("bytes_limit", 0) or 0)
                saw_stats = True
        if saw_stats:
            return total, limit
        return (
            sum(int(x.nbytes) for x in jax.live_arrays()),
            0,
        )
    except Exception:
        logger.debug("device memory read failed", exc_info=True)
        return 0, 0


class MemoryWatermark:
    """Throttled live/peak device-byte tracker for one process."""

    def __init__(self):
        self._lock = threading.Lock()
        self.live = 0
        self.peak = 0
        self.limit = 0
        self._last = 0.0

    def sample(self, min_interval_s: float = 0.0) -> int:
        """Refresh the reading unless one landed within
        ``min_interval_s`` (hot seams throttle; gauges read fresh).
        Returns the current live-byte count either way."""
        now = time.monotonic()
        with self._lock:
            if min_interval_s > 0 and now - self._last < min_interval_s:
                return self.live
            self._last = now
        live, limit = device_memory_bytes()
        with self._lock:
            self.live = live
            self.limit = limit
            if live > self.peak:
                self.peak = live
            return self.live

    def fraction(self) -> Optional[float]:
        with self._lock:
            if self.limit <= 0:
                return None
            return self.live / self.limit


_watermark: Optional[MemoryWatermark] = None
_watermark_lock = threading.Lock()


def watermark() -> MemoryWatermark:
    global _watermark
    if _watermark is None:
        with _watermark_lock:
            if _watermark is None:
                _watermark = MemoryWatermark()
    return _watermark


def sample_memory(min_interval_s: float = 0.25) -> int:
    """Seam hook: refresh the process watermark (throttled). The scan /
    fit / batch seams call this at their allocation peaks; no-op-cheap
    when accounting is off."""
    if not accounting_enabled():
        return 0
    return watermark().sample(min_interval_s)


def install_memory_gauges(metrics) -> None:
    """Register the device-memory gauges on a registry with their honest
    merge modes: ``device_mem_bytes`` sums across workers,
    ``device_mem_peak_bytes`` is a max-watermark, ``device_mem_fraction``
    averages (None until the backend reports a byte limit)."""
    if not accounting_enabled():
        return
    wm = watermark()
    metrics.set_gauge(
        "device_mem_bytes", lambda: wm.sample(0.05), merge="sum"
    )
    metrics.set_gauge("device_mem_peak_bytes", lambda: wm.peak, merge="max")
    metrics.set_gauge("device_mem_fraction", wm.fraction, merge="mean")


__all__ = [
    "MemoryWatermark",
    "accounting_enabled",
    "device_memory_bytes",
    "install_memory_gauges",
    "payload_nbytes",
    "reset",
    "sample_memory",
    "split_batch_cost",
    "watermark",
]
