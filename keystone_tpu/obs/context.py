"""Cross-process trace propagation: the context one request carries.

The tracer (``obs/tracer.py``) gives each PROCESS a span registry; this
module is what lets ONE request keep its identity while it crosses the
process tier — ClusterRouter → wire → worker → replica. A
:class:`TraceContext` is deliberately tiny (an id, the emitting hop, a
wall-clock send stamp) because it rides on every ``req`` wire frame of a
sampled request:

* ``trace_id`` — globally unique per admitted request, namespaced by the
  ORIGINATING process's pid (``"<pid-hex>-<seq-hex>"``) so two routers
  sharing a machine can never mint colliding ids, and so the stitched
  export can merge span sets from N processes without id collisions.
* ``hop`` — the name of the span that emitted the context (the parent
  hop), so a receiver can attribute its own spans under the right edge.
* ``sent_unix`` — ``time.time()`` at send. Monotonic clocks are
  process-local and useless on the wire; the unix clock is shared by
  every process on the host, so the receiver computes the TRANSPORT
  component of latency as ``time.time() - sent_unix`` — real queueing in
  the kernel socket buffers plus scheduler delay, attributed to the hop
  it belongs to instead of smeared into worker-side compute.

Sampling (the overhead contract): ``KEYSTONE_TRACE_SAMPLE`` is the
per-request trace sampling rate (default 1.0 — every request of a traced
run). Production deployments that leave tracing always-on cap its cost by
sampling down: at rate r the per-request cost is r × (a handful of span
dataclasses + one extra dict on the wire frame) and exactly 0 for
unsampled requests (one modulo check at admission). The FLIGHT RECORDER
(``obs/flight.py``) deliberately ignores sampling — its ring records
every request's summary regardless, so post-mortems never depend on a
sampling coin flip.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Optional


def sample_rate() -> float:
    """The ``KEYSTONE_TRACE_SAMPLE`` per-request trace sampling rate,
    clamped to [0, 1] (default 1.0: trace every request)."""
    from ..utils import env_float

    return min(1.0, env_float("KEYSTONE_TRACE_SAMPLE", 1.0, minimum=0.0))


class Sampler:
    """Deterministic every-Nth request sampling at ``rate``: request k is
    sampled iff ``k % round(1/rate) == 0``. Deterministic on purpose —
    a bench comparing traced vs untraced runs must sample the SAME
    request positions both times, and a test asserting "rate 0.25 traces
    1 in 4" must not flap on an RNG. Not thread-safe by design: callers
    draw under their admission lock (the router does)."""

    def __init__(self, rate: Optional[float] = None):
        self.rate = sample_rate() if rate is None else float(rate)
        self._every = (
            0 if self.rate <= 0.0 else max(1, int(round(1.0 / self.rate)))
        )
        self._seq = 0

    def admit(self) -> bool:
        """One per-request decision (count + verdict)."""
        if not self._every:
            return False
        k = self._seq
        self._seq += 1
        return k % self._every == 0


@dataclass
class TraceContext:
    """One request's cross-process identity (see module docstring)."""

    trace_id: str
    hop: Optional[str] = None
    sent_unix: Optional[float] = None

    def to_wire(self) -> dict:
        """The wire form, stamped with the send time NOW — serialize is
        part of the hop, so the stamp happens as late as possible."""
        return {
            "id": self.trace_id,
            "hop": self.hop,
            "sent_unix": time.time(),
        }

    @staticmethod
    def from_wire(enc: Optional[dict]) -> Optional["TraceContext"]:
        if not enc or not enc.get("id"):
            return None
        return TraceContext(
            trace_id=str(enc["id"]),
            hop=enc.get("hop"),
            sent_unix=enc.get("sent_unix"),
        )

    def transport_seconds(self) -> Optional[float]:
        """Wire transport + receiver wakeup since the sender stamped this
        context (clamped at 0: the unix clock can step backwards under
        NTP, and a negative transport would corrupt hop sums)."""
        if self.sent_unix is None:
            return None
        return max(0.0, time.time() - float(self.sent_unix))


def new_trace_id(seq: int) -> str:
    """A process-namespaced trace id (pid-hex + sequence-hex)."""
    return f"{os.getpid():x}-{seq:x}"
