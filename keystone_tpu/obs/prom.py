"""Prometheus text exposition over a metrics snapshot + a scrape server.

:func:`render_prometheus` turns one ``MetricsRegistry`` snapshot (a
single process's, or the router's merged fleet view) into Prometheus
text exposition format 0.0.4: counters become ``<prefix>_<name>_total``
counter families, dotted per-identity counters (``tenant.served.<t>``,
``slo_breach.<objective>``, ``shed.<priority>``) become ONE family each
with the identity as a label, the per-tenant cost table lands as four
labeled counter families (device-seconds, queue-seconds, payload bytes,
items), gauges render with their live values, and the latency /
queue-age reservoirs render summary-style with ``quantile`` labels plus
``_count``/``_sum``.

:class:`PrometheusExporter` is the bounded scrape plane: one stdlib
``ThreadingHTTPServer`` (daemon threads, loopback-bound by default)
serving ``GET /metrics`` from a snapshot callback — the router hangs it
off its already-computed merged snapshot, so a scrape costs one stats
round-trip and never touches the serving hot path. Enable on
``ClusterRouter`` with ``metrics_port=`` or ``KEYSTONE_METRICS_PORT``
(0 picks an ephemeral port).
"""

from __future__ import annotations

import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: scrape content type for text exposition format 0.0.4
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: counter families the exposition documents with ``# HELP`` lines.
#: Every name here must be incremented somewhere under ``keystone_tpu/``
#: — ``tools/lint_invariants.py`` rule 5 enforces it (a trailing ``.``
#: marks a dotted per-identity family, matched as an f-string prefix).
KNOWN_COUNTERS = (
    "submitted",
    "completed",
    "rejected",
    "expired",
    "cancelled",
    "invalid",
    "shed",
    "shed.",
    "batches",
    "batch_errors",
    "batch_retries",
    "batch_transient",
    "requeues",
    "steals",
    "restarts",
    "quarantined",
    "compiles",
    "aot_loads",
    "slo_breaches",
    "slo_breach.",
    "tenant.served.",
    "scale_ups",
    "scale_downs",
    "scale_aborts",
    "worker_errors",
    "swaps",
    "rollbacks",
    "canary_pass",
    "canary_fail",
    "trainer_restarts",
    "trainer_crashes",
    "wire.frames.",
    "wire.bytes_sent.",
    "coalesce.frames",
    "coalesce.members",
    "shm.payloads",
    "shm.bytes",
    "shm.fallback",
)

_HELP = {
    "submitted": "requests admitted by a serving front door",
    "completed": "requests answered",
    "shed": "requests refused by deadline/queue admission",
    "batches": "compiled micro-batches executed",
    "restarts": "supervised replica/worker restarts",
    "slo_breaches": "SLO objectives breached across all policies",
    "compiles": "cold pipeline traces paid",
    "aot_loads": "warm executable loads from the AOT cache",
    "coalesce.frames": "coalesced multi-member wire frames sent",
    "coalesce.members": "requests that rode a coalesced frame",
    "shm.payloads": "wire payloads moved through shared-memory slots",
    "shm.bytes": "payload bytes moved through shared-memory slots",
    "shm.fallback": "payloads degraded inline (ring full/too large)",
}

#: dotted counter prefix -> (family suffix, label name)
_LABELED_FAMILIES = (
    ("tenant.served.", "tenant_served", "tenant"),
    ("slo_breach.", "slo_breach", "objective"),
    ("shed.", "shed_by_priority", "priority"),
    ("wire.frames.", "wire_frames", "kind"),
    ("wire.bytes_sent.", "wire_bytes_sent", "kind"),
)

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str) -> str:
    """Fold an internal metric name onto the Prometheus charset
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (dots and dashes become ``_``; a
    leading digit gains a ``_`` prefix)."""
    out = _NAME_BAD_CHARS.sub("_", str(name))
    if not out or not _NAME_OK.match(out):
        out = "_" + out
    return out


def escape_label_value(value: object) -> str:
    """Escape per exposition rules: backslash, double-quote, newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value: float) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Family:
    """One metric family: a # TYPE line plus its sample lines."""

    def __init__(self, name: str, kind: str, help_text: Optional[str] = None):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.samples: List[str] = []

    def add(
        self,
        value: float,
        labels: Optional[Dict[str, object]] = None,
        suffix: str = "",
    ) -> None:
        label_str = ""
        if labels:
            parts = ",".join(
                f'{sanitize_metric_name(k)}="{escape_label_value(v)}"'
                for k, v in labels.items()
            )
            label_str = "{" + parts + "}"
        self.samples.append(f"{self.name}{suffix}{label_str} {_fmt(value)}")

    def render(self) -> List[str]:
        if not self.samples:
            return []
        out = []
        if self.help:
            out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        out.extend(self.samples)
        return out


def _counter_family(
    families: Dict[str, _Family], prefix: str, raw_name: str
) -> Tuple[_Family, Optional[Dict[str, object]]]:
    """Resolve one internal counter name to (family, labels)."""
    for dot_prefix, suffix, label in _LABELED_FAMILIES:
        if raw_name.startswith(dot_prefix) and len(raw_name) > len(dot_prefix):
            fam_name = f"{prefix}_{suffix}_total"
            fam = families.get(fam_name)
            if fam is None:
                fam = families[fam_name] = _Family(fam_name, "counter")
            return fam, {label: raw_name[len(dot_prefix):]}
    fam_name = f"{prefix}_{sanitize_metric_name(raw_name)}_total"
    fam = families.get(fam_name)
    if fam is None:
        fam = families[fam_name] = _Family(
            fam_name, "counter", _HELP.get(raw_name)
        )
    return fam, None


def _summary(
    prefix: str, name: str, quantiles: Dict[str, float],
    labels: Optional[Dict[str, object]] = None,
) -> _Family:
    fam = _Family(f"{prefix}_{name}", "summary")
    count = int(quantiles.get("count") or 0)
    for key, q in (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99")):
        if key in quantiles:
            fam.add(quantiles[key], dict(labels or {}, quantile=q))
    fam.add(count, labels, suffix="_count")
    mean = quantiles.get("mean")
    if mean is not None:
        fam.add(float(mean) * count, labels, suffix="_sum")
    return fam


def render_prometheus(snapshot: Dict[str, object], prefix: str = "keystone") -> str:
    """Render one snapshot (plain or merged) as exposition text."""
    prefix = sanitize_metric_name(prefix)
    families: Dict[str, _Family] = {}
    lines: List[str] = []

    for raw_name in sorted(snapshot.get("counters") or {}):
        value = snapshot["counters"][raw_name]
        if not isinstance(value, (int, float)):
            continue
        fam, labels = _counter_family(families, prefix, raw_name)
        fam.add(value, labels)

    for tenant, prios in sorted((snapshot.get("costs") or {}).items()):
        for priority, row in sorted(prios.items()):
            labels = {"tenant": tenant, "priority": priority}
            for field, suffix in (
                ("device_s", "tenant_device_seconds"),
                ("queue_s", "tenant_queue_seconds"),
                ("payload_bytes", "tenant_payload_bytes"),
                ("items", "tenant_items"),
            ):
                fam_name = f"{prefix}_{suffix}_total"
                fam = families.get(fam_name)
                if fam is None:
                    fam = families[fam_name] = _Family(fam_name, "counter")
                fam.add(float(row.get(field) or 0.0), labels)

    for raw_name in sorted(snapshot.get("gauges") or {}):
        value = snapshot["gauges"][raw_name]
        if not isinstance(value, (int, float)):
            continue
        fam_name = f"{prefix}_{sanitize_metric_name(raw_name)}"
        fam = families.setdefault(fam_name, _Family(fam_name, "gauge"))
        fam.add(value)

    occ = (snapshot.get("batch_occupancy") or {}).get("ratio")
    if isinstance(occ, (int, float)):
        fam_name = f"{prefix}_batch_occupancy_ratio"
        fam = families.setdefault(fam_name, _Family(fam_name, "gauge"))
        fam.add(occ)

    wm = snapshot.get("merged_from")
    if isinstance(wm, int):
        fam_name = f"{prefix}_merged_processes"
        fam = families.setdefault(fam_name, _Family(fam_name, "gauge"))
        fam.add(wm)

    for fam in families.values():
        lines.extend(fam.render())
    lat = snapshot.get("latency") or {}
    if lat.get("count"):
        lines.extend(_summary(prefix, "latency_seconds", lat).render())
    age = snapshot.get("queue_age") or {}
    if age.get("count"):
        lines.extend(_summary(prefix, "queue_age_seconds", age).render())
    prio = snapshot.get("priority_latency") or {}
    prio_fam = _Family(f"{prefix}_priority_latency_seconds", "summary")
    for pclass, quantiles in sorted(prio.items()):
        if not quantiles.get("count"):
            continue
        sub = _summary(
            prefix, "priority_latency_seconds", quantiles,
            labels={"priority": pclass},
        )
        prio_fam.samples.extend(sub.samples)
    lines.extend(prio_fam.render())
    return "\n".join(lines) + "\n"


class PrometheusExporter:
    """Bounded stdlib scrape server: ``GET /metrics`` renders the
    snapshot callback. Daemon threads, loopback by default, stopped with
    :meth:`stop` (the router's shutdown path)."""

    def __init__(
        self,
        snapshot_fn: Callable[[], Dict[str, object]],
        host: str = "127.0.0.1",
        port: int = 0,
        prefix: str = "keystone",
    ):
        self._snapshot_fn = snapshot_fn
        self._host = host
        self._port = int(port)
        self._prefix = prefix
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        if self._server is None:
            return None
        return self._server.server_address[:2]

    def start(self) -> Tuple[str, int]:
        if self._server is not None:
            return self.address
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler API)
                if self.path.split("?", 1)[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = render_prometheus(
                        exporter._snapshot_fn(), prefix=exporter._prefix
                    ).encode("utf-8")
                except Exception:
                    logger.warning("scrape render failed", exc_info=True)
                    self.send_error(500, "snapshot failed")
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                logger.debug("scrape: " + fmt, *args)

        server = ThreadingHTTPServer((self._host, self._port), _Handler)
        server.daemon_threads = True
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever,
            kwargs={"poll_interval": 0.25},
            name="keystone-metrics-http",
            daemon=True,
        )
        self._thread.start()
        logger.info(
            "metrics exposition on http://%s:%d/metrics", *self.address
        )
        return self.address

    def stop(self) -> None:
        server, thread = self._server, self._thread
        self._server = self._thread = None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)


__all__ = [
    "CONTENT_TYPE",
    "KNOWN_COUNTERS",
    "PrometheusExporter",
    "escape_label_value",
    "render_prometheus",
    "sanitize_metric_name",
]
