"""Append-only NDJSON ledgers: durable resource-accounting evidence.

Two consumers share one primitive here. :class:`NdjsonSink` is a locked,
append-only JSON-lines file that NEVER raises into its caller — ledger
writes ride hot paths (AOT load, batch completion, SLO breach emission)
and evidence collection must not be able to fail a request. On top of it:

* :class:`CompileLedger` — the persistent compile ledger living next to
  the AOT executable cache (``<cache root>/compile-ledger.ndjson``).
  Every trace, export, load, cache hit/store/evict lands as one line
  with duration, signature, and byte size: the residency-budget evidence
  the multi-model ROADMAP item prices evict-and-reload decisions with,
  and the proof a warm boot paid loads instead of traces.
* the process **events sink** — ``KEYSTONE_EVENTS=/path/events.ndjson``
  turns every flight-recorder instant (replica restarts, SLO breaches,
  autoscale decisions, trainer promotions) into a structured NDJSON
  event stream an external collector can tail, instead of evidence that
  only surfaces when a flight ring dumps.

Both file formats are one JSON object per line, each carrying ``ts``
(unix seconds), ``pid``, and an ``event`` discriminator; readers use
:func:`read_ndjson`, which skips torn/partial trailing lines so a tail
mid-append still parses.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, Iterable, List, Optional

from ..utils import env_str

logger = logging.getLogger(__name__)

#: filename of the compile ledger inside an AOT cache root
COMPILE_LEDGER_NAME = "compile-ledger.ndjson"


class NdjsonSink:
    """Locked append-only JSON-lines writer that never raises.

    One line per :meth:`append` call, written with a single ``write`` on
    an ``O_APPEND`` stream so concurrent processes sharing the path
    interleave whole lines, not bytes. The first failed append logs a
    WARNING and disables the sink (subsequent appends are no-ops) — a
    full disk must not turn into a per-batch log storm."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._dead = False

    def append(self, record: Dict[str, object]) -> bool:
        """Serialize ``record`` as one NDJSON line; True when written."""
        try:
            line = json.dumps(record, default=str, separators=(",", ":"))
        except Exception:
            logger.warning(
                "ndjson sink %s: unserializable record dropped", self.path,
                exc_info=True,
            )
            return False
        with self._lock:
            if self._dead:
                return False
            try:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                return True
            except OSError:
                self._dead = True
                logger.warning(
                    "ndjson sink %s: append failed; sink disabled",
                    self.path, exc_info=True,
                )
                return False


def read_ndjson(path: str) -> List[Dict[str, object]]:
    """Parse an NDJSON file into dict rows, skipping torn lines (a
    reader may race an in-flight append). Missing file reads as []."""
    rows: List[Dict[str, object]] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        return []
    return rows


# one sink per path process-wide, so the AOT dispatcher and the cache
# layer appending to the same ledger share one lock (and one dead-flag)
_sinks: Dict[str, NdjsonSink] = {}
_sinks_lock = threading.Lock()


def sink_for(path: str) -> NdjsonSink:
    path = os.path.abspath(str(path))
    with _sinks_lock:
        sink = _sinks.get(path)
        if sink is None:
            sink = _sinks[path] = NdjsonSink(path)
        return sink


class CompileLedger:
    """The compile/load ledger next to one AOT executable cache.

    Events (the ``event`` field): ``trace`` (a cold pipeline trace, with
    ``seconds`` of tracing/lowering time), ``export`` (the serialized
    artifact stored, with ``nbytes``), ``load`` (a warm-boot
    deserialization, with ``seconds`` paid and ``saved_s`` — the trace
    time the hit avoided), ``hit``/``store``/``evict`` (cache-layer
    movements with entry sizes). Each line also carries ``key`` (cache
    entry key) and ``label``/``shape``/``dtype`` when the caller knows
    the signature."""

    def __init__(self, path: str):
        self._sink = sink_for(path)

    @property
    def path(self) -> str:
        return self._sink.path

    @classmethod
    def for_cache_root(cls, root: str) -> "CompileLedger":
        return cls(os.path.join(str(root), COMPILE_LEDGER_NAME))

    def record(self, event: str, **fields: object) -> bool:
        rec: Dict[str, object] = {
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "event": str(event),
        }
        for k, v in fields.items():
            if v is None:
                continue
            if isinstance(v, float):
                v = round(v, 6)
            rec[k] = v
        return self._sink.append(rec)

    def entries(
        self, event: Optional[str] = None
    ) -> List[Dict[str, object]]:
        rows = read_ndjson(self.path)
        if event is None:
            return rows
        return [r for r in rows if r.get("event") == event]


# -- the process events sink (KEYSTONE_EVENTS) --------------------------

_events_sink: Optional[NdjsonSink] = None
_events_resolved = False
_events_lock = threading.Lock()


def events_sink() -> Optional[NdjsonSink]:
    """The ``KEYSTONE_EVENTS`` sink, or None when the env is unset.
    Resolved once per process; :func:`reset_events` re-reads (tests)."""
    global _events_sink, _events_resolved
    if _events_resolved:
        return _events_sink
    with _events_lock:
        if not _events_resolved:
            path = env_str("KEYSTONE_EVENTS")
            _events_sink = sink_for(path) if path else None
            _events_resolved = True
    return _events_sink


def reset_events() -> None:
    global _events_sink, _events_resolved
    with _events_lock:
        _events_sink = None
        _events_resolved = False


def emit_event(kind: str, name: str, /, **attrs: object) -> bool:
    """Append one structured event (``{ts, pid, event: kind, name,
    attrs: {...}}``) to the ``KEYSTONE_EVENTS`` sink; False when no sink
    is configured or the write failed. Attrs nest under their own key so
    an instant's attributes can never shadow the envelope fields. Never
    raises."""
    sink = events_sink()
    if sink is None:
        return False
    rec: Dict[str, object] = {
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "event": str(kind),
        "name": str(name),
    }
    clean = {k: v for k, v in attrs.items() if v is not None}
    if clean:
        rec["attrs"] = clean
    return sink.append(rec)


__all__ = [
    "COMPILE_LEDGER_NAME",
    "CompileLedger",
    "NdjsonSink",
    "emit_event",
    "events_sink",
    "read_ndjson",
    "reset_events",
    "sink_for",
]
