"""The always-on flight recorder: a bounded ring of recent evidence.

The tracer (``obs/tracer.py``) is opt-in and unbounded — perfect for a
profiling run, useless for the failure that happens at 3am with tracing
off. The :class:`FlightRecorder` is the complement: ALWAYS on (no env
knob gates recording; ``KEYSTONE_TRACE_SAMPLE`` does not apply), a
fixed-size ring of recent span summaries and fault/trace instants whose
per-record cost is one small dict + one deque append under a lock, and an
atomic JSON dump fired by the supervision paths when something actually
goes wrong — so every chaos event leaves a post-mortem artifact holding
the last N things the process did before the event.

What lands in the ring:

* **span summaries** — one dict per completed unit of work the hot paths
  already account for: a replica micro-batch (``serve.replica``), a
  router request round-trip (``rpc.request``), a fleet swap, a trainer
  refit. NOT full spans: no tree, no sync targets — name, seconds, and
  the few attrs a post-mortem needs.
* **instants** — fault injections (``fault.inject``), supervision events
  (``fault.replica_down``, ``fault.worker_down``, restarts), trainer
  verdicts (``trainer.rollback``, ``trainer.park``), SLO breaches
  (``slo.breach``).

Dump triggers (wired into the supervisors, see the callers): replica
quarantine, worker death/respawn, canary rollback, trainer batch park,
and SIGQUIT (:func:`install_sigquit_dump`). Dumps are atomic (tmp file +
``os.replace``) into ``KEYSTONE_FLIGHT_DIR`` (default: the system temp
dir) and never raise into the supervision path that triggered them.

``SITE_INSTANTS`` is the observability contract the invariant lint
(``tools/lint_invariants.py`` rule 4) enforces: every fault site
registered in ``faults/plan.py`` must map here to the recovery instant
its handling path emits — adding a new chaos site without declaring (and
emitting) its post-mortem marker fails CI.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

#: fault site (faults/plan.py constant value) -> the recovery/handling
#: instant its supervision path emits. Sites may share an instant (the
#: scan retry discipline covers both scan seams). Lint rule 4 checks
#: (a) every registered site has an entry and (b) every named instant is
#: actually emitted somewhere under keystone_tpu/.
SITE_INSTANTS = {
    "scan.chunk": "retry.attempt",
    "scan.stage": "retry.attempt",
    "replica.batch": "fault.replica_down",
    "aot.read": "aot.read_degraded",
    "worker.spawn": "fault.worker_restart",
    "trainer.ingest": "trainer.ingest_fault",
    "trainer.absorb": "trainer.park",
    "trainer.canary": "trainer.rollback",
    # both scale seams recover the same way: the autoscaler reaps the
    # half-born (or half-drained) slot and records the abort
    "scale.spawn": "scale.abort",
    "scale.drain": "scale.abort",
}

#: ring capacity default; KEYSTONE_FLIGHT_RING overrides at first use
_DEFAULT_RING = 512


def _ring_size() -> int:
    from ..utils import env_int

    return env_int("KEYSTONE_FLIGHT_RING", _DEFAULT_RING)


def _dump_dir() -> str:
    from ..utils import env_str

    return env_str("KEYSTONE_FLIGHT_DIR") or tempfile.gettempdir()


def _dump_keep() -> int:
    from ..utils import env_int

    return env_int("KEYSTONE_FLIGHT_KEEP", 32)


def _prune_dumps(dump_dir: str, keep: int) -> int:
    """Bounded retention for auto-named dumps: keep the newest ``keep``
    ``keystone-flight-*.json`` files in ``dump_dir``, deleting
    oldest-first (by mtime). Chaos benches dump on every kill; an
    unbounded KEYSTONE_FLIGHT_DIR fills with hundreds of rings nobody
    will read. Best-effort: a file another process already reaped (or a
    permission surprise) is skipped, never raised. Returns the number
    deleted."""
    try:
        names = [
            n
            for n in os.listdir(dump_dir)
            if n.startswith("keystone-flight-") and n.endswith(".json")
        ]
    except OSError:
        logger.debug("flight retention: cannot list %s", dump_dir,
                     exc_info=True)
        return 0
    if len(names) <= keep:
        return 0
    stamped = []
    for n in names:
        full = os.path.join(dump_dir, n)
        try:
            stamped.append((os.path.getmtime(full), full))
        except OSError:
            continue  # raced another pruner; already gone
    stamped.sort()
    deleted = 0
    for _, full in stamped[: max(0, len(stamped) - keep)]:
        try:
            os.unlink(full)
            deleted += 1
        except OSError:
            logger.debug("flight retention: unlink %s failed", full,
                         exc_info=True)
    return deleted


class FlightRecorder:
    """A lock-cheap bounded ring of span summaries + instants."""

    def __init__(self, ring: Optional[int] = None):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring or _ring_size())
        self._dumps = 0
        self._dropped = 0  # records displaced by the bound (ring churn)

    # -- writes ----------------------------------------------------------

    def record_span(self, name: str, seconds: float, **attrs) -> None:
        """One completed-work summary. ``attrs`` must be JSON-scalar-ish
        (the dump stringifies anything that is not)."""
        entry = {
            "t": time.time(),
            "kind": "span",
            "name": name,
            "seconds": round(float(seconds), 6),
        }
        if attrs:
            entry["attrs"] = attrs
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(entry)

    def record_instant(self, name: str, **attrs) -> None:
        entry = {"t": time.time(), "kind": "instant", "name": name}
        if attrs:
            entry["attrs"] = attrs
        with self._lock:
            if len(self._ring) == self._ring.maxlen:
                self._dropped += 1
            self._ring.append(entry)

    # -- reads -----------------------------------------------------------

    def entries(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- the dump --------------------------------------------------------

    def dump(
        self, trigger: str, path: Optional[str] = None
    ) -> Optional[str]:
        """Write the ring atomically as JSON; returns the path, or None
        on failure (logged — a post-mortem write must never take down
        the supervision path that triggered it).

        Signal-safe enough for the SIGQUIT handler: the ring lock is
        taken with a timeout because the handler may interrupt the MAIN
        thread inside a record_* call already holding it — blocking
        there would wedge the process the dump exists to explain. An
        unlocked read of the deque is best-effort (a concurrent append
        can fault the copy; the dump then ships what it got)."""
        locked = self._lock.acquire(timeout=1.0)
        try:
            try:
                entries = list(self._ring)
            except RuntimeError:
                # lock-less fallback raced a writer mid-mutation
                entries = []
            self._dumps += 1
            seq = self._dumps
            dropped = self._dropped
        finally:
            if locked:
                self._lock.release()
        doc = {
            "producer": "keystone_tpu.obs.flight",
            "trigger": trigger,
            "pid": os.getpid(),
            "host_unix": time.time(),
            "ring_capacity": self._ring.maxlen,
            "dropped_before_window": dropped,
            "entries": entries,
        }
        auto_named = path is None
        if path is None:
            path = os.path.join(
                _dump_dir(),
                f"keystone-flight-{os.getpid()}-{trigger}-{seq}.json",
            )
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)  # atomic: readers never see a torn file
        except OSError:
            logger.warning(
                "flight recorder: dump to %s failed", path, exc_info=True
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass  # lint: allow-silent -- tmp may never have been created
            return None
        logger.warning(
            "flight recorder: %d entries -> %s (trigger: %s)",
            len(entries), path, trigger,
        )
        if auto_named:
            # retention applies only to the managed dump dir — an
            # explicit path= target is the caller's file to manage
            _prune_dumps(os.path.dirname(path), _dump_keep())
        return path


# -- process-global wiring ----------------------------------------------------

_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()
_sigquit_installed = False


def recorder() -> FlightRecorder:
    """THE process flight recorder (created on first use — recording is
    always on, so there is nothing to install)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
            rec = _recorder
    return rec


def reset() -> None:
    """Drop the process recorder (test hygiene: a fresh bounded window)."""
    global _recorder
    with _recorder_lock:
        _recorder = None


def record_span(name: str, seconds: float, **attrs) -> None:
    recorder().record_span(name, seconds, **attrs)


def record_instant(name: str, **attrs) -> None:
    recorder().record_instant(name, **attrs)
    # the structured-event sink rides the same call: every flight
    # instant (restarts, SLO breaches, autoscale decisions, rollbacks)
    # is exactly the event stream an external collector wants, and the
    # sink is a no-op unless KEYSTONE_EVENTS names a path
    from . import ledger

    ledger.emit_event("instant", name, **attrs)


def dump(trigger: str, path: Optional[str] = None) -> Optional[str]:
    return recorder().dump(trigger, path=path)


def install_sigquit_dump() -> bool:
    """SIGQUIT → flight dump (then the previous handler, so the default
    core-dump behavior is preserved). Returns False outside the main
    thread (signal registration is main-thread-only) or when already
    installed."""
    import signal

    global _sigquit_installed
    if _sigquit_installed:
        return False

    prev = None

    def _on_quit(signum, frame):
        # the dump is file IO — bounded, reentrancy-safe enough for a
        # handler that by definition fires when the operator asked for
        # evidence; the previous behavior still runs after
        dump("sigquit")
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # restore and re-raise so the DEFAULT terminate/core-dump
            # behavior is genuinely preserved (SIG_DFL is not callable —
            # returning here would swallow the operator's kill)
            signal.signal(signal.SIGQUIT, signal.SIG_DFL)
            os.kill(os.getpid(), signal.SIGQUIT)

    try:
        prev = signal.signal(signal.SIGQUIT, _on_quit)
    except ValueError:
        return False  # non-main thread (embedded use)
    _sigquit_installed = True
    return True
