"""``--serve-demo``: fit a small pipeline, push synthetic traffic through
the engine — or, with ``--replicas N``, through a continuous-batching
:class:`~keystone_tpu.serving.fleet.ServingFleet`, or, with
``--workers N`` (or ``KEYSTONE_WORKERS``), through the multi-process
:class:`~keystone_tpu.cluster.ClusterRouter` — print the metrics
snapshot. The smoke path behind ``bin/serve-smoke.sh`` and the CLI's
``--serve-demo`` flag.
"""

from __future__ import annotations

import argparse
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional

import numpy as np

from ..utils import env_int


def build_demo_fitted(
    num_ffts: int = 2,
    block_size: int = 512,
    lam: float = 100.0,
    n_train: int = 2048,
    n_test: int = 64,
):
    """The smoke serving pipeline: deterministic synthetic MNIST + random-FFT
    featurizer + block least squares + argmax. Deterministic end to end, so
    two processes building it get the SAME fitted parameters — and the same
    AOT fingerprint, which is what lets the cold-start bench's second
    process boot from the first one's exported executables. Returns
    ``(fitted, test_data)``."""
    import numpy as np

    from ..nodes.util import ClassLabelIndicators, MaxClassifier
    from ..nodes.learning.linear import BlockLeastSquaresEstimator
    from ..pipelines.mnist_random_fft import (
        NUM_CLASSES,
        MnistRandomFFTConfig,
        build_featurizer,
        synthetic_mnist_device,
    )

    conf = MnistRandomFFTConfig(
        num_ffts=num_ffts, block_size=block_size, lam=lam
    )
    train, test = synthetic_mnist_device(n_train=n_train, n_test=max(n_test, 64))
    labels = ClassLabelIndicators(NUM_CLASSES).apply_batch(train.labels)
    fitted = (
        build_featurizer(conf)
        .and_then(
            BlockLeastSquaresEstimator(conf.block_size, 1, conf.lam or 0.0),
            train.data, labels,
        )
        .and_then(MaxClassifier())
        .fit()
    )
    return fitted, np.asarray(test.data.to_array())


def _serve_through_cluster(args, fitted, data, buckets) -> int:
    """The ``--workers N`` path: a ClusterRouter over N worker processes,
    each rebuilding the SAME deterministic pipeline (same fingerprint ⇒
    warm boot from the shared AOT cache when one is configured) and
    serving it from a local fleet of ``--replicas`` replicas."""
    from .. import compile as compile_mod
    from ..cluster import ClusterRouter

    # --tenants "gold:3,bronze:1": weighted-fair shares in the worker
    # fleets, traffic round-robined across the named tenants so the
    # --status QoS section has shares to render
    tenant_weights = None
    if args.tenants:
        tenant_weights = {}
        for part in args.tenants.split(","):
            name, _, w = part.partition(":")
            tenant_weights[name.strip()] = float(w) if w else 1.0
    tenant_names = list(tenant_weights) if tenant_weights else None
    cache = compile_mod.get_cache()
    router = ClusterRouter(
        ("factory", "keystone_tpu.cluster.demo:build_demo_model", {
            "num_ffts": args.numFFTs, "block_size": args.blockSize,
            "lam": args.lam, "n_train": args.nTrain,
        }),
        workers=args.workers,
        replicas_per_worker=max(1, args.replicas),
        buckets=buckets,
        datum_shape=data.shape[1:],
        max_queue=args.maxQueue,
        max_wait_ms=args.maxWaitMs,
        aot_cache=cache.root if cache is not None else None,
        tenant_weights=tenant_weights,
    )
    router.install_signal_handlers()

    def _one(i_row):
        i, row = i_row
        tenant = (
            tenant_names[i % len(tenant_names)] if tenant_names else None
        )
        return router.submit(row, timeout=120.0, tenant=tenant).result()

    with router:
        with ThreadPoolExecutor(max_workers=args.clients) as pool:
            preds = list(pool.map(_one, enumerate(data)))
        snap = router.snapshot()
        reports = [r for r in router.worker_reports if r]
        if args.status:
            from ..cluster import format_status

            # the fleet-wide timeline view: per-process metrics
            # timelines, worker liveness/restart budgets, SLO verdicts
            # (reuses the snapshot above — one stats round-trip, not two)
            print(format_status(router.status(snap=snap)))
    expected = (
        np.asarray(fitted.apply(data).to_array())
        if len(data) else np.array([])
    )
    agree = int(np.sum(np.asarray(preds).ravel() == expected.ravel()))
    c = snap["counters"]
    lat = snap["latency"]
    compiles = sum(r.get("compiles", 0) for r in reports)
    aot_loads = sum(r.get("aot_loads", 0) for r in reports)
    worker_batches = {}
    for key, row in snap.get("replicas", {}).items():
        w = key.split("/")[0]
        worker_batches[w] = worker_batches.get(w, 0) + row.get("batches", 0)
    print(
        f"SERVE ok={agree}/{len(data)} compiles={compiles} "
        f"aot_loads={aot_loads} batches={c.get('batches', 0)} "
        f"completed={c.get('completed', 0)} "
        f"p50={lat.get('p50', 0):.4f}s p99={lat.get('p99', 0):.4f}s "
        f"workers={args.workers} shed={c.get('shed', 0)} "
        f"restarts={c.get('restarts', 0)} "
        f"per_worker_batches={worker_batches}"
    )
    ok = agree == len(data) and c.get("completed", 0) == len(data)
    if len(reports) < args.workers:
        print(f"SERVE FAIL: only {len(reports)}/{args.workers} workers ready")
        ok = False
    # the router must actually spread load: every worker PROCESS served
    # at least one micro-batch
    if len(worker_batches) < args.workers or any(
        b < 1 for b in worker_batches.values()
    ):
        print(f"SERVE FAIL: idle worker (batches {worker_batches})")
        ok = False
    if args.expect_zero_compiles and compiles != 0:
        print(
            f"SERVE FAIL: warm worker boots paid {compiles} trace(s), "
            "expected 0 (shared AOT cache + manifest)"
        )
        ok = False
    print("SERVE " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser("keystone-tpu serve-demo")
    p.add_argument("--numFFTs", type=int, default=2)
    p.add_argument("--blockSize", type=int, default=512)
    p.add_argument("--lambda", dest="lam", type=float, default=100.0)
    p.add_argument("--nTrain", type=int, default=2048)
    p.add_argument("--requests", type=int, default=64)
    p.add_argument(
        "--replicas", type=int, default=1,
        help="serve from a ServingFleet of N replica workers (continuous "
             "batching + work stealing) instead of the single-worker "
             "engine; default 1 = ServingEngine",
    )
    p.add_argument(
        "--workers", type=int,
        default=env_int("KEYSTONE_WORKERS", 0, minimum=0),
        help="serve from a multi-process ClusterRouter of N worker "
             "processes (each a local fleet of --replicas workers, "
             "sharing the AOT cache dir for warm boots); default 0 = "
             "in-process serving (also: KEYSTONE_WORKERS)",
    )
    p.add_argument("--buckets", default="8,32",
                   help="comma-separated static batch-size buckets")
    p.add_argument("--maxQueue", type=int, default=256)
    p.add_argument("--maxWaitMs", type=float, default=2.0)
    p.add_argument("--clients", type=int, default=8,
                   help="concurrent submitter threads")
    p.add_argument(
        "--status", action="store_true",
        help="with --workers N: print the fleet-wide status/timeline "
             "view (ClusterRouter.status() rendered — per-process "
             "metrics timelines, worker liveness, SLO verdicts) after "
             "the traffic drains",
    )
    p.add_argument(
        "--tenants", default=None,
        help="with --workers N: 'name:weight,...' — weighted-fair tenant "
             "shares in the worker fleets; demo traffic round-robins the "
             "names, and --status renders per-tenant served shares",
    )
    p.add_argument(
        "--expect-zero-compiles", action="store_true",
        dest="expect_zero_compiles",
        help="fail unless warm-up paid ZERO pipeline traces — the warm-"
             "boot assertion for a populated AOT cache (--aot-cache / "
             "KEYSTONE_AOT_CACHE): every bucket must load its executable",
    )
    args = p.parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    from .engine import ServingEngine
    from .fleet import ServingFleet

    fitted, test_data = build_demo_fitted(
        num_ffts=args.numFFTs, block_size=args.blockSize, lam=args.lam,
        n_train=args.nTrain, n_test=args.requests,
    )
    data = test_data[: args.requests]
    if args.workers > 0:
        return _serve_through_cluster(args, fitted, data, buckets)
    if args.replicas > 1:
        engine = ServingFleet(
            fitted,
            replicas=args.replicas,
            buckets=buckets,
            datum_shape=data.shape[1:],
            max_queue=args.maxQueue,
            max_wait_ms=args.maxWaitMs,
        )
    else:
        engine = ServingEngine(
            fitted,
            buckets=buckets,
            datum_shape=data.shape[1:],
            max_queue=args.maxQueue,
            max_wait_ms=args.maxWaitMs,
        )
    with engine:
        with ThreadPoolExecutor(max_workers=args.clients) as pool:
            preds = list(pool.map(lambda row: engine.predict(row, timeout=60.0), data))

    expected = np.asarray(fitted.apply(data).to_array()) if len(data) else np.array([])
    agree = int(np.sum(np.asarray(preds).ravel() == expected.ravel()))
    snap = engine.metrics.snapshot()
    c = snap["counters"]
    lat = snap["latency"]
    occ = snap["batch_occupancy"]["ratio"]
    compiles = c.get("compiles", 0)
    aot_loads = c.get("aot_loads", 0)
    per_replica = {
        idx: row["batches"] for idx, row in snap.get("replicas", {}).items()
    }
    print(
        f"SERVE ok={agree}/{len(data)} compiles={compiles} "
        f"aot_loads={aot_loads} "
        f"batches={c.get('batches', 0)} completed={c.get('completed', 0)} "
        f"occupancy={'n/a' if occ is None else format(occ, '.3f')} "
        f"p50={lat.get('p50', 0):.4f}s p99={lat.get('p99', 0):.4f}s"
        + (
            f" replicas={args.replicas} shed={c.get('shed', 0)} "
            f"steals={c.get('steals', 0)} per_replica_batches={per_replica}"
            if args.replicas > 1 else ""
        )
    )
    ok = agree == len(data) and c.get("completed", 0) == len(data)
    if args.replicas == 1:
        # every bucket's executable arrived exactly once — traced live or
        # loaded from the AOT cache (policy dedups bucket sizes, so
        # compare against what it kept)
        ok = ok and compiles + aot_loads == len(engine.policy.batch_sizes)
    else:
        # the fleet shares ONE dispatcher across replicas, so the
        # per-bucket identity is replica-count-independent — but manifest
        # pre-warm may ADD signatures beyond the buckets, hence >=
        ok = ok and compiles + aot_loads >= len(engine.policy.batch_sizes)
        # the continuous-batching fleet must actually spread load: every
        # replica worker executed at least one micro-batch (work stealing
        # makes this robust — an idle replica steals from a busy one)
        if len(per_replica) < args.replicas or any(
            b < 1 for b in per_replica.values()
        ):
            print(f"SERVE FAIL: idle replica (batches {per_replica})")
            ok = False
    if args.expect_zero_compiles and compiles != 0:
        print(f"SERVE FAIL: warm boot paid {compiles} trace(s), expected 0")
        ok = False
    print("SERVE " + ("PASS" if ok else "FAIL"))
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
