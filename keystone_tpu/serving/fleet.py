"""The replicated serving fleet: N replicas, one admission surface.

``ServingEngine`` amortizes one compiled pipeline across concurrent
callers — but through ONE worker on ONE device. :class:`ServingFleet`
is the multi-device subsystem on top of the same parts: N
:class:`~.replica.Replica` workers (default one per mesh device,
device-pinned batches) drain a single
:class:`~.scheduler.FleetScheduler` that does continuous batching,
deadline-aware admission shedding (typed :class:`Shed`), and
work-stealing rebalance — see the scheduler module for those
disciplines. All replicas share ONE compiled executable per model
version (and one AOT cache directory under it), so the fleet pays each
bucket signature's trace exactly once no matter how many replicas serve
it; XLA specializes per device underneath without re-tracing.

``swap(fitted)`` is fleet-wide and zero-downtime: the replacement
compiles and pre-warms every bucket OFF the serving path, then replicas
flip one at a time — admission never pauses, every micro-batch runs
whole on exactly one executable, and no request is ever dropped. With
``canary_fraction > 0`` the swap first runs a **shadow/canary phase**:
a fraction of live batches is mirrored through the candidate (after the
live results are distributed, so mirroring never adds request latency),
outputs and latency are compared, and a mismatch auto-rolls-back by
raising :class:`CanaryMismatch` with the evidence — the old model keeps
serving, nothing was promoted.

``start()`` pre-warms every configured bucket AND every signature the
pipeline has ever exported per the AOT cache's bucket-signature manifest
(:mod:`keystone_tpu.compile.manifest`), so a fresh fleet against a warm
shared cache directory boots with zero traces and zero cold
first-requests.

**Replica supervision** (default on): every replica thread runs under a
supervisor. A worker that dies — an injected
:class:`~keystone_tpu.faults.ReplicaKilled`, a real crash — or that
trips the consecutive-batch-failure circuit breaker
(:class:`~.replica.ReplicaQuarantined`) has its queued AND in-flight
requests requeued to live peers with their original deadlines (a
request the learned service estimate says can no longer make it is
answered with the typed ``Shed``, never silently expired), and is
restarted up to a per-replica restart budget. ``restarts``,
``requeues`` and ``quarantined`` land in the metrics;
``fault.replica_down`` / ``fault.replica_restart`` instants land in the
trace. Shutdown is bounded: a wedged replica is joined with a timeout,
logged at WARNING, and abandoned — its work is failed typed and the
final sweep still answers every admitted request.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Sequence

from ..autoscale.qos import DEFAULT_TENANT, normalize_priority
from ..faults import ReplicaKilled
from ..obs import flight as _flight
from ..obs import resource as _resource
from ..obs.tracer import current as _trace_current
from ..workflow.pipeline import FittedPipeline
from .batching import BucketPolicy
from .errors import CanaryMismatch, EngineStopped
from .metrics import MetricsRegistry
from .replica import (
    Replica,
    ReplicaQuarantined,
    _Request,
    check_swap_contract,
    compile_pipeline,
    serving_contract,
    settle_future,
)
from .scheduler import FleetScheduler

logger = logging.getLogger(__name__)

#: manifest entries above this many elements are not pre-warmed (a
#: foreign process may have exported a full-dataset apply shape; warming
#: it would allocate that much zeros on every boot)
_MAX_WARM_ELEMENTS = 1 << 24

#: shutdown never blocks forever on a wedged replica: seconds to wait
#: for the drain to go idle, and per-thread join budget after stop —
#: a thread that misses either is logged at WARNING and abandoned
#: (daemon), and its remaining work is failed typed
_DRAIN_TIMEOUT_S = 60.0
_JOIN_TIMEOUT_S = 10.0


class ServingFleet:
    """Serves a :class:`FittedPipeline` from N replica workers behind one
    deadline-aware admission queue.

    Parameters mirror :class:`~.engine.ServingEngine` where they overlap;
    the new ones:

    replicas:
        Worker count. None (default) = one per data-axis device of the
        active mesh. More replicas than devices is allowed (co-resident
        workers overlap host-side work on shared devices).
    devices:
        Explicit replica→device placement; default
        :func:`keystone_tpu.parallel.placement.replica_devices`.
    steal:
        Work-stealing rebalance between per-replica queues (on by
        default; off pins every request to its admitted queue).
    supervise:
        Replica supervision (on by default): a replica whose thread dies
        — or trips the ``quarantine_after`` consecutive-batch-failure
        circuit breaker — has its queued and in-flight requests requeued
        to peers WITH DEADLINES INTACT (unmeetable ones get the typed
        ``Shed``) and is restarted up to ``max_restarts`` times, counted
        in the ``restarts``/``requeues``/``quarantined`` metrics and
        ``fault.*`` trace instants. ``supervise=False`` still requeues a
        dead replica's work (nothing is ever silently stranded) but
        never restarts it.
    """

    def __init__(
        self,
        fitted: FittedPipeline,
        *,
        replicas: Optional[int] = None,
        buckets: Sequence[int] = (1, 8, 32, 64),
        datum_shape: Optional[Sequence[int]] = None,
        dtype: Any = None,
        max_queue: int = 1024,
        max_wait_ms: float = 2.0,
        metrics: Optional[MetricsRegistry] = None,
        log_interval_s: float = 10.0,
        devices: Optional[Sequence[Any]] = None,
        steal: bool = True,
        supervise: bool = True,
        max_restarts: int = 2,
        quarantine_after: int = 3,
        join_timeout_s: float = _JOIN_TIMEOUT_S,
        drain_timeout_s: float = _DRAIN_TIMEOUT_S,
        tenant_weights: Optional[Dict[str, float]] = None,
    ):
        from ..parallel.placement import replica_devices

        self._fitted = fitted
        datum_shape, dtype = serving_contract(fitted, datum_shape, dtype)
        self._policy = BucketPolicy(buckets, datum_shape, dtype)
        self._metrics = metrics or MetricsRegistry(name="serving-fleet")
        if devices is None:
            devices = replica_devices(replicas)
        elif replicas is not None and len(devices) != replicas:
            raise ValueError(
                f"devices list ({len(devices)}) does not match replicas="
                f"{replicas}"
            )
        self._devices = list(devices)
        n = len(self._devices)
        self._compiled_signatures: list = []
        # ONE executable per model version, shared by every replica: the
        # fleet pays each bucket trace once; device pinning happens per
        # batch via device_put, XLA specializes per device underneath
        compiled = compile_pipeline(
            fitted,
            metrics=self._metrics,
            signatures=self._compiled_signatures,
            label="serving",
        )
        self._replicas = [
            Replica(
                compiled,
                self._policy,
                self._metrics,
                index=i,
                device=self._devices[i],
                span_name="serve.replica",
                log_interval_s=log_interval_s,
                # the breaker only makes sense with a supervisor to
                # catch it and restart the worker
                quarantine_after=quarantine_after if supervise else 0,
            )
            for i in range(n)
        ]
        # the PUBLISHED model: version/digest/executable every replica
        # must serve. A restarted replica is re-pinned to this — so a
        # canary window that outlives a replica restart can never leak
        # the candidate (or anything else) onto the fresh thread, and a
        # long rollout ends with zero version skew. Guarded by
        # _supervise_lock: the supervisor re-pins from the dying
        # replica's thread, which must not take the lifecycle lock.
        self._model_version = 1
        self._model_digest = getattr(compiled, "digest", None)
        self._published_exec = compiled
        for rep in self._replicas:
            rep.version = self._model_version
        self._scheduler = FleetScheduler(
            n,
            self._policy,
            self._metrics,
            max_queue=max_queue,
            max_wait_ms=max_wait_ms,
            steal=steal,
            tenant_weights=tenant_weights,
        )
        self._lifecycle_lock = threading.RLock()
        # serializes whole swaps (incl. the canary window, which runs
        # WITHOUT the lifecycle lock so shutdown is never blocked on a
        # quiet fleet's canary timeout)
        self._swap_lock = threading.Lock()
        # supervision state has its OWN lock: the supervisor runs in the
        # DYING replica's thread, which shutdown (holding the lifecycle
        # lock) may be joining — taking the lifecycle lock there would
        # deadlock the whole stop path
        self._supervise_lock = threading.Lock()
        self._supervise = bool(supervise)
        self._max_restarts = max_restarts if supervise else 0
        self._restart_counts = [0] * n
        self._join_timeout_s = float(join_timeout_s)
        self._drain_timeout_s = float(drain_timeout_s)
        self._threads: List[threading.Thread] = []
        self._closed = False
        self._ran = False
        self._metrics.set_gauge("queue_depth", lambda: self._scheduler.depth)
        # device-memory watermark gauges (live=sum, peak=max,
        # fraction=mean across merged worker snapshots); no-op when
        # KEYSTONE_ACCOUNTING is off
        _resource.install_memory_gauges(self._metrics)

    # -- introspection ---------------------------------------------------

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    @property
    def policy(self) -> BucketPolicy:
        return self._policy

    def qos_snapshot(self) -> Dict[str, object]:
        """Per-tenant queued depth/weight + queued-by-priority (see
        :meth:`FleetScheduler.qos_snapshot`)."""
        return self._scheduler.qos_snapshot()

    @property
    def scheduler(self) -> FleetScheduler:
        return self._scheduler

    @property
    def replicas(self) -> tuple:
        return tuple(self._replicas)

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    @property
    def compiled_signatures(self) -> list:
        """``(shape, dtype)`` of every trace the fleet paid, in compile
        order — len() equals the ``compiles`` counter."""
        return list(self._compiled_signatures)

    @property
    def fitted(self) -> FittedPipeline:
        """The currently-published model (the trainer daemon's absorb
        base — it moves only on a promoted swap)."""
        return self._fitted

    @property
    def model_version(self) -> int:
        """The published model version: 1 at boot, +1 per promoted swap."""
        with self._supervise_lock:
            return self._model_version

    def version_report(self) -> dict:
        """Per-replica version pinning state for long rollouts: the
        published ``version``/``digest`` plus what each replica is
        actually serving. ``skew`` is True when any replica disagrees
        with the published version — transiently possible only inside a
        promotion flip; a steady-state True means a pinning bug."""
        with self._supervise_lock:
            replicas = {
                rep.index: {
                    "version": rep.version,
                    "restarts": self._restart_counts[rep.index],
                }
                for rep in self._replicas
            }
            return {
                "version": self._model_version,
                "digest": self._model_digest,
                "replicas": replicas,
                "skew": any(
                    row["version"] != self._model_version
                    for row in replicas.values()
                ),
            }

    # -- lifecycle -------------------------------------------------------

    def warm_up(self, required: bool = True) -> int:
        """Pre-pay (or AOT-load) every bucket's executable on every
        replica device, plus every signature in the pipeline's AOT
        manifest — a fresh replica against a warm shared cache boots
        with zero traces AND zero cold first-requests. Returns distinct
        signatures warmed. ``required`` follows the engine's contract:
        True raises when no datum shape is known, False downgrades to a
        warning."""
        import numpy as np

        inputs = []
        if self._policy.datum_shape is None:
            if required:
                raise ValueError(
                    "warm-up requested but impossible: no datum shape is "
                    "known — pass datum_shape= to the fleet, or fit the "
                    "pipeline through and_then(estimator, data) so the "
                    "contract is recorded on the FittedPipeline"
                )
            logger.warning(
                "fleet warm-up skipped: no datum_shape configured — the "
                "first live batch of each bucket will pay its compile"
            )
        else:
            inputs = list(self._policy.warmup_inputs())
        seen = {(tuple(x.shape), str(x.dtype)) for x in inputs}
        for shape, dtype in self._manifest_signatures():
            if (shape, dtype) in seen:
                continue
            n_elem = 1
            for d in shape:
                n_elem *= max(int(d), 1)
            if n_elem > _MAX_WARM_ELEMENTS:
                logger.info(
                    "fleet warm-up: skipping oversized manifest signature "
                    "%s (%s elements)", shape, n_elem,
                )
                continue
            seen.add((shape, dtype))
            inputs.append(np.zeros(shape, dtype=dtype))
        self._warm_inputs(self._replicas[0].compiled, inputs)
        self._prewarm_segments()
        logger.info(
            "fleet warm-up: %d signature(s) ready across %d device(s) "
            "(%d traced, %d loaded from the AOT cache)",
            len(inputs), len(self._distinct_devices()),
            self._metrics.count("compiles"),
            self._metrics.count("aot_loads"),
        )
        return len(inputs)

    def _prewarm_segments(self) -> None:
        """Pre-warm every segment executable the AOT cache's segment
        manifest indexes (:mod:`keystone_tpu.compile.segment`) — so a
        warm FIT issued after this boot (a refit on the serving host, a
        cluster worker's local fit) loads whole-segment programs instead
        of tracing them. Best-effort: segment warm-up must never fail a
        fleet that serves fine without it."""
        from .. import compile as compile_mod

        cache = compile_mod.get_cache()
        if cache is None:
            return
        try:
            warmed = compile_mod.prewarm_segment_artifacts(cache)
            if warmed:
                logger.info(
                    "fleet warm-up: %d segment executable(s) pre-warmed",
                    warmed,
                )
        except Exception:
            logger.warning(
                "fleet warm-up: segment pre-warm failed — warm fits will "
                "load lazily", exc_info=True,
            )

    def _distinct_devices(self) -> list:
        seen, out = set(), []
        for d in self._devices:
            if id(d) not in seen:
                seen.add(id(d))
                out.append(d)
        return out

    def _warm_inputs(self, compiled, inputs) -> None:
        """Run each input through ``compiled`` once per DISTINCT replica
        device (co-resident replicas share executables, so warming per
        replica would re-pay per-device work for nothing)."""
        import jax

        for device in self._distinct_devices():
            for x in inputs:
                jax.block_until_ready(compiled(jax.device_put(x, device)))

    def _manifest_signatures(self) -> list:
        """Signatures the pipeline has ever exported (AOT manifest), or
        [] when no cache / no content-keyed dispatcher is active."""
        from .. import compile as compile_mod

        digest = getattr(self._replicas[0].compiled, "digest", None)
        cache = compile_mod.get_cache()
        if digest is None or cache is None:
            return []
        # the manifest records batch shapes; only warm entries matching
        # this fleet's per-item contract and dtype (a foreign config's
        # exports would trace programs this fleet can never serve). With
        # NO shape contract there is nothing to match against — warm
        # nothing rather than pay startup compiles for signatures the
        # first live request may immediately contradict.
        want = self._policy.datum_shape
        if want is None:
            return []
        sigs = compile_mod.exported_signatures(cache, digest)
        out = []
        for shape, dtype in sigs:
            if tuple(shape[1:]) != tuple(want):
                continue
            if str(dtype) != str(self._policy.dtype):
                continue
            out.append((shape, dtype))
        return out

    def start(self, warmup: Optional[bool] = None) -> "ServingFleet":
        """Warm per :meth:`warm_up` (same ``warmup`` semantics as the
        engine), then start every replica worker and begin admitting."""
        with self._lifecycle_lock:
            if self._threads:
                raise RuntimeError("fleet already started")
            if self._closed:
                raise EngineStopped("fleet was shut down")
            if warmup or warmup is None:
                self.warm_up(required=warmup is True)
            for rep in self._replicas:
                self._spawn_replica_thread(rep)
            self._ran = True
        return self

    def _spawn_replica_thread(self, rep: Replica) -> threading.Thread:
        attempt = self._restart_counts[rep.index]
        t = threading.Thread(
            target=self._run_replica,
            args=(rep,),
            name=(
                f"keystone-serving-replica-{rep.index}"
                + (f"-r{attempt}" if attempt else "")
            ),
            daemon=True,
        )
        with self._supervise_lock:
            self._threads.append(t)
        t.start()
        return t

    # -- replica supervision ---------------------------------------------

    def _run_replica(self, rep: Replica) -> None:
        """Every replica thread's real target: the loop plus the
        supervisor. A loop that exits with ANY ``BaseException`` — an
        injected :class:`ReplicaKilled`, the quarantine breaker, a truly
        unexpected death — is treated as a down worker: its queued and
        in-flight requests are requeued to peers (deadlines intact) and
        it restarts within the restart budget."""
        try:
            rep.serve_forever(self._scheduler)
        except BaseException as e:  # noqa: BLE001 — the supervision seam
            try:
                self._on_replica_down(rep, e)
            except Exception:
                logger.exception(
                    "fleet supervisor failed for replica %s", rep.index
                )

    def _on_replica_down(self, rep: Replica, exc: BaseException) -> None:
        pending = getattr(exc, "pending", None) or []
        quarantined = isinstance(exc, ReplicaQuarantined)
        killed = isinstance(exc, ReplicaKilled)
        kind = (
            "quarantined" if quarantined
            else "killed" if killed
            else "died"
        )
        with self._supervise_lock:
            used = self._restart_counts[rep.index]
            will_restart = (
                not self._closed and used < self._max_restarts
            )
            if quarantined:
                self._metrics.inc("quarantined")
            # a permanently-down replica stops receiving admissions; a
            # restarting one keeps its slot live (requeue then retries
            # locally when there is no peer — the 1-replica fleet)
            self._scheduler.set_active(rep.index, will_restart)
            moved = 0
            if pending:
                moved += self._scheduler.requeue_batch(
                    pending, rep,
                    cause=exc if isinstance(exc, Exception) else None,
                )
            moved += self._scheduler.requeue_replica(
                rep.index, keep_if_no_peer=will_restart
            )
            logger.warning(
                "fleet: replica %s %s (%s) — requeued %d request(s); "
                "restart %s (budget %d/%d used)",
                rep.index, kind, exc, moved,
                "scheduled" if will_restart else "refused",
                used, self._max_restarts,
            )
            tracer = _trace_current()
            if tracer is not None:
                tracer.instant(
                    "fault.replica_down", op_type="ServingFleet",
                    replica=rep.index, kind=kind, requeued=moved,
                    restarting=will_restart,
                )
            _flight.record_instant(
                "fault.replica_down", replica=rep.index, kind=kind,
                requeued=moved, restarting=will_restart,
            )
            if will_restart:
                self._restart_counts[rep.index] = used + 1
                self._metrics.inc("restarts")
                rep.consecutive_failures = 0
                # re-pin to the PUBLISHED model: a restart during a
                # canary window (or any long rollout) must come back on
                # the version the fleet is actually serving — promotion,
                # which flips every replica under this same lock, is the
                # only thing that moves it forward
                rep.flip(self._published_exec)
                rep.version = self._model_version
            elif not self._scheduler.any_active():
                failed = self._scheduler.fail_remaining(
                    "every replica is down and the restart budget is "
                    "exhausted"
                )
                if failed:
                    logger.warning(
                        "fleet: no live replicas remain — failed %d "
                        "queued request(s)", failed,
                    )
        # post-mortem artifacts, OUTSIDE the supervise lock (dumping is
        # file IO): quarantine always leaves one; so does a replica that
        # exhausted its restart budget (the fleet just lost capacity)
        if quarantined:
            _flight.dump("replica_quarantine")
        elif not will_restart:
            _flight.dump("replica_down")
        if will_restart:
            # spawn OUTSIDE the supervise lock (it re-takes it to
            # register the thread)
            self._spawn_replica_thread(rep)
            _flight.record_instant(
                "fault.replica_restart", replica=rep.index,
                attempt=used + 1,
            )
            tracer = _trace_current()
            if tracer is not None:
                tracer.instant(
                    "fault.replica_restart", op_type="ServingFleet",
                    replica=rep.index, attempt=used + 1,
                )

    def drain(self) -> None:
        """Stop admitting, answer every queued request, stop all workers."""
        self.shutdown(drain=True)

    def shutdown(self, drain: bool = True) -> None:
        """Stop the fleet. ``drain=True`` answers queued requests first;
        ``drain=False`` fails them with :class:`EngineStopped`.
        Idempotent and safe from multiple threads.

        Never blocks forever: the drain and every thread join are
        bounded (``drain_timeout_s`` / ``join_timeout_s``). A replica
        that wedges — a hung host callback, a stuck device — is logged
        at WARNING and abandoned (its thread is a daemon), its in-flight
        requests are failed typed, and the final ``fail_remaining``
        sweep still answers everything queued, so no admitted request is
        ever left without an answer."""
        with self._lifecycle_lock:
            self._closed = True
            self._scheduler.close()
            with self._supervise_lock:
                started = bool(self._threads)
            if not started:
                self._scheduler.fail_remaining(
                    "fleet is shut down" if self._ran else "fleet never started"
                )
                return
            if drain:
                if not self._scheduler.wait_idle(
                    timeout=self._drain_timeout_s
                ):
                    logger.warning(
                        "fleet shutdown: drain did not go idle within "
                        "%.1fs (wedged replica?) — failing the remaining "
                        "work instead of blocking forever",
                        self._drain_timeout_s,
                    )
            self._scheduler.stop()
            with self._supervise_lock:
                threads, self._threads = self._threads, []
            for t in threads:
                t.join(timeout=self._join_timeout_s)
                if t.is_alive():
                    logger.warning(
                        "fleet shutdown: thread %s did not exit within "
                        "%.1fs — abandoning it (daemon) and failing its "
                        "remaining work", t.name, self._join_timeout_s,
                    )
            # a wedged replica's in-flight batch would otherwise hang
            # its callers: answer those futures typed (a late real
            # result loses the set-once race harmlessly)
            for rep in self._replicas:
                batch = rep.current_batch
                if batch:
                    for r in batch:
                        settle_future(
                            r.future,
                            EngineStopped(
                                "fleet shut down while this request's "
                                "replica was wedged"
                            ),
                        )
            # admission-vs-close is atomic in the scheduler, so nothing
            # can land after this point; the sweep is the belt-and-braces
            # guarantee no admitted request is ever left unanswered
            self._scheduler.fail_remaining()

    def __enter__(self) -> "ServingFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission -------------------------------------------------------

    def submit(
        self,
        datum: Any,
        timeout: Optional[float] = None,
        trace: Any = None,
        priority: Optional[str] = None,
        tenant: Optional[str] = None,
    ) -> Future:
        """Enqueue one datum; returns a Future of its prediction row.

        ``timeout`` (seconds) is the request's deadline. Raises typed:
        :class:`QueueFull` at capacity, :class:`Shed` when the deadline
        cannot be met given the learned service time and queue depth,
        :class:`EngineStopped` after shutdown. ``trace`` is an optional
        :class:`~keystone_tpu.obs.context.TraceContext` — a sampled
        request's cross-process identity, carried so the replica's
        queue-wait and batch spans record under it (the cluster worker
        passes the context it received off the wire). ``priority``
        (``high``/``normal``/``low``, default normal) sets the shedding
        class; ``tenant`` names the weighted-fair share the request is
        served from (see :mod:`keystone_tpu.autoscale.qos`)."""
        now = time.monotonic()
        req = _Request(
            datum=datum,
            deadline=(now + timeout) if timeout is not None else None,
            enqueued=now,
            trace=trace,
            priority=normalize_priority(priority),
            tenant=str(tenant) if tenant else DEFAULT_TENANT,
        )
        self._scheduler.admit(req)  # counts "submitted" atomically
        return req.future

    def predict(self, datum: Any, timeout: Optional[float] = None) -> Any:
        """Synchronous convenience: submit + wait (see the engine's
        :meth:`~.engine.ServingEngine.predict` contract)."""
        if not self._threads:
            raise RuntimeError(
                "predict() needs a started fleet (call start() or use "
                "the context manager)"
            )
        return self.submit(datum, timeout=timeout).result()

    # -- fleet-wide zero-downtime swap -----------------------------------

    def swap(
        self,
        fitted: FittedPipeline,
        *,
        warmup: Optional[bool] = None,
        canary_fraction: float = 0.0,
        canary_batches: int = 4,
        canary_timeout_s: float = 30.0,
        atol: float = 1e-5,
        rtol: float = 1e-5,
        max_latency_ratio: Optional[float] = None,
    ) -> dict:
        """Replace the served model fleet-wide with zero downtime.

        The replacement compiles strictly and pre-warms every bucket on
        every replica device OFF the serving path; replicas then flip one
        at a time (each micro-batch runs whole on exactly one executable;
        admission never pauses; no request is dropped).

        With ``canary_fraction > 0``, a shadow phase first mirrors that
        fraction of live micro-batches through the candidate — AFTER each
        batch's live results are distributed, so mirroring adds zero
        request latency — and compares outputs (``atol``/``rtol``) and
        execution latency. Any output mismatch (or a latency ratio above
        ``max_latency_ratio``, when given) AUTO-ROLLS-BACK: the candidate
        is discarded, the old model keeps serving, and
        :class:`CanaryMismatch` carries the evidence. The phase ends
        after ``canary_batches`` mirrored batches or ``canary_timeout_s``
        seconds (a quiet fleet promotes on whatever evidence arrived —
        zero mirrored batches included; set a longer timeout to insist).

        Returns a report dict: replicas flipped, signatures warmed,
        compiles/aot_loads paid, and the canary verdict."""
        check_swap_contract(fitted, self._policy)
        with self._swap_lock:
            with self._lifecycle_lock:
                if self._closed:
                    raise EngineStopped("fleet is draining / shut down")
            # compile + warm-up + canary all run WITHOUT the lifecycle
            # lock: a swap that traces fresh buckets (tens of seconds on
            # a real chip) or waits out a quiet canary must never block a
            # concurrent shutdown. _swap_lock serializes competing swaps;
            # _promote re-checks closed, so a shutdown that slips in here
            # merely wastes the candidate's compile.
            compiles_before = self._metrics.count("compiles")
            loads_before = self._metrics.count("aot_loads")
            candidate = compile_pipeline(
                fitted,
                metrics=self._metrics,
                signatures=self._compiled_signatures,
                label="serving",
            )
            warmed = 0
            if (
                (warmup or warmup is None)
                and self._policy.datum_shape is not None
            ):
                inputs = list(self._policy.warmup_inputs())
                self._warm_inputs(candidate, inputs)
                warmed = len(inputs)
            elif warmup is True:
                raise ValueError(
                    "swap(warmup=True) but no datum shape is known — "
                    "the fleet cannot pre-pay the replacement's compiles"
                )

            # the canary window runs WITHOUT the lifecycle lock: waiting
            # (up to canary_timeout_s) for mirrored traffic must never
            # block a concurrent shutdown; _swap_lock still serializes
            # competing swaps end to end
            canary_report = None
            if canary_fraction > 0:
                canary_report = self._run_canary(
                    candidate,
                    fraction=canary_fraction,
                    target_batches=canary_batches,
                    timeout_s=canary_timeout_s,
                    atol=atol,
                    rtol=rtol,
                    max_latency_ratio=max_latency_ratio,
                )

            return self._promote(
                fitted, candidate, warmed, canary_report,
                compiles_before, loads_before,
            )

    def _promote(
        self, fitted, candidate, warmed, canary_report,
        compiles_before, loads_before,
    ) -> dict:
        with self._lifecycle_lock:
            if self._closed:
                raise EngineStopped(
                    "fleet shut down during the swap — nothing promoted"
                )
            # promotion: a rolling flip, one replica at a time. There is
            # no quiesce step and none is needed — run_batch reads the
            # executable reference ONCE per batch, so each in-flight
            # batch finishes whole on whichever executable it dispatched
            # with; the flip is one atomic store per replica. The
            # published version advances FIRST under the supervise lock,
            # so a replica restart racing the flip loop re-pins to the
            # candidate and the loop's own flip is then a no-op — either
            # order ends with every replica on the new version.
            with self._supervise_lock:
                self._model_version += 1
                self._model_digest = getattr(candidate, "digest", None)
                self._published_exec = candidate
                version = self._model_version
                for rep in self._replicas:
                    rep.flip(candidate)
                    rep.version = version
            self._fitted = fitted
            self._metrics.inc("swaps")
            report = {
                "replicas_flipped": len(self._replicas),
                "buckets_warmed": warmed,
                "compiles": self._metrics.count("compiles") - compiles_before,
                "aot_loads": self._metrics.count("aot_loads") - loads_before,
                "canary": canary_report,
                "version": version,
            }
            _flight.record_instant(
                "serve.swap", version=version,
                replicas=len(self._replicas), buckets_warmed=warmed,
            )
            tracer = _trace_current()
            if tracer is not None:
                with tracer.span(
                    "serve.swap",
                    op_type="ServingFleet",
                    replicas=len(self._replicas),
                    version=version,
                    buckets_warmed=warmed,
                    compiles=report["compiles"],
                    aot_loads=report["aot_loads"],
                    canary="pass" if canary_report else None,
                    queue_depth=self._scheduler.depth,
                    live=bool(self._threads),
                ):
                    pass
            logger.info(
                "fleet swap: model replaced on %d replica(s) (%d "
                "signature(s) warmed, %d traced, %d AOT-loaded%s)",
                len(self._replicas), warmed,
                report["compiles"], report["aot_loads"],
                (
                    f"; canary pass on {canary_report['batches_compared']} "
                    "mirrored batch(es)"
                    if canary_report else ""
                ),
            )
            return report

    def _run_canary(
        self,
        candidate,
        *,
        fraction: float,
        target_batches: int,
        timeout_s: float,
        atol: float,
        rtol: float,
        max_latency_ratio: Optional[float],
    ) -> dict:
        """Mirror live traffic through ``candidate``; raise
        :class:`CanaryMismatch` (auto-rollback) on any output mismatch or
        latency blow-up; return the pass report otherwise."""
        shadow = _Shadow(
            candidate,
            fraction=fraction,
            target_batches=target_batches,
            atol=atol,
            rtol=rtol,
        )
        for rep in self._replicas:
            rep.set_shadow(shadow.observe)
        try:
            # poll-wait so a fleet shutdown mid-canary ends the window
            # immediately instead of sitting out the full timeout
            deadline = time.monotonic() + timeout_s
            while not shadow.wait(0.2):
                if self._closed or time.monotonic() >= deadline:
                    break
        finally:
            for rep in self._replicas:
                rep.set_shadow(None)
        report = shadow.report()
        ratio = report.get("latency_ratio")
        too_slow = (
            max_latency_ratio is not None
            and ratio is not None
            and ratio > max_latency_ratio
        )
        if report["mismatches"] or too_slow:
            self._metrics.inc("canary_fail")
            why = (
                f"{report['mismatches']} mismatched batch(es) of "
                f"{report['batches_compared']} mirrored"
                if report["mismatches"]
                else f"candidate latency ratio {ratio:.2f} exceeds "
                     f"{max_latency_ratio}"
            )
            logger.warning("fleet canary FAILED — rolling back: %s", why)
            _flight.record_instant(
                "serve.canary_rollback",
                mismatches=report["mismatches"],
                batches_compared=report["batches_compared"],
                latency_ratio=ratio,
            )
            _flight.dump("canary_rollback")
            raise CanaryMismatch(
                f"canary auto-rollback: {why}; the fleet is still serving "
                "the previous model",
                report,
            )
        self._metrics.inc("canary_pass")
        return report


class _Shadow:
    """Mirrors sampled live batches through a candidate executable and
    accumulates the comparison evidence. Installed as every replica's
    shadow hook during a canaried swap; thread-safe (N replicas call
    ``observe`` concurrently)."""

    def __init__(
        self,
        candidate,
        *,
        fraction: float,
        target_batches: int,
        atol: float,
        rtol: float,
    ):
        self._candidate = candidate
        # deterministic sampling: every k-th completed batch mirrors
        self._every = max(1, int(round(1.0 / max(fraction, 1e-9))))
        self._target = max(1, int(target_batches))
        self._atol = atol
        self._rtol = rtol
        self._lock = threading.Lock()
        self._seen = 0
        self._compared = 0
        self._n_mismatch = 0  # full count; the detail list below is capped
        self._mismatches: list = []
        self._ratios: list = []
        self._done = threading.Event()

    def observe(self, replica, padded, primary_out, n_valid, bucket) -> None:
        import jax
        import numpy as np

        with self._lock:
            self._seen += 1
            if self._compared >= self._target:
                self._done.set()
                return
            if (self._seen - 1) % self._every:
                return
        t0 = time.perf_counter()
        try:
            cand = jax.device_get(self._candidate(padded))
        except Exception as e:
            # a candidate that cannot even run its bucket is the clearest
            # possible mismatch — count it, never break the live batch
            with self._lock:
                self._compared += 1
                self._n_mismatch += 1
                if len(self._mismatches) < 8:
                    self._mismatches.append(
                        {"replica": replica.index, "bucket": bucket,
                         "error": repr(e)[:200]}
                    )
                self._done.set()  # any mismatch decides the verdict
            return
        cand_s = time.perf_counter() - t0
        primary_leaves = jax.tree_util.tree_leaves(primary_out)
        cand_leaves = jax.tree_util.tree_leaves(cand)
        detail = None
        if len(primary_leaves) != len(cand_leaves):
            detail = {"structure": "output tree shape differs"}
        else:
            for a, b in zip(primary_leaves, cand_leaves):
                a, b = np.asarray(a)[:n_valid], np.asarray(b)[:n_valid]
                if a.shape != b.shape:
                    detail = {"shapes": [list(a.shape), list(b.shape)]}
                    break
                if not np.allclose(a, b, atol=self._atol, rtol=self._rtol):
                    diff = np.max(np.abs(
                        a.astype(np.float64) - b.astype(np.float64)
                    ))
                    detail = {"max_abs_diff": float(diff)}
                    break
        with self._lock:
            self._compared += 1
            if replica.last_exec_seconds:
                self._ratios.append(cand_s / replica.last_exec_seconds)
            if detail is not None:
                self._n_mismatch += 1
                if len(self._mismatches) < 8:
                    detail.update(
                        {"replica": replica.index, "bucket": bucket}
                    )
                    self._mismatches.append(detail)
            if detail is not None or self._compared >= self._target:
                # any mismatch decides the verdict — no need to keep
                # mirroring; the swap thread wakes and rolls back
                self._done.set()

    def wait(self, timeout_s: float) -> bool:
        return self._done.wait(timeout=timeout_s)

    def report(self) -> dict:
        import statistics

        with self._lock:
            return {
                "batches_compared": self._compared,
                "mismatches": self._n_mismatch,
                "mismatch_details": list(self._mismatches),
                "latency_ratio": (
                    round(statistics.median(self._ratios), 3)
                    if self._ratios else None
                ),
            }
