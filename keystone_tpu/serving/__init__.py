"""Online serving: micro-batched, shape-bucketed inference over compiled
pipelines.

The training side of the system already fuses a fitted pipeline into ONE
jitted XLA program (:meth:`FittedPipeline.compile`); this package is the
layer that amortizes that program across concurrent request traffic:

* :class:`ServingEngine` — bounded admission queue + worker loop that
  drains requests into micro-batches (max batch size, max-wait timeout),
  with per-request deadlines, backpressure, and per-request error
  isolation.
* :class:`BucketPolicy` — pads micro-batches to a small static set of
  bucket shapes so the compiled function traces once per bucket (XLA
  specializes per shape; without bucketing every new batch size pays a
  full recompile under live traffic).
* :class:`MetricsRegistry` — queue depth, batch occupancy, compile count,
  and p50/p95/p99 request latency, with a programmatic ``snapshot()`` and
  periodic INFO logging.
"""

from .batching import BucketPolicy
from .engine import ServingEngine
from .errors import (
    DeadlineExceeded,
    EngineClosed,
    InvalidRequest,
    QueueFull,
    ServingError,
)
from .metrics import MetricsRegistry

__all__ = [
    "ServingEngine",
    "BucketPolicy",
    "MetricsRegistry",
    "ServingError",
    "QueueFull",
    "DeadlineExceeded",
    "InvalidRequest",
    "EngineClosed",
]
