"""Online serving: micro-batched, shape-bucketed inference over compiled
pipelines.

The training side of the system already fuses a fitted pipeline into ONE
jitted XLA program (:meth:`FittedPipeline.compile`); this package is the
layer that amortizes that program across concurrent request traffic:

* :class:`ServingEngine` — bounded admission queue + worker loop that
  drains requests into micro-batches (max batch size, max-wait timeout),
  with per-request deadlines, backpressure, and per-request error
  isolation.
* :class:`BucketPolicy` — pads micro-batches to a small static set of
  bucket shapes so the compiled function traces once per bucket (XLA
  specializes per shape; without bucketing every new batch size pays a
  full recompile under live traffic).
* :class:`MetricsRegistry` — queue depth, batch occupancy (fleet-wide and
  per replica), compile count, p50/p95/p99 request latency and queue age,
  with a programmatic ``snapshot()`` and periodic INFO logging.
* :class:`ServingFleet` — N :class:`Replica` workers (one per mesh device
  by default, device-pinned batches) behind one
  :class:`FleetScheduler`: continuous batching, deadline-aware admission
  shedding (typed :class:`Shed`), work-stealing rebalance, and
  fleet-wide zero-downtime hot swap with an optional shadow/canary
  comparison phase (auto-rollback raises :class:`CanaryMismatch`).
"""

from .batching import BucketPolicy
from .engine import ServingEngine
from .errors import (
    CanaryMismatch,
    DeadlineExceeded,
    EngineClosed,
    EngineStopped,
    InvalidRequest,
    QueueFull,
    ServingError,
    Shed,
)
from .fleet import ServingFleet
from .metrics import MetricsRegistry
from .replica import Replica
from .scheduler import FleetScheduler
from .slo import SloBreach, SloPolicy, SloWatchdog

__all__ = [
    "SloBreach",
    "SloPolicy",
    "SloWatchdog",
    "ServingEngine",
    "ServingFleet",
    "Replica",
    "FleetScheduler",
    "BucketPolicy",
    "MetricsRegistry",
    "ServingError",
    "QueueFull",
    "Shed",
    "DeadlineExceeded",
    "InvalidRequest",
    "EngineClosed",
    "EngineStopped",
    "CanaryMismatch",
]
