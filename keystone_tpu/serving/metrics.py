"""Serving metrics: counters, gauges, latency quantiles, phase export.

Parity note: the reference inherits per-stage counters and timelines from
the Spark UI; here a process-local registry plays that role for the
serving path. Everything is thread-safe (the engine's worker thread and N
submitter threads write concurrently), ``snapshot()`` is the programmatic
read used by tests and the demo, and ``maybe_log`` emits a rate-limited
one-line INFO summary through the same stdlib logging that
``utils.obs.configure`` levels.

Phase stats from ``utils.timing`` (the hot-solver profiling registry) are
embedded in every snapshot under ``"phases"`` — the engine wraps its batch
execution in ``timing.phase("serve.batch", ...)``, so under
``KEYSTONE_PROFILE=1`` the serving batches show up in the same per-phase
device-time table as the solvers.

Tracer spans (``keystone_tpu.obs``) land under ``"spans"`` in the SAME
``{name: {"seconds", "calls", ...}}`` schema as ``"phases"`` — and the
engine's span is named ``serve.microbatch`` (fleet replicas:
``serve.replica``) vs the phase's ``serve.batch`` — so bench/serve
exports can concatenate the two dicts without key collisions or shape
mismatches.

Fleet additions: one registry serves all N replica workers —
``observe_batch(..., replica=i)`` attributes occupancy per replica
(``snapshot()["replicas"]``), ``observe_queue_age`` tracks time-queued
quantiles separately from end-to-end latency (p99 queue age grows before
p99 latency does), and the periodic INFO line carries the shed count and
canary verdicts next to the classic counters.
"""

from __future__ import annotations

import logging
import math
import threading
from collections import defaultdict, deque
from typing import Callable, Dict, Optional, Sequence

from ..utils import timing
from ..utils.obs import every

logger = logging.getLogger(__name__)

#: quantiles reported by :meth:`MetricsRegistry.latency_quantiles`
_QUANTILES = (0.50, 0.95, 0.99)

#: how a gauge folds across process snapshots in :meth:`MetricsRegistry.merge`
#: — additive quantities sum (queue depth, live bytes across distinct
#: devices), watermarks take the max (peak memory), ratios average
#: (utilization fractions: summing two 0.9s into 1.8 is fiction)
GAUGE_MERGE_MODES = ("sum", "max", "mean")

#: per-(tenant, priority) accumulator columns, in storage order
_COST_FIELDS = ("device_s", "queue_s", "payload_bytes", "items")


class MetricsRegistry:
    """Thread-safe counters + gauges + a bounded latency reservoir."""

    def __init__(
        self,
        name: str = "serving",
        latency_window: int = 4096,
        timeline_window: int = 256,
    ):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._gauge_modes: Dict[str, str] = {}
        # (tenant, priority) -> [device_s, queue_s, payload_bytes, items]:
        # the per-identity cost table every replica batch is split into
        self._costs: Dict[tuple, list] = {}
        # device_s/items cursor per tenant for timeline cost deltas
        self._costs_prev: Dict[str, list] = {}
        self._latencies: deque = deque(maxlen=latency_window)
        self._queue_ages: deque = deque(maxlen=latency_window)
        # priority class -> bounded reservoir: the per-class latency the
        # QoS gates assert (high's p99 in budget while low absorbs shed)
        self._priority_latencies: Dict[str, deque] = {}
        self._latency_window = latency_window
        self._batch_items = 0
        self._batch_capacity = 0
        # replica index -> [items, capacity, batches]: per-replica
        # occupancy for the fleet (one registry, N replica workers)
        self._replica_batches: Dict[int, list] = {}
        #: the bounded metrics timeline: one row per sample_timeline()
        #: call (the health/periodic loops drive the cadence) — the
        #: queue-age-over-time view a point-in-time snapshot cannot give
        self._timeline: deque = deque(maxlen=timeline_window)
        self._timeline_prev: Dict[str, int] = {}

    # -- writes ---------------------------------------------------------

    def inc(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counters[counter] += n

    def set_gauge(
        self, name: str, read: Callable[[], float], merge: str = "sum"
    ) -> None:
        """Register a live-value gauge (e.g. queue depth); ``read`` is
        called at snapshot time. ``merge`` declares how the gauge folds
        across process snapshots (see :data:`GAUGE_MERGE_MODES`): additive
        quantities ``sum``, watermarks ``max``, ratios ``mean``."""
        if merge not in GAUGE_MERGE_MODES:
            raise ValueError(
                f"gauge merge mode {merge!r} not in {GAUGE_MERGE_MODES}"
            )
        with self._lock:
            self._gauges[name] = read
            self._gauge_modes[name] = merge

    def observe_cost(
        self,
        tenant: str,
        priority: str = "normal",
        device_s: float = 0.0,
        queue_s: float = 0.0,
        payload_bytes: int = 0,
        items: int = 0,
    ) -> None:
        """Charge one batch share to a (tenant, priority) identity:
        attributed device-seconds, queue-seconds waited before dispatch,
        and payload bytes carried. Accumulates the per-tenant cost table
        that ``snapshot()["costs"]`` exposes, :meth:`merge` folds
        fleet-wide, and :meth:`sample_timeline` emits as windowed
        ``device_s`` deltas for per-tenant spend budgeting."""
        with self._lock:
            row = self._costs.setdefault(
                (str(tenant), str(priority)), [0.0, 0.0, 0, 0]
            )
            row[0] += float(device_s)
            row[1] += float(queue_s)
            row[2] += int(payload_bytes)
            row[3] += int(items)

    def observe_latency(
        self, seconds: float, priority: Optional[str] = None
    ) -> None:
        """One end-to-end request latency; ``priority`` additionally
        files it under that QoS class's own reservoir so per-priority
        quantiles survive (aggregate p99 hides a starved class)."""
        with self._lock:
            self._latencies.append(seconds)
            if priority is not None:
                res = self._priority_latencies.get(priority)
                if res is None:
                    res = self._priority_latencies[priority] = deque(
                        maxlen=self._latency_window
                    )
                res.append(seconds)

    def observe_queue_age(self, seconds: float) -> None:
        """Time one request spent queued before its batch dispatched —
        the queueing-delay component of latency. p99 queue age is the
        fleet's early-warning signal: it grows before end-to-end p99
        does, because it excludes compute."""
        with self._lock:
            self._queue_ages.append(seconds)

    def observe_batch(
        self, items: int, capacity: int, replica: Optional[int] = None
    ) -> None:
        """One executed micro-batch: ``items`` real rows in a
        ``capacity``-row bucket. The running ratio is batch occupancy —
        how much of each compiled program's work is real traffic vs
        padding. ``replica`` additionally attributes the batch to one
        fleet worker so per-replica occupancy (and a stalled or starved
        replica) is visible in the snapshot."""
        with self._lock:
            self._counters["batches"] += 1
            self._batch_items += items
            self._batch_capacity += capacity
            if replica is not None:
                row = self._replica_batches.setdefault(replica, [0, 0, 0])
                row[0] += items
                row[1] += capacity
                row[2] += 1

    # -- reads ----------------------------------------------------------

    def count(self, counter: str) -> int:
        with self._lock:
            return self._counters[counter]

    def cost_table(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        """The cumulative cost table as ``{tenant: {priority: {device_s,
        queue_s, payload_bytes, items}}}`` (seconds rounded to µs)."""
        with self._lock:
            rows = {key: list(row) for key, row in self._costs.items()}
        out: Dict[str, Dict[str, Dict[str, float]]] = {}
        for (tenant, priority), row in sorted(rows.items()):
            out.setdefault(tenant, {})[priority] = {
                "device_s": round(row[0], 6),
                "queue_s": round(row[1], 6),
                "payload_bytes": int(row[2]),
                "items": int(row[3]),
            }
        return out

    def latency_quantiles(self) -> Dict[str, float]:
        with self._lock:
            lat = sorted(self._latencies)
        return self._quantiles(lat)

    def queue_age_quantiles(self) -> Dict[str, float]:
        """p50/p95/p99 of time-spent-queued, same schema as latency."""
        with self._lock:
            ages = sorted(self._queue_ages)
        return self._quantiles(ages)

    def priority_latency_quantiles(self) -> Dict[str, Dict[str, float]]:
        """Per-priority-class latency quantiles, one row per class that
        has observed traffic (same schema per row as ``latency``)."""
        with self._lock:
            per = {
                p: sorted(res) for p, res in self._priority_latencies.items()
            }
        return {p: self._quantiles(vals) for p, vals in sorted(per.items())}

    @staticmethod
    def _quantiles(vals: list) -> Dict[str, float]:
        out: Dict[str, float] = {"count": len(vals)}
        if not vals:
            return out
        out["mean"] = sum(vals) / len(vals)
        for q in _QUANTILES:
            # nearest-rank: ceil(q*n)-1, clamped (int(q*n) alone is biased
            # one rank high — p99 of a full window would report the max)
            idx = min(len(vals) - 1, max(0, math.ceil(q * len(vals)) - 1))
            out[f"p{int(q * 100)}"] = vals[idx]
        return out

    # -- the timeline ---------------------------------------------------

    def sample_timeline(self, now: Optional[float] = None) -> Dict[str, object]:
        """Append one ``(ts, counter deltas, gauges, quantiles,
        occupancy)`` row to the bounded timeline ring and return it.

        Counters land as DELTAS since the previous sample (a timeline of
        cumulative totals only ever goes up and hides the burst), so a
        row reads as "what happened in this window"; quantiles are the
        reservoir's current view. Callers drive the cadence — the
        cluster router's health loop, the worker's ping handler — so one
        registry never pays two samplers."""
        import time as _time

        ts = _time.time() if now is None else float(now)
        with self._lock:
            counters = dict(self._counters)
            gauges = list(self._gauges.items())
            items, capacity = self._batch_items, self._batch_capacity
            prev = self._timeline_prev
            deltas = {
                k: v - prev.get(k, 0)
                for k, v in counters.items()
                if v - prev.get(k, 0)
            }
            self._timeline_prev = counters
            # per-tenant spend THIS window (device_s/items deltas summed
            # across priorities) — what SloPolicy's tenant budget judges
            tenant_totals: Dict[str, list] = {}
            for (tenant, _prio), row in self._costs.items():
                slot = tenant_totals.setdefault(tenant, [0.0, 0])
                slot[0] += row[0]
                slot[1] += row[3]
            cost_deltas = {}
            for tenant, (dev, n) in tenant_totals.items():
                pdev, pn = self._costs_prev.get(tenant, (0.0, 0))
                if dev - pdev > 1e-9 or n - pn:
                    cost_deltas[tenant] = {
                        "device_s": round(dev - pdev, 6),
                        "items": n - pn,
                    }
            self._costs_prev = {
                t: list(v) for t, v in tenant_totals.items()
            }
        gauge_vals = {}
        for k, read in gauges:
            try:
                v = read()
            except Exception:
                logger.debug("timeline gauge %s failed", k, exc_info=True)
                continue
            if isinstance(v, (int, float)):
                gauge_vals[k] = round(float(v), 6)
        row: Dict[str, object] = {
            "ts": ts,
            "counters": deltas,
            "gauges": gauge_vals,
            "latency": self.latency_quantiles(),
            "queue_age": self.queue_age_quantiles(),
            "occupancy": (items / capacity) if capacity else None,
        }
        if cost_deltas:
            row["costs"] = cost_deltas
        with self._lock:
            self._timeline.append(row)
        return row

    def timeline(self) -> list:
        """The bounded sample rows, oldest first."""
        with self._lock:
            return [dict(r) for r in self._timeline]

    def snapshot(self, sketches: bool = False) -> Dict[str, object]:
        """Everything at once: counters, evaluated gauges, occupancy,
        latency quantiles, and the process phase-timing table.

        ``sketches=True`` additionally includes the raw bounded latency /
        queue-age reservoirs under ``"sketch"`` — the mergeable form a
        worker process ships to the cluster router so :meth:`merge` can
        recompute exact fleet-wide quantiles instead of averaging
        per-process percentiles (which is statistically meaningless)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = list(self._gauges.items())
            gauge_modes = dict(self._gauge_modes)
            items, capacity = self._batch_items, self._batch_capacity
            replicas = {
                idx: list(row) for idx, row in self._replica_batches.items()
            }
            sketch = (
                {
                    "latencies": [float(x) for x in self._latencies],
                    "queue_ages": [float(x) for x in self._queue_ages],
                    "priority_latencies": {
                        p: [float(x) for x in res]
                        for p, res in self._priority_latencies.items()
                    },
                }
                if sketches
                else None
            )
        snap: Dict[str, object] = {
            "name": self.name,
            "counters": counters,
            "gauges": {k: read() for k, read in gauges},
            "gauge_modes": gauge_modes,
            "costs": self.cost_table(),
            "batch_occupancy": {
                "items": items,
                "capacity": capacity,
                "ratio": (items / capacity) if capacity else None,
            },
            "replicas": {
                str(idx): {
                    "items": row[0],
                    "capacity": row[1],
                    "batches": row[2],
                    "occupancy": (row[0] / row[1]) if row[1] else None,
                }
                for idx, row in sorted(replicas.items())
            },
            "latency": self.latency_quantiles(),
            "queue_age": self.queue_age_quantiles(),
            "priority_latency": self.priority_latency_quantiles(),
            "phases": timing.snapshot(prefix="serve."),
            "spans": self._span_summary(),
            # the bounded timeline rides every snapshot (cheap: <=
            # timeline_window small dicts) so a worker's rows cross the
            # wire with its stats reply and survive the merge intact
            "timeline": self.timeline(),
        }
        if sketch is not None:
            snap["sketch"] = sketch
        return snap

    @staticmethod
    def merge(
        snapshots: "Sequence[Dict[str, object]]", name: str = "merged"
    ) -> Dict[str, object]:
        """Aggregate N process/worker snapshots into ONE snapshot-shaped
        view: counters and occupancy summed, numeric gauges summed,
        per-replica rows namespaced ``<snapshot-name>/<replica>``, and
        latency / queue-age quantiles recomputed from the merged raw
        sketches (take the inputs with ``snapshot(sketches=True)``).
        Phase/span tables fold per key (seconds and calls summed).

        A snapshot without a sketch still contributes its counters and
        occupancy; its latency reservoir simply cannot participate in
        the merged quantiles (the merged ``count`` reflects only
        sketch-bearing inputs — exact over what was shipped, never a
        made-up percentile). This is what the cluster router's periodic
        INFO line and ``snapshot()`` report: fleet-wide shed / queue-age
        / occupancy, not per-process shards."""
        counters: Dict[str, int] = defaultdict(int)
        # gauge name -> list of observed values; folded per declared mode
        gauge_vals: Dict[str, list] = defaultdict(list)
        gauge_modes: Dict[str, str] = {}
        costs: Dict[tuple, list] = {}
        items = capacity = 0
        replicas: Dict[str, object] = {}
        lats: list = []
        ages: list = []
        prio_lats: Dict[str, list] = defaultdict(list)
        phases: Dict[str, Dict[str, float]] = {}
        spans: Dict[str, Dict[str, float]] = {}
        timelines: Dict[str, list] = {}

        def _fold_table(dst, src):
            for key, row in (src or {}).items():
                if not isinstance(row, dict):
                    continue
                slot = dst.setdefault(key, defaultdict(float))
                for k, v in row.items():
                    if isinstance(v, (int, float)):
                        slot[k] += v

        for i, snap in enumerate(snapshots):
            if not snap:
                continue
            label = str(snap.get("name") or i)
            for k, v in (snap.get("counters") or {}).items():
                counters[k] += int(v)
            modes = snap.get("gauge_modes") or {}
            for k, v in (snap.get("gauges") or {}).items():
                if isinstance(v, (int, float)):
                    gauge_vals[k].append(float(v))
                    # first declared mode wins; undeclared gauges sum
                    # (the historical behavior — correct for depths)
                    gauge_modes.setdefault(k, modes.get(k, "sum"))
            for tenant, prios in (snap.get("costs") or {}).items():
                for priority, row in prios.items():
                    slot = costs.setdefault(
                        (str(tenant), str(priority)), [0.0, 0.0, 0, 0]
                    )
                    slot[0] += float(row.get("device_s") or 0.0)
                    slot[1] += float(row.get("queue_s") or 0.0)
                    slot[2] += int(row.get("payload_bytes") or 0)
                    slot[3] += int(row.get("items") or 0)
            occ = snap.get("batch_occupancy") or {}
            items += int(occ.get("items") or 0)
            capacity += int(occ.get("capacity") or 0)
            for idx, row in (snap.get("replicas") or {}).items():
                replicas[f"{label}/{idx}"] = dict(row)
            sketch = snap.get("sketch") or {}
            lats.extend(sketch.get("latencies") or [])
            ages.extend(sketch.get("queue_ages") or [])
            for p, vals in (sketch.get("priority_latencies") or {}).items():
                prio_lats[p].extend(vals)
            _fold_table(phases, snap.get("phases"))
            _fold_table(spans, snap.get("spans"))
            # timelines stay PER-PROCESS, never blended: each row is one
            # process's windowed view, and summing two processes' p99
            # columns (or interleaving their delta rows) would fabricate
            # a timeline no process ever observed
            rows = snap.get("timeline")
            if rows:
                timelines[label] = [dict(r) for r in rows]
        gauges: Dict[str, float] = {}
        for k, vals in gauge_vals.items():
            mode = gauge_modes.get(k, "sum")
            if mode == "max":
                gauges[k] = max(vals)
            elif mode == "mean":
                gauges[k] = sum(vals) / len(vals)
            else:
                gauges[k] = sum(vals)
        merged_costs: Dict[str, Dict[str, Dict[str, float]]] = {}
        for (tenant, priority), row in sorted(costs.items()):
            merged_costs.setdefault(tenant, {})[priority] = {
                "device_s": round(row[0], 6),
                "queue_s": round(row[1], 6),
                "payload_bytes": int(row[2]),
                "items": int(row[3]),
            }
        return {
            "name": name,
            "merged_from": len(list(snapshots)),
            "counters": dict(counters),
            "gauges": gauges,
            "gauge_modes": gauge_modes,
            "costs": merged_costs,
            "batch_occupancy": {
                "items": items,
                "capacity": capacity,
                "ratio": (items / capacity) if capacity else None,
            },
            "replicas": replicas,
            "latency": MetricsRegistry._quantiles(sorted(lats)),
            "queue_age": MetricsRegistry._quantiles(sorted(ages)),
            "priority_latency": {
                p: MetricsRegistry._quantiles(sorted(vals))
                for p, vals in sorted(prio_lats.items())
            },
            "phases": {k: dict(v) for k, v in phases.items()},
            "spans": {k: dict(v) for k, v in spans.items()},
            "timelines": timelines,
        }

    @staticmethod
    def _span_summary() -> Dict[str, object]:
        """Serving spans from the installed tracer, ``{}`` when tracing is
        off — same shape as ``"phases"`` (see module docstring). Like
        ``"phases"``, this is PROCESS scope (the tracer registry is one
        per process): with several engines live, it aggregates all of
        them, whereas ``"counters"``/``"latency"`` are per-engine."""
        from ..obs.tracer import current

        tracer = current()
        if tracer is None:
            return {}
        return tracer.span_summary(prefix="serve.")

    # -- periodic logging ----------------------------------------------

    def maybe_log(self, interval_s: float = 10.0) -> bool:
        """Log a one-line INFO summary, at most once per ``interval_s``
        per registry instance (two engines with the same registry name
        must not suppress each other's summaries). Returns True when it
        logged."""
        if not every(f"metrics:{self.name}:{id(self)}", interval_s):
            return False
        snap = self.snapshot()
        lat = snap["latency"]
        age = snap["queue_age"]
        occ = snap["batch_occupancy"]["ratio"]
        c = snap["counters"]
        canary = (
            f"{c.get('canary_pass', 0)}pass/{c.get('canary_fail', 0)}fail"
            if c.get("canary_pass") or c.get("canary_fail")
            else None
        )
        logger.info(
            "%s: counters=%s queue=%s occupancy=%s shed=%s canary=%s "
            "p50=%s p99=%s queue_age_p99=%s",
            self.name,
            c,
            snap["gauges"].get("queue_depth"),
            None if occ is None else round(occ, 3),
            c.get("shed", 0),
            canary,
            round(lat["p50"], 4) if "p50" in lat else None,
            round(lat["p99"], 4) if "p99" in lat else None,
            round(age["p99"], 4) if "p99" in age else None,
        )
        return True
