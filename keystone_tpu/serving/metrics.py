"""Serving metrics: counters, gauges, latency quantiles, phase export.

Parity note: the reference inherits per-stage counters and timelines from
the Spark UI; here a process-local registry plays that role for the
serving path. Everything is thread-safe (the engine's worker thread and N
submitter threads write concurrently), ``snapshot()`` is the programmatic
read used by tests and the demo, and ``maybe_log`` emits a rate-limited
one-line INFO summary through the same stdlib logging that
``utils.obs.configure`` levels.

Phase stats from ``utils.timing`` (the hot-solver profiling registry) are
embedded in every snapshot under ``"phases"`` — the engine wraps its batch
execution in ``timing.phase("serve.batch", ...)``, so under
``KEYSTONE_PROFILE=1`` the serving batches show up in the same per-phase
device-time table as the solvers.

Tracer spans (``keystone_tpu.obs``) land under ``"spans"`` in the SAME
``{name: {"seconds", "calls", ...}}`` schema as ``"phases"`` — and the
engine's span is named ``serve.microbatch`` vs the phase's
``serve.batch`` — so bench/serve exports can concatenate the two dicts
without key collisions or shape mismatches.
"""

from __future__ import annotations

import logging
import math
import threading
from collections import defaultdict, deque
from typing import Callable, Dict, Optional

from ..utils import timing
from ..utils.obs import every

logger = logging.getLogger(__name__)

#: quantiles reported by :meth:`MetricsRegistry.latency_quantiles`
_QUANTILES = (0.50, 0.95, 0.99)


class MetricsRegistry:
    """Thread-safe counters + gauges + a bounded latency reservoir."""

    def __init__(self, name: str = "serving", latency_window: int = 4096):
        self.name = name
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = defaultdict(int)
        self._gauges: Dict[str, Callable[[], float]] = {}
        self._latencies: deque = deque(maxlen=latency_window)
        self._batch_items = 0
        self._batch_capacity = 0

    # -- writes ---------------------------------------------------------

    def inc(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self._counters[counter] += n

    def set_gauge(self, name: str, read: Callable[[], float]) -> None:
        """Register a live-value gauge (e.g. queue depth); ``read`` is
        called at snapshot time."""
        with self._lock:
            self._gauges[name] = read

    def observe_latency(self, seconds: float) -> None:
        with self._lock:
            self._latencies.append(seconds)

    def observe_batch(self, items: int, capacity: int) -> None:
        """One executed micro-batch: ``items`` real rows in a
        ``capacity``-row bucket. The running ratio is batch occupancy —
        how much of each compiled program's work is real traffic vs
        padding."""
        with self._lock:
            self._counters["batches"] += 1
            self._batch_items += items
            self._batch_capacity += capacity

    # -- reads ----------------------------------------------------------

    def count(self, counter: str) -> int:
        with self._lock:
            return self._counters[counter]

    def latency_quantiles(self) -> Dict[str, float]:
        with self._lock:
            lat = sorted(self._latencies)
        out: Dict[str, float] = {"count": len(lat)}
        if not lat:
            return out
        out["mean"] = sum(lat) / len(lat)
        for q in _QUANTILES:
            # nearest-rank: ceil(q*n)-1, clamped (int(q*n) alone is biased
            # one rank high — p99 of a full window would report the max)
            idx = min(len(lat) - 1, max(0, math.ceil(q * len(lat)) - 1))
            out[f"p{int(q * 100)}"] = lat[idx]
        return out

    def snapshot(self) -> Dict[str, object]:
        """Everything at once: counters, evaluated gauges, occupancy,
        latency quantiles, and the process phase-timing table."""
        with self._lock:
            counters = dict(self._counters)
            gauges = list(self._gauges.items())
            items, capacity = self._batch_items, self._batch_capacity
        return {
            "name": self.name,
            "counters": counters,
            "gauges": {k: read() for k, read in gauges},
            "batch_occupancy": {
                "items": items,
                "capacity": capacity,
                "ratio": (items / capacity) if capacity else None,
            },
            "latency": self.latency_quantiles(),
            "phases": timing.snapshot(prefix="serve."),
            "spans": self._span_summary(),
        }

    @staticmethod
    def _span_summary() -> Dict[str, object]:
        """Serving spans from the installed tracer, ``{}`` when tracing is
        off — same shape as ``"phases"`` (see module docstring). Like
        ``"phases"``, this is PROCESS scope (the tracer registry is one
        per process): with several engines live, it aggregates all of
        them, whereas ``"counters"``/``"latency"`` are per-engine."""
        from ..obs.tracer import current

        tracer = current()
        if tracer is None:
            return {}
        return tracer.span_summary(prefix="serve.")

    # -- periodic logging ----------------------------------------------

    def maybe_log(self, interval_s: float = 10.0) -> bool:
        """Log a one-line INFO summary, at most once per ``interval_s``
        per registry instance (two engines with the same registry name
        must not suppress each other's summaries). Returns True when it
        logged."""
        if not every(f"metrics:{self.name}:{id(self)}", interval_s):
            return False
        snap = self.snapshot()
        lat = snap["latency"]
        occ = snap["batch_occupancy"]["ratio"]
        logger.info(
            "%s: counters=%s queue=%s occupancy=%s p50=%s p99=%s",
            self.name,
            snap["counters"],
            snap["gauges"].get("queue_depth"),
            None if occ is None else round(occ, 3),
            round(lat["p50"], 4) if "p50" in lat else None,
            round(lat["p99"], 4) if "p99" in lat else None,
        )
        return True
