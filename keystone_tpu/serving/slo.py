"""Declarative SLO policy over the metrics timeline.

The metrics timeline (``MetricsRegistry.sample_timeline``) turns the
registry's point-in-time snapshot into rows of windowed evidence; this
module is the judgment layer on top: a :class:`SloPolicy` names the
budgets (p99 vs deadline budget, shed rate, restart-budget burn, trainer
staleness/drift), :meth:`SloPolicy.evaluate` prices one row against
them, and the :class:`SloWatchdog` runs that evaluation per sample —
emitting each violation as a typed :class:`SloBreach` into

* the **flight recorder** (``slo.breach`` instants — a breach is exactly
  the kind of pre-failure evidence a post-mortem ring exists for),
* the **trace** (when a tracer is installed),
* the **metrics** (an ``slo_breaches`` counter plus per-objective
  ``slo_breach.<objective>`` counters, so the periodic INFO line and the
  merged cluster snapshot carry the burn), and
* a rate-limited WARNING log.

Every budget is Optional: an unset objective is not evaluated, so a
policy names exactly the SLOs a deployment actually has. This is the
observation substrate the ROADMAP's autoscaling item reads — "queue age
approaching the deadline budget" is literally a breach row here.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class SloBreach:
    """One objective violated by one timeline row."""

    objective: str  # policy field name, e.g. "p99_budget_s"
    observed: float
    budget: float
    ts: float
    #: optional identity the breach attributes to (the overspending
    #: tenant for ``tenant_device_s_budget``); "" for fleet-wide ones
    detail: str = ""

    def as_attrs(self) -> Dict[str, object]:
        attrs: Dict[str, object] = {
            "objective": self.objective,
            "observed": round(self.observed, 6),
            "budget": self.budget,
        }
        if self.detail:
            attrs["detail"] = self.detail
        return attrs


@dataclass
class SloPolicy:
    """Budgets per objective; None disables that objective.

    p99_budget_s / queue_age_p99_budget_s:
        End-to-end and time-queued p99 ceilings (seconds). Queue age is
        the early-warning twin: it breaches before latency does. Judged
        only in windows that saw traffic (a ``submitted`` or
        ``completed`` counter delta): the reservoirs are cumulative, so
        a quiet window's quantiles are a PAST burst's evidence — left
        unjudged, or an autoscaler fed by these breaches would hold a
        long-idle fleet at peak size forever.
    max_shed_rate:
        Ceiling on the share of OFFERED traffic refused within one
        sample window: ``(shed + rejected) / (submitted + shed +
        rejected)`` — admission surfaces count ``submitted`` only for
        admitted requests, so the denominator reconstructs what was
        offered. (A request shed AFTER admission on a requeue counts in
        both terms, slightly diluting the rate; those windows also burn
        ``max_restart_burn``, which is the objective that owns them.)
        Windows with no traffic are not judged.
    max_restart_burn:
        Supervised restarts (replica + worker) tolerated per sample
        window — restart-budget burn-RATE, distinct from the absolute
        budgets the supervisors enforce: a fleet recovering this often
        is failing its availability SLO even while every restart
        succeeds.
    max_staleness_s / max_drift_score:
        Trainer-loop objectives over the ``staleness_s`` / ``drift_score``
        gauges the daemon exports: a model too old, or drifting past the
        monitor's threshold, is an SLO breach even when serving is fast.
    tenant_device_s_budget:
        Per-tenant spend ceiling: attributed device-seconds any single
        tenant may burn within ONE sample window (the ``costs`` deltas
        the timeline rows carry from the per-tenant cost tables). The
        breach's ``detail`` names the overspending tenant — this is a
        fairness/abuse objective, not a capacity one, so the autoscaler
        does not scale up on it.
    device_mem_budget_bytes:
        Device-memory watermark ceiling over the ``device_mem_bytes``
        gauge the resource plane samples on scan/fit/batch seams; like
        the tenant budget, more workers do not shrink a per-process
        footprint, so it warns without triggering scale-up.
    """

    p99_budget_s: Optional[float] = None
    queue_age_p99_budget_s: Optional[float] = None
    max_shed_rate: Optional[float] = None
    max_restart_burn: Optional[int] = None
    max_staleness_s: Optional[float] = None
    max_drift_score: Optional[float] = None
    tenant_device_s_budget: Optional[float] = None
    device_mem_budget_bytes: Optional[float] = None

    def evaluate(self, row: Dict[str, object]) -> List[SloBreach]:
        """Judge one ``sample_timeline`` row; returns the breaches (empty
        when every set objective holds)."""
        ts = float(row.get("ts") or time.time())
        counters: Dict[str, int] = dict(row.get("counters") or {})
        gauges: Dict[str, float] = dict(row.get("gauges") or {})
        out: List[SloBreach] = []

        def breach(objective: str, observed, budget) -> None:
            out.append(SloBreach(objective, float(observed), float(budget), ts))

        # latency/queue-age quantiles come from cumulative reservoirs:
        # only a window that saw traffic may be judged by them (see the
        # class docstring — stale evidence must not breach forever)
        active = (
            counters.get("submitted", 0) + counters.get("completed", 0) > 0
        )
        lat = row.get("latency") or {}
        if (
            active
            and self.p99_budget_s is not None
            and lat.get("p99", 0.0) > self.p99_budget_s
        ):
            breach("p99_budget_s", lat["p99"], self.p99_budget_s)
        age = row.get("queue_age") or {}
        if (
            active
            and self.queue_age_p99_budget_s is not None
            and age.get("p99", 0.0) > self.queue_age_p99_budget_s
        ):
            breach(
                "queue_age_p99_budget_s", age["p99"],
                self.queue_age_p99_budget_s,
            )
        if self.max_shed_rate is not None:
            submitted = counters.get("submitted", 0)
            refused = counters.get("shed", 0) + counters.get("rejected", 0)
            if submitted + refused > 0:
                rate = refused / (submitted + refused)
                if rate > self.max_shed_rate:
                    breach("max_shed_rate", rate, self.max_shed_rate)
        if self.max_restart_burn is not None:
            burn = counters.get("restarts", 0) + counters.get(
                "trainer_restarts", 0
            )
            if burn > self.max_restart_burn:
                breach("max_restart_burn", burn, self.max_restart_burn)
        if self.max_staleness_s is not None:
            staleness = gauges.get("staleness_s")
            if staleness is not None and staleness > self.max_staleness_s:
                breach("max_staleness_s", staleness, self.max_staleness_s)
        if self.max_drift_score is not None:
            drift = gauges.get("drift_score")
            if drift is not None and drift > self.max_drift_score:
                breach("max_drift_score", drift, self.max_drift_score)
        if self.tenant_device_s_budget is not None:
            for tenant, cost in sorted(
                (row.get("costs") or {}).items()
            ):
                spent = float((cost or {}).get("device_s") or 0.0)
                if spent > self.tenant_device_s_budget:
                    out.append(SloBreach(
                        "tenant_device_s_budget", spent,
                        float(self.tenant_device_s_budget), ts,
                        detail=str(tenant),
                    ))
        if self.device_mem_budget_bytes is not None:
            mem = gauges.get("device_mem_bytes")
            if mem is not None and mem > self.device_mem_budget_bytes:
                breach(
                    "device_mem_budget_bytes", mem,
                    self.device_mem_budget_bytes,
                )
        return out


class SloWatchdog:
    """Per-sample SLO evaluation bound to one registry.

    ``tick()`` samples the registry's timeline and judges the fresh row;
    the caller owns the cadence (the cluster router's health loop, a
    fleet's periodic logging path). ``source`` labels the emitted
    evidence so merged views attribute breaches to their tier."""

    def __init__(
        self,
        metrics,
        policy: SloPolicy,
        source: str = "serving",
    ):
        self._metrics = metrics
        self.policy = policy
        self.source = source
        self.breaches: List[SloBreach] = []  # bounded by _MAX_KEPT
        self._MAX_KEPT = 256

    def tick(self) -> List[SloBreach]:
        row = self._metrics.sample_timeline()
        found = self.policy.evaluate(row)
        for b in found:
            self._emit(b)
        if found:
            self.breaches.extend(found)
            del self.breaches[: -self._MAX_KEPT]
        return found

    def _emit(self, b: SloBreach) -> None:
        from ..obs import flight
        from ..obs.tracer import current as _trace_current
        from ..utils.obs import every

        self._metrics.inc("slo_breaches")
        self._metrics.inc(f"slo_breach.{b.objective}")
        attrs = b.as_attrs()
        flight.record_instant("slo.breach", source=self.source, **attrs)
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(
                "slo.breach", op_type=type(self).__name__,
                source=self.source, **attrs,
            )
        if every(f"slo:{self.source}:{b.objective}", 10.0):
            logger.warning(
                "SLO breach [%s] %s: observed %.4f vs budget %.4f",
                self.source, b.objective, b.observed, b.budget,
            )
