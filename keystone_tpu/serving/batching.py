"""Shape bucketing: a small static set of batch shapes for the compiled fn.

XLA specializes every program to its input shapes, so a naive serving loop
that stacks whatever requests happen to be in the queue presents a new
batch size — and pays a full recompile — almost every batch (tens of
seconds for the image stacks). The policy here is the standard fix: round
every micro-batch up to the next of a few configured bucket sizes by
padding rows, so the jitted function traces once per bucket, ever, and
steady-state traffic runs with ZERO compiles. ``warmup_inputs`` lets the
engine pay all of those compiles before admitting traffic.

Padding repeats the batch's first row (same trick as
``FittedPipeline.apply_chunked``): padded rows stay in-distribution for
any row-wise chain and are sliced off before results are returned.
"""

from __future__ import annotations

import threading
from typing import Any, Iterator, Optional, Sequence, Tuple

import numpy as np

from .errors import InvalidRequest


class BucketPolicy:
    """Pads micro-batches to static bucket sizes; validates request data.

    ``datum_shape`` (per-item shape, no batch dim) may be given up front —
    enabling warm-up before any traffic — or left None, in which case it
    locks to the first valid datum seen and warm-up is skipped.
    """

    def __init__(
        self,
        batch_sizes: Sequence[int] = (1, 8, 32, 64),
        datum_shape: Optional[Sequence[int]] = None,
        dtype: Any = np.float32,
    ):
        sizes = sorted(set(int(b) for b in batch_sizes))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket sizes must be positive ints, got {batch_sizes!r}")
        self.batch_sizes: Tuple[int, ...] = tuple(sizes)
        self.datum_shape: Optional[Tuple[int, ...]] = (
            tuple(int(d) for d in datum_shape) if datum_shape is not None else None
        )
        self.dtype = np.dtype(dtype)
        # guards the lazy shape lock-in: N fleet replica workers may
        # validate first requests concurrently, and exactly ONE shape may
        # win — the losers' requests must fail typed, not flip the contract
        self._shape_lock = threading.Lock()

    @property
    def max_size(self) -> int:
        return self.batch_sizes[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket holding ``n`` rows. The engine never gathers
        more than ``max_size`` requests per batch, so ``n`` always fits."""
        if n < 1:
            raise ValueError("empty batch has no bucket")
        for b in self.batch_sizes:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} exceeds the largest bucket ({self.max_size}); "
            "the engine must split it"
        )

    # -- validation -----------------------------------------------------

    def validate(self, datum: Any) -> np.ndarray:
        """Convert one request datum to the service's array contract, or
        raise :class:`InvalidRequest`. Locks ``datum_shape`` on first use
        when it was not configured."""
        try:
            arr = np.asarray(datum, dtype=self.dtype)
        except (TypeError, ValueError) as e:
            raise InvalidRequest(f"datum not castable to {self.dtype}: {e}") from e
        if self.datum_shape is None:
            with self._shape_lock:
                if self.datum_shape is None:
                    self.datum_shape = tuple(arr.shape)
        if tuple(arr.shape) != self.datum_shape:
            raise InvalidRequest(
                f"datum shape {tuple(arr.shape)} != service shape {self.datum_shape}"
            )
        return arr

    # -- padding / warm-up ----------------------------------------------

    def pad(self, stacked: np.ndarray, bucket: int) -> np.ndarray:
        """Pad ``stacked`` (n ≤ bucket rows) up to ``bucket`` rows by
        repeating its first row."""
        n = int(stacked.shape[0])
        if n == bucket:
            return stacked
        if n > bucket:
            raise ValueError(f"{n} rows do not fit bucket {bucket}")
        return np.concatenate(
            [stacked, np.repeat(stacked[:1], bucket - n, axis=0)], axis=0
        )

    def warmup_inputs(self) -> Iterator[np.ndarray]:
        """One zero batch per bucket, in the exact shape+dtype live
        traffic will present — running these through the compiled fn
        pre-pays every compile the policy allows."""
        if self.datum_shape is None:
            raise ValueError(
                "warm-up needs datum_shape; configure it or serve a first "
                "request to lock the shape"
            )
        for b in self.batch_sizes:
            yield np.zeros((b, *self.datum_shape), dtype=self.dtype)
