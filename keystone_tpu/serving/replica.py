"""The reusable serving worker: one replica = one device-pinned executable
behind one batch loop.

:class:`ServingEngine` (one replica, gather-then-dispatch batching) and
:class:`~keystone_tpu.serving.fleet.ServingFleet` (N replicas behind a
shared continuous-batching scheduler) both run THIS worker; what differs
between them is only the :class:`BatchSource` that decides which requests
form the next micro-batch. The replica owns the parts every serving
topology shares:

* the **executable reference** — read once per batch at dispatch time, so
  a hot swap is one atomic store and every micro-batch runs whole on
  exactly one executable, never a mix;
* **device pinning** — a replica constructed with a device stages each
  padded batch onto it before dispatch, so N replicas spread over the
  mesh keep every chip busy (placement comes from
  :func:`keystone_tpu.parallel.placement.replica_devices`);
* the **batch execution discipline** — deadline expiry, per-request
  validation isolation, one D2H fetch per batch, per-request result
  distribution, queue-age/latency/occupancy metrics, and the
  ``serve.replica``/``serve.microbatch`` span;
* the **shadow hook** — when a canary swap is in flight, the fleet
  installs a shadow that mirrors completed batches through the candidate
  executable AFTER results are distributed, so comparison never adds
  latency to live requests.

The compile path (:func:`compile_pipeline`) is shared too: strict trace
accounting plus the AOT executable cache ride identically under an
engine, a fleet replica, or a swap candidate.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

from ..faults import (
    REPLICA_BATCH,
    ReplicaDown,
    fault_point,
    is_transient,
)
from ..obs import flight as _flight
from ..obs import resource as _resource
from ..obs.span import Span
from ..obs.tracer import current as _trace_current
from ..utils import timing
from ..workflow.pipeline import FittedPipeline, NotTraceableError
from .batching import BucketPolicy
from .errors import DeadlineExceeded, EngineStopped, InvalidRequest
from .metrics import MetricsRegistry

logger = logging.getLogger(__name__)

#: sentinel a BatchSource returns to stop the replica's loop
STOP = object()


class ReplicaQuarantined(ReplicaDown):
    """The circuit breaker tripped: this replica failed
    ``quarantine_after`` consecutive batches, so its loop exits and the
    fleet supervisor takes over (requeue its work, restart it within the
    restart budget). A ``BaseException`` like its base — it must pass the
    worker loop's ``except Exception`` backstop."""


class _TransientBatchFault(Exception):
    """Internal signal: a batch failed for a TRANSIENT reason (injected
    chaos fault, flaky device I/O) — its unanswered requests should be
    requeued to peers rather than failed, because a retry elsewhere is
    expected to succeed. ``pending`` is those requests, ``cause`` the
    original error."""

    def __init__(self, cause: BaseException, pending: list):
        super().__init__(str(cause))
        self.cause = cause
        self.pending = pending


def settle_future(fut: Future, exc: BaseException) -> bool:
    """Answer a request future with ``exc`` regardless of whether it is
    still pending or already marked running (popped into a batch that
    never finished). Returns True when this call delivered the answer."""
    if fut.done():
        return False
    try:
        try:
            live = fut.set_running_or_notify_cancel()
        except Exception:  # lint: allow-silent -- already RUNNING: settle
            live = True
        if not live:
            return False  # cancelled by the caller
        fut.set_exception(exc)
        return True
    except Exception:  # lint: allow-silent -- lost the set-once race: fine
        return False


@dataclass
class _Request:
    datum: Any
    deadline: Optional[float]  # time.monotonic() timestamp, or None
    enqueued: float
    future: Future = field(default_factory=Future)
    #: times this request has been requeued off a failed/dead replica —
    #: bounds the reroute loop for deadline-less requests, which the
    #: shed check can never retire
    hops: int = 0
    #: cross-process trace context (obs/context.py) for a sampled
    #: request: the replica records its queue-wait and batch spans under
    #: this identity so one request's hops stitch across the tier
    trace: Any = None
    #: QoS identity (autoscale/qos.py): ``priority`` is the shedding
    #: axis (low sheds before high), ``tenant`` the fairness axis (the
    #: weighted-fair queues serve tenants proportionally to weight).
    #: Carried ON the request so requeue clones, steals, and wire hops
    #: preserve both with no side-channel bookkeeping.
    priority: str = "normal"
    tenant: str = "default"


# ---------------------------------------------------------------------------
# shared compile path
# ---------------------------------------------------------------------------


def compile_pipeline(
    fitted: FittedPipeline,
    *,
    metrics: MetricsRegistry,
    signatures: list,
    label: str = "serving",
) -> Callable:
    """Strictly compile ``fitted`` against private trace accounting: every
    XLA trace paid appends its ``(shape, dtype)`` to ``signatures`` and
    bumps the ``compiles`` counter; with an AOT executable cache
    configured, each signature first tries to LOAD a previously exported
    executable (``aot_loads`` counts them) so a warm boot pays zero
    traces. Raises :class:`NotTraceableError` for an unjittable chain —
    at construction, never per-request under traffic."""
    import jax

    # ONE static check drives blockers + the trace build (trace_fn +
    # untraceable_nodes would each re-run the whole-graph pass)
    report = fitted.check(span=False)
    blockers = report.untraceable_labels()
    if blockers:
        raise NotTraceableError(blockers)
    fn = fitted._build_trace_fn()

    def _note_trace(sig):
        signatures.append(sig)
        metrics.inc("compiles")

    aot = _build_aot_dispatcher(fitted, fn, _note_trace, metrics, label)
    if aot is not None:
        return aot

    def _traced(x):
        _note_trace((tuple(x.shape), str(x.dtype)))
        return fn(x)

    return jax.jit(_traced)


def _build_aot_dispatcher(fitted, fn, note_trace, metrics, label):
    """The cache-aware compile path (same isolation contract as the
    private jit). None when no cache is configured or the pipeline cannot
    be content-keyed — then the legacy jit serves."""
    from .. import compile as compile_mod

    cache = compile_mod.get_cache()
    if cache is None:
        return None
    try:
        digest = fitted.fingerprint()
    except compile_mod.FingerprintError as e:
        logger.info(
            "serving: AOT cache skipped (pipeline not fingerprintable): %s", e
        )
        return None
    except Exception:
        # e.g. RecursionError on self-referential operator state: a
        # pipeline that serves fine without the cache must not crash
        # at construction because caching was enabled
        logger.warning(
            "serving: AOT cache skipped (fingerprinting failed)",
            exc_info=True,
        )
        return None

    def _note_load(sig):
        # NOT a compiled signature: no trace was paid for this bucket
        metrics.inc("aot_loads")

    return compile_mod.AotDispatcher(
        fn, digest, cache,
        on_trace=note_trace, on_load=_note_load, label=label,
    )


def serving_report(fitted: FittedPipeline):
    """The static check report of a pipeline about to serve: the datum
    contract (fit-time hint) plus the traceability verdicts every
    serving-path validation reads (keystone_tpu/check/). One call, zero
    executions."""
    return fitted.check(span=False)


def serving_contract(
    fitted: FittedPipeline,
    datum_shape: Optional[Sequence[int]],
    dtype: Any,
    *,
    verb: str = "serve",
    report=None,
):
    """Resolve the per-item (shape, dtype) contract and reject chains the
    bucket policy would silently corrupt — via the static checker's
    :class:`~keystone_tpu.check.CheckReport`, so the refusal carries the
    offending NODE. Explicit args win; otherwise the contract recorded on
    the fitted pipeline at fit time is used."""
    if report is None:
        report = serving_report(fitted)
    # same hazard apply_chunked guards: bucket padding repeats rows, so a
    # node computing whole-batch statistics would silently fold the
    # padding into every real request's answer. require_contract with an
    # open (None) shape/dtype checks ONLY the coupling verdict here.
    report.require_contract(None, None, verb=verb)
    # shape and dtype fall back independently — an explicit shape must not
    # discard the recorded dtype (warming float32 buckets for float64
    # traffic would re-trace every bucket under load)
    if datum_shape is None:
        datum_shape = report.datum_shape
    if dtype is None:
        dtype = report.datum_dtype or "float32"
    return datum_shape, dtype


def check_swap_contract(fitted: FittedPipeline, policy: BucketPolicy) -> None:
    """A replacement model must satisfy the live datum contract (shape +
    dtype) and must not be batch-coupled — re-bucketing or re-shaping a
    live engine/fleet is a restart, not a swap. Validation is the static
    CheckReport compared against the live policy: mismatches raise the
    typed, node-attributed
    :class:`~keystone_tpu.check.ContractMismatchError`."""
    serving_report(fitted).require_contract(
        policy.datum_shape, policy.dtype, verb="swap"
    )


# ---------------------------------------------------------------------------
# the replica worker
# ---------------------------------------------------------------------------


class Replica:
    """One serving worker: a compiled-executable reference, an optional
    pinned device, and the batch loop. Batching POLICY lives in the
    ``source`` handed to :meth:`serve_forever` — the replica only
    executes what the source forms."""

    def __init__(
        self,
        compiled: Callable,
        policy: BucketPolicy,
        metrics: MetricsRegistry,
        *,
        index: Optional[int] = None,
        device: Any = None,
        span_name: str = "serve.replica",
        log_interval_s: float = 10.0,
        quarantine_after: int = 0,
    ):
        #: fleet position, or None for a single-worker topology (the
        #: engine) — None keeps per-replica metrics rows and span attrs
        #: out of snapshots that never had them
        self.index = index
        self.device = device
        self._compiled = compiled
        self._policy = policy
        self._metrics = metrics
        self._span_name = span_name
        self._log_interval = log_interval_s
        self._shadow: Optional[Callable] = None
        #: wall seconds of the last executed batch (compute + D2H), read
        #: by the fleet scheduler to learn its service-time estimate
        self.last_exec_seconds: Optional[float] = None
        #: circuit breaker: this many CONSECUTIVE failed batches raise
        #: :class:`ReplicaQuarantined` out of the loop (0 = disabled —
        #: the single-worker engine, which has no supervisor to catch it)
        self.quarantine_after = int(quarantine_after)
        self.consecutive_failures = 0
        #: the batch currently executing, for the fleet's shutdown path
        #: to requeue/fail if this worker wedges (None between batches)
        self.current_batch: Optional[list] = None
        #: monotonically-increasing model version this replica serves,
        #: stamped by the fleet at construction and on every flip — the
        #: skew-detection surface for long rollouts (a restarted replica
        #: is re-pinned to the PUBLISHED version until promotion)
        self.version: int = 0

    @property
    def compiled(self) -> Callable:
        return self._compiled

    def flip(self, compiled: Callable) -> None:
        """THE swap: one reference store, read once per batch at dispatch
        time — each batch runs whole on exactly one executable."""
        self._compiled = compiled

    def set_shadow(self, shadow: Optional[Callable]) -> None:
        """Install (or clear) the canary mirror: ``shadow(replica, padded,
        primary_out, n_valid, bucket)`` runs after a batch's results are
        distributed, so mirroring never delays live responses."""
        self._shadow = shadow

    # -- the loop -------------------------------------------------------

    def serve_forever(self, source) -> None:
        """Run batches from ``source`` until it returns :data:`STOP`.
        ``source.next_batch(replica)`` returns a request list, None (poll
        again), or STOP; ``source.batch_done(batch, replica)`` runs after
        every batch, exception or not (queue accounting).

        Failure discipline: a TRANSIENT batch failure (injected chaos
        fault, flaky I/O) requeues its unanswered requests through
        ``source.requeue_batch`` when the source offers it (the fleet
        scheduler does; the single-worker engine fails them — it has no
        peers to retry on). Any other ``Exception`` hits the backstop as
        before. A ``BaseException`` — an injected :class:`ReplicaKilled`,
        the quarantine circuit breaker, interpreter teardown — ESCAPES
        with the unanswered requests attached as ``pending``, exactly so
        the fleet supervisor can requeue them and restart the worker."""
        while True:
            batch = source.next_batch(self)
            if batch is STOP:
                return
            if batch:
                self.current_batch = batch
                try:
                    self.run_batch(batch)
                except _TransientBatchFault as e:
                    self._requeue_or_fail(e, source)
                except Exception:  # run_batch isolates; the backstop
                    logger.exception(
                        "serving replica %s: unexpected batch failure",
                        self.index,
                    )
                    self.consecutive_failures += 1
                    for r in batch:
                        if not r.future.done():
                            settle_future(
                                r.future,
                                EngineStopped("internal batch failure"),
                            )
                except BaseException as e:
                    if getattr(e, "pending", None) is None:
                        try:
                            e.pending = [
                                r for r in batch if not r.future.done()
                            ]
                        except Exception:
                            # best-effort annotation for the supervisor;
                            # slots-only exceptions legitimately refuse it
                            logger.debug(
                                "could not attach pending batch to %r",
                                type(e).__name__, exc_info=True,
                            )
                    raise
                finally:
                    self.current_batch = None
                    source.batch_done(batch, self)
                self._maybe_quarantine()
            try:
                # user-registered gauges run inside snapshot(); an
                # exception there must not kill a worker thread
                self._metrics.maybe_log(self._log_interval)
            except Exception:
                logger.exception("serving replica: metrics logging failed")

    def _requeue_or_fail(self, fault: _TransientBatchFault, source) -> None:
        """Route a transient batch failure's unanswered requests back to
        the fleet (deadlines intact) — or fail them when the source has
        no requeue surface (the engine)."""
        pending = [r for r in fault.pending if not r.future.done()]
        requeue = getattr(source, "requeue_batch", None)
        if requeue is not None and pending:
            n = requeue(pending, self, fault.cause)
            logger.warning(
                "serving replica %s: transient batch failure (%s) — "
                "requeued %d of %d request(s) to peers",
                self.index, fault.cause, n, len(pending),
            )
            return
        self._metrics.inc("batch_errors")
        for r in pending:
            settle_future(r.future, fault.cause)

    def _maybe_quarantine(self) -> None:
        if (
            self.quarantine_after
            and self.consecutive_failures >= self.quarantine_after
        ):
            raise ReplicaQuarantined(
                f"replica {self.index} circuit-broken after "
                f"{self.consecutive_failures} consecutive batch failures"
            )

    # -- batch execution ------------------------------------------------

    def run_batch(self, batch: Sequence[_Request]) -> int:
        """Execute one micro-batch through the current executable on this
        replica's device. Returns the number of requests answered with a
        result."""
        import contextlib

        import jax
        import numpy as np

        # cleared up front: a batch that never executes (all expired, all
        # invalid, execution error) must not leave the PREVIOUS batch's
        # duration for the scheduler to re-fold into its service EWMA
        self.last_exec_seconds = None
        try:
            # the chaos seam: kill-kind faults escape as ReplicaDown
            # (thread death), transient-kind become a requeueable batch
            # fault — BEFORE any future is marked running
            fault_point(REPLICA_BATCH, replica=self.index)
        except ReplicaDown:
            raise
        except Exception as e:
            if is_transient(e):
                self._metrics.inc("batch_transient")
                self.consecutive_failures += 1
                raise _TransientBatchFault(e, list(batch)) from e
            raise
        now = time.monotonic()
        tracer = _trace_current()
        live = []
        for r in batch:
            if not r.future.set_running_or_notify_cancel():
                self._metrics.inc("cancelled")
                continue
            if r.deadline is not None and now > r.deadline:
                self._metrics.inc("expired")
                r.future.set_exception(
                    DeadlineExceeded(
                        f"deadline passed {now - r.deadline:.4f}s before batching"
                    )
                )
                continue
            queue_age = now - r.enqueued
            self._metrics.observe_queue_age(queue_age)
            if r.trace is not None and tracer is not None:
                # the queue-wait hop of a traced request: a completed
                # span backdated over the enqueued->dispatched window so
                # the stitched cross-process trace shows WHERE the time
                # went (queued here vs executing below)
                end_pc = time.perf_counter()
                tracer.record_complete(Span(
                    name="serve.queue",
                    start=end_pc - queue_age,
                    end=end_pc,
                    op_type="FleetScheduler",
                    attrs={
                        "trace_id": r.trace.trace_id,
                        "replica": self.index,
                        "queue_age_s": round(queue_age, 6),
                    },
                ))
            live.append(r)

        valid, rows = [], []
        for r in live:
            try:
                rows.append(self._policy.validate(r.datum))
                valid.append(r)
            except InvalidRequest as e:
                self._metrics.inc("invalid")
                r.future.set_exception(e)
        if not valid:
            return 0

        bucket = self._policy.bucket_for(len(valid))
        padded = self._policy.pad(np.stack(rows), bucket)
        if self.device is not None:
            # pin the batch (and so the executable) to this replica's
            # device — N replicas keep N chips busy instead of letting
            # XLA park every dispatch on the default device
            padded = jax.device_put(padded, self.device)
        compiled = self._compiled  # one read: the whole batch runs on it
        t0 = time.perf_counter()
        try:
            # span name differs from the phase's "serve.batch" so a merged
            # {name: {seconds, calls, ...}} export of phases + spans never
            # collides on keys
            span_attrs = {"items": len(valid), "bucket": bucket}
            if self.index is not None:
                span_attrs["replica"] = self.index
            traced_ids = [
                r.trace.trace_id for r in valid if r.trace is not None
            ]
            if traced_ids:
                # the batch span carries the first sampled member's
                # identity; members 2..N get their OWN execution spans
                # below (consumers group by args.trace_id, so every
                # coalesced member must own a span over the interval)
                span_attrs["trace_id"] = traced_ids[0]
            with contextlib.ExitStack() as stack:
                sp = (
                    stack.enter_context(
                        tracer.span(
                            self._span_name,
                            op_type="Replica",
                            **span_attrs,
                        )
                    )
                    if tracer is not None
                    else None
                )
                with timing.phase("serve.batch") as hold:
                    out = compiled(padded)
                    hold.append(out)
                if sp is not None:
                    sp.sync_on(out)
            out = jax.device_get(out)  # one D2H fetch for the whole batch
        except Exception as e:  # batch-level failure → every member errors
            self.consecutive_failures += 1
            if is_transient(e):
                # transient (injected / flaky I/O): a retry on a peer is
                # expected to succeed — hand the batch back instead of
                # failing every member
                self._metrics.inc("batch_transient")
                raise _TransientBatchFault(e, valid) from e
            self._metrics.inc("batch_errors")
            for r in valid:
                r.future.set_exception(e)
            return 0
        self.last_exec_seconds = time.perf_counter() - t0
        self.consecutive_failures = 0
        # the always-on flight ring gets every batch's summary — with
        # tracing OFF this (one dict + deque append) is the whole
        # observability cost of a batch, and it is what a post-mortem
        # dump shows the replica doing in the seconds before a trigger
        _flight.record_span(
            self._span_name, self.last_exec_seconds,
            items=len(valid), bucket=bucket, replica=self.index,
        )
        if len(traced_ids) > 1 and tracer is not None:
            # coalesced traced members beyond the first: each owns an
            # execution span over the shared batch interval, so per-
            # trace-id grouping never loses a member's compute hop
            # (capped — a full 64-bucket of sampled traffic must not
            # 64x the span volume)
            for extra_tid in traced_ids[1:16]:
                tracer.record_complete(Span(
                    name=self._span_name,
                    start=t0,
                    end=t0 + self.last_exec_seconds,
                    op_type="Replica",
                    attrs={
                        "trace_id": extra_tid,
                        "replica": self.index,
                        "bucket": bucket,
                        "coalesced": True,
                    },
                ))

        done = time.monotonic()
        for i, r in enumerate(valid):
            try:
                r.future.set_result(
                    jax.tree_util.tree_map(lambda a: a[i], out)
                )
            except Exception:  # lint: allow-silent -- set-once race:
                # already settled — a bounded shutdown failed this wedged
                # batch typed while it was still executing; the late real
                # result loses the set-once race, and the REST of the
                # batch must still distribute
                continue
            self._metrics.observe_latency(
                done - r.enqueued, priority=r.priority
            )
        self._metrics.inc("completed", len(valid))
        self._metrics.observe_batch(len(valid), bucket, replica=self.index)
        if _resource.accounting_enabled():
            # charge the batch to its members: measured device-seconds
            # split across the coalesced requests, queue-seconds against
            # the dispatch timestamp, payload bytes from the validated
            # rows — keyed by each request's (tenant, priority) identity
            for (tenant, priority), cost in _resource.split_batch_cost(
                valid, self.last_exec_seconds, now, payloads=rows
            ).items():
                self._metrics.observe_cost(tenant, priority, **cost)
            # batch seam of the device-memory watermark (throttled)
            _resource.sample_memory()

        shadow = self._shadow
        if shadow is not None:
            # canary mirroring rides AFTER result distribution: the
            # candidate's cost lands on the worker, never on live latency
            try:
                shadow(self, padded, out, len(valid), bucket)
            except Exception:
                logger.exception(
                    "serving replica %d: canary shadow failed", self.index
                )
        return len(valid)
