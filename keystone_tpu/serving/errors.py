"""Typed serving errors.

Every way a request can fail without the engine itself being broken gets
its own type, so callers can branch (retry / shed / fix the datum) instead
of string-matching, and so a failed request NEVER stalls the worker loop —
the error becomes that request's result and the batch continues.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base of all serving-layer errors."""


class QueueFull(ServingError):
    """Admission queue at capacity — the request was rejected at submit
    time (backpressure by load-shedding, never unbounded growth)."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed while it waited in the queue; it was
    dropped before wasting a batch slot on an answer nobody is waiting
    for."""


class InvalidRequest(ServingError):
    """The request's datum failed validation (wrong shape / uncastable
    payload). Isolated per request: the rest of its micro-batch completes
    normally."""


class EngineClosed(ServingError):
    """Submit after :meth:`ServingEngine.drain` / ``shutdown``."""
