"""Typed serving errors.

Every way a request can fail without the engine itself being broken gets
its own type, so callers can branch (retry / shed / fix the datum) instead
of string-matching, and so a failed request NEVER stalls the worker loop —
the error becomes that request's result and the batch continues.
"""

from __future__ import annotations


class ServingError(Exception):
    """Base of all serving-layer errors."""


class QueueFull(ServingError):
    """Admission queue at capacity — the request was rejected at submit
    time (backpressure by load-shedding, never unbounded growth)."""


class Shed(ServingError):
    """The request's deadline cannot be met given the fleet's current
    queue depth and learned batch service time, so it was refused at
    ADMISSION — before it burned a queue slot and device time only to
    expire. Distinct from :class:`DeadlineExceeded` (which is the late
    detection of the same condition at batch time): a shed request never
    entered the system, so the caller can immediately retry elsewhere or
    degrade."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed while it waited in the queue; it was
    dropped before wasting a batch slot on an answer nobody is waiting
    for."""


class InvalidRequest(ServingError):
    """The request's datum failed validation (wrong shape / uncastable
    payload). Isolated per request: the rest of its micro-batch completes
    normally."""


class EngineClosed(ServingError):
    """Submit after :meth:`ServingEngine.drain` / ``shutdown``."""


class EngineStopped(EngineClosed):
    """The engine/fleet has been stopped: admission observed the closed
    flag (the admission-vs-shutdown check-and-enqueue is atomic, so a
    submit either lands before the close and is answered by the drain,
    or gets this — never a stranded future). Subclasses
    :class:`EngineClosed` so existing handlers keep working; the distinct
    type lets fleet callers tell an orderly stop from other close paths."""


class CanaryMismatch(ServingError):
    """A canaried :meth:`ServingFleet.swap` was auto-rolled back: the
    candidate pipeline's outputs (or latency) diverged from the live
    model on mirrored traffic. The fleet is still serving the OLD model —
    nothing was promoted. ``report`` carries the mirrored-batch evidence
    (batches compared, mismatch details, latency ratio)."""

    def __init__(self, message: str, report: dict = None):
        super().__init__(message)
        self.report = report or {}
