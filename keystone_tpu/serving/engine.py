"""The serving engine: admission queue → micro-batches → compiled pipeline.

Lifecycle: construct (compiles the pipeline strictly — an untraceable
chain fails HERE with :class:`NotTraceableError`, not per-request under
traffic), ``start()`` (pre-compiles every bucket, then admits traffic),
``submit``/``predict`` from any number of threads, ``drain()`` /
``shutdown()``. Also a context manager: ``with engine:`` starts and
drains.

Batching policy: the worker blocks for the first queued request, then
gathers more until either the largest bucket is full or ``max_wait_ms``
elapses — the classic micro-batching latency/throughput knob. Backpressure
is reject-at-admission (:class:`QueueFull`) on a bounded queue, never
unbounded growth. Requests carry optional deadlines; a request that
expires while queued gets :class:`DeadlineExceeded` instead of wasting a
batch slot. A datum that fails validation gets :class:`InvalidRequest`
while the REST of its micro-batch completes — per-request error isolation.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional, Sequence

from ..obs.tracer import current as _trace_current
from ..workflow.pipeline import FittedPipeline
from .batching import BucketPolicy
from .errors import EngineClosed, EngineStopped, QueueFull
from .metrics import MetricsRegistry
from .replica import (
    STOP,
    Replica,
    _Request,
    check_swap_contract,
    compile_pipeline,
    serving_contract,
    settle_future,
)

logger = logging.getLogger(__name__)


class ServingEngine:
    """Serves a :class:`FittedPipeline` to concurrent callers.

    Parameters
    ----------
    fitted:
        The estimator-free pipeline; compiled strictly at construction.
    buckets:
        Static batch-size buckets (largest = max micro-batch size).
    datum_shape / dtype:
        Per-item array contract. With ``datum_shape`` given, ``start()``
        pre-compiles every bucket before traffic. When omitted, the
        contract recorded on the fitted pipeline at fit time
        (``FittedPipeline.datum_shape``/``datum_dtype``) is used; only
        when neither exists does the shape lock to the first request
        (whose batch then pays its compile).
    max_queue:
        Admission-queue bound; submissions beyond it raise
        :class:`QueueFull`.
    max_wait_ms:
        Micro-batch gather window after the first request of a batch.
    """

    def __init__(
        self,
        fitted: FittedPipeline,
        *,
        buckets: Sequence[int] = (1, 8, 32, 64),
        datum_shape: Optional[Sequence[int]] = None,
        dtype: Any = None,
        max_queue: int = 256,
        max_wait_ms: float = 2.0,
        metrics: Optional[MetricsRegistry] = None,
        log_interval_s: float = 10.0,
    ):
        self._fitted = fitted
        if max_queue < 1:
            # Queue(maxsize=0) would mean UNBOUNDED in python — the exact
            # opposite of the backpressure contract
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        # the per-item serving contract: explicit args win; otherwise fall
        # back to what the pipeline recorded at fit time, so a warm-up-able
        # engine needs no out-of-band shape plumbing (replica.py holds the
        # shared resolution + batch-coupled rejection)
        datum_shape, dtype = serving_contract(fitted, datum_shape, dtype)
        self._policy = BucketPolicy(buckets, datum_shape, dtype)
        self._metrics = metrics or MetricsRegistry()
        # Strict compile: fail at construction, naming the blocking node,
        # rather than degrading per-call under traffic. The jit is PRIVATE
        # to this engine — fitted.compile() would hijack the pipeline's own
        # compiled state, letting unrelated apply_compiled/apply_chunked
        # calls pollute this engine's compile accounting (and a second
        # engine discard the first's warm cache). Every XLA trace — one per
        # distinct padded shape — records its signature and bumps the
        # "compiles" counter, the invariant the bucket policy protects.
        # With an AOT executable cache configured (KEYSTONE_AOT_CACHE /
        # --aot-cache), each bucket shape first tries to LOAD a previously
        # exported executable — a warm boot pays ZERO traces ("aot_loads"
        # counts them) — and a miss traces once, then exports for the next
        # process.
        self._compiled_signatures: list = []
        # the worker loop itself lives in replica.py (shared with the
        # fleet); the engine keeps its classic gather-then-dispatch
        # batching as this replica's batch source
        self._replica = Replica(
            self._compile_for(fitted),
            self._policy,
            self._metrics,
            span_name="serve.microbatch",
            log_interval_s=log_interval_s,
        )
        self._queue: "queue.Queue[_Request]" = queue.Queue(maxsize=max_queue)
        self._max_wait = max_wait_ms / 1000.0
        self._log_interval = log_interval_s
        # orders every admission against the _closed flip in drain/shutdown:
        # a put either completes before _closed is set (and is answered by
        # the drain) or observes _closed and is rejected — no request can
        # land in the queue after the post-join sweep
        self._admit_lock = threading.Lock()
        # serializes start/drain/shutdown against each other (e.g. an
        # atexit handler racing the context manager's __exit__)
        self._lifecycle_lock = threading.RLock()
        self._closed = False
        self._abort = False
        self._stop = False
        self._ran = False  # distinguishes never-started from shut-down
        self._thread: Optional[threading.Thread] = None
        self._metrics.set_gauge("queue_depth", self._queue.qsize)

    def _compile_for(self, fitted: FittedPipeline):
        """Strictly compile ``fitted`` against this engine's private trace
        accounting (the ``compiles`` counter + signature list): the
        constructor's compile path, shared by :meth:`swap` so a replacement
        model's traces are audited exactly like the original's. The jit is
        PRIVATE to this engine — see :func:`.replica.compile_pipeline`."""
        return compile_pipeline(
            fitted,
            metrics=self._metrics,
            signatures=self._compiled_signatures,
            label="serving",
        )

    @property
    def _compiled(self):
        return self._replica.compiled

    @property
    def metrics(self) -> MetricsRegistry:
        return self._metrics

    @property
    def policy(self) -> BucketPolicy:
        return self._policy

    @property
    def compiled_signatures(self) -> list:
        """``(shape, dtype)`` of every trace this engine's jit paid, in
        compile order — len() equals the metrics ``compiles`` counter."""
        return list(self._compiled_signatures)

    # -- lifecycle ------------------------------------------------------

    def warm_up(self, required: bool = True) -> int:
        """Run one zero batch per bucket through the compiled fn, paying
        (or — with an AOT cache — loading) every bucket's executable
        before traffic. Returns buckets warmed.

        ``required=True`` (the default, and any direct call) RAISES when
        warm-up is impossible because no datum shape is known — a service
        that asked to pre-pay its compiles must not silently boot cold and
        pay them under traffic. ``required=False`` (``start()``'s
        best-effort default) downgrades that to the old warning + 0."""
        import jax

        if self._policy.datum_shape is None:
            if required:
                raise ValueError(
                    "warm-up requested but impossible: no datum shape is "
                    "known — pass datum_shape= to the engine, or fit the "
                    "pipeline through and_then(estimator, data) so the "
                    "contract is recorded on the FittedPipeline"
                )
            logger.warning(
                "serving warm-up skipped: no datum_shape configured — the "
                "first live batch of each bucket will pay its compile"
            )
            return 0
        n = 0
        for x in self._policy.warmup_inputs():
            jax.block_until_ready(self._compiled(x))
            n += 1
        logger.info(
            "serving warm-up: %d bucket(s) %s ready (%d traced, %d loaded "
            "from the AOT cache)",
            n, self._policy.batch_sizes,
            self._metrics.count("compiles"), self._metrics.count("aot_loads"),
        )
        return n

    def start(self, warmup: Optional[bool] = None) -> "ServingEngine":
        """Start the worker. ``warmup=None`` (default) warms up when the
        datum shape is known and skips with a warning otherwise;
        ``warmup=True`` demands it (raises if impossible); ``warmup=False``
        boots cold."""
        with self._lifecycle_lock:
            if self._thread is not None:
                raise RuntimeError("engine already started")
            if self._closed:
                raise EngineClosed("engine was shut down")
            if warmup or warmup is None:
                self.warm_up(required=warmup is True)
            self._thread = threading.Thread(
                target=self._worker_main,
                name="keystone-serving-worker",
                daemon=True,
            )
            self._thread.start()
            self._ran = True
        return self

    def _worker_main(self) -> None:
        """The worker thread body. The single-worker engine has no
        supervisor, so a loop-escaping death (an injected
        :class:`~keystone_tpu.faults.ReplicaKilled`, interpreter
        teardown) must at least fail the queue typed instead of
        stranding every queued future forever."""
        try:
            self._replica.serve_forever(_GatherSource(self))
        except BaseException as e:  # noqa: BLE001 — last-resort backstop
            logger.exception(
                "serving engine: worker thread died — closing admission "
                "and failing queued requests (a ServingFleet would have "
                "restarted it)"
            )
            try:
                # close FIRST: with no consumer left, a later submit
                # would strand its future and a drain-shutdown would
                # deadlock on queue.join() — the _admit_lock ordering
                # guarantees every request either lands before this flip
                # (swept below) or is typed-refused at submit
                with self._admit_lock:
                    self._closed = True
                for r in getattr(e, "pending", None) or []:
                    settle_future(
                        r.future, EngineStopped("engine worker died")
                    )
                self._reject_queued("engine worker died")
            except Exception:
                logger.exception(
                    "engine worker death cleanup failed; queued requests "
                    "may be stranded"
                )

    def swap(self, fitted: FittedPipeline, *, warmup: Optional[bool] = None) -> int:
        """Atomically replace the served model with ``fitted`` — the
        publish step of an incremental refit (``FittedPipeline.absorb``).

        The replacement compiles strictly and pre-warms every bucket OFF
        the serving path (with an AOT cache configured the warmed buckets
        load, zero traces); only then does the engine's dispatch reference
        flip — one atomic store, read once per micro-batch at dispatch
        time. No request is ever dropped: every batch runs whole on
        exactly one executable (whichever the worker reads when it
        dispatches — a batch gathered just before the flip may run on the
        new model), and admission never pauses.

        The new pipeline must satisfy the engine's existing datum contract
        (shape + dtype) and bucket policy — re-bucketing a live engine is
        a restart, not a swap. ``warmup`` follows :meth:`start`'s
        semantics: None warms when the shape is known, True demands it,
        False flips cold (the first batch per bucket pays its compile).
        Returns the number of buckets warmed.
        """
        check_swap_contract(fitted, self._policy)
        cur_shape = self._policy.datum_shape
        with self._lifecycle_lock:
            if self._closed:
                raise EngineClosed("engine is draining / shut down")
            compiles_before = self._metrics.count("compiles")
            loads_before = self._metrics.count("aot_loads")
            compiled = self._compile_for(fitted)
            warmed = 0
            if (warmup or warmup is None) and cur_shape is not None:
                import jax

                for x in self._policy.warmup_inputs():
                    jax.block_until_ready(compiled(x))
                    warmed += 1
            elif warmup is True:
                raise ValueError(
                    "swap(warmup=True) but no datum shape is known — the "
                    "engine cannot pre-pay the replacement's compiles"
                )
            # THE swap: one reference store, read once per batch by the
            # worker at dispatch time — each batch runs whole on exactly
            # one executable, never a mix
            self._replica.flip(compiled)
            self._fitted = fitted
            self._metrics.inc("swaps")
            tracer = _trace_current()
            if tracer is not None:
                with tracer.span(
                    "serve.swap",
                    op_type="ServingEngine",
                    buckets_warmed=warmed,
                    compiles=self._metrics.count("compiles") - compiles_before,
                    aot_loads=self._metrics.count("aot_loads") - loads_before,
                    queue_depth=self._queue.qsize(),
                    live=self._thread is not None,
                ):
                    pass
            logger.info(
                "serving swap: model replaced (%d bucket(s) warmed, "
                "%d traced, %d AOT-loaded; queue depth %d)",
                warmed,
                self._metrics.count("compiles") - compiles_before,
                self._metrics.count("aot_loads") - loads_before,
                self._queue.qsize(),
            )
            return warmed

    def drain(self) -> None:
        """Stop admitting, answer every queued request, stop the worker.
        Equivalent to ``shutdown(drain=True)`` — a drained engine must not
        leave its worker polling an empty queue for the process lifetime."""
        self.shutdown(drain=True)

    def shutdown(self, drain: bool = True) -> None:
        """Stop the engine. ``drain=True`` answers queued requests first;
        ``drain=False`` fails them with :class:`EngineClosed`. Idempotent
        and safe to call from multiple threads."""
        with self._lifecycle_lock:
            with self._admit_lock:
                self._closed = True
            if self._thread is None:
                self._reject_queued(
                    "engine is shut down" if self._ran else "engine never started"
                )
                return
            if drain:
                self._queue.join()
            else:
                self._abort = True
            self._stop = True
            self._thread.join()
            self._thread = None
            # _admit_lock ordered every put against the _closed flip above,
            # so nothing can land after this point; the sweep is a belt-and-
            # braces guarantee no admitted request is ever left unanswered.
            self._reject_queued()

    def _reject_queued(self, reason: str = "engine is shut down") -> None:
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                return
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(EngineStopped(reason))
            self._queue.task_done()

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown(drain=True)

    # -- admission ------------------------------------------------------

    def submit(self, datum: Any, timeout: Optional[float] = None) -> Future:
        """Enqueue one datum; returns a Future of its prediction row.

        ``timeout`` (seconds) is the request's deadline: if the batch it
        would join runs after the deadline, the Future fails with
        :class:`DeadlineExceeded`. Raises :class:`QueueFull` immediately
        when the admission queue is at capacity."""
        now = time.monotonic()
        req = _Request(
            datum=datum,
            deadline=(now + timeout) if timeout is not None else None,
            enqueued=now,
        )
        with self._admit_lock:
            if self._closed:
                raise EngineStopped("engine is draining / shut down")
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                self._metrics.inc("rejected")
                raise QueueFull(
                    f"admission queue at capacity ({self._queue.maxsize})"
                ) from None
        self._metrics.inc("submitted")
        return req.future

    def predict(self, datum: Any, timeout: Optional[float] = None) -> Any:
        """Synchronous convenience: submit + wait for the result.

        On a STARTED engine every admitted request reaches a terminal
        state — a result or a typed :mod:`~keystone_tpu.serving.errors`
        exception (deadline expiry is decided by the worker at batch
        time; shutdown sweeps the queue) — so this waits without its own
        deadline. A compile in flight can legitimately hold a
        first-of-bucket request for tens of seconds; warm up to avoid
        that. ``submit()`` MAY buffer before ``start()`` (the futures
        resolve once the worker runs), but a synchronous wait then has
        nothing to wake it, so this raises instead."""
        if self._thread is None:
            raise RuntimeError(
                "predict() needs a started engine (call start() or use "
                "the context manager); submit() may buffer before start"
            )
        return self.submit(datum, timeout=timeout).result()

    def _fail_and_drain(self, first: _Request) -> None:
        """Abortive shutdown: answer everything queued with EngineClosed."""
        reqs = [first]
        while True:
            try:
                reqs.append(self._queue.get_nowait())
            except queue.Empty:
                break
        for r in reqs:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(EngineClosed("engine aborted"))
            self._queue.task_done()


class _GatherSource:
    """The engine's classic batching policy as a replica batch source:
    block for the first queued request, then gather more until the
    largest bucket is full or ``max_wait_ms`` elapses — the original
    gather-then-dispatch loop, verbatim. (The fleet's continuous-batching
    scheduler is the other implementation of this protocol.)"""

    def __init__(self, engine: ServingEngine):
        self._engine = engine

    def next_batch(self, replica):
        e = self._engine
        try:
            first = e._queue.get(timeout=0.05)
        except queue.Empty:
            return STOP if e._stop else None
        if e._abort:
            e._fail_and_drain(first)
            return None
        batch = [first]
        gather_until = time.monotonic() + e._max_wait
        while len(batch) < e._policy.max_size:
            remaining = gather_until - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(e._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def batch_done(self, batch, replica) -> None:
        for _ in batch:
            self._engine._queue.task_done()
