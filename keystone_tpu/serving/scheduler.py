"""The fleet's admission surface and continuous-batching dispatcher.

One :class:`FleetScheduler` sits between N submitter threads and N
:class:`~keystone_tpu.serving.replica.Replica` workers. It replaces the
single engine's gather-then-dispatch loop with **continuous batching**:
a replica that frees up immediately starts forming its next micro-batch
from whatever is queued NOW, and requests that arrive while the batch is
forming join it — admission never waits for a batch boundary, and a
batch never waits for a worker.

Three disciplines, all under one lock (two replicas on two shared vCPUs
do not need finer granularity; the hold times are microseconds):

* **Deadline-aware admission.** A request whose deadline cannot be met —
  ``now + estimated_wait > deadline``, where the estimate is the learned
  EWMA of batch service time scaled by the queue depth ahead of the
  request — is refused with a typed :class:`Shed` BEFORE it occupies a
  queue slot or device time. Shedding at admission is strictly better
  than the engine's expiry-at-batch-time (which still runs the queue
  ahead of the doomed request); the fleet keeps both: admission sheds
  what it can predict, the replica expires what it could not. With no
  service evidence yet the scheduler never sheds (it cannot justify
  refusing work it knows nothing about).

* **Occupancy-maximizing dispatch.** A free replica pops its queue and
  keeps gathering until the forming batch exactly fills its bucket
  (occupancy 1.0), the ``max_wait`` window closes, or the tightest
  deadline in the batch says further waiting would expire it —
  whichever comes first. That picks the largest bucket the traffic and
  the deadlines allow, instead of always padding to whatever happened to
  be queued.

* **Work stealing.** Admission places each request on the shallowest
  per-replica queue, but replicas drain at different rates (a 64-bucket
  batch on one, singles on another). A replica whose own queue is empty
  steals the newest half of the deepest peer's queue — the victim keeps
  its oldest (tightest-deadline) work, the thief takes the back of the
  line — so one stalled replica's bucket mix cannot idle the rest of the
  fleet.

QoS (``keystone_tpu/autoscale/qos.py``) rides all three: each request
carries a ``priority`` and a ``tenant``. Admission prices a request's
wait against only the queue depth at its priority OR BETTER — exact
here, because the scheduler owns its queues — so at equal deadline
slack low sheds strictly before high, and a cold scheduler still never
sheds. The per-replica queues are :class:`WeightedFairQueue` s: deficit
round-robin serves tenants proportionally to weight instead of FIFO
(the batch-service EWMA prices each turn's worth identically across
tenants, so share-of-requests IS share-of-service), and requeue/steal/
hop machinery preserves both identities because they live on the
request itself.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..autoscale.qos import (
    PRIORITIES,
    PRIORITY_RANK,
    WeightedFairQueue,
    request_rank,
    request_tenant,
)
from ..obs.tracer import current as _trace_current
from .batching import BucketPolicy
from .errors import EngineStopped, QueueFull, Shed
from .metrics import MetricsRegistry
from .replica import STOP, _Request, settle_future


def _chain_futures(clone, orig) -> None:
    """Forward a requeued clone's outcome to the original future. The
    original may already be marked RUNNING (it was popped into the batch
    the dead replica never finished), so it cannot simply re-enter a
    queue — a fresh request carries the datum, and the answer flows back
    here."""

    def _copy(done):
        if orig.done():
            return
        try:
            if done.cancelled():
                orig.cancel()
                return
            exc = done.exception()
            if exc is not None:
                orig.set_exception(exc)
            else:
                orig.set_result(done.result())
        except Exception:  # lint: allow-silent -- lost a race with
            pass           # another settler: the designed outcome

    clone.add_done_callback(_copy)

logger = logging.getLogger(__name__)

#: EWMA smoothing for the learned batch service time: heavy enough to
#: follow a swap to a slower model within a few batches, light enough
#: that one straggler batch does not triple the shed threshold
_SERVICE_ALPHA = 0.3


class ServiceEstimate:
    """The learned batch-service-time EWMA and its admission pricing —
    the ONE deadline-shedding discipline, shared by every admission
    surface: the in-process :class:`FleetScheduler` below and the
    cluster router (:mod:`keystone_tpu.cluster.router`), which prices
    front-door shedding from aggregate queue depth ÷ fleet capacity with
    exactly this object. Not thread-safe on its own; callers fold
    observations under their admission lock (a torn float read on the
    lock-free paths is harmless — the EWMA converges regardless)."""

    def __init__(self, alpha: float = _SERVICE_ALPHA):
        self._alpha = alpha
        self._ewma: Optional[float] = None

    @property
    def estimate(self) -> Optional[float]:
        """Learned seconds per micro-batch, None before any evidence."""
        return self._ewma

    def observe(self, seconds: float) -> None:
        prev = self._ewma
        self._ewma = (
            seconds if prev is None
            else prev + self._alpha * (seconds - prev)
        )

    def wait(self, depth: int, capacity: int) -> float:
        """Deterministic completion estimate for a request admitted NOW:
        its own batch's service time plus the whole batches already
        queued ahead of it (``depth`` requests over ``capacity`` rows of
        concurrent batch capacity). Zero before any evidence — a cold
        admission surface must not shed traffic it cannot price."""
        s = self._ewma
        if s is None:
            return 0.0
        return s * (1 + depth // max(int(capacity), 1))

    #: fraction of one learned batch-service time a coalescer may spend
    #: holding a partial frame open: small enough that the added wait
    #: disappears inside the service time it amortizes against
    COALESCE_FRACTION = 0.25

    def coalesce_window(
        self,
        now: float,
        tightest_deadline: Optional[float] = None,
        cap: float = 0.002,
    ) -> float:
        """Max seconds the router's front-door coalescer may hold an
        already-started frame open for more members — the same evidence
        the shed path prices from, pointed at batching instead of
        refusal. Three ceilings, all of them protective:

        * a FRACTION of the learned batch-service EWMA (waiting longer
          than the work itself takes can only hurt p99);
        * ``cap`` — the operator's absolute bound (the router passes its
          ``max_wait_ms``, the same knob that bounds worker-side batch
          gathering);
        * the tightest member deadline minus one service time — the
          frame must still be SERVABLE for its most impatient member
          when the window closes.

        Zero before any evidence: a cold coalescer, like a cold shedder,
        never delays traffic it cannot price."""
        s = self._ewma
        if s is None:
            return 0.0
        w = min(float(cap), self.COALESCE_FRACTION * s)
        if tightest_deadline is not None:
            w = min(w, tightest_deadline - now - s)
        return max(0.0, w)


class FleetScheduler:
    """Shared admission queue + per-replica run queues for N replicas."""

    def __init__(
        self,
        n_replicas: int,
        policy: BucketPolicy,
        metrics: MetricsRegistry,
        *,
        max_queue: int = 1024,
        max_wait_ms: float = 2.0,
        steal: bool = True,
        tenant_weights: Optional[Dict[str, float]] = None,
    ):
        if n_replicas < 1:
            raise ValueError(f"need at least one replica, got {n_replicas}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self._n = n_replicas
        self._policy = policy
        self._metrics = metrics
        self._max_queue = max_queue
        self._max_wait = max_wait_ms / 1000.0
        self._steal = steal
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        #: configured tenant -> weight (unlisted tenants weigh 1.0)
        self._tenant_weights = dict(tenant_weights or {})
        #: per-replica run queues: weighted-fair across tenants (DRR),
        #: priority-ordered within one tenant — deque-compatible, so the
        #: steal/requeue machinery below drives them unchanged
        self._queues: List[WeightedFairQueue] = [
            WeightedFairQueue(self._tenant_weights)
            for _ in range(n_replicas)
        ]
        #: replica liveness, maintained by the fleet's supervisor: a dead
        #: (restart-budget-exhausted) replica stops receiving admissions
        self._active: List[bool] = [True] * n_replicas
        self._depth = 0  # total queued across all replica queues
        self._in_flight = 0  # batches handed to replicas, not yet done
        self._closed = False  # no further admission
        self._stop = False  # workers should exit
        self._service = ServiceEstimate()

    # -- introspection ---------------------------------------------------

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    @property
    def service_estimate(self) -> Optional[float]:
        """Learned seconds per micro-batch (EWMA), None before evidence."""
        return self._service.estimate

    def queue_depths(self) -> List[int]:
        with self._lock:
            return [len(q) for q in self._queues]

    def qos_snapshot(self) -> Dict[str, object]:
        """Point-in-time QoS view of the queues: per-tenant queued depth
        and configured weight, plus queued count per priority class —
        the fleet/router status surfaces render this directly."""
        with self._lock:
            tenants: Dict[str, Dict[str, float]] = {}
            by_rank = [0] * len(PRIORITIES)
            for q in self._queues:
                for t, n in q.tenant_depths().items():
                    row = tenants.setdefault(
                        t, {"queued": 0, "weight": q.weight(t)}
                    )
                    row["queued"] += n
                for rank, n in enumerate(q.rank_lens()):
                    by_rank[rank] += n
            for t, w in self._tenant_weights.items():
                tenants.setdefault(t, {"queued": 0, "weight": w})
            return {
                "tenants": tenants,
                "queued_by_priority": {
                    p: by_rank[PRIORITY_RANK[p]] for p in PRIORITIES
                },
            }

    # -- service-time learning -------------------------------------------

    def observe_service(self, seconds: float) -> None:
        """Fold one measured batch execution into the service EWMA (also
        the seam tests and benches use to seed a known estimate)."""
        self._service.observe(seconds)

    def estimated_wait(self, rank: Optional[int] = None) -> float:
        """Deterministic completion estimate for a request admitted NOW
        (see :meth:`ServiceEstimate.wait`) across the fleet's capacity.

        ``rank`` (a priority rank, 0 best) prices only the queue depth
        at that priority or better — the depth that actually outranks
        the request under priority-ordered dispatch. This is what makes
        the shed ordering deterministic: low pays for everything queued,
        high only for its own class, so at equal deadline slack low
        sheds strictly first. ``None`` keeps the aggregate estimate."""
        if rank is None:
            depth = self._depth
        else:
            depth = 0
            for q in self._queues:
                lens = q.rank_lens()
                depth += sum(lens[: rank + 1])
        return self._service.wait(depth, self._n * self._policy.max_size)

    def _rank_waits(self) -> List[float]:
        """``estimated_wait`` per priority rank, computed once for the
        requeue sweeps (lock held)."""
        return [
            self.estimated_wait(rank) for rank in range(len(PRIORITIES))
        ]

    # -- admission -------------------------------------------------------

    def admit(self, req: _Request) -> None:
        """Place one request, or raise typed: :class:`EngineStopped` after
        close, :class:`QueueFull` at capacity, :class:`Shed` when the
        deadline is unmeetable. The closed-check and the enqueue are one
        atomic step — a request either lands before the close (and is
        answered by the drain) or gets the typed error, never stranded."""
        with self._cond:
            if self._closed:
                raise EngineStopped("fleet is draining / shut down")
            if self._depth >= self._max_queue:
                self._metrics.inc("rejected")
                raise QueueFull(
                    f"admission queue at capacity ({self._max_queue})"
                )
            if req.deadline is not None:
                est = self.estimated_wait(request_rank(req))
                if time.monotonic() + est > req.deadline:
                    self._metrics.inc("shed")
                    self._metrics.inc(f"shed.{req.priority}")
                    raise Shed(
                        f"deadline unmeetable at admission: estimated wait "
                        f"{est:.4f}s (at priority {req.priority!r}) exceeds "
                        f"the request's "
                        f"{max(req.deadline - time.monotonic(), 0):.4f}s budget"
                    )
            # shallowest LIVE queue: depth-balanced placement; drain-rate
            # imbalance is work-stealing's job, not admission's
            live = [i for i in range(self._n) if self._active[i]]
            if not live:
                raise EngineStopped(
                    "no live replicas (every worker is down and the "
                    "restart budget is exhausted)"
                )
            target = min(live, key=lambda i: len(self._queues[i]))
            self._queues[target].append(req)
            self._depth += 1
            # counted here, under the lock, so a snapshot can never
            # observe a request completed before it was submitted
            self._metrics.inc("submitted")
            self._cond.notify_all()

    # -- dispatch (replica batch source protocol) ------------------------

    def next_batch(self, replica):
        """Form the next micro-batch for ``replica`` — continuous
        batching: start from its own queue (stealing when empty), then
        keep admitting arrivals into the forming batch until the bucket
        is exactly full, ``max_wait`` closes, or the tightest deadline
        forces dispatch."""
        t0 = time.monotonic()
        with self._cond:
            while True:
                if self._stop:
                    return STOP
                stolen = self._maybe_steal(replica.index)
                own = self._queues[replica.index]
                if own:
                    break
                # idle (including the drained-and-closed case): poll so
                # the final STOP is observed promptly
                self._cond.wait(timeout=0.05)
            batch = self._gather(replica.index)
            self._in_flight += 1
        tracer = _trace_current()
        if tracer is not None:
            bucket = self._policy.bucket_for(len(batch))
            tracer.instant(
                "serve.dispatch",
                op_type="FleetScheduler",
                replica=replica.index,
                items=len(batch),
                bucket=bucket,
                occupancy=round(len(batch) / bucket, 3),
                stolen=stolen,
                waited_ms=round((time.monotonic() - t0) * 1e3, 3),
                queue_depth=self._depth,
            )
        return batch

    def batch_done(self, batch, replica) -> None:
        exec_s = replica.last_exec_seconds
        with self._cond:
            self._in_flight -= 1
            if exec_s is not None:
                self.observe_service(exec_s)
            self._cond.notify_all()

    def _gather(self, index: int) -> List[_Request]:
        """Pop the forming batch from queue ``index`` (lock held). Waits
        for further arrivals only while (a) the forming batch does not
        yet fill its bucket exactly, (b) the max-wait window is open, and
        (c) every gathered deadline still affords the wait."""
        own = self._queues[index]
        batch = [own.popleft()]
        self._depth -= 1
        gather_until = time.monotonic() + self._max_wait
        while len(batch) < self._policy.max_size:
            while own and len(batch) < self._policy.max_size:
                batch.append(own.popleft())
                self._depth -= 1
            bucket = self._policy.bucket_for(len(batch))
            if len(batch) == bucket:
                break  # exactly full: occupancy 1.0, nothing to wait for
            now = time.monotonic()
            wait_budget = gather_until - now
            # the service estimate is how long the batch will take once
            # dispatched; waiting may only consume slack beyond that
            exec_s = self._service.estimate or 0.0
            for r in batch:
                if r.deadline is not None:
                    wait_budget = min(
                        wait_budget, r.deadline - now - exec_s
                    )
            if wait_budget <= 0:
                break
            if not self._cond.wait(timeout=wait_budget):
                # window closed with no arrival: dispatch what we have
                if not own:
                    break
        served: Dict[str, int] = {}
        for r in batch:
            t = request_tenant(r)
            served[t] = served.get(t, 0) + 1
        for t, n in served.items():
            # per-tenant service counters: what the QoS status view's
            # share column renders, summable across worker processes
            self._metrics.inc(f"tenant.served.{t}", n)
        return batch

    def _maybe_steal(self, index: int) -> int:
        """With queue ``index`` empty, move the newest half of the deepest
        peer queue over (lock held). Returns requests moved."""
        if not self._steal or self._queues[index]:
            return 0
        victim = max(
            (i for i in range(self._n) if i != index),
            key=lambda i: len(self._queues[i]),
            default=None,
        )
        if victim is None or not self._queues[victim]:
            return 0
        vq = self._queues[victim]
        take = len(vq) // 2 or 1
        # steal from the BACK: the victim keeps its oldest (tightest-
        # deadline) requests in FIFO order; the thief takes the newest
        moved = [vq.pop() for _ in range(take)]
        self._queues[index].extend(reversed(moved))
        self._metrics.inc("steals", take)
        return take

    # -- replica supervision (fleet failure recovery) --------------------

    def set_active(self, index: int, active: bool) -> None:
        """Mark one replica live/dead for admission placement (the fleet
        supervisor flips this around deaths and restarts)."""
        with self._cond:
            self._active[index] = bool(active)
            self._cond.notify_all()

    def any_active(self) -> bool:
        with self._cond:
            return any(self._active)

    def _shed_requeued(self, req: _Request, est: float, now: float) -> None:
        self._metrics.inc("shed")
        self._metrics.inc(f"shed.{getattr(req, 'priority', 'normal')}")
        settle_future(
            req.future,
            Shed(
                f"deadline unmeetable after replica failure: estimated "
                f"wait {est:.4f}s exceeds the request's remaining "
                f"{max(req.deadline - now, 0):.4f}s budget"
            ),
        )

    def requeue_replica(self, index: int, keep_if_no_peer: bool = False) -> int:
        """Move a down replica's QUEUED requests to live peers, deadlines
        intact. A request whose deadline the learned estimate says can no
        longer be met is answered with a typed :class:`Shed` here, not
        left to expire silently replica-side. With no live peer:
        ``keep_if_no_peer`` leaves the queue in place (the replica is
        about to restart), else the requests fail typed. Returns the
        count moved."""
        with self._cond:
            q = self._queues[index]
            if not q:
                return 0
            reqs = list(q)
            q.clear()
            now = time.monotonic()
            ests = self._rank_waits()
            peers = [
                i for i in range(self._n) if self._active[i] and i != index
            ]
            moved = 0
            for req in reqs:
                if req.future.done():
                    self._depth -= 1
                    continue
                est = ests[request_rank(req)]
                if req.deadline is not None and now + est > req.deadline:
                    self._depth -= 1
                    self._shed_requeued(req, est, now)
                    continue
                if peers:
                    target = min(peers, key=lambda i: len(self._queues[i]))
                    self._queues[target].append(req)
                    moved += 1
                elif keep_if_no_peer:
                    q.append(req)
                else:
                    self._depth -= 1
                    settle_future(
                        req.future,
                        EngineStopped(
                            "no live replicas to take over this request"
                        ),
                    )
            if moved:
                self._metrics.inc("requeues", moved)
            self._cond.notify_all()
        return moved

    #: a request rerouted off this many failed replicas stops bouncing
    #: and is answered with the failure instead — the bound that keeps a
    #: deadline-less request from livelocking across a recurring fault
    MAX_REQUEUE_HOPS = 3

    def requeue_batch(self, requests, replica, cause=None) -> int:
        """Re-admit a dead/faulted replica's IN-FLIGHT requests. Their
        futures may already be marked running, so each request re-enters
        as a fresh clone whose outcome chains back to the original;
        deadlines and enqueue times carry over unchanged (satellite
        contract: rerouting never extends a deadline). Unmeetable
        deadlines get the typed :class:`Shed`; a request already
        rerouted :data:`MAX_REQUEUE_HOPS` times is answered with
        ``cause`` (the failure that keeps chasing it) instead of
        bouncing forever; the rest land at the FRONT of the shallowest
        live peer queue (they are the oldest work in the system).
        Returns the count requeued."""
        index = getattr(replica, "index", None)
        fail_exc = (
            cause if isinstance(cause, Exception)
            else EngineStopped("request lost its replica repeatedly")
        )
        with self._cond:
            now = time.monotonic()
            ests = self._rank_waits()
            peers = [
                i for i in range(self._n) if self._active[i] and i != index
            ]
            moved = 0
            # appendleft reverses, so walk the batch back-to-front to
            # keep the original FIFO order at the head of the queue
            for req in reversed(list(requests)):
                if req.future.done():
                    continue
                est = ests[request_rank(req)]
                if req.deadline is not None and now + est > req.deadline:
                    self._shed_requeued(req, est, now)
                    continue
                if req.hops >= self.MAX_REQUEUE_HOPS:
                    settle_future(req.future, fail_exc)
                    continue
                if peers:
                    target = min(peers, key=lambda i: len(self._queues[i]))
                elif index is not None and self._active[index]:
                    target = index  # restarting in place: retry locally
                else:
                    settle_future(
                        req.future,
                        EngineStopped(
                            "no live replicas to take over this request"
                        ),
                    )
                    continue
                clone = _Request(
                    datum=req.datum, deadline=req.deadline,
                    enqueued=req.enqueued, hops=req.hops + 1,
                    trace=req.trace,  # the retry keeps its identity
                    priority=req.priority, tenant=req.tenant,
                )
                _chain_futures(clone.future, req.future)
                self._queues[target].appendleft(clone)
                self._depth += 1
                moved += 1
            if moved:
                self._metrics.inc("requeues", moved)
            self._cond.notify_all()
        return moved

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Stop admission (submits now raise EngineStopped). Queued and
        in-flight work keeps draining."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every queued request has been dispatched AND every
        in-flight batch has completed. True on idle, False on timeout."""
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        with self._cond:
            while self._depth > 0 or self._in_flight > 0:
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(timeout=remaining if remaining else 0.1)
            return True

    def stop(self) -> None:
        """Tell every worker's next ``next_batch`` to return STOP."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()

    def fail_remaining(self, reason: str = "fleet is shut down") -> int:
        """Answer everything still queued with :class:`EngineStopped`
        (the abortive-shutdown path and the post-join sweep). Returns
        requests failed."""
        with self._cond:
            remaining: List[_Request] = []
            for q in self._queues:
                remaining.extend(q)
                q.clear()
            self._depth = 0
            self._cond.notify_all()
        for r in remaining:
            if r.future.set_running_or_notify_cancel():
                r.future.set_exception(EngineStopped(reason))
        return len(remaining)
