"""Autoscaling + QoS: the actuator half of the serving control loop.

The observation half already exists — ``serving/slo.py`` judges the
metrics timeline into typed breach rows, the flight ring and
``ClusterRouter.status()`` carry them. This package ACTS on that
evidence:

* :mod:`~keystone_tpu.autoscale.qos` — the priority vocabulary
  (``high``/``normal``/``low``: the shedding axis) and the per-tenant
  :class:`WeightedFairQueue` (deficit round-robin: the fairness axis)
  the fleet scheduler's queues are built from.
* :mod:`~keystone_tpu.autoscale.policy` — :class:`ScalePolicy`, the
  declarative bounds (min/max workers, cooldowns, breach hysteresis).
* :mod:`~keystone_tpu.autoscale.scaler` — :class:`Autoscaler`, riding
  the cluster router's health loop: breach rows + timeline deltas in,
  policy-bounded spawn/drain decisions out, every decision a typed
  timeline row + flight instant + ``scale.*`` trace span.
"""

from .policy import ScalePolicy
from .qos import (
    DEFAULT_PRIORITY,
    DEFAULT_TENANT,
    PRIORITIES,
    PRIORITY_RANK,
    SHED_BIAS,
    WeightedFairQueue,
    normalize_priority,
)
from .scaler import Autoscaler, ScaleDecision

__all__ = [
    "Autoscaler",
    "DEFAULT_PRIORITY",
    "DEFAULT_TENANT",
    "PRIORITIES",
    "PRIORITY_RANK",
    "SHED_BIAS",
    "ScaleDecision",
    "ScalePolicy",
    "WeightedFairQueue",
    "normalize_priority",
]
