"""QoS primitives: the priority vocabulary and the weighted-fair queue.

Two axes, deliberately orthogonal:

* **Priority** (``high`` / ``normal`` / ``low``) is the SHEDDING axis.
  Admission prices a request's wait against its deadline, and priority
  decides how much of the queue ahead it must pay for: the in-process
  scheduler counts only same-or-higher-priority depth (exact — it owns
  the queues), the cluster front door scales its aggregate estimate by
  :data:`SHED_BIAS` (coarse — outstanding work is already inside worker
  processes). Both orderings are deterministic: at equal deadline slack
  a low request always sheds before a high one, because low pays for
  strictly more queue (or a strictly larger bias) than high does.
* **Tenant** is the FAIRNESS axis. Each per-replica queue is a
  :class:`WeightedFairQueue`: deficit round-robin across tenants, so
  ``next_batch``/``_gather`` serves tenants proportionally to weight
  instead of FIFO — one hot tenant can saturate its share, never the
  fleet. Within a tenant, dispatch is priority-ordered (high first);
  across tenants, priority does NOT jump the fairness schedule — that
  is what keeps a tenant from buying the whole fleet by marking
  everything ``high``.

The queue is deque-compatible on purpose: the fleet scheduler's
admission / gather / steal / requeue machinery drives it through the
same ``append`` / ``appendleft`` / ``popleft`` / ``pop`` verbs it used
on plain deques, and every request object carries its own ``priority``
and ``tenant`` — so cloning, stealing, and requeueing preserve QoS
identity with no extra bookkeeping.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Iterator, List, Optional

#: the priority vocabulary, best first (also the dispatch order within
#: one tenant's lanes)
PRIORITIES = ("high", "normal", "low")

#: priority -> dispatch rank (0 serves first, sheds last)
PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}

DEFAULT_PRIORITY = "normal"
DEFAULT_TENANT = "default"

#: the cluster front door's admission bias: the aggregate-depth wait
#: estimate is scaled by this per priority. The router cannot see inside
#: its workers' queues, so the bias encodes what weighted-fair dispatch
#: will do to each class: high is served ahead of lower classes in its
#: tenant (it waits for less than the average), low is served last (it
#: waits for more). Monotone in rank, which is what makes the shed
#: ordering deterministic.
SHED_BIAS = {"high": 0.5, "normal": 1.0, "low": 1.5}


def normalize_priority(priority: Optional[str]) -> str:
    """The canonical priority string (``None`` -> ``normal``); raises
    ``ValueError`` on anything outside the vocabulary — a typo'd
    priority must fail the submit, not silently serve as normal."""
    if priority is None:
        return DEFAULT_PRIORITY
    p = str(priority).lower()
    if p not in PRIORITY_RANK:
        raise ValueError(
            f"unknown priority {priority!r} (expected one of {PRIORITIES})"
        )
    return p


def request_rank(req) -> int:
    """Dispatch rank of a request-like object (duck-typed: anything with
    an optional ``priority`` attr)."""
    return PRIORITY_RANK.get(
        getattr(req, "priority", DEFAULT_PRIORITY), PRIORITY_RANK["normal"]
    )


def request_tenant(req) -> str:
    return getattr(req, "tenant", DEFAULT_TENANT) or DEFAULT_TENANT


class WeightedFairQueue:
    """Deficit-round-robin queue over per-tenant priority lanes.

    Each tenant owns one deque per priority rank. ``popleft`` runs DRR:
    the tenant at the head of the round is charged one quantum per
    visit (its weight normalized by the largest active weight, so the
    heaviest tenant's quantum is exactly one request); a tenant whose
    deficit reaches 1.0 serves the head of its highest-priority
    non-empty lane and pays 1.0, otherwise it rotates to the back and
    keeps the deficit — over any window the served ratio converges to
    the weight ratio, deterministically (seeded tests assert the exact
    sequence). A tenant that empties leaves the round with its deficit
    forfeited: fairness shares the present backlog, it does not bank
    credit for traffic a tenant never offered.

    Deque-compat: ``append``/``appendleft`` place into the request's
    own (tenant, rank) lane; ``pop`` (the work-stealing verb) takes the
    newest request of the LOWEST-priority populated rank from its
    deepest tenant — the victim keeps its oldest, tightest work and its
    best traffic class; ``__iter__`` yields everything (requeue drains
    via ``list(q)``).
    """

    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        default_weight: float = 1.0,
    ):
        self._weights = {
            str(k): float(v) for k, v in (weights or {}).items()
        }
        for t, w in self._weights.items():
            if w <= 0:
                raise ValueError(
                    f"tenant {t!r} weight must be > 0, got {w}"
                )
        self._default_weight = float(default_weight)
        #: tenant -> one deque per priority rank
        self._lanes: Dict[str, List[deque]] = {}
        self._round: deque = deque()  # tenants holding DRR turns
        self._in_round: set = set()
        self._deficit: Dict[str, float] = {}
        self._charged: Dict[str, bool] = {}
        self._len = 0

    # -- weights ---------------------------------------------------------

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self._default_weight)

    def _quantum(self, tenant: str) -> float:
        mx = max(self.weight(t) for t in self._round)
        return self.weight(tenant) / mx

    # -- deque-compatible writes ----------------------------------------

    def _enter(self, req) -> List[deque]:
        tenant = request_tenant(req)
        lanes = self._lanes.get(tenant)
        if lanes is None:
            lanes = [deque() for _ in PRIORITIES]
            self._lanes[tenant] = lanes
        if tenant not in self._in_round:
            self._in_round.add(tenant)
            self._round.append(tenant)
            self._deficit.setdefault(tenant, 0.0)
            self._charged.setdefault(tenant, False)
        return lanes

    def append(self, req) -> None:
        self._enter(req)[request_rank(req)].append(req)
        self._len += 1

    def appendleft(self, req) -> None:
        """Front-of-line within the request's own (tenant, rank) lane —
        the requeue verb: rerouted work is the oldest in the system and
        must not re-pay the line, but it re-pays only ITS line, never
        another tenant's or a better class's."""
        self._enter(req)[request_rank(req)].appendleft(req)
        self._len += 1

    def extend(self, reqs) -> None:
        for r in reqs:
            self.append(r)

    # -- deque-compatible reads/removals --------------------------------

    def _retire(self, tenant: str) -> None:
        """Drop an emptied tenant from the round, deficit forfeited."""
        if self._round and self._round[0] == tenant:
            self._round.popleft()
        else:
            try:
                self._round.remove(tenant)
            except ValueError:
                pass  # lint: allow-silent -- already out of the round
        self._in_round.discard(tenant)
        self._deficit[tenant] = 0.0
        self._charged[tenant] = False

    @staticmethod
    def _pop_ranked(lanes: List[deque]):
        for lane in lanes:
            if lane:
                return lane.popleft()
        raise IndexError("pop from empty tenant lanes")

    def popleft(self):
        """DRR dispatch: the next request the fairness schedule owes."""
        if not self._len:
            raise IndexError("pop from an empty WeightedFairQueue")
        spins = 0
        while True:
            tenant = self._round[0]
            lanes = self._lanes[tenant]
            if not any(lanes):
                self._retire(tenant)
                continue
            if len(self._round) == 1:
                # sole active tenant: fairness is moot, serve directly
                # (and keep its deficit parked — no banking)
                self._len -= 1
                return self._pop_ranked(lanes)
            if not self._charged[tenant]:
                self._deficit[tenant] += self._quantum(tenant)
                self._charged[tenant] = True
            if self._deficit[tenant] >= 1.0 or spins > 64 * len(self._round):
                # the spin guard bounds pathological weight ratios; DRR
                # order is preserved for any sane (< ~1:64) spread
                self._deficit[tenant] = max(
                     0.0, self._deficit[tenant] - 1.0
                )
                self._charged[tenant] = False
                self._round.rotate(-1)
                self._len -= 1
                return self._pop_ranked(lanes)
            # insufficient deficit: keep it, yield the turn
            self._charged[tenant] = False
            self._round.rotate(-1)
            spins += 1

    def pop(self):
        """The work-stealing verb: newest request of the lowest-priority
        populated rank, from the tenant deepest in that rank — the
        victim keeps its oldest work and its best traffic class."""
        if not self._len:
            raise IndexError("pop from an empty WeightedFairQueue")
        for rank in range(len(PRIORITIES) - 1, -1, -1):
            best = None
            for tenant, lanes in self._lanes.items():
                if lanes[rank] and (
                    best is None
                    or len(lanes[rank]) > len(self._lanes[best][rank])
                ):
                    best = tenant
            if best is not None:
                self._len -= 1
                return self._lanes[best][rank].pop()
        raise IndexError("pop from an empty WeightedFairQueue")  # unreachable

    def clear(self) -> None:
        self._lanes.clear()
        self._round.clear()
        self._in_round.clear()
        self._deficit.clear()
        self._charged.clear()
        self._len = 0

    def __iter__(self) -> Iterator:
        for lanes in self._lanes.values():
            for lane in lanes:
                yield from lane

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __getitem__(self, index: int):
        """Positional peek in iteration order (tenant insertion order,
        priority-then-FIFO within each) — test/introspection seam, not a
        hot path."""
        n = self._len
        if index < 0:
            index += n
        if not 0 <= index < n:
            raise IndexError(index)
        for i, req in enumerate(self):
            if i == index:
                return req
        raise IndexError(index)  # unreachable: _len guards above

    # -- QoS introspection ----------------------------------------------

    def rank_lens(self) -> List[int]:
        """Queued count per priority rank (index = rank) — what the
        scheduler's priority-aware admission pricing sums."""
        out = [0] * len(PRIORITIES)
        for lanes in self._lanes.values():
            for rank, lane in enumerate(lanes):
                out[rank] += len(lane)
        return out

    def tenant_depths(self) -> Dict[str, int]:
        return {
            t: sum(len(lane) for lane in lanes)
            for t, lanes in self._lanes.items()
            if any(lanes)
        }
