"""The declarative bounds on fleet elasticity.

A :class:`ScalePolicy` is everything the operator gets to say about
scaling, and everything the :class:`~keystone_tpu.autoscale.Autoscaler`
is ALLOWED to do: hard worker-count bounds, breach-count hysteresis (one
noisy sample must not buy a worker), and per-direction cooldowns (a
scale-up's effect takes a boot to show; deciding again before the
evidence reflects the last decision just oscillates). The scaler reads
the policy, never the other way around — policies are plain data,
picklable into status views and decision rows.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass


@dataclass(frozen=True)
class ScalePolicy:
    """Bounds + hysteresis for breach-driven fleet scaling.

    min_workers / max_workers:
        Hard bounds on the worker-process count. The scaler restores a
        fleet below ``min_workers`` (e.g. after a failed spawn) and
        never grows past ``max_workers`` no matter how red the SLO.
    up_breaches / breach_window_s:
        Scale-up hysteresis: at least ``up_breaches`` SLO breach rows
        within the trailing ``breach_window_s`` seconds before one
        worker is added. The window is cleared by a scale-up decision,
        so each worker is bought by fresh evidence.
    up_cooldown_s / down_cooldown_s:
        Minimum seconds between same-direction decisions. Up-cooldown
        should cover a worker boot (the breach stream does not reflect
        the new capacity until it serves); down-cooldown should be the
        longer of the two — releasing capacity is cheap to delay and
        expensive to regret.
    idle_queue_depth / down_after_idle_ticks:
        Scale-down evidence: a health tick is "idle" when the timeline
        row shows no fresh breach and the queue-depth gauge at or below
        ``idle_queue_depth``; after ``down_after_idle_ticks``
        CONSECUTIVE idle ticks (any loaded tick resets the run) one
        worker is drained, down to ``min_workers``.
    """

    min_workers: int = 1
    max_workers: int = 4
    up_breaches: int = 2
    breach_window_s: float = 30.0
    up_cooldown_s: float = 20.0
    down_cooldown_s: float = 60.0
    idle_queue_depth: float = 0.0
    down_after_idle_ticks: int = 5

    def __post_init__(self):
        if self.min_workers < 1:
            raise ValueError(
                f"min_workers must be >= 1, got {self.min_workers}"
            )
        if self.max_workers < self.min_workers:
            raise ValueError(
                f"max_workers ({self.max_workers}) must be >= min_workers "
                f"({self.min_workers})"
            )
        if self.up_breaches < 1:
            raise ValueError(
                f"up_breaches must be >= 1, got {self.up_breaches}"
            )
        if self.down_after_idle_ticks < 1:
            raise ValueError(
                "down_after_idle_ticks must be >= 1, got "
                f"{self.down_after_idle_ticks}"
            )

    def as_dict(self) -> dict:
        return asdict(self)

    def clamp(self, n: int) -> int:
        return max(self.min_workers, min(self.max_workers, n))
