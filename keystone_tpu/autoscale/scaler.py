"""The breach-driven scaler: SLO evidence in, worker-count decisions out.

The :class:`Autoscaler` rides the cluster router's health loop — the
same cadence that pings workers and drives the
:class:`~keystone_tpu.serving.slo.SloWatchdog` — and closes the loop the
watchdog only observes: fresh breach rows plus the timeline's
queue-depth gauge become scale-up / scale-down decisions, bounded by a
declarative :class:`~keystone_tpu.autoscale.policy.ScalePolicy`.

The scaler never touches sockets or processes itself. It drives an
ACTUATOR (the router, duck-typed) through five verbs::

    service_estimate        -> Optional[float]  (cold fleet? do nothing)
    scale_view()            -> {"admitting", "booting", "draining"}
    scale_up_slot()         -> new slot index (spawn via the existing
                               _spawn_worker path: warm-boots zero-
                               compile from the shared AOT cache)
    pick_drain_candidate()  -> slot index or None
    begin_drain(index)      -> stop admitting, drain, join, release
    reap_slot(index)        -> force-retire a half-born/wedged slot

which keeps the control plane unit-testable against a stub and keeps
the failure discipline in one place: both apply paths run through
registered fault sites (``scale.spawn`` / ``scale.drain``), and a kill
injected mid-apply reaps the half-born slot, lands a ``scale.abort``
instant, and leaves the next tick (post-cooldown) to converge the fleet
back inside the policy bounds — zero admitted requests are failed by a
scaling accident, because a slot is only routed to once it reports
ready.

Every decision is evidence three ways: a typed timeline row (the
``scale_ups`` / ``scale_downs`` / ``scale_aborts`` counter deltas in the
next sample), a flight-ring instant (``scale.up`` / ``scale.down`` /
``scale.abort``), and a ``scale.*`` trace span when a tracer is
installed — plus the bounded :attr:`Autoscaler.decisions` list the
status view renders with each decision's triggering breach.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

from ..faults import SCALE_DRAIN, SCALE_SPAWN, fault_point
from ..obs import flight as _flight
from ..obs.span import Span
from ..obs.tracer import current as _trace_current
from .policy import ScalePolicy

logger = logging.getLogger(__name__)

#: decisions kept for the status view
_MAX_DECISIONS = 64

#: SLO objectives that do NOT evidence a capacity shortage — adding a
#: worker cannot fix a tenant blowing its spend budget or one process's
#: device-memory footprint, so their breaches never buy scale-ups
NON_CAPACITY_OBJECTIVES = frozenset(
    {"tenant_device_s_budget", "device_mem_budget_bytes"}
)


@dataclass(frozen=True)
class ScaleDecision:
    """One scaling decision, with the evidence that triggered it."""

    action: str  # "up" | "down"
    ok: bool  # False: the apply was aborted (fault/spawn failure)
    reason: str  # "breach" | "below_min" | "idle"
    from_workers: int
    to_workers: int
    ts: float  # unix time, for rendering next to timeline rows
    worker: Optional[int] = None  # slot index acted on, when known
    #: the breach that bought this decision (objective/observed/budget),
    #: empty for idle-driven scale-downs and min-bound restores
    trigger: Dict[str, object] = field(default_factory=dict)

    def as_row(self) -> dict:
        return asdict(self)


class Autoscaler:
    """Policy-bounded scale decisions off breach + timeline evidence.

    Not thread-safe by itself: ``tick`` is called from exactly one
    thread (the router's health loop; tests call it directly)."""

    def __init__(self, policy: ScalePolicy, actuator, metrics=None):
        self.policy = policy
        self._actuator = actuator
        self._metrics = metrics
        self.decisions: deque = deque(maxlen=_MAX_DECISIONS)
        self._breach_window: deque = deque()  # (monotonic ts, breach)
        self._idle_ticks = 0
        self._last_up = -float("inf")
        self._last_down = -float("inf")
        self._target: Optional[int] = None

    # -- introspection ---------------------------------------------------

    @property
    def target_workers(self) -> Optional[int]:
        """The worker count the scaler currently wants (None before the
        first tick)."""
        return self._target

    def describe(self) -> dict:
        """The status-view payload: policy knobs, current target, and
        the last decisions newest-last."""
        return {
            "policy": self.policy.as_dict(),
            "target": self._target,
            "decisions": [d.as_row() for d in self.decisions],
        }

    # -- the control loop ------------------------------------------------

    def tick(self, breaches=None, row: Optional[dict] = None) -> List[ScaleDecision]:
        """One control-loop step: fold this tick's fresh breach rows and
        timeline row in, decide. Returns the decisions made (usually
        none). A COLD fleet — no learned service estimate yet — never
        scales: the scaler prices capacity from the same evidence the
        admission surfaces price waits from, and without it a breach row
        cannot exist and an idle queue proves nothing."""
        if getattr(self._actuator, "service_estimate", None) is None:
            return []
        now = time.monotonic()
        for b in breaches or ():
            if getattr(b, "objective", None) in NON_CAPACITY_OBJECTIVES:
                # a tenant overspending its device-second budget or a
                # per-process memory watermark is not a capacity
                # shortage: buying a worker fixes neither, so these
                # breaches warn (flight/status/counters) without feeding
                # the scale-up hysteresis
                continue
            self._breach_window.append((now, b))
        horizon = now - self.policy.breach_window_s
        while self._breach_window and self._breach_window[0][0] < horizon:
            self._breach_window.popleft()

        view = self._actuator.scale_view()
        committed = int(view.get("admitting", 0)) + int(view.get("booting", 0))
        self._target = self.policy.clamp(committed)
        out: List[ScaleDecision] = []

        # -- scale-up: bounds first, then breach hysteresis --------------
        up_ready = now - self._last_up >= self.policy.up_cooldown_s
        if committed < self.policy.min_workers and up_ready:
            out.append(self._apply_up(committed, reason="below_min"))
        elif (
            committed < self.policy.max_workers
            and up_ready
            and len(self._breach_window) >= self.policy.up_breaches
        ):
            trigger = self._trigger_attrs(self._breach_window[-1][1])
            self._breach_window.clear()  # each worker needs fresh evidence
            out.append(
                self._apply_up(committed, reason="breach", trigger=trigger)
            )

        # -- scale-down: consecutive idle ticks, bounded below by min ----
        if not out:
            queue_depth = float(
                ((row or {}).get("gauges") or {}).get("queue_depth", 0.0)
            )
            idle = (
                not breaches
                and not self._breach_window
                and queue_depth <= self.policy.idle_queue_depth
            )
            self._idle_ticks = self._idle_ticks + 1 if idle else 0
            if (
                self._idle_ticks >= self.policy.down_after_idle_ticks
                and committed > self.policy.min_workers
                and now - self._last_down >= self.policy.down_cooldown_s
                and now - self._last_up >= self.policy.down_cooldown_s
            ):
                d = self._apply_down(committed)
                if d is not None:
                    self._idle_ticks = 0
                    out.append(d)

        if out:
            self._target = self.policy.clamp(
                committed
                + sum(1 for d in out if d.action == "up" and d.ok)
                - sum(1 for d in out if d.action == "down" and d.ok)
            )
        return out

    # -- apply paths (fault-instrumented) --------------------------------

    def _apply_up(
        self, committed: int, reason: str, trigger: Optional[dict] = None
    ) -> ScaleDecision:
        self._last_up = time.monotonic()
        t0 = time.perf_counter()
        index: Optional[int] = None
        try:
            index = self._actuator.scale_up_slot()
            # the registered chaos seam sits BETWEEN spawn and ready —
            # a kill here is a worker dying mid-scale-up, before the
            # router ever admits traffic to it
            fault_point(SCALE_SPAWN, worker=index)
        except BaseException as e:  # noqa: BLE001 — incl. injected kills
            logger.warning(
                "autoscale: scale-up aborted (%s) — reaping slot %s",
                e, index,
            )
            return self._abort(
                "up", committed, index, reason, trigger, t0, cause=e
            )
        return self._commit(
            "up", committed, committed + 1, index, reason, trigger, t0
        )

    def _apply_down(self, committed: int) -> Optional[ScaleDecision]:
        index = self._actuator.pick_drain_candidate()
        if index is None:
            return None
        self._last_down = time.monotonic()
        t0 = time.perf_counter()
        try:
            self._actuator.begin_drain(index)
            # chaos seam: a kill here is a worker dying mid-drain — the
            # reap force-retires it and the router's down-handler
            # requeues its in-flight work with deadlines intact
            fault_point(SCALE_DRAIN, worker=index)
        except BaseException as e:  # noqa: BLE001 — incl. injected kills
            logger.warning(
                "autoscale: drain of worker %s aborted (%s) — reaping it",
                index, e,
            )
            return self._abort(
                "down", committed, index, "idle", None, t0, cause=e
            )
        return self._commit(
            "down", committed, committed - 1, index, "idle", None, t0
        )

    # -- decision bookkeeping + evidence ---------------------------------

    def _trigger_attrs(self, breach) -> dict:
        out = {}
        for k in ("objective", "observed", "budget"):
            v = getattr(breach, k, None)
            if v is None and isinstance(breach, dict):
                v = breach.get(k)
            if v is not None:
                out[k] = v
        return out

    def _commit(
        self, action, from_n, to_n, index, reason, trigger, t0
    ) -> ScaleDecision:
        d = ScaleDecision(
            action=action, ok=True, reason=reason,
            from_workers=from_n, to_workers=to_n,
            ts=time.time(), worker=index, trigger=dict(trigger or {}),
        )
        self._record(d, t0)
        logger.info(
            "autoscale: scale-%s -> %d worker(s) (reason: %s, slot %s)",
            action, to_n, reason, index,
        )
        return d

    def _abort(
        self, action, committed, index, reason, trigger, t0, cause
    ) -> ScaleDecision:
        if index is not None:
            try:
                self._actuator.reap_slot(index)
            except Exception:
                logger.exception(
                    "autoscale: reaping slot %d after a failed scale-%s "
                    "failed too", index, action,
                )
        d = ScaleDecision(
            action=action, ok=False, reason=reason,
            from_workers=committed, to_workers=committed,
            ts=time.time(), worker=index,
            trigger=dict(trigger or {}, cause=str(cause)[:200]),
        )
        self._record(d, t0)
        return d

    def _record(self, d: ScaleDecision, t0: float) -> None:
        self.decisions.append(d)
        name = f"scale.{d.action}" if d.ok else "scale.abort"
        attrs = {
            "action": d.action, "reason": d.reason, "worker": d.worker,
            "from_workers": d.from_workers, "to_workers": d.to_workers,
            **{f"trigger_{k}": v for k, v in d.trigger.items()},
        }
        if d.ok:
            _flight.record_instant(
                "scale.up" if d.action == "up" else "scale.down", **attrs
            )
        else:
            # the recovery instant both scale.* fault sites map to in
            # obs/flight.SITE_INSTANTS: the half-born (or half-drained)
            # slot was reaped and the fleet stays inside policy bounds
            _flight.record_instant("scale.abort", **attrs)
        if self._metrics is not None:
            if not d.ok:
                self._metrics.inc("scale_aborts")
            elif d.action == "up":
                self._metrics.inc("scale_ups")
            else:
                self._metrics.inc("scale_downs")
        tracer = _trace_current()
        if tracer is not None:
            tracer.record_complete(Span(
                name=name, start=t0, end=time.perf_counter(),
                op_type="Autoscaler", attrs=attrs,
            ))
