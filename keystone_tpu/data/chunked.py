"""Out-of-core datasets: a re-iterable row-chunk form of :class:`Dataset`.

Parity: the reference's training sets are Spark RDDs — partitioned, lazily
recomputed from lineage on every scan, cached only when they fit executor
memory (``ImageNetSiftLcsFV.scala:98-135`` never materializes the featurized
set; ``BlockWeightedLeastSquares.scala:177-313`` iterates per-partition Grams
over it). :class:`ChunkedDataset` is the TPU-native analogue: the payload is a
*factory* producing an iterator of batched row chunks, so

  * transformer chains compose lazily per chunk (``map_batch`` returns a new
    chunked dataset; nothing executes until a scan);
  * every scan recomputes the chain from the source — lineage semantics —
    unless :meth:`cache` finds the materialized form fits a byte budget;
  * estimators that know how to stream (the block/weighted solvers, scalers)
    accumulate per-chunk statistics instead of calling ``to_array()``, so a
    featurized training set larger than HBM never materializes.

Chunks carry a common leading batch dimension and may be arrays or tuples of
arrays (the gather node zips branch chunks into tuples).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterator, List, Optional, Sequence

import jax
import numpy as np

from .dataset import Dataset, _rebatch


def _payload_rows(payload: Any) -> int:
    leaves = jax.tree_util.tree_leaves(payload)
    return int(leaves[0].shape[0])


def _payload_bytes(payload: Any) -> int:
    total = 0
    for leaf in jax.tree_util.tree_leaves(payload):
        total += int(np.prod(leaf.shape)) * int(
            np.dtype(getattr(leaf, "dtype", np.float32)).itemsize
        )
    return total


def default_cache_budget_bytes() -> int:
    """HBM budget under which :meth:`ChunkedDataset.cache` materializes.

    Mirrors Spark's storage-fraction decision: a chunked set whose
    materialized form fits comfortably is pinned; anything bigger keeps
    recompute-on-scan semantics. Override with KEYSTONE_CHUNK_CACHE_BUDGET
    (bytes)."""
    return int(os.environ.get("KEYSTONE_CHUNK_CACHE_BUDGET", 2 << 30))


def prefetch_to_device(chunks, depth: int = 2):
    """Iterate ``chunks`` with up to ``depth`` device uploads in flight —
    fit-ingestion double buffering (VERDICT r4 weak #4). Host (numpy)
    chunks are ``jax.device_put`` ahead of the consumer so the H2D
    transfer streams while the previous chunk's compute runs; device
    arrays pass through untouched. Order is preserved."""
    from collections import deque

    q: deque = deque()
    it = iter(chunks)

    def put(c):
        leaves = jax.tree_util.tree_leaves(c)
        if any(isinstance(leaf, np.ndarray) for leaf in leaves):
            return jax.device_put(c)
        return c

    while True:
        while it is not None and len(q) < depth:
            try:
                q.append(put(next(it)))
            except StopIteration:
                it = None
        if not q:
            return
        yield q.popleft()


def rechunk_batched(dataset: "Dataset", sizes: Sequence[int]) -> "ChunkedDataset":
    """Chunked view of a materialized batched dataset at given boundaries —
    used to align an in-memory gather branch with a chunked one."""
    payload = dataset.payload
    n = sum(sizes)

    def factory():
        i = 0
        for sz in sizes:
            lo = i
            yield jax.tree_util.tree_map(lambda a: a[lo : lo + sz], payload)
            i += sz

    return ChunkedDataset(factory, n, label="rechunk")


def align_and_zip(datasets: Sequence["Dataset"]) -> "ChunkedDataset":
    """Zip mixed chunked/materialized branches into one chunked dataset of
    tuples, WITHOUT a probing scan: the first chunked branch drives the
    boundaries at iteration time; materialized branches are sliced by a
    running row cursor and additional chunked branches are pulled in
    lockstep (all chunked branches derive from one source, so their
    boundaries agree by construction — checked per chunk)."""
    chunked_idx = [
        i for i, ds in enumerate(datasets) if isinstance(ds, ChunkedDataset)
    ]
    if not chunked_idx:
        raise ValueError("align_and_zip needs at least one chunked branch")
    n = len(datasets[0])
    for ds in datasets[1:]:
        if len(ds) != n:
            raise ValueError("align_and_zip of datasets with different lengths")
    lead = chunked_idx[0]
    parents = {i: datasets[i]._payload for i in chunked_idx}
    payloads = {
        i: ds.payload
        for i, ds in enumerate(datasets)
        if i not in parents
    }

    def factory():
        iters = {i: p() for i, p in parents.items()}
        cursor = 0
        for lead_chunk in iters[lead]:
            rows = _payload_rows(lead_chunk)
            out: List[Any] = []
            for i in range(len(datasets)):
                if i == lead:
                    out.append(lead_chunk)
                elif i in iters:
                    c = next(iters[i], None)
                    if c is None or _payload_rows(c) != rows:
                        raise ValueError(
                            "align_and_zip: misaligned chunk boundaries"
                        )
                    out.append(c)
                else:
                    lo = cursor
                    out.append(
                        jax.tree_util.tree_map(
                            lambda a: a[lo : lo + rows], payloads[i]
                        )
                    )
            cursor += rows
            yield tuple(out)
        if cursor != n:
            raise ValueError(
                f"align_and_zip: chunked branch produced {cursor} rows, expected {n}"
            )
        for i in chunked_idx[1:]:
            if next(iters[i], None) is not None:
                raise ValueError("align_and_zip: branch chunk counts differ")

    return ChunkedDataset(factory, n, label="zip")


class ChunkedDataset(Dataset):
    """N rows produced in batched chunks by a re-iterable factory."""

    def __init__(
        self,
        chunk_factory: Callable[[], Iterator[Any]],
        num_rows: int,
        *,
        label: Optional[str] = None,
    ):
        # payload = the factory: DatasetOperator's payload-identity equality
        # then keys on the factory object, which is what "same logical data"
        # means for a lineage-recomputed collection.
        super().__init__(chunk_factory, batched=True)
        self._num_rows = int(num_rows)
        self._label = label or "chunked"

    # ---- constructors ---------------------------------------------------

    @staticmethod
    def from_array(arr: Any, chunk_rows: int) -> "ChunkedDataset":
        """Chunked *view* of an in-memory array (testing + apply paths)."""
        n = int(arr.shape[0])
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")

        def factory():
            for i in range(0, n, chunk_rows):
                yield arr[i : i + chunk_rows]

        return ChunkedDataset(factory, n, label=f"array[{n}]")

    @staticmethod
    def from_chunk_fn(
        chunk_fn: Callable[[int], Any],
        num_chunks: int,
        num_rows: int,
        *,
        label: Optional[str] = None,
    ) -> "ChunkedDataset":
        """Chunks generated by index — the deterministic-regeneration source
        (synthetic benches, seeded loaders): ``chunk_fn(i)`` must return the
        same payload for the same ``i`` on every scan."""

        def factory():
            for i in range(num_chunks):
                yield chunk_fn(i)

        return ChunkedDataset(factory, num_rows, label=label)

    # ---- shape / access -------------------------------------------------

    @property
    def is_chunked(self) -> bool:
        return True

    def __len__(self) -> int:
        return self._num_rows

    def chunks(self) -> Iterator[Any]:
        """One scan: recomputes the whole lazy chain chunk-by-chunk."""
        return iter(self._payload())

    def __iter__(self) -> Iterator[Any]:
        for chunk in self.chunks():
            rows = _payload_rows(chunk)
            for i in range(rows):
                yield jax.tree_util.tree_map(lambda a: a[i], chunk)

    def first(self) -> Any:
        chunk = next(self.chunks())
        return jax.tree_util.tree_map(lambda a: a[0], chunk)

    def to_array(self):
        """Materialize by concatenating every chunk (small results only —
        sampled descriptor sets, predictions; estimators stream instead)."""
        import jax.numpy as jnp

        parts = list(self.chunks())
        if not parts:
            raise ValueError("empty chunked dataset")
        if len(parts) == 1:
            return parts[0]
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts
        )

    # ---- functional ops (lazy) ------------------------------------------

    def map_batch(self, fn: Callable[[Any], Any]) -> "ChunkedDataset":
        """Lazily apply ``fn`` to every chunk — the transformer-chain hook.
        The returned dataset recomputes ``fn`` per scan (lineage)."""
        parent = self._payload

        def factory():
            for chunk in parent():
                yield fn(chunk)

        return ChunkedDataset(
            factory, self._num_rows, label=f"{self._label}|map_batch"
        )

    def map(self, fn: Callable[[Any], Any]) -> "ChunkedDataset":
        """Per-item fallback, applied within each chunk and restacked."""
        parent = self._payload

        import jax.numpy as jnp

        def factory():
            for chunk in parent():
                rows = _payload_rows(chunk)
                items = [
                    jnp.asarray(
                        fn(jax.tree_util.tree_map(lambda a: a[i], chunk))
                    )
                    for i in range(rows)
                ]
                yield _rebatch(items).payload

        return ChunkedDataset(
            factory, self._num_rows, label=f"{self._label}|map"
        )

    def cache(self, budget_bytes: Optional[int] = None) -> Dataset:
        """Materialize iff the full set fits ``budget_bytes`` in HBM;
        otherwise keep lineage-recompute semantics (returns self).

        The size estimate computes ONE chunk (cost: one chunk of the chain);
        a set that does materialize reuses that chunk's scan, so the decision
        costs nothing extra in the fits-in-memory case."""
        import jax.numpy as jnp

        budget = default_cache_budget_bytes() if budget_bytes is None else budget_bytes
        it = self.chunks()
        try:
            head = next(it)
        except StopIteration:
            raise ValueError("empty chunked dataset")
        head_rows = _payload_rows(head)
        est_total = _payload_bytes(head) * (self._num_rows / max(head_rows, 1))
        if est_total > budget:
            return self
        parts: List[Any] = [head]
        total = _payload_bytes(head)
        for chunk in it:
            total += _payload_bytes(chunk)
            if total > budget:  # estimate was low (ragged chunks) — bail out
                return self
            parts.append(chunk)
        payload = (
            parts[0]
            if len(parts) == 1
            else jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts
            )
        )
        return Dataset(payload, batched=True)

    # ---- combination ----------------------------------------------------

    @staticmethod
    def zip_chunks(datasets: Sequence["ChunkedDataset"]) -> "ChunkedDataset":
        """Zip N aligned chunked datasets into one whose chunks are tuples —
        the gather node's chunked form. All inputs must share chunk
        boundaries (true by construction when they derive from one source)."""
        if not datasets:
            raise ValueError("zip_chunks of zero datasets")
        n = len(datasets[0])
        for ds in datasets[1:]:
            if len(ds) != n:
                raise ValueError("zip_chunks of datasets with different lengths")
        parents = [ds._payload for ds in datasets]

        def factory():
            iters = [p() for p in parents]
            for chunks in zip(*iters):
                rows = {_payload_rows(c) for c in chunks}
                if len(rows) != 1:
                    raise ValueError(
                        f"zip_chunks: misaligned chunk boundaries {rows}"
                    )
                yield tuple(chunks)
            for it in iters:  # all branches must be exhausted together
                if next(it, None) is not None:
                    raise ValueError("zip_chunks: branch chunk counts differ")

        return ChunkedDataset(factory, n, label="zip")
