"""Out-of-core datasets: a re-iterable row-chunk form of :class:`Dataset`.

Parity: the reference's training sets are Spark RDDs — partitioned, lazily
recomputed from lineage on every scan, cached only when they fit executor
memory (``ImageNetSiftLcsFV.scala:98-135`` never materializes the featurized
set; ``BlockWeightedLeastSquares.scala:177-313`` iterates per-partition Grams
over it). :class:`ChunkedDataset` is the TPU-native analogue: the payload is a
*factory* producing an iterator of batched row chunks, so

  * transformer chains compose lazily per chunk (``map_batch`` returns a new
    chunked dataset; nothing executes until a scan);
  * every scan recomputes the chain from the source — lineage semantics —
    unless :meth:`cache` finds the materialized form fits a byte budget;
  * estimators that know how to stream (the block/weighted solvers, scalers)
    accumulate per-chunk statistics instead of calling ``to_array()``, so a
    featurized training set larger than HBM never materializes.

Chunks carry a common leading batch dimension and may be arrays or tuples of
arrays (the gather node zips branch chunks into tuples).
"""

from __future__ import annotations

from typing import Any, Callable, Iterator, List, Optional, Sequence

import jax

from .dataset import Dataset, _rebatch
from .pipeline_scan import (
    map_workers,
    payload_nbytes as _payload_bytes,
    payload_rows as _payload_rows,
    scan_pipeline,
    serial_staged,
)


def default_cache_budget_bytes() -> int:
    """HBM budget under which :meth:`ChunkedDataset.cache` materializes.

    Mirrors Spark's storage-fraction decision: a chunked set whose
    materialized form fits comfortably is pinned; anything bigger keeps
    recompute-on-scan semantics. Override with KEYSTONE_CHUNK_CACHE_BUDGET
    (bytes)."""
    from ..utils import env_int

    return env_int("KEYSTONE_CHUNK_CACHE_BUDGET", 2 << 30, minimum=0)


def prefetch_to_device(chunks, depth: int = 2):
    """Iterate ``chunks`` with up to ``depth`` device uploads in flight —
    fit-ingestion double buffering (VERDICT r4 weak #4). Superseded by the
    pipelined scan runtime (``pipeline_scan.scan_pipeline``, which adds a
    producer thread in front of the same staging ring); kept as the
    serial/legacy spelling and as the ``KEYSTONE_SCAN_PIPELINE=0``
    fallback. Order is preserved."""
    return serial_staged(chunks, depth)


class _InjectedChunks:
    """The ``scan.chunk`` fault-injection + retry seam: fires the fault
    point before each pull, INSIDE this iterator, so a transient fault
    retries (bounded backoff) without killing the underlying generator;
    exhaustion propagates the original error. The ``retry_budget`` is
    exposed so a wrapping :class:`~keystone_tpu.data.pipeline_scan.
    ScanPipeline` ADOPTS it — one budget (and one span-visible retry
    count) per scan across the chunk and staging stages. Only installed
    when a fault plan is active."""

    def __init__(self, it: Iterator[Any], label: str):
        from ..faults import RetryBudget

        self._it = it
        self._label = label
        self.retry_budget = RetryBudget(label=f"scan[{label}]")

    def __iter__(self) -> "_InjectedChunks":
        return self

    def __next__(self) -> Any:
        from ..faults import SCAN_CHUNK, retry_call

        retry_call(
            lambda: None, self.retry_budget, SCAN_CHUNK, label=self._label
        )
        return next(self._it)

    def close(self) -> None:
        close = getattr(self._it, "close", None)
        if close is not None:
            close()

    @property
    def shards(self) -> int:
        """Producer shards behind this seam (ScanPipeline reads it for
        the span's production-split attrs)."""
        return getattr(self._it, "shards", 1)

    @property
    def shard_chunks(self):
        return getattr(self._it, "shard_chunks", None)


def _maybe_inject(it: Iterator[Any], label: str) -> Iterator[Any]:
    """Wrap ``it`` with the fault seam iff a plan is active (one dict
    lookup on the no-plan path — zero overhead wrapping)."""
    from ..faults import active_plan

    if active_plan() is None:
        return it
    return _InjectedChunks(it, label)


def rechunk_batched(dataset: "Dataset", sizes: Sequence[int]) -> "ChunkedDataset":
    """Chunked view of a materialized batched dataset at given boundaries —
    used to align an in-memory gather branch with a chunked one."""
    payload = dataset.payload
    n = sum(sizes)

    def factory():
        i = 0
        for sz in sizes:
            lo = i
            yield jax.tree_util.tree_map(lambda a: a[lo : lo + sz], payload)
            i += sz

    return ChunkedDataset(factory, n, label="rechunk")


def align_and_zip(datasets: Sequence["Dataset"]) -> "ChunkedDataset":
    """Zip mixed chunked/materialized branches into one chunked dataset of
    tuples, WITHOUT a probing scan: the first chunked branch drives the
    boundaries at iteration time; materialized branches are sliced by a
    running row cursor and additional chunked branches are pulled in
    lockstep (all chunked branches derive from one source, so their
    boundaries agree by construction — checked per chunk)."""
    chunked_idx = [
        i for i, ds in enumerate(datasets) if isinstance(ds, ChunkedDataset)
    ]
    if not chunked_idx:
        raise ValueError("align_and_zip needs at least one chunked branch")
    n = len(datasets[0])
    for ds in datasets[1:]:
        if len(ds) != n:
            raise ValueError("align_and_zip of datasets with different lengths")
    lead = chunked_idx[0]
    parents = {i: datasets[i]._payload for i in chunked_idx}
    payloads = {
        i: ds.payload
        for i, ds in enumerate(datasets)
        if i not in parents
    }

    def factory():
        iters = {i: p() for i, p in parents.items()}
        cursor = 0
        for lead_chunk in iters[lead]:
            rows = _payload_rows(lead_chunk)
            out: List[Any] = []
            for i in range(len(datasets)):
                if i == lead:
                    out.append(lead_chunk)
                elif i in iters:
                    c = next(iters[i], None)
                    if c is None or _payload_rows(c) != rows:
                        raise ValueError(
                            "align_and_zip: misaligned chunk boundaries"
                        )
                    out.append(c)
                else:
                    lo = cursor
                    out.append(
                        jax.tree_util.tree_map(
                            lambda a: a[lo : lo + rows], payloads[i]
                        )
                    )
            cursor += rows
            yield tuple(out)
        if cursor != n:
            raise ValueError(
                f"align_and_zip: chunked branch produced {cursor} rows, expected {n}"
            )
        for i in chunked_idx[1:]:
            if next(iters[i], None) is not None:
                raise ValueError("align_and_zip: branch chunk counts differ")

    return ChunkedDataset(factory, n, label="zip")


class ChunkedDataset(Dataset):
    """N rows produced in batched chunks by a re-iterable factory."""

    def __init__(
        self,
        chunk_factory: Callable[[], Iterator[Any]],
        num_rows: int,
        *,
        label: Optional[str] = None,
    ):
        # payload = the factory: DatasetOperator's payload-identity equality
        # then keys on the factory object, which is what "same logical data"
        # means for a lineage-recomputed collection.
        super().__init__(chunk_factory, batched=True)
        self._num_rows = int(num_rows)
        self._label = label or "chunked"
        #: optional ``fn(start, step=1) -> iterator`` yielding chunk
        #: indices ``start, start+step, …`` WITHOUT producing the
        #: skipped ones — set by the indexable constructors (from_array /
        #: from_chunk_fn) and propagated through map/map_batch. ``step=1``
        #: is the checkpoint-resume hook (re-enter at a cursor instead of
        #: rescanning); ``step=N`` is the sharded-production hook (shard
        #: s of N produces s, s+N, … — see :mod:`~keystone_tpu.data.shards`)
        self._skip_factory: Optional[Callable[..., Iterator[Any]]] = None
        #: optional statically-known per-item ``(shape, dtype)`` of the
        #: chunks this factory yields — set by constructors that can see
        #: it (from_array), consumed by the static checker
        #: (keystone_tpu/check/) so out-of-core scans carry specs without
        #: producing a chunk. Cleared by map/map_batch (the mapped
        #: element spec is not derivable without executing).
        self._item_spec: Optional[tuple] = None

    # ---- constructors ---------------------------------------------------

    @staticmethod
    def from_array(arr: Any, chunk_rows: int) -> "ChunkedDataset":
        """Chunked *view* of an in-memory array (testing + apply paths)."""
        n = int(arr.shape[0])
        if chunk_rows <= 0:
            raise ValueError("chunk_rows must be positive")

        def from_chunk(start: int, step: int = 1):
            for i in range(start * chunk_rows, n, chunk_rows * step):
                yield arr[i : i + chunk_rows]

        ds = ChunkedDataset(
            lambda: from_chunk(0), n, label=f"array[{n}]"
        )
        ds._skip_factory = from_chunk
        shape = getattr(arr, "shape", None)
        dtype = getattr(arr, "dtype", None)
        if shape is not None and dtype is not None:
            ds._item_spec = (
                tuple(int(d) for d in shape[1:]), str(dtype)
            )
        return ds

    @staticmethod
    def from_chunk_fn(
        chunk_fn: Callable[[int], Any],
        num_chunks: int,
        num_rows: int,
        *,
        label: Optional[str] = None,
    ) -> "ChunkedDataset":
        """Chunks generated by index — the deterministic-regeneration source
        (synthetic benches, seeded loaders): ``chunk_fn(i)`` must return the
        same payload for the same ``i`` on every scan.

        Because production is re-callable by index, this is the source
        class where transient chunk-load failures (a typed
        :class:`~keystone_tpu.faults.TransientError` from ``chunk_fn``)
        genuinely RETRY under the scan's ``KEYSTONE_SCAN_RETRIES``
        budget, instead of failing the scan on the first flake."""
        from ..faults import SCAN_CHUNK, RetryBudget, retry_call

        def from_chunk(start: int, step: int = 1):
            # one regeneration budget per iterator — a shard's retries
            # are bounded exactly as the single producer's were
            budget = RetryBudget(label=f"chunk_fn[{label or 'chunked'}]")
            for i in range(start, num_chunks, step):
                yield retry_call(
                    lambda i=i: chunk_fn(i), budget, SCAN_CHUNK,
                    inject=False,
                )

        ds = ChunkedDataset(
            lambda: from_chunk(0), num_rows, label=label
        )
        ds._skip_factory = from_chunk
        return ds

    # ---- shape / access -------------------------------------------------

    @property
    def is_chunked(self) -> bool:
        return True

    @property
    def item_spec(self) -> Optional[tuple]:
        """Statically-known per-item ``(shape, dtype)``, or None. Never
        produces a chunk."""
        # getattr: instances from pre-spec pickles/subclasses stay valid
        return getattr(self, "_item_spec", None)

    def __len__(self) -> int:
        return self._num_rows

    def chunks(
        self, lanes: Optional[int] = None, shards: Optional[int] = None
    ) -> Iterator[Any]:
        """One scan: recomputes the whole lazy chain chunk-by-chunk.

        Runs through the pipelined scan runtime (``pipeline_scan.py``):
        the chain executes in a background producer while an H2D
        staging ring keeps device uploads ahead of the consumer, so host
        production, transfer, and device compute overlap on every
        streaming consumer. ``lanes`` round-robins chunks across that many
        data-axis devices with one staging ring each (mesh-distributed
        scan) — pass it ONLY from consumers that keep per-lane partial
        accumulators; the default single-lane scan is what ``to_array``/
        ``cache`` and other whole-stream consumers need.

        ``shards`` (default ``KEYSTONE_SCAN_SHARDS``) splits chunk
        PRODUCTION across that many producer shards partitioning the
        chunk index space — the host-side counterpart of lanes, for
        index-addressable chains (:mod:`~keystone_tpu.data.shards`); the
        merged stream is bit-identical to the single producer's.
        ``KEYSTONE_SCAN_PIPELINE=0`` restores the serial in-thread scan."""
        return scan_pipeline(
            self._production(shards),
            label=self._label, lanes=lanes or 1,
        )

    def _production(self, shards: Optional[int] = None) -> Iterator[Any]:
        """The produced (pre-staging) chunk stream: sharded across
        producer shards when asked and possible, single otherwise; the
        fault-injection seam wraps the MERGED stream either way, so
        chaos-schedule indices follow chunk order deterministically."""
        from .shards import maybe_shard

        return _maybe_inject(
            maybe_shard(
                self._skip_factory,
                lambda: iter(self._payload()),
                shards=shards,
                label=self._label,
            ),
            self._label,
        )

    def raw_chunks(self, skip: int = 0) -> Iterator[Any]:
        """One scan WITHOUT the pipelined runtime — for composition sites
        that feed another scan (derived factories, solvers that wrap the
        source in their own ``scan_pipeline``) where nesting pipelines
        would stack threads for no additional overlap. Under
        ``KEYSTONE_SCAN_SHARDS > 1`` production still shards (the N
        producer threads replace the absent pipeline thread; the solver
        scans that wrap this in ``scan_pipeline`` are exactly where the
        producer bottleneck lives).

        ``skip`` starts the scan at chunk index ``skip`` — the
        checkpoint-resume hook. Indexable sources (and chains built on
        them through map/map_batch) skip WITHOUT producing the prefix;
        opaque factories fall back to producing and discarding it (the
        resume still skips the fold work, just not the production)."""
        if skip <= 0:
            return self._production()
        if self._skip_factory is not None:
            from .shards import maybe_shard

            return _maybe_inject(
                maybe_shard(
                    self._skip_factory,
                    lambda: iter(self._skip_factory(skip)),
                    start=skip,
                    label=self._label,
                ),
                self._label,
            )
        it = iter(self._payload())
        for _ in range(skip):
            if next(it, None) is None:
                break
        return _maybe_inject(it, self._label)

    def __iter__(self) -> Iterator[Any]:
        # stage=False: per-row consumers are host code — hand them chunks
        # in whatever form the chain produced (numpy stays numpy; no
        # speculative H2D), while chain production still overlaps the
        # per-row work in the producer thread
        for chunk in scan_pipeline(
            self._payload(), stage=False, label=f"{self._label}|iter"
        ):
            rows = _payload_rows(chunk)
            for i in range(rows):
                yield jax.tree_util.tree_map(lambda a: a[i], chunk)

    def take(self, n: int) -> Dataset:
        """The first ``n`` rows, materialized from a raw leading-chunk peek:
        no producer thread, no staged readahead, and the scan stops at the
        first chunk that completes ``n`` rows — a 24-item optimizer sample
        of a million-row chunked set pays for one chunk, not the dataset."""
        if n < 0:
            raise ValueError("take of a negative count")
        parts: List[Any] = []
        rows = 0
        it = self.raw_chunks()
        try:
            while rows < n:
                chunk = next(it, None)
                if chunk is None:
                    break
                need = n - rows
                got = _payload_rows(chunk)
                if got > need:
                    chunk = jax.tree_util.tree_map(lambda a: a[:need], chunk)
                    got = need
                parts.append(chunk)
                rows += got
        finally:
            close = getattr(it, "close", None)
            if close is not None:
                close()
        if not parts:
            if n == 0:
                peek = self.raw_chunks()
                try:
                    chunk = next(peek, None)
                finally:
                    close = getattr(peek, "close", None)
                    if close is not None:
                        close()
                if chunk is not None:
                    return Dataset(
                        jax.tree_util.tree_map(lambda a: a[:0], chunk),
                        batched=True,
                    )
            # parity with Dataset.take on an empty payload: an empty
            # dataset back, never an exception
            return Dataset([], batched=False)
        if len(parts) == 1:
            payload = parts[0]
        else:
            import jax.numpy as jnp

            payload = jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts
            )
        return Dataset(payload, batched=True)

    def first(self) -> Any:
        # one row off the take(1) peek — same raw one-chunk scan; first()
        # must not pay depth chunks of production for one row
        head = self.take(1)
        if len(head) == 0:
            # same exception family as Dataset.first on an empty list
            raise IndexError("first() of an empty chunked dataset")
        return jax.tree_util.tree_map(lambda a: a[0], head.payload)

    def to_array(self):
        """Materialize by concatenating every chunk (small results only —
        sampled descriptor sets, predictions; estimators stream instead)."""
        import jax.numpy as jnp

        parts = list(self.chunks())
        if not parts:
            raise ValueError("empty chunked dataset")
        if len(parts) == 1:
            return parts[0]
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *parts
        )

    # ---- functional ops (lazy) ------------------------------------------

    def map_batch(self, fn: Callable[[Any], Any]) -> "ChunkedDataset":
        """Lazily apply ``fn`` to every chunk — the transformer-chain hook.
        The returned dataset recomputes ``fn`` per scan (lineage)."""
        parent = self._payload
        parent_skip = self._skip_factory

        def factory():
            for chunk in parent():
                yield fn(chunk)

        ds = ChunkedDataset(
            factory, self._num_rows, label=f"{self._label}|map_batch"
        )
        if parent_skip is not None:
            # striding the parent also strides fn over the skipped
            # chunks — a shard runs the WHOLE chain for its indices
            ds._skip_factory = lambda start, step=1: (
                fn(c) for c in parent_skip(start, step)
            )
        return ds

    def map(self, fn: Callable[[Any], Any]) -> "ChunkedDataset":
        """Per-item fallback, applied within each chunk and restacked.

        Items within a chunk run across an order-preserving thread pool
        (size from ``KEYSTONE_MAP_WORKERS``, default min(4, cores); 1
        disables it) — this path is host featurizers whose numpy work
        releases the GIL, and the serial per-row loop was the dominant
        cost of per-item chains over large chunks. Results are ordered,
        but ``fn`` executes CONCURRENTLY within a chunk: an fn with
        shared mutable state (a stateful rng, an accumulator closure)
        needs ``KEYSTONE_MAP_WORKERS=1``."""
        parent = self._payload
        parent_skip = self._skip_factory

        import jax.numpy as jnp

        def one(chunk, i):
            return jnp.asarray(
                fn(jax.tree_util.tree_map(lambda a: a[i], chunk))
            )

        def run(chunks):
            from concurrent.futures import ThreadPoolExecutor

            workers = map_workers()
            pool = ThreadPoolExecutor(workers) if workers > 1 else None
            try:
                for chunk in chunks:
                    rows = _payload_rows(chunk)
                    if pool is None or rows <= 1:
                        items = [one(chunk, i) for i in range(rows)]
                    else:
                        items = list(
                            pool.map(one, [chunk] * rows, range(rows))
                        )
                    yield _rebatch(items).payload
            finally:
                if pool is not None:
                    pool.shutdown(wait=True)

        ds = ChunkedDataset(
            lambda: run(parent()), self._num_rows,
            label=f"{self._label}|map",
        )
        if parent_skip is not None:
            ds._skip_factory = lambda start, step=1: run(
                parent_skip(start, step)
            )
        return ds

    def cache(self, budget_bytes: Optional[int] = None) -> Dataset:
        """Materialize iff the full set fits ``budget_bytes`` in HBM;
        otherwise keep lineage-recompute semantics (returns self).

        The size estimate computes ONE chunk (cost: one chunk of the chain);
        a set that does materialize reuses that chunk's scan, so the decision
        costs nothing extra in the fits-in-memory case."""
        import jax.numpy as jnp

        budget = default_cache_budget_bytes() if budget_bytes is None else budget_bytes
        it = self.chunks()
        try:
            try:
                head = next(it)
            except StopIteration:
                raise ValueError("empty chunked dataset")
            head_rows = _payload_rows(head)
            est_total = _payload_bytes(head) * (
                self._num_rows / max(head_rows, 1)
            )
            if est_total > budget:
                return self
            parts: List[Any] = [head]
            total = _payload_bytes(head)
            for chunk in it:
                total += _payload_bytes(chunk)
                if total > budget:  # estimate was low (ragged chunks) — bail
                    return self
                parts.append(chunk)
        finally:
            # the over-budget paths abandon a live scan — join its producer
            close = getattr(it, "close", None)
            if close is not None:
                close()
        payload = (
            parts[0]
            if len(parts) == 1
            else jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *parts
            )
        )
        return Dataset(payload, batched=True)

    # ---- combination ----------------------------------------------------

    @staticmethod
    def zip_chunks(datasets: Sequence["ChunkedDataset"]) -> "ChunkedDataset":
        """Zip N aligned chunked datasets into one whose chunks are tuples —
        the gather node's chunked form. All inputs must share chunk
        boundaries (true by construction when they derive from one source)."""
        if not datasets:
            raise ValueError("zip_chunks of zero datasets")
        n = len(datasets[0])
        for ds in datasets[1:]:
            if len(ds) != n:
                raise ValueError("zip_chunks of datasets with different lengths")
        parents = [ds._payload for ds in datasets]

        def factory():
            iters = [p() for p in parents]
            for chunks in zip(*iters):
                rows = {_payload_rows(c) for c in chunks}
                if len(rows) != 1:
                    raise ValueError(
                        f"zip_chunks: misaligned chunk boundaries {rows}"
                    )
                yield tuple(chunks)
            for it in iters:  # all branches must be exhausted together
                if next(it, None) is not None:
                    raise ValueError("zip_chunks: branch chunk counts differ")

        return ChunkedDataset(factory, n, label="zip")
