"""Pipelined out-of-core scans: overlap host production, H2D staging, and
device compute.

Every :class:`~keystone_tpu.data.chunked.ChunkedDataset` scan used to run
serially: the host produced chunk *i* (tar decode, host featurizers,
per-item Python maps) while the device sat idle, then the device computed
while the host sat idle. The reference never sees this problem — Spark's
RDD partition pipelining overlaps production and consumption for free
(KeystoneML, arXiv:1610.09451) — and the follow-up performance study
(arXiv:1612.01437) shows data movement, not FLOPs, is where distributed
ML pipelines lose their time.

:func:`scan_pipeline` is the TPU-native counterpart: a bounded
producer/consumer pipeline with three overlapped stages —

  * a background **producer** thread runs the whole lazy chunk chain (all
    host work) into a bounded queue;
  * an **H2D staging** ring issues ``jax.device_put`` up to ``depth``
    chunks ahead of the consumer, so transfers stream while the previous
    chunk's compute runs (generalizing and subsuming the old
    ``prefetch_to_device`` double buffer);
  * the **consumer** (streaming solver / fused chain / materializer)
    overlaps its device compute with the next chunk's production.

Contract: chunk order is preserved, producer exceptions surface in the
consumer with the original traceback attached, and early consumer exit
(``close()``, garbage collection of an abandoned iterator, or
``GeneratorExit`` unwinding a wrapping generator) drains the buffer and
joins the producer thread — no orphan threads, no deadlock.

Mesh-distributed scans (``lanes > 1``): when the active mesh has a >1-wide
data axis, consumers that keep per-device partial accumulators (the
streaming solvers, column means, the streaming StandardScaler) request one
staging **lane per data-axis device** — chunk ``i`` is committed to the
device of lane ``i % lanes`` (``parallel/lanes.py``), each lane running its
own ``depth``-deep H2D ring, so the whole mesh ingests the stream
concurrently. The round-robin is deterministic and order is still
preserved, so a consumer recovers a chunk's lane from its position alone.
Lane consumers reduce their partials across the mesh once per block or
once at finalize (``reduce_lane_partials``) and the transfer count lands on
the scan's span as ``collectives`` — the PAPERS.md #3 gate is that this is
O(blocks), never O(chunks). ``lanes=1`` (any 1-device mesh, or
``KEYSTONE_SCAN_LANES=1``) is byte-identical to the single-device scan.

Fault tolerance (``keystone_tpu/faults``): every scan owns one bounded
transient-retry budget (``KEYSTONE_SCAN_RETRIES``, default 0 = fail
fast). With a budget, transient failures — injected chaos faults at the
``scan.chunk``/``scan.stage`` fault points, flaky H2D staging, a
re-callable ``from_chunk_fn`` source raising a typed
:class:`~keystone_tpu.faults.TransientError` — retry with bounded
exponential backoff (``KEYSTONE_SCAN_RETRY_BACKOFF``); exhaustion
propagates the ORIGINAL exception with its original traceback, exactly
the pre-retry behavior.

Knobs: ``KEYSTONE_SCAN_PIPELINE=0`` is the kill switch (serial scan, the
staging double buffer kept — lane placement preserved); ``KEYSTONE_SCAN_DEPTH``
sets the buffer and per-lane staging depth (default 2; a K-lane scan keeps
up to ``depth x K`` chunks in flight); ``KEYSTONE_SCAN_LANES`` overrides
the lane count; ``KEYSTONE_CHUNK_BUCKETS=0`` disables ragged-chunk shape
bucketing (:class:`ChunkPadder`); ``KEYSTONE_MAP_WORKERS`` sizes the
per-chunk item thread pool in ``ChunkedDataset.map``.

Per-scan counters (producer-stall vs consumer-stall seconds, staged H2D
bytes — per lane on sharded scans — peak buffer occupancy, collective
count) land as ``scan.pipeline`` spans in the tracer (``obs/scan.py``)
when tracing is on.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from queue import Empty, Full, Queue
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..faults import SCAN_STAGE, RetryBudget, retry_call
from ..utils import env_flag as _env_flag, env_int as _env_int
from ..utils.obs import every as _log_every

logger = logging.getLogger(__name__)

DEFAULT_DEPTH = 2
_JOIN_TIMEOUT = 5.0


def pipeline_enabled() -> bool:
    """KEYSTONE_SCAN_PIPELINE kill switch (default on). Read per scan so
    a process can toggle it (bench A/B, test isolation)."""
    return _env_flag("KEYSTONE_SCAN_PIPELINE", True)


def bucketing_enabled() -> bool:
    """KEYSTONE_CHUNK_BUCKETS switch for :class:`ChunkPadder` (default on)."""
    return _env_flag("KEYSTONE_CHUNK_BUCKETS", True)


def pipeline_depth() -> int:
    return _env_int("KEYSTONE_SCAN_DEPTH", DEFAULT_DEPTH)


def map_workers() -> int:
    """Pool size for ChunkedDataset.map's per-item fallback. Default
    min(4, cores): the per-item fns are host featurizers whose numpy work
    releases the GIL; 1 disables the pool."""
    return _env_int("KEYSTONE_MAP_WORKERS", min(4, os.cpu_count() or 1))


def payload_rows(payload: Any) -> int:
    leaves = jax.tree_util.tree_leaves(payload)
    return int(leaves[0].shape[0])


def payload_nbytes(payload: Any) -> int:
    """Materialized bytes of a chunk payload. Leaves without a dtype
    (Python scalars, nested lists) are measured through numpy's view of
    them rather than assumed float32 — ``cache()`` budget decisions
    depend on this being right for float64/int8 payloads."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(payload):
        dt = getattr(leaf, "dtype", None)
        shape = getattr(leaf, "shape", None)
        if dt is None or shape is None:
            leaf = np.asarray(leaf)
            dt, shape = leaf.dtype, leaf.shape
        total += int(np.prod(shape)) * int(np.dtype(dt).itemsize)
    return total


def _on_device(leaf: Any, device: Any) -> bool:
    from ..parallel.lanes import _single_device

    return _single_device(leaf) == device


def _stage_chunk(chunk: Any, device: Any = None) -> Tuple[Any, int]:
    """Issue the H2D transfer for host (numpy) chunks; device arrays and
    non-array payloads pass through. With a lane ``device``, every array
    leaf is committed there — numpy via H2D, device arrays (e.g. a
    mesh-sharded featurized chunk) via D2D gather — so a lane's partial
    accumulators never mix devices. Returns (staged_chunk, bytes_staged)."""
    leaves = jax.tree_util.tree_leaves(chunk)
    if device is not None:
        movable = any(
            isinstance(leaf, np.ndarray) or hasattr(leaf, "devices")
            for leaf in leaves
        )
        if not movable or all(_on_device(leaf, device) for leaf in leaves):
            return chunk, 0
        return jax.device_put(chunk, device), payload_nbytes(chunk)
    if any(isinstance(leaf, np.ndarray) for leaf in leaves):
        return jax.device_put(chunk), payload_nbytes(chunk)
    return chunk, 0


@dataclass
class ScanStats:
    """Counters for one pipelined scan — the tracer schema's
    ``scan.pipeline`` span attrs (obs/scan.py)."""

    label: str = "scan"
    depth: int = DEFAULT_DEPTH
    chunks: int = 0
    #: host production time inside the producer thread (next(source))
    producer_seconds: float = 0.0
    #: producer blocked on a full buffer (consumer-bound scan)
    producer_stall_seconds: float = 0.0
    #: consumer blocked on an empty buffer (producer-bound scan)
    consumer_stall_seconds: float = 0.0
    staged_bytes: int = 0
    occupancy_max: int = 0
    start: float = 0.0
    end: float = 0.0
    #: staging lanes (data-axis devices) this scan round-robins over;
    #: 1 = the single-device path, no lane accounting
    lanes: int = 1
    #: chunks / staged bytes per lane (len == lanes when lanes > 1) —
    #: skew across lanes is the straggler signal the obs audit reads
    lane_chunks: List[int] = field(default_factory=list)
    lane_bytes: List[int] = field(default_factory=list)
    #: str(device) per lane, for device attribution in spans
    lane_devices: List[str] = field(default_factory=list)
    #: consumer-reported cross-mesh transfers (partial-accumulator
    #: reductions + per-block model broadcasts) attributed to this scan
    collectives: int = 0
    #: transient-failure retries consumed from the scan's RetryBudget
    retries: int = 0
    #: producer shards feeding this scan (data/shards.py); 1 = the
    #: single-producer path, no shard accounting
    shards: int = 1
    #: chunks produced per shard (len == shards when shards > 1) —
    #: production skew is the host-side straggler signal
    shard_chunks: List[int] = field(default_factory=list)


_CHUNK, _ERROR, _DONE = 0, 1, 2


def _producer_put(q: Queue, stop: threading.Event, stats: ScanStats, item) -> bool:
    t0 = time.perf_counter()
    while not stop.is_set():
        try:
            q.put(item, timeout=0.05)
        except Full:
            continue
        if item[0] == _CHUNK:
            stats.producer_stall_seconds += time.perf_counter() - t0
            occ = q.qsize()
            if occ > stats.occupancy_max:
                stats.occupancy_max = occ
        return True
    return False


def _producer_loop(
    source: Iterator[Any], q: Queue, stop: threading.Event, stats: ScanStats,
) -> None:
    """The producer thread body. A MODULE-LEVEL function on purpose: the
    thread must not hold a reference to the ScanPipeline, or an abandoned
    iterator could never be garbage-collected (the thread registry would
    pin it) and its producer would run to exhaustion unreaped.

    Fault injection note: the ``scan.chunk`` fault point lives at the
    :class:`~keystone_tpu.data.chunked.ChunkedDataset` seam (inside the
    source this loop pulls), NOT here — a generator source is dead once
    it raises, so retrying ``next(source)`` from outside would silently
    truncate the stream; injecting (and retrying) INSIDE the source's
    own loop keeps the generator alive across retries."""
    try:
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                item = next(source)
            except StopIteration:
                break
            stats.producer_seconds += time.perf_counter() - t0
            if not _producer_put(q, stop, stats, (_CHUNK, item)):
                return
    except BaseException as e:  # noqa: BLE001 — surfaces in the consumer
        _producer_put(q, stop, stats, (_ERROR, e))
        return
    finally:
        # deterministic cleanup of the chain (file handles, tar readers)
        close = getattr(source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                # a source whose close() fails mid-teardown must not kill
                # the scan, but an injected fault vanishing here would make
                # the chaos schedule unreadable — say what happened
                if _log_every("scan.source_close", 30.0):
                    logger.warning(
                        "scan[%s]: chunk-source close() failed",
                        stats.label, exc_info=True,
                    )
    _producer_put(q, stop, stats, (_DONE, None))


class ScanPipeline:
    """One pipelined scan: an order-preserving iterator of chunks backed
    by a producer thread and a bounded buffer. See the module docstring
    for the contract; construct through :func:`scan_pipeline`."""

    def __init__(
        self,
        source: Any,
        *,
        depth: Optional[int] = None,
        stage: bool = True,
        label: str = "scan",
        lanes: int = 1,
        devices: Optional[Sequence[Any]] = None,
    ):
        self._depth = depth or pipeline_depth()
        self._do_stage = stage
        self._lanes = max(1, int(lanes))
        if self._lanes > 1 and stage:
            if devices is None:
                from ..parallel.lanes import lane_devices as _lane_devices

                devices = _lane_devices(self._lanes)
            self._devices: Optional[List[Any]] = list(devices)
        else:
            # lanes without staging is meaningless; collapse to one lane so
            # the single-device contract (and its span schema) holds
            self._lanes = 1
            self._devices = None
        self._ring = self._depth * self._lanes
        self._seq = 0
        self._q: Queue = Queue(maxsize=self._ring)
        self._stop = threading.Event()
        self._staged: deque = deque()
        self._source_done = False
        self._error: Optional[BaseException] = None
        self._closed = False
        self._recorded = False
        self._span = None
        self.stats = ScanStats(
            label=label, depth=self._depth, start=time.perf_counter()
        )
        # ONE transient-retry budget per scan: when the source is the
        # chunk-fault injection seam (chunked._InjectedChunks) its budget
        # is ADOPTED, so chunk-production and staging retries draw from
        # the same bounded pool and both land in the span's retry count
        self._retry = (
            getattr(source, "retry_budget", None)
            or RetryBudget(label=f"scan[{label}]")
        )
        # a sharded producer feeding this scan stamps its production
        # split onto the span at shutdown (counts grow until then)
        self._shard_source = (
            source if getattr(source, "shards", 1) > 1 else None
        )
        if self._devices is not None:
            self.stats.lanes = self._lanes
            self.stats.lane_chunks = [0] * self._lanes
            self.stats.lane_bytes = [0] * self._lanes
            self.stats.lane_devices = [str(d) for d in self._devices]
        self._thread = threading.Thread(
            target=_producer_loop,
            args=(iter(source), self._q, self._stop, self.stats),
            name=f"ks-scan[{label}]",
            daemon=True,
        )
        self._thread.start()

    # -- consumer ---------------------------------------------------------

    @property
    def lanes(self) -> int:
        """Staging lane count; chunk ``i`` lives on lane ``i % lanes``."""
        return self._lanes

    @property
    def lane_devices(self) -> Optional[List[Any]]:
        """Per-lane devices (None on single-lane scans)."""
        return self._devices

    def record_collectives(self, n: int) -> None:
        """Consumer-reported cross-mesh transfers (per-lane partial
        reductions, per-block model broadcasts) attributed to this scan.
        Works before or after exhaustion — a finalize-time reduction still
        lands on the already-recorded span."""
        self.stats.collectives += int(n)
        if self._span is not None:
            self._span.attrs["collectives"] = self.stats.collectives

    def __iter__(self) -> "ScanPipeline":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        # top up the staging rings so `depth` H2D transfers per lane are in
        # flight while the caller computes on the chunk we hand back
        while not self._source_done and len(self._staged) < self._ring:
            if self._staged:
                try:
                    kind, payload = self._q.get_nowait()
                except Empty:
                    break  # staged work available — don't wait on the producer
            else:
                t0 = time.perf_counter()
                kind, payload = self._get_blocking()
                self.stats.consumer_stall_seconds += time.perf_counter() - t0
            if kind == _DONE:
                self._source_done = True
            elif kind == _ERROR:
                self._source_done = True
                self._error = payload
            else:
                if self._do_stage:
                    lane = self._seq % self._lanes
                    dev = self._devices[lane] if self._devices else None
                    # H2D staging is idempotent (device_put of the same
                    # payload), so transient failures — injected at the
                    # scan.stage fault point or real — retry in place
                    chunk, nbytes = retry_call(
                        lambda: _stage_chunk(payload, dev),
                        self._retry, SCAN_STAGE, label=self.stats.label,
                    )
                    self.stats.staged_bytes += nbytes
                    if self._devices is not None:
                        self.stats.lane_chunks[lane] += 1
                        self.stats.lane_bytes[lane] += nbytes
                else:
                    chunk = payload
                self._seq += 1
                self._staged.append(chunk)
        if self._staged:
            self.stats.chunks += 1
            return self._staged.popleft()
        if self._error is not None:
            err, self._error = self._error, None
            self._shutdown()
            raise err
        self._shutdown()
        raise StopIteration

    def _get_blocking(self) -> Tuple[int, Any]:
        while True:
            try:
                return self._q.get(timeout=0.1)
            except Empty:
                if not self._thread.is_alive():
                    try:
                        return self._q.get_nowait()
                    except Empty:
                        # producer died without a sentinel (process teardown
                        # mid-scan) — fail loudly rather than hang
                        raise RuntimeError(
                            "scan pipeline producer thread died without "
                            "finishing the scan"
                        ) from None

    # -- shutdown ----------------------------------------------------------

    def close(self) -> None:
        """Early consumer exit: stop the producer, drain the buffer so a
        blocked put unblocks, and join the thread."""
        if self._closed:
            return
        self._stop.set()
        self._drain()
        self._shutdown()

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except Empty:
                return

    def _shutdown(self) -> None:
        self._closed = True
        self._source_done = True
        self._stop.set()
        self._staged.clear()
        if self._thread.is_alive():
            self._thread.join(timeout=_JOIN_TIMEOUT)
        if self._recorded:
            return
        self._recorded = True
        self.stats.end = time.perf_counter()
        self.stats.retries = self._retry.attempts
        if self._shard_source is not None:
            self.stats.shards = int(self._shard_source.shards)
            self.stats.shard_chunks = list(
                getattr(self._shard_source, "shard_chunks", []) or []
            )
        try:
            from ..obs.scan import record_scan_span

            # keep the span handle: finalize-time collective counts are
            # stamped onto it after exhaustion (record_collectives)
            self._span = record_scan_span(self.stats)
        except Exception:
            # span recording must never fail a scan, but losing the span
            # silently hides exactly the evidence a chaos run needs
            if _log_every("scan.span_record", 30.0):
                logger.warning(
                    "scan[%s]: failed to record scan.pipeline span",
                    self.stats.label, exc_info=True,
                )

    def __del__(self):
        try:
            if not self._closed:
                self.close()
        except Exception:
            # a GC-time close failure leaves a daemon producer behind —
            # visible at WARNING instead of vanishing (the logging itself
            # is guarded: __del__ can run during interpreter teardown)
            try:
                if _log_every("scan.del_close", 30.0):
                    logger.warning(
                        "scan[%s]: close() failed during garbage "
                        "collection", self.stats.label, exc_info=True,
                    )
            except Exception:  # lint: allow-silent -- interpreter teardown:
                pass           # the logging machinery itself may be gone

    def __enter__(self) -> "ScanPipeline":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def serial_staged(
    chunks: Any,
    depth: int = DEFAULT_DEPTH,
    lanes: int = 1,
    devices: Optional[Sequence[Any]] = None,
):
    """The no-thread fallback (and the old ``prefetch_to_device`` body):
    iterate ``chunks`` with up to ``depth`` device uploads in flight per
    lane. Host (numpy) chunks are ``jax.device_put`` ahead of the consumer
    so the H2D transfer streams while the previous chunk's compute runs;
    device arrays pass through untouched (single-lane) or gather to their
    lane's device (``lanes > 1`` — the round-robin placement contract must
    hold even with the producer thread killed, so lane consumers stay
    correct under KEYSTONE_SCAN_PIPELINE=0). Order is preserved."""
    lanes = max(1, int(lanes))
    if lanes > 1 and devices is None:
        from ..parallel.lanes import lane_devices as _lane_devices

        devices = _lane_devices(lanes)
    q: deque = deque()
    it = iter(chunks)
    seq = 0
    while True:
        while it is not None and len(q) < depth * lanes:
            try:
                chunk = next(it)
            except StopIteration:
                it = None
                break
            dev = devices[seq % lanes] if devices is not None else None
            q.append(_stage_chunk(chunk, dev)[0])
            seq += 1
        if not q:
            return
        yield q.popleft()


def scan_pipeline(
    chunks: Any,
    *,
    depth: Optional[int] = None,
    stage: bool = True,
    label: str = "scan",
    lanes: int = 1,
    devices: Optional[Sequence[Any]] = None,
):
    """THE streaming-scan entry point: wrap any chunk iterable in the
    pipelined runtime. Idempotent (an already-pipelined iterator passes
    through — including its lane layout, so callers must read the
    effective count off ``.lanes`` — and solver sites can wrap
    ``dataset.chunks()`` blindly without stacking threads). ``stage=False``
    skips the H2D staging ring for consumers that want host chunks.
    ``lanes > 1`` round-robins chunks across the data-axis devices (see
    the module docstring); only consumers that keep per-lane partial
    accumulators should ask for it. With ``KEYSTONE_SCAN_PIPELINE=0``
    this degrades to the serial :func:`serial_staged` buffer, lane
    placement preserved."""
    if isinstance(chunks, ScanPipeline):
        return chunks
    if not pipeline_enabled():
        if stage:
            return serial_staged(
                chunks, depth or pipeline_depth(), lanes=lanes, devices=devices
            )
        return iter(chunks)
    return ScanPipeline(
        chunks, depth=depth, stage=stage, label=label, lanes=lanes,
        devices=devices,
    )


# -- chunk-shape bucketing ---------------------------------------------------


def bucket_ladder(
    lead_rows: int, levels: int = 4, multiple: int = 1
) -> Tuple[int, ...]:
    """Bucket row counts for a scan whose lead chunk has ``lead_rows``:
    ``{ceil(lead/2^i) for i < levels}``, ascending. A ragged tail pads to
    the next bucket up (at most ~2× its own rows of wasted compute,
    bounded by lead/2^(levels-1) pad rows), and a fused chain compiles at
    most ``levels`` times per scan instead of once per distinct shape.

    ``multiple`` rounds every bucket UP to a multiple (collapsing rungs
    that collide) — the mesh-sharded fused-chain path needs every bucket
    divisible by the data-axis size so the per-chunk program can span the
    mesh: a 7-row tail on a 4-device axis must pad to 8, not 7."""
    vals = {
        max(1, (lead_rows + (1 << i) - 1) >> i) for i in range(max(1, levels))
    }
    if multiple > 1:
        vals = {((v + multiple - 1) // multiple) * multiple for v in vals}
    return tuple(sorted(vals))


class ChunkPadder:
    """Wrap a per-chunk callable so ragged (tail) chunks pad up to a small
    static bucket ladder derived from the first chunk seen, killing the
    one-XLA-compile-per-distinct-chunk-shape cost of fused chains over
    out-of-core scans.

    Padding repeats the chunk's first row (in-distribution for any
    row-wise chain — the same trick as ``serving/batching.py`` and
    ``FittedPipeline.apply_chunked``) and is sliced off the result, so
    outputs are exact. The wrapped ``fn`` must be row-wise in its leading
    axis (true for fused transformer chains; batch-coupled nodes are
    rejected upstream). The ladder locks on the first chunk and is shared
    across scans, so re-scans (lineage recompute) reuse the compiles.
    ``KEYSTONE_CHUNK_BUCKETS=0`` makes this a transparent pass-through.

    Mesh-sharded scans: bucket targets round up to a ``multiple`` of the
    data-axis lane count (default: the active mesh's, via
    ``parallel.lanes.scan_lanes``) so every padded chunk divides evenly
    over the mesh, and ``shard=True`` commits the padded chunk with
    ``batch_sharding`` before calling ``fn`` — the fused program then
    computes SPMD across the whole mesh per chunk instead of on one
    device. A 1-lane mesh keeps both knobs inert (today's exact path)."""

    def __init__(
        self,
        fn: Callable[[Any], Any],
        levels: int = 4,
        multiple: Optional[int] = None,
        shard: bool = False,
    ):
        self.fn = fn
        self.levels = levels
        self.multiple = multiple
        self.shard = shard
        self._buckets: Optional[Tuple[int, ...]] = None
        self._resolved_multiple = 1
        self._lock = threading.Lock()

    @staticmethod
    def _lane_multiple() -> int:
        try:
            from ..parallel.lanes import scan_lanes

            return scan_lanes()
        except Exception:
            # falling back to 1 is safe (unsharded buckets), but a mesh
            # probe failing is news — a sharded scan would silently lose
            # its lane alignment if this kept vanishing
            if _log_every("scan.lane_multiple", 30.0):
                logger.warning(
                    "chunk bucketing: mesh lane probe failed — padding "
                    "buckets without a lane multiple", exc_info=True,
                )
            return 1

    def _run(self, chunk: Any, rows: int) -> Any:
        """Invoke ``fn``, committing the chunk mesh-sharded first when the
        sharded path is on and the (padded) row count divides the FULL
        data axis — ``batch_sharding`` spans every data-axis device, so a
        KEYSTONE_SCAN_LANES narrower than the axis (lane multiple < axis
        width) must fall back to the unsharded call rather than hand XLA
        an indivisible dim."""
        if self.shard and self._resolved_multiple > 1:
            from ..parallel.mesh import (
                DATA_AXIS,
                batch_sharding,
                default_mesh,
            )

            mesh = default_mesh()
            if rows % int(mesh.shape[DATA_AXIS]) != 0:
                return self.fn(chunk)

            def place(a):
                nd = getattr(a, "ndim", None)
                if not nd:  # scalars / non-arrays pass through
                    return a
                return jax.device_put(a, batch_sharding(mesh, nd))

            chunk = jax.tree_util.tree_map(place, chunk)
        return self.fn(chunk)

    def __call__(self, chunk: Any) -> Any:
        if not bucketing_enabled():
            return self.fn(chunk)
        rows = payload_rows(chunk)
        if self._buckets is None:
            with self._lock:
                if self._buckets is None:
                    m = self.multiple
                    if m is None:
                        m = self._lane_multiple()
                    self._resolved_multiple = max(1, int(m))
                    self._buckets = bucket_ladder(
                        rows, self.levels, multiple=self._resolved_multiple
                    )
        target = next((b for b in self._buckets if b >= rows), None)
        if target is None or target == rows:
            # at-or-above the lead shape: run unpadded (a growing source
            # compiles per such shape, exactly as before)
            return self._run(chunk, rows)
        padded = jax.tree_util.tree_map(
            lambda a: _pad_rows(a, rows, target), chunk
        )
        out = self._run(padded, target)
        return jax.tree_util.tree_map(lambda a: a[:rows], out)


def _pad_rows(a: Any, rows: int, target: int):
    import jax.numpy as jnp

    a = jnp.asarray(a)
    pad = jnp.broadcast_to(a[:1], (target - rows,) + a.shape[1:])
    return jnp.concatenate([a, pad], axis=0)
