"""Sharded chunk production: N producer shards partition the chunk index
space of one out-of-core scan.

Every pipelined scan used to be fed by ONE producer thread running the
whole lazy chunk chain — tar decode, host featurizers, per-item maps —
while the staging lanes and the device waited on it. The Spark-perf
study (PAPERS.md #3) calls this the driver/host bottleneck, and it is
exactly the shape the reference never has: RDD *partitions* produce in
parallel. :class:`ShardedChunkProducer` is that counterpart for the
chunk-factory world: shard ``s`` of ``N`` produces chunk indices
``s, s+N, s+2N, …`` through the dataset's stride factory (each shard
runs the WHOLE lazy chain for its indices — production cost genuinely
splits), and the consumer-side merge pops the per-shard queues
round-robin in index order, so the merged stream is **bit-identical**
to the single-producer scan: same chunks, same order, same values.

The seam is deliberately process-shaped: a shard is "anything that
yields chunk ``s, s+N, …`` into a bounded queue". Today's shards are
threads (the chunk chains are numpy/JAX host work that releases the
GIL; a thread per shard already overlaps production on shared cores) —
a process-backed shard only has to speak the same queue protocol.

Contracts preserved from the single-producer scan:

* **Order** — the merge is strict round-robin by index; a fast shard
  waits in its queue, never overtakes.
* **Errors** — a shard failure surfaces in the consumer AT THE INDEX it
  occurred (chunks before it are still delivered), with the original
  traceback.
* **Early exit** — ``close()`` (or garbage collection) stops every
  shard, drains the queues so blocked puts unblock, and joins the
  threads: no orphans, no deadlock.
* **Fault injection** — the ``scan.chunk`` fault point stays OUTSIDE,
  at the merged-iterator seam (``chunked._maybe_inject`` wraps the
  producer), so a chaos schedule's invocation indices match the merged
  chunk order deterministically regardless of shard interleaving.
* **Retry budgets** — a ``from_chunk_fn`` source's per-index
  regeneration retries ride inside each shard's own iterator, bounded
  per shard exactly as the single producer bounds its one iterator.

``KEYSTONE_SCAN_SHARDS`` (default 1 = today's single producer) sets the
shard count; sources without a stride factory (opaque generators) fall
back to one producer with a rate-limited log line, never an error.
"""

from __future__ import annotations

import logging
import threading
import time
from queue import Empty, Full, Queue
from typing import Any, Callable, Iterator, List, Optional

from ..utils import env_int as _env_int
from ..utils.obs import every as _log_every

logger = logging.getLogger(__name__)

#: per-shard queue depth: how far one shard may run ahead of the merge
DEFAULT_SHARD_DEPTH = 2
_JOIN_TIMEOUT = 5.0

_CHUNK, _ERROR, _DONE = 0, 1, 2


def scan_shards() -> int:
    """Producer shards per scan: ``KEYSTONE_SCAN_SHARDS``, default 1
    (single producer, byte-identical to the pre-shard path). Read per
    scan so tests and benches can flip it."""
    return _env_int("KEYSTONE_SCAN_SHARDS", 1)


def _shard_loop(
    it: Iterator[Any],
    q: Queue,
    stop: threading.Event,
    counts: List[int],
    shard: int,
) -> None:
    """One shard's thread body: run the stride iterator into the bounded
    queue. Module-level for the same reason as the scan pipeline's
    producer: the thread must not pin the owning producer object."""

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except Full:
                continue
        return False

    try:
        while not stop.is_set():
            try:
                chunk = next(it)
            except StopIteration:
                break
            if not put((_CHUNK, chunk)):
                return
            counts[shard] += 1
    except BaseException as e:  # noqa: BLE001 — surfaces in the consumer
        put((_ERROR, e))
        return
    finally:
        close = getattr(it, "close", None)
        if close is not None:
            try:
                close()
            except Exception:
                if _log_every("shards.source_close", 30.0):
                    logger.warning(
                        "sharded scan: shard %d source close() failed",
                        shard, exc_info=True,
                    )
    put((_DONE, None))


class ShardedChunkProducer:
    """Order-preserving merge of N shard producers over one stride
    factory (``fn(start, step) -> iterator of chunks start, start+step,
    …``). Iterate it like any chunk source; hand it to
    ``scan_pipeline`` as the scan's source."""

    def __init__(
        self,
        stride_factory: Callable[[int, int], Iterator[Any]],
        shards: int,
        *,
        start: int = 0,
        depth: int = DEFAULT_SHARD_DEPTH,
        label: str = "scan",
    ):
        if shards < 2:
            raise ValueError(
                f"ShardedChunkProducer needs >= 2 shards, got {shards} "
                "(1 shard IS the single-producer path)"
            )
        self.shards = int(shards)
        self.label = label
        #: chunks produced per shard — the span's skew/straggler signal
        self.shard_chunks: List[int] = [0] * self.shards
        self._queues: List[Queue] = [
            Queue(maxsize=max(1, depth)) for _ in range(self.shards)
        ]
        self._stop = threading.Event()
        self._next = 0  # merged chunk cursor; pops queue _next % shards
        self._closed = False
        self._threads: List[threading.Thread] = []
        for s in range(self.shards):
            t = threading.Thread(
                target=_shard_loop,
                args=(
                    iter(stride_factory(start + s, self.shards)),
                    self._queues[s],
                    self._stop,
                    self.shard_chunks,
                    s,
                ),
                name=f"ks-shard[{label}]{s}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    def __iter__(self) -> "ShardedChunkProducer":
        return self

    def __next__(self) -> Any:
        if self._closed:
            raise StopIteration
        s = self._next % self.shards
        kind, payload = self._get(s)
        if kind == _CHUNK:
            self._next += 1
            return payload
        # _DONE from shard s means chunk index `self._next` does not
        # exist — and chunk indices are dense, so nothing beyond it
        # exists either: the scan is over regardless of what later
        # shards still hold (they can only hold SMALLER indices already
        # consumed, or nothing).
        self.close()
        if kind == _ERROR:
            raise payload
        raise StopIteration

    def _get(self, s: int):
        q = self._queues[s]
        t = self._threads[s]
        while True:
            try:
                return q.get(timeout=0.1)
            except Empty:
                if not t.is_alive():
                    try:
                        return q.get_nowait()
                    except Empty:
                        raise RuntimeError(
                            f"sharded scan[{self.label}]: shard {s} died "
                            "without finishing its index range"
                        ) from None

    def close(self) -> None:
        """Stop every shard, drain the queues, join the threads."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        for q in self._queues:
            while True:
                try:
                    q.get_nowait()
                except Empty:
                    break
        deadline = time.monotonic() + _JOIN_TIMEOUT
        for t in self._threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
            if t.is_alive() and _log_every("shards.join", 30.0):
                logger.warning(
                    "sharded scan[%s]: shard thread %s did not exit "
                    "within %.1fs — abandoning it (daemon)",
                    self.label, t.name, _JOIN_TIMEOUT,
                )

    def __del__(self):
        try:
            if not self._closed:
                self.close()
        except Exception:  # lint: allow-silent -- interpreter teardown:
            pass           # close targets may already be collected


def maybe_shard(
    stride_factory: Optional[Callable[[int, int], Iterator[Any]]],
    fallback: Callable[[], Iterator[Any]],
    *,
    shards: Optional[int] = None,
    start: int = 0,
    label: str = "scan",
) -> Iterator[Any]:
    """The one decision point: a sharded producer when the knob asks for
    one AND the source can stride, else the plain single-producer
    iterator. An opaque source under ``KEYSTONE_SCAN_SHARDS > 1`` logs
    (rate-limited) and falls back — sharding is an optimization, never
    a requirement."""
    n = scan_shards() if shards is None else int(shards)
    if n <= 1:
        return fallback()
    if stride_factory is None:
        if _log_every(f"shards.fallback:{label}", 30.0):
            logger.info(
                "scan[%s]: KEYSTONE_SCAN_SHARDS=%d requested but the "
                "chunk source is not index-addressable — producing "
                "single-threaded", label, n,
            )
        return fallback()
    return ShardedChunkProducer(
        stride_factory, n, start=start, label=label
    )
