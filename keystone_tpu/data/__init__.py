from .dataset import Dataset

__all__ = ["Dataset"]
