from .chunked import ChunkedDataset
from .dataset import Dataset
from .sparse import SparseRows

__all__ = ["ChunkedDataset", "Dataset", "SparseRows"]
