from .dataset import Dataset
from .sparse import SparseRows

__all__ = ["Dataset", "SparseRows"]
