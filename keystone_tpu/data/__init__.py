from .chunked import ChunkedDataset, prefetch_to_device
from .dataset import Dataset
from .pipeline_scan import ChunkPadder, ScanPipeline, scan_pipeline
from .sparse import SparseRows

__all__ = [
    "ChunkPadder",
    "ChunkedDataset",
    "Dataset",
    "ScanPipeline",
    "SparseRows",
    "prefetch_to_device",
    "scan_pipeline",
]
