"""Padded sparse-row batches — the TPU answer to the reference's
``SparseVector[Double]`` rows (breeze sparse vectors inside RDDs).

XLA has no dynamic sparsity, so sparse feature rows are stored as a padded
COO batch: ``indices (n, m) int32`` + ``values (n, m) float32`` with a
static row capacity ``m`` (max nnz, rounded up). Padding entries carry
``value == 0`` at index 0, which is algebraically inert for every consumer:

  * ``matmul(W)``   — embedding-style gather ``W[indices]·values`` (the MXU
                      path for SparseLinearMapper / sparse LBFGS gradients);
                      zero values contribute nothing.
  * ``to_dense()``  — scatter-add; zero values contribute nothing.
  * class sums      — scatter-add into (classes, d); same argument.

This is the SURVEY §7 "sparse text features" decision point: top-K feature
selection (CommonSparseFeatures) keeps K bounded, rows keep a small static
capacity, and everything downstream is gathers/scatters XLA tiles well.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _round_up(x: int, multiple: int = 8) -> int:
    return max(multiple, ((x + multiple - 1) // multiple) * multiple)


@jax.tree_util.register_pytree_node_class
class SparseRows:
    """A batch of n sparse feature rows over a d-dim feature space."""

    def __init__(self, indices, values, num_features: int):
        self.indices = indices  # (n, m) int32, padded with 0
        self.values = values    # (n, m) float32, padded with 0.0
        self.num_features = int(num_features)

    # -- pytree protocol -------------------------------------------------

    def tree_flatten(self):
        return (self.indices, self.values), self.num_features

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    # -- shape -----------------------------------------------------------

    @property
    def shape(self) -> Tuple[int, int]:
        return (int(self.indices.shape[0]), self.num_features)

    @property
    def row_capacity(self) -> int:
        return int(self.indices.shape[-1])

    @property
    def nnz(self) -> int:
        return int(np.sum(np.asarray(self.values) != 0))

    def __len__(self) -> int:
        return int(self.indices.shape[0])

    # -- constructors ----------------------------------------------------

    @staticmethod
    def from_pairs(
        rows: Iterable[Sequence[Tuple[int, float]]],
        num_features: int,
        row_capacity: int = None,
    ) -> "SparseRows":
        """Build from per-row (feature_index, value) pair lists. Rows longer
        than the capacity keep their largest-|value| entries."""
        rows = [list(r) for r in rows]
        max_nnz = max((len(r) for r in rows), default=1)
        m = row_capacity or _round_up(max_nnz)
        n = len(rows)
        idx = np.zeros((n, m), dtype=np.int32)
        val = np.zeros((n, m), dtype=np.float32)
        for i, r in enumerate(rows):
            if len(r) > m:
                r = sorted(r, key=lambda p: -abs(p[1]))[:m]
            for j, (f, v) in enumerate(r):
                idx[i, j] = f
                val[i, j] = v
        return SparseRows(jnp.asarray(idx), jnp.asarray(val), num_features)

    @staticmethod
    def from_scipy(mat) -> "SparseRows":
        import scipy.sparse as sp

        csr = sp.csr_matrix(mat)
        rows = [
            list(zip(csr.indices[s:e], csr.data[s:e]))
            for s, e in zip(csr.indptr[:-1], csr.indptr[1:])
        ]
        return SparseRows.from_pairs(rows, csr.shape[1])

    # -- consumers -------------------------------------------------------

    def to_dense(self, dtype=None) -> jnp.ndarray:
        """(n, d) dense scatter. ``dtype`` bounds the target's memory.

        NOTE for large inputs: XLA's TPU scatter pads its index/update
        operands ~66×, so one 25M-update scatter allocates 10+ GB of
        pure padding. Callers densifying big matrices should scatter row
        SLICES (``row_slice``) and consume each block before the next —
        see SparseLBFGSwithL2's streamed Gram accumulation."""
        n, m = self.indices.shape
        dtype = dtype or self.values.dtype
        out = jnp.zeros((n, self.num_features), dtype=dtype)
        rows = jnp.broadcast_to(jnp.arange(n)[:, None], (n, m))
        return out.at[rows, self.indices].add(self.values.astype(dtype))

    def row_slice(self, start: int, stop: int) -> "SparseRows":
        """A row-range view (shared buffers, sliced padded arrays)."""
        return SparseRows(
            self.indices[start:stop], self.values[start:stop],
            self.num_features,
        )

    def matmul(self, W) -> jnp.ndarray:
        """X @ W without densifying: gather W rows by feature index, weight
        by values, reduce over the row capacity. W: (d, k) → (n, k)."""
        W = jnp.asarray(W)
        gathered = W[self.indices]  # (n, m, k)
        return jnp.einsum("nmk,nm->nk", gathered, self.values)

    def rmatmul(self, R) -> jnp.ndarray:
        """Xᵀ @ R without densifying: scatter-add row contributions into a
        (d, k) accumulator. R: (n, k) → (d, k). This is the gradient-side
        primitive (Aᵀ·residual) of the sparse solvers."""
        R = jnp.asarray(R)
        k = R.shape[1]
        contrib = self.values[:, :, None] * R[:, None, :]  # (n, m, k)
        out = jnp.zeros((self.num_features, k), dtype=self.values.dtype)
        idx = jnp.broadcast_to(self.indices[:, :, None], contrib.shape)
        col = jnp.broadcast_to(jnp.arange(k)[None, None, :], contrib.shape)
        return out.at[idx, col].add(contrib)

    def class_sums(self, onehot) -> jnp.ndarray:
        """onehotᵀ @ X without densifying: scatter-add values into a
        (classes, d) accumulator. onehot: (n, k) → (k, d). Pure jnp —
        safe under jit/vmap. Callers with hard int labels should use
        :meth:`label_sums`, which scatters (n, m) elements instead of
        (n, m, k)."""
        onehot = jnp.asarray(onehot)
        k = onehot.shape[1]
        # (n, m, k) contributions scattered by feature index
        contrib = self.values[:, :, None] * onehot[:, None, :]  # (n, m, k)
        out = jnp.zeros((k, self.num_features), dtype=self.values.dtype)
        idx = jnp.broadcast_to(
            self.indices[:, :, None], contrib.shape
        )
        cls = jnp.broadcast_to(
            jnp.arange(k)[None, None, :], contrib.shape
        )
        return out.at[cls, idx].add(contrib)

    def label_sums(self, y, k: int) -> jnp.ndarray:
        """Per-class feature sums for hard int labels: (k, d) via ONE
        (n, m)-element scatter-add (padded slots carry value 0, so they
        add nothing wherever they land)."""
        y = jnp.asarray(y, dtype=jnp.int32)
        cls = jnp.broadcast_to(y[:, None], self.values.shape)
        out = jnp.zeros((k, self.num_features), dtype=self.values.dtype)
        return out.at[cls, self.indices].add(self.values)

    def density(self) -> float:
        n, d = self.shape
        return self.nnz / float(max(n * d, 1))

    @staticmethod
    def datum_from_pairs(x, num_features: int) -> Optional["SparseRows"]:
        """Interpret a per-datum value as a 1-row SparseRows when it is a
        sparse (index, value) pair list (what SparseFeatureVectorizer /
        HashingTF emit per item — the reference's SparseVector role).
        Returns None when ``x`` is not pair-shaped."""
        if isinstance(x, SparseRows):
            return x
        if isinstance(x, (list, tuple)) and (
            len(x) == 0
            or (
                isinstance(x[0], (tuple, list))
                and len(x[0]) == 2
                and isinstance(x[0][0], (int, np.integer))
            )
        ):
            return SparseRows.from_pairs([x], num_features)
        return None

    def __getitem__(self, i) -> "SparseRows":
        sl = self.indices[i], self.values[i]
        if np.ndim(sl[0]) == 1:  # single row → keep 2-D batch form
            sl = (sl[0][None], sl[1][None])
        return SparseRows(sl[0], sl[1], self.num_features)
