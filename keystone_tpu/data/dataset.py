"""The host-side logical collection type that stands in for the reference's RDD.

The reference distributes ``RDD[T]`` over Spark executors and gets per-partition
batching by stacking rows into local matrices (``utils/MatrixUtils.scala:41-77``).
On TPU the natural layout is the opposite: data lives *already batched* as a
stacked ``jax.Array`` in HBM (leading batch dimension), optionally sharded over a
device mesh, and per-item views are the derived form. ``Dataset`` wraps either:

  * ``batched`` payload — one array (or pytree of arrays) with a common leading
    batch dimension. This is the fast path every numeric node uses.
  * ``items`` payload — a Python list of arbitrary objects (ragged images,
    token lists, strings) for data that has no rectangular layout.

Transformers prefer ``map_batch`` over arrays; ``map`` is the per-item fallback.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _is_arraylike(x: Any) -> bool:
    return isinstance(x, (np.ndarray, jnp.ndarray, jax.Array))


class Dataset:
    """A logical collection of N items, batched (stacked array) or listed."""

    def __init__(self, payload: Any, *, batched: bool):
        self._payload = payload
        self._batched = batched

    # ---- constructors ---------------------------------------------------

    @staticmethod
    def of(data: Any) -> "Dataset":
        """Wrap ``data``: arrays become batched datasets, iterables item lists."""
        if isinstance(data, Dataset):
            return data
        if _is_arraylike(data):
            return Dataset(data, batched=True)
        return Dataset(list(data), batched=False)

    @staticmethod
    def from_array(arr: Any) -> "Dataset":
        return Dataset(jnp.asarray(arr), batched=True)

    @staticmethod
    def from_items(items: Iterable[Any]) -> "Dataset":
        return Dataset(list(items), batched=False)

    # ---- shape / access -------------------------------------------------

    @property
    def is_batched(self) -> bool:
        return self._batched

    @property
    def payload(self) -> Any:
        return self._payload

    def __len__(self) -> int:
        if self._batched:
            leaves = jax.tree_util.tree_leaves(self._payload)
            return int(leaves[0].shape[0])
        return len(self._payload)

    def __iter__(self) -> Iterator[Any]:
        if self._batched:
            n = len(self)
            for i in range(n):
                yield jax.tree_util.tree_map(lambda a: a[i], self._payload)
        else:
            yield from self._payload

    def first(self) -> Any:
        if self._batched:
            return jax.tree_util.tree_map(lambda a: a[0], self._payload)
        return self._payload[0]

    def take(self, n: int) -> "Dataset":
        """The first ``n`` items as a dataset, WITHOUT materializing the
        rest: batched payloads are sliced views (no per-item unstacking,
        unlike ``collect()[:n]``), item lists slice the list. Sampling
        paths (node optimization, profiling) go through here."""
        if n < 0:
            raise ValueError("take of a negative count")
        if self._batched:
            return Dataset(
                jax.tree_util.tree_map(lambda a: a[:n], self._payload),
                batched=True,
            )
        return Dataset(self._payload[:n], batched=False)

    def collect(self) -> List[Any]:
        """Materialize as a list of per-item values (host)."""
        return list(self)

    def to_array(self) -> jnp.ndarray:
        """The stacked-array view; stacks list items if necessary."""
        if self._batched:
            return self._payload
        return jnp.stack([jnp.asarray(x) for x in self._payload])

    # ---- functional ops -------------------------------------------------

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Per-item map on the host. Result is re-batched if items are arrays
        of identical shape."""
        items = [fn(x) for x in self]
        return _rebatch(items)

    def map_batch(self, fn: Callable[[Any], Any]) -> "Dataset":
        """Apply ``fn`` to the whole stacked payload at once (the TPU path)."""
        return Dataset(fn(self.to_array()), batched=True)

    def zip(self, *others: "Dataset") -> "Dataset":
        cols = [self, *others]
        n = len(self)
        for o in others:
            if len(o) != n:
                raise ValueError("zip of datasets with different lengths")
        return Dataset.from_items(list(zip(*[c.collect() for c in cols])))

    def cache(self) -> "Dataset":
        """Materialize on device (batched) or as a list; identity semantics."""
        if self._batched:
            payload = jax.tree_util.tree_map(jnp.asarray, self._payload)
            return Dataset(payload, batched=True)
        return Dataset(list(self._payload), batched=False)


def _rebatch(items: Sequence[Any]) -> Dataset:
    """Stack per-item results back into a batched dataset when rectangular."""
    if items and all(_is_arraylike(x) for x in items):
        shape = np.shape(items[0])
        if all(np.shape(x) == shape for x in items):
            return Dataset(jnp.stack([jnp.asarray(x) for x in items]), batched=True)
    return Dataset(list(items), batched=False)
