"""Stupid Backoff n-gram language model (Brants et al., 2007).

Parity: nodes/nlp/StupidBackoff.scala:25-200. Scores are relative
frequencies with a recursive α-discounted backoff:

    S(w_i | context) = freq(ngram)/freq(context)        if freq(ngram) > 0
                     = α · S(w_i | shorter context)      otherwise
    S(w_i)           = freq(w_i) / numTokens

The reference keeps counts in an RDD partitioned by initial bigram
(InitialBigramPartitioner) and scores per-partition; here two forms exist:

* the **dict form** (:class:`StupidBackoffModel`) — the single-process
  reduction of that shuffle: one host dict keyed by tuples
  (NGramIndexerImpl packing), scored per query in Python. Scale ceiling:
  per-query Python recursion + per-key tuple hashing make it practical to
  ~10^6 table entries / ~10^5 queries per call; beyond that use the
  packed form.
* the **packed array form** (:class:`PackedStupidBackoffModel`) — the
  TPU-shaped layout (NaiveBitPackIndexer): every n-gram of order ≤ 3 is
  one int64, the whole table is a pair of sorted flat arrays, and
  scoring is a fixed number of vectorized backoff sweeps
  (``searchsorted`` per level, numpy masks for hit/miss). Bounded by
  host RAM (~10^8-10^9 entries) with O(log n) per query per level; the
  same flat-int64 layout is what a device port would shard (the table
  rides HBM, queries gather) — kept on host here because the tables are
  corpus-sized, not model-sized.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from .indexers import NaiveBitPackIndexer, NGramIndexerImpl


def score_stupid_backoff(
    ngram: tuple,
    ngram_counts: Dict[tuple, int],
    unigram_counts: Dict, num_tokens: int,
    alpha: float = 0.4,
    accum: float = 1.0,
) -> float:
    """(parity: StupidBackoff.scoreLocally, StupidBackoff.scala:63-95)."""
    indexer = NGramIndexerImpl
    freq = ngram_counts.get(ngram, 0)
    # Unigram queries: the fitted table usually holds orders >= 2 (the
    # pipeline counts 2..n-grams), so fall back to the unigram table.
    if freq == 0 and indexer.ngram_order(ngram) == 1:
        freq = unigram_counts.get(ngram[0], 0)
    while True:
        order = indexer.ngram_order(ngram)
        if order == 1:
            return accum * freq / num_tokens
        if freq != 0:
            context = indexer.remove_current_word(ngram)
            if order != 2:
                context_freq = ngram_counts.get(context, 0)
            else:
                context_freq = unigram_counts.get(context[0], 0)
            return accum * freq / context_freq
        # out-of-corpus ngram: back off
        ngram = indexer.remove_farthest_word(ngram)
        order = indexer.ngram_order(ngram)
        if order != 1:
            freq = ngram_counts.get(ngram, 0)
        else:
            freq = unigram_counts.get(ngram[0], 0)
        accum *= alpha


class StupidBackoffModel(Transformer):
    """Query with ``score(ngram)`` / ``score_batch(ngrams)``
    (parity: StupidBackoffModel, StupidBackoff.scala:100-135; like the
    reference, it is not meant to be chained)."""

    def __init__(self, scores: Dict[tuple, float],
                 ngram_counts: Dict[tuple, int],
                 unigram_counts: Dict, num_tokens: int,
                 alpha: float = 0.4):
        self.scores = scores
        self.ngram_counts = ngram_counts
        self.unigram_counts = unigram_counts
        self.num_tokens = num_tokens
        self.alpha = alpha

    def score(self, ngram: Sequence) -> float:
        return score_stupid_backoff(
            tuple(ngram), self.ngram_counts, self.unigram_counts,
            self.num_tokens, self.alpha,
        )

    def score_batch(self, ngrams: Sequence[Sequence]) -> List[float]:
        return [self.score(g) for g in ngrams]

    def apply(self, x):
        raise TypeError(
            "Doesn't make sense to chain this node; use score(ngram)."
        )


class StupidBackoffEstimator(Estimator):
    """Fit the score table from corpus (ngram, count) pairs
    (parity: StupidBackoffEstimator, StupidBackoff.scala:155-200).

    ``unigram_counts`` maps word → count (the pre-computed unigrams the
    reference also takes as a constructor argument)."""

    def __init__(self, unigram_counts: Dict, alpha: float = 0.4):
        self.unigram_counts = dict(unigram_counts)
        self.alpha = alpha

    def fit(self, data: Dataset) -> StupidBackoffModel:
        data = Dataset.of(data)
        ngram_counts: Dict[tuple, int] = {}
        for ngram, count in data:
            key = tuple(ngram)
            ngram_counts[key] = ngram_counts.get(key, 0) + int(count)
        num_tokens = sum(self.unigram_counts.values())
        scores = {}
        for ngram, freq in ngram_counts.items():
            s = score_stupid_backoff(
                ngram, ngram_counts, self.unigram_counts,
                num_tokens, self.alpha,
            )
            if not (0.0 <= s <= 1.0):
                raise AssertionError(
                    f"score = {s:.4f} not in [0,1], ngram = {ngram}"
                )
            scores[ngram] = s
        return StupidBackoffModel(
            scores, ngram_counts, self.unigram_counts, num_tokens, self.alpha
        )


class PackedStupidBackoffModel(Transformer):
    """Stupid Backoff over NaiveBitPackIndexer-packed int64 arrays.

    Same recursion as :func:`score_stupid_backoff` (parity:
    StupidBackoff.scala:63-95), executed as at most ``max_order`` masked
    vectorized sweeps over the whole query batch: each sweep settles
    unigram queries (freq/numTokens), settles hits (freq/contextFreq via
    one context ``searchsorted``), and backs off the rest (strip the
    farthest word, multiply α in). Agreement with the dict path is exact
    (same operation order per query) — asserted in
    tests/nodes/test_nlp.py.
    """

    def __init__(self, keys: np.ndarray, counts: np.ndarray,
                 uni_ids: np.ndarray, uni_counts: np.ndarray,
                 num_tokens: int, alpha: float = 0.4):
        order = np.argsort(keys, kind="stable")
        self.keys = np.asarray(keys, dtype=np.int64)[order]
        self.counts = np.asarray(counts, dtype=np.int64)[order]
        order = np.argsort(uni_ids, kind="stable")
        self.uni_ids = np.asarray(uni_ids, dtype=np.int64)[order]
        self.uni_counts = np.asarray(uni_counts, dtype=np.int64)[order]
        self.num_tokens = int(num_tokens)
        self.alpha = float(alpha)

    @classmethod
    def from_model(cls, model: StupidBackoffModel) -> "PackedStupidBackoffModel":
        """Build the packed tables from a fitted dict-form model. Orders
        above 3 don't fit the 64-bit packing — the dict form remains the
        only representation there (stated ceiling, module docstring)."""
        if any(len(g) > 3 for g in model.ngram_counts):
            raise ValueError(
                "packed form covers orders <= 3 (NaiveBitPackIndexer); "
                "use the dict-form StupidBackoffModel for higher orders"
            )
        for g in model.ngram_counts:
            if not all(isinstance(w, (int, np.integer)) for w in g):
                raise ValueError(
                    f"packed form needs integer word ids in [0, 2^20) "
                    f"(got {g!r}); encode words first, e.g. via "
                    f"WordFrequencyEncoder"
                )
            break  # one key suffices for the type check — homogeneous
        # Negative ids (e.g. WordFrequencyEncoder's -1 OOV sentinel) would
        # sign-extend into the control bits and corrupt the packed order —
        # reject them here; pack() rejects ids >= 2^20.
        if any(w < 0 for g in model.ngram_counts for w in g):
            raise ValueError(
                "packed form needs non-negative word ids; filter or remap "
                "the -1 unknown-token sentinel before packing"
            )
        items = list(model.ngram_counts.items())
        if items:
            keys = np.fromiter(
                (NaiveBitPackIndexer.pack(g) for g, _ in items),
                dtype=np.int64, count=len(items),
            )
            counts = np.fromiter(
                (c for _, c in items), dtype=np.int64, count=len(items)
            )
        else:  # pragma: no cover - empty corpus
            keys = counts = np.zeros(0, dtype=np.int64)
        uni = list(model.unigram_counts.items())
        uni_ids = np.asarray([w for w, _ in uni], dtype=np.int64)
        uni_counts = np.asarray([c for _, c in uni], dtype=np.int64)
        return cls(keys, counts, uni_ids, uni_counts, model.num_tokens,
                   model.alpha)

    @staticmethod
    def _sorted_probe(keys: np.ndarray, vals: np.ndarray,
                      q: np.ndarray) -> np.ndarray:
        """count-or-0 lookup of q in the sorted (keys, vals) table."""
        if not len(keys):
            return np.zeros(q.shape, dtype=np.int64)
        pos = np.searchsorted(keys, q)
        pos = np.minimum(pos, len(keys) - 1)
        return np.where(keys[pos] == q, vals[pos], 0)

    def _lookup_table(self, q: np.ndarray) -> np.ndarray:
        return self._sorted_probe(self.keys, self.counts, q)

    def _lookup_uni(self, word_ids: np.ndarray) -> np.ndarray:
        return self._sorted_probe(self.uni_ids, self.uni_counts, word_ids)

    def _freq_initial(self, q: np.ndarray, orders: np.ndarray) -> np.ndarray:
        """freq for the ORIGINAL query: the n-gram table first, with the
        dict path's unigram fallback for order-1 queries that miss
        (score_stupid_backoff's pre-loop lookup)."""
        freq = self._lookup_table(q)
        uni = orders == 1
        if uni.any():
            fallback = self._lookup_uni(NaiveBitPackIndexer.farthest_word_batch(q))
            freq = np.where(uni & (freq == 0), fallback, freq)
        return freq

    def _freq_backoff(self, q: np.ndarray, orders: np.ndarray) -> np.ndarray:
        """freq after a backoff step: order-1 results read ONLY the
        unigram table (the dict path's in-loop lookup never consults the
        n-gram table for backed-off unigrams)."""
        uni = orders == 1
        freq = self._lookup_table(q)
        if uni.any():
            freq = np.where(
                uni, self._lookup_uni(NaiveBitPackIndexer.farthest_word_batch(q)), freq
            )
        return freq

    def score_packed(self, q: np.ndarray) -> np.ndarray:
        q = np.asarray(q, dtype=np.int64).copy()
        # Keys holding the -1 OOV sentinel (pack_batch deliberately skips
        # validation) sign-extend to control bits 0xF; order_batch would
        # read order 16 and remove_farthest_word_batch would then alias a
        # REAL bigram key — a wrong score or a spurious "count table
        # inconsistent" error, not a miss. Reject them here; the dict-form
        # model handles such queries via legitimate backoff.
        bad = (q < 0) | (((q >> 60) & 0xF) > 2)
        if bad.any():
            raise ValueError(
                "score_packed: invalid packed key(s) (negative word id / "
                "corrupt control bits — e.g. a -1 OOV sentinel packed by "
                "pack_batch); score such queries via the dict-form model "
                "or filter OOV ids before packing"
            )
        n = len(q)
        accum = np.ones(n, dtype=np.float64)
        score = np.zeros(n, dtype=np.float64)
        done = np.zeros(n, dtype=bool)
        orders = NaiveBitPackIndexer.order_batch(q)
        freq = self._freq_initial(q, orders)
        for _ in range(NaiveBitPackIndexer.max_ngram_order):
            # unigrams: S(w) = freq(w)/numTokens
            m = (orders == 1) & ~done
            score[m] = accum[m] * freq[m] / self.num_tokens
            done |= m
            # hits: S = freq(ngram)/freq(context)
            hit = ~done & (freq != 0)
            if hit.any():
                ctx = NaiveBitPackIndexer.remove_current_word_batch(
                    q[hit], orders[hit]
                )
                cfreq = np.where(
                    orders[hit] == 2,
                    self._lookup_uni(NaiveBitPackIndexer.farthest_word_batch(ctx)),
                    self._lookup_table(ctx),
                )
                if np.any(cfreq == 0):  # fail fast like the dict path
                    raise ZeroDivisionError(
                        "context frequency 0 for a fitted n-gram — the "
                        "count table is inconsistent (missing context)"
                    )
                score[hit] = accum[hit] * freq[hit] / cfreq
                done |= hit
            if done.all():
                break
            # misses: back off to the shorter context, α-discounted
            rest = ~done
            q[rest] = NaiveBitPackIndexer.remove_farthest_word_batch(
                q[rest], orders[rest]
            )
            orders[rest] -= 1
            freq[rest] = self._freq_backoff(q[rest], orders[rest])
            accum[rest] *= self.alpha
        return score

    def score(self, ngram: Sequence) -> float:
        return float(
            self.score_packed(
                np.asarray([NaiveBitPackIndexer.pack(tuple(ngram))])
            )[0]
        )

    def score_batch(self, ngrams: Sequence[Sequence]) -> np.ndarray:
        packed = np.fromiter(
            (NaiveBitPackIndexer.pack(tuple(g)) for g in ngrams),
            dtype=np.int64, count=len(ngrams),
        )
        return self.score_packed(packed)

    def apply(self, x):
        raise TypeError(
            "Doesn't make sense to chain this node; use score(ngram)."
        )
