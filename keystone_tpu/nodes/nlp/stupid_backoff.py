"""Stupid Backoff n-gram language model (Brants et al., 2007).

Parity: nodes/nlp/StupidBackoff.scala:25-200. Scores are relative
frequencies with a recursive α-discounted backoff:

    S(w_i | context) = freq(ngram)/freq(context)        if freq(ngram) > 0
                     = α · S(w_i | shorter context)      otherwise
    S(w_i)           = freq(w_i) / numTokens

The reference keeps counts in an RDD partitioned by initial bigram
(InitialBigramPartitioner) and scores per-partition; here the count table is
one host dict (the single-process reduction of that shuffle) and
``score_batch`` vectorizes scoring over an array of n-grams via the same
recursion. The n-gram keys are tuples (NGramIndexerImpl packing).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from .indexers import NGramIndexerImpl


def score_stupid_backoff(
    ngram: tuple,
    ngram_counts: Dict[tuple, int],
    unigram_counts: Dict, num_tokens: int,
    alpha: float = 0.4,
    accum: float = 1.0,
) -> float:
    """(parity: StupidBackoff.scoreLocally, StupidBackoff.scala:63-95)."""
    indexer = NGramIndexerImpl
    freq = ngram_counts.get(ngram, 0)
    # Unigram queries: the fitted table usually holds orders >= 2 (the
    # pipeline counts 2..n-grams), so fall back to the unigram table.
    if freq == 0 and indexer.ngram_order(ngram) == 1:
        freq = unigram_counts.get(ngram[0], 0)
    while True:
        order = indexer.ngram_order(ngram)
        if order == 1:
            return accum * freq / num_tokens
        if freq != 0:
            context = indexer.remove_current_word(ngram)
            if order != 2:
                context_freq = ngram_counts.get(context, 0)
            else:
                context_freq = unigram_counts.get(context[0], 0)
            return accum * freq / context_freq
        # out-of-corpus ngram: back off
        ngram = indexer.remove_farthest_word(ngram)
        order = indexer.ngram_order(ngram)
        if order != 1:
            freq = ngram_counts.get(ngram, 0)
        else:
            freq = unigram_counts.get(ngram[0], 0)
        accum *= alpha


class StupidBackoffModel(Transformer):
    """Query with ``score(ngram)`` / ``score_batch(ngrams)``
    (parity: StupidBackoffModel, StupidBackoff.scala:100-135; like the
    reference, it is not meant to be chained)."""

    def __init__(self, scores: Dict[tuple, float],
                 ngram_counts: Dict[tuple, int],
                 unigram_counts: Dict, num_tokens: int,
                 alpha: float = 0.4):
        self.scores = scores
        self.ngram_counts = ngram_counts
        self.unigram_counts = unigram_counts
        self.num_tokens = num_tokens
        self.alpha = alpha

    def score(self, ngram: Sequence) -> float:
        return score_stupid_backoff(
            tuple(ngram), self.ngram_counts, self.unigram_counts,
            self.num_tokens, self.alpha,
        )

    def score_batch(self, ngrams: Sequence[Sequence]) -> List[float]:
        return [self.score(g) for g in ngrams]

    def apply(self, x):
        raise TypeError(
            "Doesn't make sense to chain this node; use score(ngram)."
        )


class StupidBackoffEstimator(Estimator):
    """Fit the score table from corpus (ngram, count) pairs
    (parity: StupidBackoffEstimator, StupidBackoff.scala:155-200).

    ``unigram_counts`` maps word → count (the pre-computed unigrams the
    reference also takes as a constructor argument)."""

    def __init__(self, unigram_counts: Dict, alpha: float = 0.4):
        self.unigram_counts = dict(unigram_counts)
        self.alpha = alpha

    def fit(self, data: Dataset) -> StupidBackoffModel:
        data = Dataset.of(data)
        ngram_counts: Dict[tuple, int] = {}
        for ngram, count in data:
            key = tuple(ngram)
            ngram_counts[key] = ngram_counts.get(key, 0) + int(count)
        num_tokens = sum(self.unigram_counts.values())
        scores = {}
        for ngram, freq in ngram_counts.items():
            s = score_stupid_backoff(
                ngram, ngram_counts, self.unigram_counts,
                num_tokens, self.alpha,
            )
            if not (0.0 <= s <= 1.0):
                raise AssertionError(
                    f"score = {s:.4f} not in [0,1], ngram = {ngram}"
                )
            scores[ngram] = s
        return StupidBackoffModel(
            scores, ngram_counts, self.unigram_counts, num_tokens, self.alpha
        )
