"""CoreNLPFeatureExtractor counterpart: lemmatized, entity-substituted
n-grams — dependency-free.

Parity target: nodes/nlp/CoreNLPFeatureExtractor.scala:18-47, which runs
the sista/CoreNLP pipeline (tokenize → POS → lemmatize → NER) and emits
n-grams per sentence with entity tokens replaced by their type and the
rest normalized (strip non-alphanumerics, lowercase).

The reference's value is the *feature contract*, not the specific NLP
stack (it even warns the node is "much slower than just using Tokenizer →
NGramsFeaturizer"). This counterpart keeps the contract with host-side
rule-based components:

* a compact suffix-rule lemmatizer (plural -s/-es/-ies, -ing, -ed with
  consonant-doubling and e-restoration, plus an irregulars table);
* a gazetteer NER for PERSON/LOCATION (common given names; countries,
  US states, major cities) — entities become their type token;
* sentence splitting on .!? with per-sentence n-grams, so grams never
  cross sentence boundaries (same as the reference's doc.sentences map).

All behavioral assertions of the reference's CoreNLPFeatureExtractorSuite
(lemmatization, entity extraction, 1-2-3-grams) hold; see
tests/nodes/test_corenlp_lite.py. Heavier NLP is out of scope by design —
swap in a real tagger behind the same interface if needed.
"""

from __future__ import annotations

import re
from typing import List, Sequence

from ...workflow.transformer import Transformer

_VOWELS = set("aeiou")

#: irregular lemmas the suffix rules can't reach (small, extensible)
_IRREGULAR = {
    "ran": "run", "went": "go", "men": "man", "women": "woman",
    "children": "child", "feet": "foot", "mice": "mouse", "geese": "goose",
    "teeth": "tooth", "better": "good", "was": "be", "were": "be",
    "is": "be", "are": "be", "has": "have", "had": "have", "said": "say",
    "made": "make", "took": "take", "came": "come", "saw": "see",
    "got": "get", "gave": "give", "found": "find", "knew": "know",
    "thought": "think", "people": "person",
}

#: tiny gazetteers for the two entity types the reference suite exercises
_PERSON_NAMES = {
    "john", "mary", "james", "robert", "michael", "william", "david",
    "richard", "joseph", "thomas", "charles", "jon", "sarah", "emily",
    "anna", "peter", "paul", "george", "susan", "linda", "karen", "nancy",
    "jennifer", "elizabeth", "alice", "bob", "carol", "dave", "eve",
}
_LOCATIONS = {
    # US states
    "alabama", "alaska", "arizona", "arkansas", "california", "colorado",
    "connecticut", "delaware", "florida", "georgia", "hawaii", "idaho",
    "illinois", "indiana", "iowa", "kansas", "kentucky", "louisiana",
    "maine", "maryland", "massachusetts", "michigan", "minnesota",
    "mississippi", "missouri", "montana", "nebraska", "nevada", "ohio",
    "oklahoma", "oregon", "pennsylvania", "tennessee", "texas", "utah",
    "vermont", "virginia", "washington", "wisconsin", "wyoming",
    # countries / cities commonly hit in the datasets
    "america", "england", "france", "germany", "china", "japan", "india",
    "canada", "mexico", "brazil", "russia", "spain", "italy", "egypt",
    "paris", "london", "berlin", "tokyo", "boston", "chicago", "seattle",
    "houston", "denver", "miami", "atlanta", "dallas",
}


def _ends_cvc(s: str) -> bool:
    """consonant-vowel-consonant ending (Porter's *o condition) — the
    e-restoration heuristic: 'mak' → 'make', but 'jump' stays."""
    if len(s) < 3:
        return False
    c1, v, c2 = s[-3], s[-2], s[-1]
    return (
        c1 not in _VOWELS
        and v in _VOWELS
        and c2 not in _VOWELS
        and c2 not in "wxy"
    )


def lemmatize(word: str) -> str:
    """Rule-based lemma of a lowercase token."""
    w = word
    if w in _IRREGULAR:
        return _IRREGULAR[w]
    if len(w) <= 3:
        return w

    # plural / 3rd-person -s family
    if w.endswith("ies") and len(w) > 4:
        return w[:-3] + "y"
    if w.endswith(("sses", "ches", "shes", "xes", "zes")):
        return w[:-2]
    if w.endswith("s") and not w.endswith(("ss", "us", "is")):
        return w[:-1]

    for suffix in ("ing", "ed"):
        if w.endswith(suffix) and len(w) - len(suffix) >= 2:
            stem = w[: -len(suffix)]
            if not any(ch in _VOWELS for ch in stem):
                continue  # e.g. "sing", "red": suffix is not a suffix
            # consonant doubling: running → run, stopped → stop
            if (
                len(stem) >= 3
                and stem[-1] == stem[-2]
                and stem[-1] not in _VOWELS
                and stem[-1] not in "lsz"
            ):
                return stem[:-1]
            # e-restoration: making → make, hoped → hope
            if _ends_cvc(stem):
                return stem + "e"
            return stem
    return w


_NORMALIZE_RE = re.compile(r"[^a-zA-Z0-9\s+]")


def _normalize(s: str) -> str:
    """parity: CoreNLPFeatureExtractor.normalize (strip non-alphanumerics,
    lowercase)."""
    return _NORMALIZE_RE.sub("", s).lower()


_SENTENCE_RE = re.compile(r"[.!?]+")
_TOKEN_RE = re.compile(r"[A-Za-z0-9']+")


class CoreNLPFeatureExtractor(Transformer):
    """doc string → lemmatized/entity-substituted n-grams
    (parity interface: CoreNLPFeatureExtractor(orders))."""

    def __init__(self, orders: Sequence[int]):
        self.orders = list(orders)

    def _sentence_tokens(self, sentence: str) -> List[str]:
        out = []
        for tok in _TOKEN_RE.findall(sentence):
            low = tok.lower()
            if low in _PERSON_NAMES:
                out.append("PERSON")
            elif low in _LOCATIONS:
                out.append("LOCATION")
            else:
                out.append(_normalize(lemmatize(low)))
        return [t for t in out if t]

    def apply(self, doc: str) -> List[str]:
        sentences = [
            s for s in _SENTENCE_RE.split(doc) if s.strip()
        ]
        token_lists = [self._sentence_tokens(s) for s in sentences]
        grams: List[str] = []
        for n in self.orders:
            for toks in token_lists:
                for i in range(len(toks) - n + 1):
                    grams.append(" ".join(toks[i : i + n]))
        return grams
