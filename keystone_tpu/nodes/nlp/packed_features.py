"""Fused, vectorized text featurization over packed-int64 n-grams.

``PackedTextFeatures(orders, num_features, tf)`` is semantically identical
to the composed chain

    NGramsFeaturizer(orders) → TermFrequency(tf) →
    CommonSparseFeatures(num_features)

(parity: ngrams.scala:20-97 + TermFrequency.scala:18-21 +
CommonSparseFeatures.scala:19-67 — the chain every reference text pipeline
uses), but runs as corpus-level numpy array programs instead of
per-document Python objects: token ids are packed into one int64 per
n-gram (the 20-bit layout of :class:`..nlp.indexers.NaiveBitPackIndexer`),
per-document counting is one lexsort + run-length pass over the whole
corpus, and document-frequency ranking replicates the reference's
(count desc, first-appearance asc) order bit-for-bit — including the
first-appearance uid, which the composed chain derives from per-document
first-occurrence order. Equality with the composed chain is pinned by
tests/nodes/test_packed_features.py.

Why it exists: the host featurization substrate is the measured bottleneck
of the text pipelines (bench.py ``text_featurization``: featurize/solve
ratio >> 1 at 20k docs). This is the same fusion philosophy the device
side gets from whole-chain jit — collapse a chain of per-item stages into
one batched program — applied to the host stages in front of the device
boundary.

Limits: n-gram orders must lie in {1, 2, 3} (the bit-pack layout) and the
vocabulary must stay under 2^20 distinct tokens; both hold for every
reference workload (newsgroups/amazon use 1-2 grams over <=1M-token
vocabularies). Outside those bounds, use the composed chain.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ...data.dataset import Dataset
from ...data.sparse import SparseRows, _round_up
from ...workflow.transformer import Estimator, Transformer
from .indexers import NaiveBitPackIndexer
from .ngrams import validate_orders

_WORD_BITS = 20
_MAX_VOCAB = 1 << _WORD_BITS


def _py_tokenize_raw(docs: Sequence[str], trim: bool, lower: bool):
    """Pure-Python frontend fallback: Trim → LowerCase → Tokenizer applied
    per doc — the spec the native ks_text_frontend is pinned against."""
    from .text import Tokenizer

    tok = Tokenizer()
    out = []
    for d in docs:
        if trim:
            d = d.strip()
        if lower:
            d = d.lower()
        out.append(tok.apply(d))
    return out


def _frontend_ids(
    docs: Sequence[str],
    vocab: Dict[str, int],
    grow: bool,
    trim: bool,
    lower: bool,
    vocab_by_id: List[str],
):
    """Raw strings → per-doc int64 id arrays via the native fused
    trim/lower/tokenize/id pass, or None (caller falls back to the Python
    node chain + _token_ids). Mutates ``vocab`` when growing.
    ``vocab_by_id`` is the id-ordered token list matching ``vocab`` ([]
    for a fresh fit); callers own building/caching it."""
    from ...native import text_frontend_batch

    res = text_frontend_batch(docs, vocab_by_id, grow, trim=trim, lower=lower)
    if res is None:
        return None
    ids_flat, tok_off, new_tokens = res
    if grow:
        base = len(vocab)
        for j, t in enumerate(new_tokens):
            vocab[t] = base + j
        if len(vocab) > _MAX_VOCAB:
            raise ValueError(
                f"vocabulary {len(vocab)} exceeds the 2^{_WORD_BITS} "
                "packed-id limit; use the composed NGramsFeaturizer chain"
            )
    return [a for a in np.split(ids_flat, tok_off[1:-1])]


#: beyond this token width the fixed-width-unicode fast path costs more
#: memory than it saves (see _token_ids); the dict loop takes over
_MAX_VECTORIZED_TOKEN_LEN = 256


def _token_ids_dict(
    docs: Sequence[Sequence[str]],
    vocab: Dict[str, int],
    grow: bool,
) -> List[np.ndarray]:
    """Per-token dict loop — the fallback for pathologically wide tokens."""
    out = []
    get = vocab.get
    if grow:
        for doc in docs:
            arr = np.empty(len(doc), dtype=np.int64)
            for i, t in enumerate(doc):
                j = get(t)
                if j is None:
                    j = len(vocab)
                    vocab[t] = j
                arr[i] = j
            out.append(arr)
    else:
        for doc in docs:
            out.append(
                np.fromiter(
                    (get(t, -1) for t in doc), dtype=np.int64, count=len(doc)
                )
            )
    if len(vocab) > _MAX_VOCAB:
        raise ValueError(
            f"vocabulary {len(vocab)} exceeds the 2^{_WORD_BITS} packed-id "
            "limit; use the composed NGramsFeaturizer chain"
        )
    return out


def _sorted_vocab(vocab: Dict[str, int]):
    """(sorted keys array, aligned ids) for the vectorized lookup; built
    once per fitted vectorizer (the vocab is immutable after fit). Returns
    None when any key exceeds the fixed-width limit (the lookup would
    allocate V×max_len×4 bytes) — callers fall back to the dict loop."""
    if any(len(k) > _MAX_VECTORIZED_TOKEN_LEN for k in vocab):
        return None
    keys = np.asarray(list(vocab.keys()), dtype=str)
    vals = np.asarray(list(vocab.values()), dtype=np.int64)
    sort = np.argsort(keys)
    return keys[sort], vals[sort]


def _token_ids(
    docs: Sequence[Sequence[str]],
    vocab: Dict[str, int],
    grow: bool,
    sorted_vocab=None,
) -> List[np.ndarray]:
    """Map token-list docs to int64 id arrays. ``grow=True`` extends the
    vocabulary (fit); otherwise unknown tokens become -1 (apply).

    Vectorized (VERDICT r3 #7): the per-token Python dict loop was the
    text path's host tail. One ``np.concatenate`` over the corpus, one
    ``np.unique``/``np.searchsorted`` in C, and a small lookup table —
    with ids still assigned in FIRST-SEEN order over the concatenated
    stream, bit-identical to the dict loop (selection tie-breaks depend
    on id order, so this must not change)."""
    lengths = [len(doc) for doc in docs]
    total = sum(lengths)
    if total == 0:
        return [np.empty(0, dtype=np.int64) for _ in docs]
    # fixed-width '<U' arrays give C-speed unique/searchsorted, but their
    # width is the LONGEST token — one 10k-char base64 blob in a 5M-token
    # corpus would inflate the allocation to corpus×max_len×4 bytes. Fall
    # back to the dict loop beyond a sane token width.
    max_len = max(
        (len(t) for doc in docs for t in doc), default=0
    )
    if max_len > _MAX_VECTORIZED_TOKEN_LEN:
        return _token_ids_dict(docs, vocab, grow)
    flat = np.concatenate([np.asarray(doc, dtype=object) for doc in docs])
    flat = flat.astype(str)
    if grow:
        # vocab may already hold entries (not in practice, but keep the
        # dict-API contract): seed the unique pass with existing order
        base = len(vocab)
        uniq, first_idx, inv = np.unique(
            flat, return_index=True, return_inverse=True
        )
        known = (
            np.fromiter(
                (vocab.get(t, -1) for t in uniq), dtype=np.int64,
                count=len(uniq),
            )
            if base
            else np.full(len(uniq), -1, dtype=np.int64)
        )
        # new tokens get ids by first appearance in the stream
        new_mask = known < 0
        order = np.argsort(first_idx[new_mask], kind="stable")
        lut = known.copy()
        new_ids = np.empty(int(new_mask.sum()), dtype=np.int64)
        new_ids[order] = base + np.arange(len(new_ids))
        lut[new_mask] = new_ids
        for t, j in zip(uniq[new_mask], lut[new_mask]):
            vocab[str(t)] = int(j)
        ids_flat = lut[inv]
    else:
        if not vocab:
            ids_flat = np.full(total, -1, dtype=np.int64)
        else:
            # sorted_vocab: None = build here; False = caller already
            # determined the fixed-width lookup is unsafe (wide keys)
            sv = _sorted_vocab(vocab) if sorted_vocab is None \
                else (sorted_vocab or None)
            if sv is None:  # wide vocab keys: fixed-width lookup unsafe
                return _token_ids_dict(docs, vocab, grow)
            keys, vals = sv
            pos = np.searchsorted(keys, flat)
            pos = np.clip(pos, 0, len(keys) - 1)
            hit = keys[pos] == flat
            ids_flat = np.where(hit, vals[pos], -1)
    if len(vocab) > _MAX_VOCAB:
        raise ValueError(
            f"vocabulary {len(vocab)} exceeds the 2^{_WORD_BITS} packed-id "
            "limit; use the composed NGramsFeaturizer chain"
        )
    splits = np.cumsum(lengths)[:-1]
    return [a for a in np.split(ids_flat, splits)]


def _corpus_grams(
    ids_list: List[np.ndarray], orders: Sequence[int]
) -> tuple:
    """All n-grams of every doc as flat corpus-level arrays
    ``(doc_ids, grams, emit_keys)`` — one vectorized pass per order over
    the concatenated token stream, with grams crossing doc boundaries
    masked out. ``emit_keys`` reproduces NGramsFeaturizer's emission order
    (position-major, then order ascending) so first-occurrence ties rank
    identically. OOV components (-1) drop the gram."""
    n_docs = len(ids_list)
    total = sum(len(a) for a in ids_list)
    if total == 0:
        e = np.empty(0, np.int64)
        return e, e, e
    flat = np.concatenate(ids_list) if total else np.empty(0, np.int64)
    lengths = np.fromiter(
        (len(a) for a in ids_list), dtype=np.int64, count=n_docs
    )
    doc_of = np.repeat(np.arange(n_docs, dtype=np.int64), lengths)
    n_orders = len(orders)
    parts_d, parts_g, parts_k = [], [], []
    for oi, order in enumerate(orders):
        if total < order:
            continue
        end = total - order + 1
        # sliding word windows; one bit-pack via the canonical indexer so
        # the int64 layout has a single source of truth
        windows = np.stack(
            [flat[j : end + j] for j in range(order)], axis=1
        )
        valid = (windows >= 0).all(axis=1) & (
            doc_of[:end] == doc_of[order - 1 :]
        )
        packed = NaiveBitPackIndexer.pack_batch(windows, order)
        idx = np.flatnonzero(valid)
        parts_d.append(doc_of[idx])
        parts_g.append(packed[idx])
        parts_k.append(idx * n_orders + oi)
    if not parts_d:
        e = np.empty(0, np.int64)
        return e, e, e
    return (
        np.concatenate(parts_d),
        np.concatenate(parts_g),
        np.concatenate(parts_k),
    )


def _per_doc_unique(doc_ids, flat, emit_keys) -> tuple:
    """Corpus-level (doc_id, gram, count) for every distinct (doc, gram)
    pair, ordered exactly like the composed chain's pair stream:
    doc-major, within-doc first-emission order."""
    # group by (doc, gram)
    order = np.lexsort((flat, doc_ids))
    d_s, g_s, p_s = doc_ids[order], flat[order], emit_keys[order]
    if len(g_s):
        new_group = np.empty(len(g_s), dtype=bool)
        new_group[0] = True
        new_group[1:] = (d_s[1:] != d_s[:-1]) | (g_s[1:] != g_s[:-1])
        starts = np.flatnonzero(new_group)
        counts = np.diff(np.append(starts, len(g_s)))
        first_pos = np.minimum.reduceat(p_s, starts)
        d_u, g_u = d_s[starts], g_s[starts]
    else:
        counts = np.zeros(0, dtype=np.int64)
        first_pos = d_u = g_u = np.zeros(0, dtype=np.int64)
    # uid order: docs in order, within doc by first occurrence
    uid_order = np.lexsort((first_pos, d_u))
    return d_u[uid_order], g_u[uid_order], counts[uid_order]


def _grams_unique(ids_list: List[np.ndarray], orders: Sequence[int]):
    """(d_u, g_u, counts) per distinct (doc, gram) pair, doc-major and
    within-doc first-emission ordered — native doc-local pass when
    available, numpy corpus-lexsort otherwise (output-identical; pinned by
    tests/nodes/test_native_hashing.py)."""
    from ...native import packed_grams_unique

    res = packed_grams_unique(ids_list, orders)
    if res is not None:
        return res
    return _per_doc_unique(*_corpus_grams(ids_list, orders))


def _apply_tf(counts: np.ndarray, fun: Optional[Callable]) -> np.ndarray:
    if fun is None:
        return counts.astype(np.float32)
    distinct = np.unique(counts)
    lut = np.asarray([float(fun(int(c))) for c in distinct], np.float32)
    return lut[np.searchsorted(distinct, counts)]


def _to_sparse_rows(
    doc_ids: np.ndarray,
    cols: np.ndarray,
    values: np.ndarray,
    n_docs: int,
    num_features: int,
) -> SparseRows:
    """Padded SparseRows from flat (doc, col, value) triples, rows sorted
    by column id like SparseFeatureVectorizer.apply."""
    order = np.lexsort((cols, doc_ids))
    d, c, v = doc_ids[order], cols[order], values[order]
    nnz = np.bincount(d, minlength=n_docs).astype(np.int64)
    m = _round_up(int(nnz.max()) if len(nnz) and nnz.max() > 0 else 1)
    indices = np.zeros((n_docs, m), dtype=np.int32)
    vals = np.zeros((n_docs, m), dtype=np.float32)
    offsets = np.concatenate([[0], np.cumsum(nnz)[:-1]])
    slot = np.arange(len(d)) - offsets[d]
    indices[d, slot] = c
    vals[d, slot] = v
    return SparseRows(indices, vals, num_features)


class PackedTextVectorizer(Transformer):
    """Fitted vectorizer: token lists → SparseRows over the selected
    n-gram feature space (the fused analogue of NGramsFeaturizer +
    TermFrequency + SparseFeatureVectorizer)."""

    def __init__(
        self,
        vocab: Dict[str, int],
        selected: np.ndarray,
        columns: np.ndarray,
        orders: Sequence[int],
        tf_fun: Optional[Callable],
        trim: bool = True,
        lower: bool = True,
    ):
        self.vocab = vocab
        self.selected = selected  # sorted packed grams
        self.columns = columns    # column id per selected gram
        self.orders = list(orders)
        self.tf_fun = tf_fun
        #: raw-string frontend config (applies only when docs arrive as
        #: strings rather than token lists)
        self.trim = trim
        self.lower = lower
        #: lazily-built id-ordered token list for the native frontend
        self._vocab_by_id = None
        #: (payload object, per-doc gram stream) handed over by fit so
        #: applying to the training set skips re-tokenizing/re-gramming.
        #: A STRONG reference compared with ``is`` — an id() key could be
        #: reused after GC and silently serve another dataset's grams.
        #: Consumed (cleared) on its one hit; dropped on pickle.
        self._train_cache = None
        #: lazily-built (sorted keys, ids) for the vectorized OOV lookup
        self._sorted_vocab = None

    @property
    def num_features(self) -> int:
        return len(self.selected)

    def _ids(self, docs) -> List[np.ndarray]:
        """Per-doc id arrays from either raw strings (native fused
        frontend, Python chain fallback) or token lists."""
        if docs and isinstance(docs[0], str):
            if self._vocab_by_id is None:
                vb: List[str] = [None] * len(self.vocab)
                for t, i in self.vocab.items():
                    vb[i] = t
                self._vocab_by_id = vb
            ids = _frontend_ids(
                docs, self.vocab, grow=False, trim=self.trim,
                lower=self.lower, vocab_by_id=self._vocab_by_id,
            )
            if ids is not None:
                return ids
            docs = _py_tokenize_raw(docs, self.trim, self.lower)
        if self._sorted_vocab is None and self.vocab:
            # False = built-and-unsafe (wide vocab keys): _token_ids
            # takes the dict path without re-scanning the vocab keys
            # on every serve call
            self._sorted_vocab = _sorted_vocab(self.vocab) or False
        return _token_ids(
            docs, self.vocab, grow=False, sorted_vocab=self._sorted_vocab
        )

    def _match(self, docs, precomputed=None) -> tuple:
        """Flat (doc_ids, columns, tf_values) for every selected gram in
        ``docs``, doc-major."""
        if precomputed is not None:
            d_u, g_u, counts = precomputed
        else:
            ids = self._ids(docs)
            d_u, g_u, counts = _grams_unique(ids, self.orders)
        pos = np.searchsorted(self.selected, g_u)
        pos = np.clip(pos, 0, max(len(self.selected) - 1, 0))
        keep = (
            (self.selected[pos] == g_u)
            if len(self.selected)
            else np.zeros(len(g_u), dtype=bool)
        )
        values = _apply_tf(counts[keep], self.tf_fun)
        return d_u[keep], self.columns[pos[keep]], values

    def _vectorize(self, docs, precomputed=None) -> SparseRows:
        d, c, v = self._match(docs, precomputed=precomputed)
        return _to_sparse_rows(d, c, v, len(docs), self.num_features)

    def apply(self, tokens):
        # pair-list path, including zero tf values (a padded SparseRows
        # row cannot represent those, but the composed chain's
        # SparseFeatureVectorizer.apply emits them — stay identical)
        one = [tokens] if isinstance(tokens, str) else [list(tokens)]
        _, cols, vals = self._match(one)
        order = np.argsort(cols)
        return [
            (int(c), float(v)) for c, v in zip(cols[order], vals[order])
        ]

    def apply_batch(self, data) -> Dataset:
        data = Dataset.of(data)
        if self._train_cache is not None:
            payload, fingerprint, (d_u, g_u, counts, n_docs) = self._train_cache
            if payload is data.payload:
                # one intended hit (fit → apply on the train set): release
                # the pinned corpus/grams afterwards. The fingerprint
                # (doc count + total tokens) catches SIZE-CHANGING in-place
                # mutation of the payload between fit and apply — fall
                # through to a fresh featurization rather than serve stale
                # grams. Same-size element edits are not detected (full
                # content hashing would cost what the cache saves); docs
                # without __len__ (e.g. generators, already consumed by
                # fit) skip the check — they cannot be re-featurized at
                # all, so the cached grams are the only correct answer.
                self._train_cache = None
                n_now, tok_now = 0, 0
                sized = True
                for doc in data:
                    if not hasattr(doc, "__len__"):
                        sized = False
                        break
                    n_now += 1
                    tok_now += len(doc)
                if not sized or (n_now, tok_now) == fingerprint:
                    rows = self._vectorize(
                        [None] * n_docs, precomputed=(d_u, g_u, counts)
                    )
                    return Dataset(rows, batched=True)
        items = list(data)
        if items and isinstance(items[0], str):
            docs = items  # raw strings: _ids runs the fused frontend
        else:
            docs = [list(doc) for doc in items]
        return Dataset(self._vectorize(docs), batched=True)

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_train_cache"] = None   # process-local identity cache
        state["_sorted_vocab"] = None  # rebuilt lazily after load
        state["_vocab_by_id"] = None   # ditto
        return state


class PackedTextFeatures(Estimator):
    """Fused NGramsFeaturizer(orders) → TermFrequency(tf) →
    CommonSparseFeatures(num_features), vectorized over the whole corpus.

    Accepts token-list docs (the composed-chain contract) OR raw strings —
    the latter additionally fuse the Trim → LowerCase → Tokenizer frontend,
    running it in the native runtime (``native/hashing.cpp:
    ks_text_frontend``: one C pass doing trim/lowercase/split/first-seen
    vocabulary ids over the concatenated corpus) with the Python node chain
    as spec and fallback. This is the same host-fusion philosophy as the
    packed counting itself, extended to the last host stage (VERDICT r4
    #7)."""

    def __init__(
        self,
        orders: Sequence[int],
        num_features: int,
        tf_fun: Optional[Callable] = None,
        trim: bool = True,
        lower: bool = True,
    ):
        orders = validate_orders(orders)
        if max(orders) > 3:
            raise ValueError(
                "packed path supports orders <= 3; use the composed chain"
            )
        self.orders = orders
        self.num_features = num_features
        self.tf_fun = tf_fun
        self.trim = trim
        self.lower = lower

    def fit(self, data: Dataset) -> PackedTextVectorizer:
        data = Dataset.of(data)
        items = list(data)
        vocab: Dict[str, int] = {}
        if items and isinstance(items[0], str):
            ids = _frontend_ids(
                items, vocab, grow=True, trim=self.trim, lower=self.lower,
                vocab_by_id=[],
            )
            if ids is None:  # no native / non-ASCII: Python node chain
                ids = _token_ids(
                    _py_tokenize_raw(items, self.trim, self.lower),
                    vocab, grow=True,
                )
        else:
            items = [list(doc) for doc in items]
            ids = _token_ids(items, vocab, grow=True)
        docs = items
        # fingerprint over the normalized items (chars for raw strings,
        # tokens for lists) — the apply-side mutation check walks the same
        # representation; generators were materialized above
        fingerprint = (len(docs), sum(len(doc) for doc in docs))
        d_u, g_u, counts = _grams_unique(ids, self.orders)
        # document frequency + first-seen uid over the uid-ordered stream
        sel, first_seen, df = np.unique(
            g_u, return_index=True, return_counts=True
        )
        rank = np.lexsort((first_seen, -df))[: self.num_features]
        chosen = sel[rank]
        sort_order = np.argsort(chosen)
        v = PackedTextVectorizer(
            vocab,
            chosen[sort_order],
            np.arange(len(chosen), dtype=np.int64)[sort_order],
            self.orders,
            self.tf_fun,
            trim=self.trim,
            lower=self.lower,
        )
        # The standard pipeline flow applies the fitted vectorizer to the
        # SAME training dataset next; the per-doc gram stream was just
        # computed, so hand it over keyed by payload identity (the Spark
        # analogue: the training featurization RDD stays cached).
        v._train_cache = (
            data.payload, fingerprint, (d_u, g_u, counts, len(docs))
        )
        return v
