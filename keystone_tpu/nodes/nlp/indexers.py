"""Packed n-gram indexers.

Parity: nodes/nlp/indexers.scala:49-140 (NaiveBitPackIndexer /
NGramIndexerImpl over the BackoffIndexer trait). The bit-packed form is the
TPU-relevant one: a trigram becomes one int64, so corpora of n-grams are
dense integer arrays that sort/unique/gather on device. All pack/unpack
ops here are exposed both per-ngram (parity API) and vectorized over numpy
int64 arrays (the batch path language models use).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np

_WORD_BITS = 20
_WORD_MASK = (1 << _WORD_BITS) - 1


class NaiveBitPackIndexer:
    """Packs up to 3 word ids (each < 2^20) into one int64
    (parity: NaiveBitPackIndexer, indexers.scala:49-115).

    Layout (msb→lsb): [4 control bits][farthest word][middle][current],
    left-aligned; control bits 00=unigram, 01=bigram, 10=trigram.
    """

    min_ngram_order = 1
    max_ngram_order = 3

    @staticmethod
    def pack(ngram: Sequence[int]) -> int:
        for w in ngram:
            if w >= (1 << _WORD_BITS) or w < 0:
                # negative ids (e.g. the -1 OOV sentinel) would sign-extend
                # into the control bits and corrupt the packed order
                raise ValueError(f"word id {w} outside [0, 2^20)")
        n = len(ngram)
        if n == 1:
            return ngram[0] << 40
        if n == 2:
            return (ngram[1] << 20) | (ngram[0] << 40) | (1 << 60)
        if n == 3:
            return ngram[2] | (ngram[1] << 20) | (ngram[0] << 40) | (1 << 61)
        raise ValueError("ngram order need to be in { 1, 2, 3 } for now")

    @staticmethod
    def unpack(ngram: int, pos: int) -> int:
        if pos == 0:
            return (ngram >> 40) & _WORD_MASK
        if pos == 1:
            return (ngram >> 20) & _WORD_MASK
        if pos == 2:
            return ngram & _WORD_MASK
        raise ValueError("ngram order need to be in { 1, 2, 3 } for now")

    @classmethod
    def ngram_order(cls, ngram: int) -> int:
        order = (ngram >> 60) & 0xF
        if not (cls.min_ngram_order <= order + 1 <= cls.max_ngram_order):
            raise ValueError(f"raw control bits {order} are invalid")
        return order + 1

    @classmethod
    def remove_farthest_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        stripped = ngram & ((1 << 40) - 1)
        shifted = stripped << 20
        if order == 2:
            return shifted  # becomes a unigram (control 00)
        if order == 3:
            return shifted | (1 << 60)  # becomes a bigram
        raise ValueError(f"ngram order is either invalid or not supported: {order}")

    @classmethod
    def remove_current_word(cls, ngram: int) -> int:
        order = cls.ngram_order(ngram)
        if order == 2:
            return ngram & ~((1 << 40) - 1) & ~(0xF << 60)
        if order == 3:
            return (ngram & ~(_WORD_MASK) & ~(0xF << 60)) | (1 << 60)
        raise ValueError(f"ngram order is either invalid or not supported: {order}")

    # -- vectorized batch forms (the TPU-side layout) --------------------

    @staticmethod
    def pack_batch(words: np.ndarray, order: int) -> np.ndarray:
        """(n, order) int word-id matrix → (n,) packed int64 array.

        Unlike scalar :meth:`pack`, ids are NOT range-checked: the
        packed-features apply path deliberately streams the -1 OOV
        sentinel through — any gram containing -1 sign-extends negative,
        and legitimate packs are non-negative, so OOV grams can never
        collide with a real key (they just miss every lookup). Callers
        doing table *construction* (not lookup) must validate ids
        themselves, as PackedStupidBackoffModel.from_model does."""
        words = np.asarray(words, dtype=np.int64)
        if order == 1:
            return words[:, 0] << 40
        if order == 2:
            return (words[:, 1] << 20) | (words[:, 0] << 40) | (1 << 60)
        if order == 3:
            return (
                words[:, 2]
                | (words[:, 1] << 20)
                | (words[:, 0] << 40)
                | (1 << 61)
            )
        raise ValueError("order must be in {1, 2, 3}")

    @staticmethod
    def unpack_batch(packed: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(n,) packed → ((n, 3) word ids, (n,) orders)."""
        packed = np.asarray(packed, dtype=np.int64)
        orders = ((packed >> 60) & 0xF) + 1
        words = np.stack(
            [
                (packed >> 40) & _WORD_MASK,
                (packed >> 20) & _WORD_MASK,
                packed & _WORD_MASK,
            ],
            axis=1,
        )
        return words, orders

    @staticmethod
    def order_batch(packed: np.ndarray) -> np.ndarray:
        """(n,) packed → (n,) orders (control bits + 1)."""
        return ((np.asarray(packed, dtype=np.int64) >> 60) & 0xF) + 1

    @staticmethod
    def farthest_word_batch(packed: np.ndarray) -> np.ndarray:
        """(n,) packed → (n,) word id at position 0."""
        return (np.asarray(packed, dtype=np.int64) >> 40) & _WORD_MASK

    @staticmethod
    def remove_current_word_batch(
        q: np.ndarray, orders: np.ndarray
    ) -> np.ndarray:
        """Vectorized :meth:`remove_current_word` (orders 2 and 3 only —
        other entries produce unspecified values; callers mask them)."""
        lo40 = np.int64((1 << 40) - 1)
        ctrl = np.int64(0xF) << np.int64(60)
        bigram_to_uni = q & ~lo40 & ~ctrl
        trigram_to_bi = (q & ~np.int64(_WORD_MASK) & ~ctrl) | (
            np.int64(1) << np.int64(60)
        )
        return np.where(orders == 2, bigram_to_uni, trigram_to_bi)

    @staticmethod
    def remove_farthest_word_batch(
        q: np.ndarray, orders: np.ndarray
    ) -> np.ndarray:
        """Vectorized :meth:`remove_farthest_word` (orders 2 and 3)."""
        shifted = (q & np.int64((1 << 40) - 1)) << np.int64(20)
        return np.where(
            orders == 2, shifted, shifted | (np.int64(1) << np.int64(60))
        )


class NGramIndexerImpl:
    """Tuple-based indexer for arbitrary orders
    (parity: NGramIndexerImpl, indexers.scala:117-140)."""

    min_ngram_order = 1
    max_ngram_order = 5

    @staticmethod
    def pack(ngram: Sequence) -> tuple:
        return tuple(ngram)

    @staticmethod
    def unpack(ngram: tuple, pos: int):
        return ngram[pos]

    @staticmethod
    def remove_farthest_word(ngram: tuple) -> tuple:
        return tuple(ngram[1:])

    @staticmethod
    def remove_current_word(ngram: tuple) -> tuple:
        return tuple(ngram[:-1])

    @staticmethod
    def ngram_order(ngram: tuple) -> int:
        return len(ngram)
