"""String preprocessing transformers.

Parity: nodes/nlp/StringUtils.scala:13-33 (Tokenizer / Trim / LowerCase).
Host-side by nature (strings are not device data); each is a per-item
Transformer whose batch form maps over the item list. The device boundary
comes later in text pipelines, at the sparse-vectorization step.
"""

from __future__ import annotations

import re

from ...workflow.transformer import Transformer


class Tokenizer(Transformer):
    """Split on a delimiting regex; default matches the reference's
    punctuation+whitespace class (StringUtils.scala:13-15). Java's split
    drops trailing empties but keeps a leading empty token when the string
    starts with a separator — reproduced here for oracle parity."""

    def __init__(self, sep: str = r"[^\w]+"):
        self.sep = sep
        self._re = re.compile(sep)

    def apply(self, x: str):
        parts = self._re.split(x)
        # Java String.split: trailing empty strings removed, leading kept
        while parts and parts[-1] == "":
            parts.pop()
        return parts

    def __getstate__(self):
        return {"sep": self.sep}

    def __setstate__(self, state):
        self.sep = state["sep"]
        self._re = re.compile(self.sep)


class Trim(Transformer):
    """Strip leading/trailing whitespace (StringUtils.scala:20)."""

    def apply(self, x: str) -> str:
        return x.strip()


class LowerCase(Transformer):
    """Lower-case (StringUtils.scala:28)."""

    def apply(self, x: str) -> str:
        return x.lower()
