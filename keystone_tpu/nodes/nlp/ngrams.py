"""N-gram featurization, counting, and frequency encoding.

Parity: nodes/nlp/ngrams.scala:20-180 (NGramsFeaturizer / NGram /
NGramsCounts) and nodes/nlp/WordFrequencyEncoder.scala:7-66. N-grams are
plain Python tuples (hashable, ordered — the role of the reference's NGram
wrapper class, ngrams.scala:100-140). Counting and vocabulary building are
host-side corpus reductions (the reference's reduceByKey/sortBy shuffles,
ngrams.scala:175-180); the device boundary comes at vectorization.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence, Tuple

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer


def validate_orders(orders: Sequence[int]) -> list:
    """Shared n-gram order validation (consecutive positive ints) used by
    NGramsFeaturizer and NGramsHashingTF, which must stay output-identical."""
    orders = list(orders)
    if min(orders) < 1:
        raise ValueError(f"minimum order is not >= 1, found {min(orders)}")
    for a, b in zip(orders, orders[1:]):
        if b != a + 1:
            raise ValueError(
                f"orders are not consecutive; contains {a} and {b}"
            )
    return orders


class NGramsFeaturizer(Transformer):
    """Token sequence → all n-grams for consecutive ``orders``
    (parity: NGramsFeaturizer, ngrams.scala:20-97)."""

    def __init__(self, orders: Sequence[int]):
        orders = validate_orders(orders)
        self.orders = orders
        self.min_order = orders[0]
        self.max_order = orders[-1]

    def apply(self, tokens: Sequence) -> List[tuple]:
        tokens = list(tokens)
        out: List[tuple] = []
        n = len(tokens)
        for i in range(n - self.min_order + 1):
            for order in range(self.min_order, self.max_order + 1):
                if i + order > n:
                    break
                out.append(tuple(tokens[i : i + order]))
        return out


class NGramsCounts(Transformer):
    """Corpus-level n-gram occurrence counts, sorted by descending frequency
    (parity: NGramsCounts, ngrams.scala:152-180). A dataset-level reduction:
    input is a dataset of per-document n-gram lists, output a dataset of
    (ngram, count) pairs. mode='noadd' skips the sort (the reference's
    NoAdd skips cross-partition aggregation)."""

    def __init__(self, mode: str = "default"):
        mode = mode.lower()
        if mode not in ("default", "noadd"):
            raise ValueError("`mode` must be `default` or `noAdd`")
        self.mode = mode

    def apply(self, ngram_list: Sequence[tuple]) -> List[Tuple[tuple, int]]:
        counts = Counter(tuple(g) for g in ngram_list)
        return list(counts.items())

    def apply_batch(self, data) -> Dataset:
        data = Dataset.of(data)
        counts: Counter = Counter()
        for doc in data:
            counts.update(tuple(g) for g in doc)
        items = list(counts.items())
        if self.mode == "default":
            items.sort(key=lambda kv: -kv[1])
        return Dataset.from_items(items)


class WordFrequencyTransformer(Transformer):
    """Token → frequency-rank index; out-of-vocabulary → -1
    (parity: WordFrequencyTransformer, WordFrequencyEncoder.scala:43-66)."""

    OOV_INDEX = -1

    def __init__(self, word_index: Dict[str, int],
                 unigram_counts: Dict[int, int]):
        self.word_index = dict(word_index)
        self.unigram_counts = dict(unigram_counts)

    def apply(self, words: Sequence[str]) -> List[int]:
        idx = self.word_index
        return [idx.get(w, self.OOV_INDEX) for w in words]


class WordFrequencyEncoder(Estimator):
    """Build the sorted-by-frequency vocabulary encoding
    (parity: WordFrequencyEncoder, WordFrequencyEncoder.scala:7-31)."""

    def fit(self, data: Dataset) -> WordFrequencyTransformer:
        data = Dataset.of(data)
        unigrams = (
            NGramsCounts().apply_batch(
                Dataset.from_items(
                    [NGramsFeaturizer([1]).apply(doc) for doc in data]
                )
            )
        ).collect()
        # indexes respect the sorted (desc-frequency) order
        word_index = {gram[0]: i for i, (gram, _) in enumerate(unigrams)}
        unigram_counts = {
            word_index[gram[0]]: cnt for gram, cnt in unigrams
        }
        return WordFrequencyTransformer(word_index, unigram_counts)
