"""Feature hashing with exact reference-hash parity.

Parity: nodes/nlp/HashingTF.scala:15-32 and NGramsHashingTF.scala:25-146.
The reference hashes terms with Scala's ``.##`` (Java hashCode for strings,
MurmurHash3 seq-hash for Seq[String] n-grams) and asserts the rolling
NGramsHashingTF "should return the exact same feature vector" as
NGramsFeaturizer→HashingTF. We reproduce those hash functions bit-for-bit
(32-bit two's complement), which makes that invariant a cross-implementation
test oracle here too — and means feature indices match the reference's,
so models are checkpoint-compatible at the feature level.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ...data.sparse import SparseRows
from ...data.dataset import Dataset
from ...workflow.transformer import Transformer

_M32 = 0xFFFFFFFF


def _signed32(x: int) -> int:
    x &= _M32
    return x - (1 << 32) if x >= (1 << 31) else x


def _rotl32(x: int, n: int) -> int:
    x &= _M32
    return ((x << n) | (x >> (32 - n))) & _M32


def java_string_hash(s: str) -> int:
    """java.lang.String.hashCode (what Scala's ``"x".##`` returns)."""
    h = 0
    for c in s:
        h = (31 * h + ord(c)) & _M32
    return _signed32(h)


def _mix_last(hash_: int, data: int) -> int:
    k = (data & _M32) * 0xCC9E2D51 & _M32
    k = _rotl32(k, 15)
    k = k * 0x1B873593 & _M32
    return (hash_ ^ k) & _M32


def _mix(hash_: int, data: int) -> int:
    h = _mix_last(hash_, data)
    h = _rotl32(h, 13)
    return (h * 5 + 0xE6546B64) & _M32


def _avalanche(h: int) -> int:
    h &= _M32
    h ^= h >> 16
    h = h * 0x85EBCA6B & _M32
    h ^= h >> 13
    h = h * 0xC2B2AE35 & _M32
    h ^= h >> 16
    return h


def _finalize(hash_: int, length: int) -> int:
    return _signed32(_avalanche(hash_ ^ length))


SEQ_SEED = java_string_hash("Seq")  # scala.util.hashing.MurmurHash3.seqSeed


def murmur3_seq_hash(element_hashes: Sequence[int]) -> int:
    """scala MurmurHash3.seqHash over pre-hashed elements (the Seq.## of an
    n-gram of strings)."""
    h = SEQ_SEED & _M32
    for eh in element_hashes:
        h = _mix(h, eh)
    return _finalize(h, len(element_hashes))


def scala_hash(term) -> int:
    """Scala ``.##`` for the term types the reference hashes: strings,
    ints, and seqs of either (n-grams)."""
    if isinstance(term, str):
        return java_string_hash(term)
    if isinstance(term, bool):
        return 1231 if term else 1237
    if isinstance(term, int):
        return _signed32(term)  # Int.## == value (within 32 bits)
    if isinstance(term, (tuple, list)):
        return murmur3_seq_hash([scala_hash(t) for t in term])
    return _signed32(hash(term))


def _non_negative_mod(x: int, mod: int) -> int:
    r = int(_signed32(x)) % mod
    # Python % is already non-negative for positive mod; the reference's
    # branch is for Java semantics. Kept for clarity.
    return r + mod if r < 0 else r


def _flat_string_hashes(docs):
    """(hashes int32, doc_offsets int64) for all-string docs via the
    native hasher, else None (non-string terms use scala_hash's type
    dispatch — Python path)."""
    from ... import native

    if native.get_lib() is None:
        return None
    flat: List[str] = []
    lens: List[int] = []
    for doc in docs:
        for t in doc:
            if type(t) is not str:
                return None
        flat.extend(doc)
        lens.append(len(doc))
    import numpy as np

    hashes = native.java_string_hash_batch(flat)
    if hashes is None:
        return None
    doc_offsets = np.zeros(len(lens) + 1, dtype=np.int64)
    np.cumsum(np.asarray(lens, dtype=np.int64), out=doc_offsets[1:])
    return hashes, doc_offsets


def _tf_sparse_from_features(feats, doc_offsets, n_docs, num_features):
    """Flat per-position feature indices → padded SparseRows, fully
    vectorized: one corpus-level unique over (doc, feature) keys replaces
    the per-doc dict counting (rows come out sorted by feature id, the
    dict path's ``sorted(tf.items())`` order)."""
    import numpy as np

    from .packed_features import _to_sparse_rows

    counts = np.diff(doc_offsets)
    doc_ids = np.repeat(np.arange(n_docs, dtype=np.int64), counts)
    key = doc_ids * num_features + feats.astype(np.int64)
    u, c = np.unique(key, return_counts=True)
    return _to_sparse_rows(
        u // num_features, (u % num_features).astype(np.int64),
        c.astype(np.float32), n_docs, num_features,
    )


def _native_string_tf_sparse(docs, num_features: int):
    """Batch HashingTF → SparseRows via the native hasher, or None."""
    hashed = _flat_string_hashes(docs)
    if hashed is None:
        return None
    import numpy as np

    hashes, doc_offsets = hashed
    feats = hashes.astype(np.int64) % num_features  # python-sign modulo
    return _tf_sparse_from_features(
        feats, doc_offsets, len(docs), num_features
    )


def _native_ngram_tf_sparse(docs, min_order: int, max_order: int,
                            num_features: int):
    """Batch NGramsHashingTF → SparseRows via the native rolling hasher,
    or None."""
    from ... import native

    hashed = _flat_string_hashes(docs)
    if hashed is None:
        return None
    hashes, doc_offsets = hashed
    res = native.ngram_hash_features_batch(
        hashes, doc_offsets, min_order, max_order, num_features, SEQ_SEED
    )
    if res is None:
        return None
    flat_feats, out_offsets = res
    return _tf_sparse_from_features(
        flat_feats, out_offsets, len(docs), num_features
    )


class HashingTF(Transformer):
    """Term sequence → sparse term-frequency row by the hashing trick
    (parity: HashingTF.scala:15-32)."""

    def __init__(self, num_features: int):
        self.num_features = num_features

    def apply(self, document) -> List[Tuple[int, float]]:
        tf = {}
        for term in document:
            i = _non_negative_mod(scala_hash(term), self.num_features)
            tf[i] = tf.get(i, 0.0) + 1.0
        return sorted(tf.items())

    def apply_batch(self, data) -> Dataset:
        data = Dataset.of(data)
        docs = [list(doc) for doc in data]
        sr = _native_string_tf_sparse(docs, self.num_features)
        if sr is None:
            sr = SparseRows.from_pairs(
                [self.apply(doc) for doc in docs], self.num_features
            )
        return Dataset(sr, batched=True)


class NGramsHashingTF(Transformer):
    """Rolling-hash n-gram HashingTF: identical output to
    NGramsFeaturizer(orders)→HashingTF, without constructing the n-grams
    (parity: NGramsHashingTF.scala:25-146)."""

    def __init__(self, orders: Sequence[int], num_features: int):
        from .ngrams import validate_orders

        orders = validate_orders(orders)
        self.orders = orders
        self.min_order = orders[0]
        self.max_order = orders[-1]
        self.num_features = num_features

    def apply(self, line: Sequence[str]) -> List[Tuple[int, float]]:
        hashes = [java_string_hash(w) for w in line]
        n = len(hashes)
        tf = {}
        for i in range(n - self.min_order + 1):
            h = SEQ_SEED & _M32
            for j in range(i, i + self.min_order):
                h = _mix(h, hashes[j])
            feat = _non_negative_mod(
                _finalize(h, self.min_order), self.num_features
            )
            tf[feat] = tf.get(feat, 0.0) + 1.0
            order = self.min_order + 1
            while order <= self.max_order and i + order <= n:
                h = _mix(h, hashes[i + order - 1])
                feat = _non_negative_mod(
                    _finalize(h, order), self.num_features
                )
                tf[feat] = tf.get(feat, 0.0) + 1.0
                order += 1
        return sorted(tf.items())

    def apply_batch(self, data) -> Dataset:
        data = Dataset.of(data)
        docs = [list(doc) for doc in data]
        sr = _native_ngram_tf_sparse(
            docs, self.min_order, self.max_order, self.num_features
        )
        if sr is None:
            sr = SparseRows.from_pairs(
                [self.apply(doc) for doc in docs], self.num_features
            )
        return Dataset(sr, batched=True)
