"""Text/NLP nodes (parity: nodes/nlp/ — StringUtils, ngrams, HashingTF,
indexers, StupidBackoff, WordFrequencyEncoder)."""

from .corenlp_lite import CoreNLPFeatureExtractor
from .hashing import (
    HashingTF,
    NGramsHashingTF,
    java_string_hash,
    murmur3_seq_hash,
    scala_hash,
)
from .indexers import NaiveBitPackIndexer, NGramIndexerImpl
from .packed_features import PackedTextFeatures, PackedTextVectorizer
from .ngrams import (
    NGramsCounts,
    NGramsFeaturizer,
    WordFrequencyEncoder,
    WordFrequencyTransformer,
)
from .stupid_backoff import (
    PackedStupidBackoffModel,
    StupidBackoffEstimator,
    StupidBackoffModel,
    score_stupid_backoff,
)
from .text import LowerCase, Tokenizer, Trim

__all__ = [
    "CoreNLPFeatureExtractor",
    "HashingTF",
    "NGramsHashingTF",
    "java_string_hash",
    "murmur3_seq_hash",
    "scala_hash",
    "NaiveBitPackIndexer",
    "NGramIndexerImpl",
    "NGramsCounts",
    "NGramsFeaturizer",
    "PackedTextFeatures",
    "PackedTextVectorizer",
    "WordFrequencyEncoder",
    "WordFrequencyTransformer",
    "PackedStupidBackoffModel",
    "StupidBackoffEstimator",
    "StupidBackoffModel",
    "score_stupid_backoff",
    "LowerCase",
    "Tokenizer",
    "Trim",
]
