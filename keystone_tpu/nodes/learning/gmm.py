"""Diagonal-covariance Gaussian mixture models.

Parity: nodes/learning/GaussianMixtureModel.scala:19 (posterior-assignment
transformer) and GaussianMixtureModelEstimator.scala:25 (EM following the
Sanchez et al. IJCV'13 Appendix B recipe: k-means++ init, incremental
log-sum-exp likelihood, aggressive posterior thresholding, variance floors).

The whole E and M steps are batched matrix algebra — one jit program each —
with the convergence test host-side, mirroring the reference's driver loop.
The native enceval EM path (utils/external/EncEval.scala computeGMM via JNI)
is subsumed: this on-device implementation IS the fast path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param
from ...utils.jit import nestable_jit
from .kmeans import KMeansPlusPlusEstimator

KMEANS_PLUS_PLUS_INITIALIZATION = "kmeans++"
RANDOM_INITIALIZATION = "random"


# The Mahalanobis term is an expanded quadratic (‖x‖²/σ² − 2xμ/σ² + ‖μ‖²/σ²
# as GEMMs — the TPU-right shape), which cancels catastrophically: at
# single-pass-bf16 matmul precision the residual error (~4e-3 of the large
# terms) lands in the exponent of the posterior softmax and flips
# assignments depending on how XLA fused the surrounding program (observed:
# the SAME FisherVector inputs gave posteriors differing by O(1) inside vs
# outside a whole-chain jit). precision=high keeps the cancellation at f32
# noise, making the encoding fusion-invariant.
_PREC = "high"


@nestable_jit
def _posteriors(X, means, variances, weights, weight_threshold):
    """Thresholded posterior assignments q (n, k)
    (parity: GaussianMixtureModel.apply:47-82). means/variances here are
    (k, d) row-major."""
    Xsq = X * X
    half_inv_var = 0.5 / variances
    sq_mahal = (
        jnp.matmul(Xsq, half_inv_var.T, precision=_PREC)
        - jnp.matmul(X, (means / variances).T, precision=_PREC)
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    d = X.shape[1]
    log_prior = (
        -0.5 * d * math.log(2 * math.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
    )
    llh = log_prior - sq_mahal
    llh = llh - jnp.max(llh, axis=1, keepdims=True)
    q = jnp.exp(llh)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    return q / jnp.sum(q, axis=1, keepdims=True)


@nestable_jit
def _e_step(X, means, variances, weights, weight_threshold):
    """One fused E-step: (mean log-sum-exp likelihood, thresholded
    posteriors) from a single Mahalanobis computation — the reference reuses
    llh for both too (GaussianMixtureModelEstimator.scala:118-165)."""
    Xsq = X * X
    sq_mahal = (
        jnp.matmul(Xsq, (0.5 / variances).T, precision=_PREC)
        - jnp.matmul(X, (means / variances).T, precision=_PREC)
        + 0.5 * jnp.sum(means * means / variances, axis=1)
    )
    d = X.shape[1]
    log_prior = (
        -0.5 * d * math.log(2 * math.pi)
        - 0.5 * jnp.sum(jnp.log(variances), axis=1)
        + jnp.log(weights)
    )
    llh = log_prior - sq_mahal
    cost = jnp.mean(jax.scipy.special.logsumexp(llh, axis=1))
    shifted = llh - jnp.max(llh, axis=1, keepdims=True)
    q = jnp.exp(shifted)
    q = q / jnp.sum(q, axis=1, keepdims=True)
    q = jnp.where(q > weight_threshold, q, 0.0)
    return cost, q / jnp.sum(q, axis=1, keepdims=True)


@nestable_jit
def _m_step(X, q, var_floor):
    q_sum = jnp.sum(q, axis=0)
    weights = q_sum / X.shape[0]
    means = jnp.matmul(q.T, X, precision=_PREC) / q_sum[:, None]
    variances = (
        jnp.matmul(q.T, X * X, precision=_PREC) / q_sum[:, None]
        - means * means
    )
    variances = jnp.maximum(variances, var_floor)
    return weights, means, variances, q_sum


@functools.partial(
    jax.jit,
    static_argnames=("max_iterations", "weight_threshold",
                     "stop_tolerance", "min_cluster_size"),
)
def _em_loop(X, means, variances, weights, var_floor, *,
             max_iterations: int, weight_threshold: float,
             stop_tolerance: float, min_cluster_size: int):
    """The whole EM iteration as ONE device program (lax.while_loop).

    The eager loop paid two host round-trips per iteration (the f32 cost
    scalar for the convergence test, the q_sum min-cluster check); through
    a tunneled transport that dominated GMM fitting. Break semantics match
    the reference loop exactly (GaussianMixtureModelEstimator.scala:
    118-165): stop on non-improving cost or an unbalanced cluster, in both
    cases KEEPING the previous iteration's parameters."""

    def cond(carry):
        i, done, *_ = carry
        return (i < max_iterations) & ~done

    def body(carry):
        i, done, prev_cost, has_prev, m, v, w = carry
        cost, q = _e_step(X, m, v, w, weight_threshold)
        stop_conv = has_prev & ~(
            cost - prev_cost >= stop_tolerance * jnp.abs(prev_cost)
        )
        new_w, new_m, new_v, q_sum = _m_step(X, q, var_floor)
        unbalanced = jnp.any(q_sum < min_cluster_size)
        advance = ~stop_conv & ~unbalanced
        m2 = jnp.where(advance, new_m, m)
        v2 = jnp.where(advance, new_v, v)
        w2 = jnp.where(advance, new_w, w)
        return (i + 1, stop_conv | unbalanced, cost, True, m2, v2, w2)

    init = (
        jnp.int32(0),
        jnp.bool_(False),
        jnp.float32(0.0),
        jnp.bool_(False),
        means,
        variances,
        weights,
    )
    _, _, _, _, m, v, w = jax.lax.while_loop(cond, body, init)
    return m, v, w


class GaussianMixtureModel(Transformer):
    """Posterior-assignment transformer. Stored column-major like the
    reference: ``means``/``variances`` are (d, k), ``weights`` (k,)
    (parity: GaussianMixtureModel.scala:19-85)."""

    def __init__(self, means, variances, weights,
                 weight_threshold: float = 1e-4):
        self.means = as_param(means)
        self.variances = as_param(variances)
        self.weights = as_param(weights)
        self.weight_threshold = weight_threshold
        self.k = self.means.shape[1]
        self.dim = self.means.shape[0]

    def trace_batch(self, X):
        return _posteriors(
            X, self.means.T, self.variances.T, self.weights,
            self.weight_threshold,
        )

    @staticmethod
    def load(mean_file: str, vars_file: str, weights_file: str
             ) -> "GaussianMixtureModel":
        """CSV checkpoint load (parity: GaussianMixtureModel.load:97-105)."""
        means = np.loadtxt(mean_file, delimiter=",", ndmin=2)
        variances = np.loadtxt(vars_file, delimiter=",", ndmin=2)
        weights = np.loadtxt(weights_file, delimiter=",").ravel()
        return GaussianMixtureModel(means, variances, weights)


class GaussianMixtureModelEstimator(Estimator):
    """EM for diagonal GMMs (parity:
    GaussianMixtureModelEstimator.scala:25-193)."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        min_cluster_size: int = 40,
        stop_tolerance: float = 1e-4,
        weight_threshold: float = 1e-4,
        small_variance_threshold: float = 1e-2,
        absolute_variance_threshold: float = 1e-9,
        initialization_method: str = KMEANS_PLUS_PLUS_INITIALIZATION,
        seed: int = 0,
    ):
        if k <= 0 or max_iterations <= 0 or min_cluster_size <= 0:
            raise ValueError("k, max_iterations, min_cluster_size must be > 0")
        self.k = k
        self.max_iterations = max_iterations
        self.min_cluster_size = min_cluster_size
        self.stop_tolerance = stop_tolerance
        self.weight_threshold = weight_threshold
        self.small_variance_threshold = small_variance_threshold
        self.absolute_variance_threshold = absolute_variance_threshold
        self.initialization_method = initialization_method
        self.seed = seed

    def fit(self, data: Dataset) -> GaussianMixtureModel:
        return self.fit_matrix(Dataset.of(data).to_array())

    def fit_matrix(self, X) -> GaussianMixtureModel:
        X = jnp.asarray(X, dtype=jnp.float32)
        n, d = X.shape
        k = self.k

        mean_g = jnp.mean(X, axis=0)
        var_g = jnp.mean(X * X, axis=0) - mean_g * mean_g

        if self.initialization_method == KMEANS_PLUS_PLUS_INITIALIZATION:
            km = KMeansPlusPlusEstimator(k, 1, seed=self.seed).fit_matrix(X)
            assign = km.trace_batch(X)
            mass = jnp.sum(assign, axis=0)
            weights = mass / n
            means = (assign.T @ X) / mass[:, None]
            variances = (assign.T @ (X * X)) / mass[:, None] - means * means
        else:
            rng = np.random.default_rng(self.seed)
            col_min = jnp.min(X, axis=0)
            col_range = jnp.max(X, axis=0) - col_min
            means = (
                jnp.asarray(rng.random((k, d)), dtype=X.dtype) * col_range
                + col_min
            )
            variances = 0.1 * jnp.ones((k, d), X.dtype) * col_range * col_range
            weights = jnp.full((k,), 1.0 / k, X.dtype)

        var_floor = jnp.maximum(
            self.small_variance_threshold * var_g,
            self.absolute_variance_threshold,
        )
        variances = jnp.maximum(variances, var_floor)

        means, variances, weights = _em_loop(
            X, means, variances, weights, var_floor,
            max_iterations=self.max_iterations,
            weight_threshold=self.weight_threshold,
            stop_tolerance=self.stop_tolerance,
            min_cluster_size=self.min_cluster_size,
        )

        return GaussianMixtureModel(
            means.T, variances.T, weights, self.weight_threshold
        )
