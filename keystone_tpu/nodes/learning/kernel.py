"""Kernel methods: Gaussian kernel blocks + Gauss-Seidel kernel ridge
regression (arXiv:1602.05310 recipe).

Parity: nodes/learning/KernelGenerator.scala:36,84,138-206 (lazy column-block
kernel computation), KernelMatrix.scala:17,50 (block caching),
KernelRidgeRegression.scala:37,67,86-235 (blockwise Gauss-Seidel solve),
KernelBlockLinearMapper.scala:28 (test-time application).

Mesh-native shape: the n×n kernel matrix is never materialized — one n×b
column block at a time is computed as a single GEMM + elementwise exp
(row-sharded train data × replicated block), cached in HBM, and freed after
its solve; exactly the reference's streaming pattern with the
broadcast/treeReduce choreography replaced by XLA collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...linalg.row_matrix import solve_spd
from ...utils.timing import phase
from ...utils.jit import nestable_jit
from ...workflow.transformer import LabelEstimator, Transformer
from ...workflow.node_optimization import Optimizable
from .cost import AutoSolverFrontDoor, CostModel, combine_cost


@nestable_jit
def _gaussian_block_xla(X, Xb, gamma):
    """exp(−γ‖x−y‖²) for all (row of X, row of Xb): (n, b)
    (parity: computeKernel, KernelGenerator.scala:138-206)."""
    xn = jnp.sum(X * X, axis=1, keepdims=True)
    bn = jnp.sum(Xb * Xb, axis=1)
    sq = xn - 2.0 * (X @ Xb.T) + bn
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


def _gaussian_block(X, Xb, gamma):
    """Kernel-block front door: the fused Pallas kernel on TPU when the
    tile working set fits VMEM (ops/gaussian_kernel.py), identical-math
    XLA lowering otherwise."""
    from ...ops.gaussian_kernel import (
        gaussian_kernel_block_pallas,
        pallas_block_supported,
    )

    if pallas_block_supported(X.shape[0], X.shape[1], Xb.shape[0]):
        return gaussian_kernel_block_pallas(X, Xb, gamma)
    return _gaussian_block_xla(X, Xb, gamma)


class BlockKernelMatrix:
    """Lazily computed, cached n×b kernel column blocks
    (parity: BlockKernelMatrix, KernelMatrix.scala:50-90)."""

    def __init__(self, X, gamma: float, cache_blocks: bool = True):
        self.X = jnp.asarray(X, dtype=jnp.float32)
        self.gamma = gamma
        self.cache_blocks = cache_blocks
        self._cache: Dict[tuple, jnp.ndarray] = {}

    def block(self, idxs) -> jnp.ndarray:
        key = (int(idxs[0]), int(idxs[-1]))
        if key in self._cache:
            return self._cache[key]
        Kb = _gaussian_block(
            self.X, self.X[jnp.asarray(np.asarray(idxs))], self.gamma
        )
        if self.cache_blocks:
            self._cache[key] = Kb
        return Kb

    def diag_block(self, idxs) -> jnp.ndarray:
        Kb = self.block(idxs)
        return Kb[jnp.asarray(np.asarray(idxs))]

    def unpersist(self, idxs) -> None:
        self._cache.pop((int(idxs[0]), int(idxs[-1])), None)


class KernelBlockLinearMapper(Transformer):
    """Apply a kernel model: out = Σ_B K(test, train_B) · W_B
    (parity: KernelBlockLinearMapper.scala:28-90)."""

    # Never trace-fuse: train_X/W are dataset-sized, so baking them into a
    # fused XLA module as literals (or fetching them host-side) is exactly
    # the wrong trade. They stay device-resident; _gaussian_block takes them
    # as jit *arguments*.
    no_fuse = True

    def __init__(self, train_X, model_W, gamma: float, block_size: int):
        self.train_X = jnp.asarray(train_X, dtype=jnp.float32)
        self.W = jnp.asarray(model_W, dtype=jnp.float32)  # (n_train, k)
        self.gamma = gamma
        self.block_size = block_size

    def trace_batch(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        n_train = self.train_X.shape[0]
        out = jnp.zeros((X.shape[0], self.W.shape[1]), dtype=jnp.float32)
        for start in range(0, n_train, self.block_size):
            end = min(start + self.block_size, n_train)
            Kb = _gaussian_block(X, self.train_X[start:end], self.gamma)
            out = out + Kb @ self.W[start:end]
        return out


def _krr_block_step_impl(X, Y, W, start, gamma, lam, *, bs):
    """One Gauss-Seidel block step as ONE fused program (kernel-block
    generation from a dynamic row slice, residual, SPD solve, in-place
    model update). The eager form paid four separate TPU sins per block:
    a row GATHER for X[idxs] (~20M elem/s on this part vs dense streaming),
    an LU factorization where Cholesky applies (K_BB + λI is SPD), a
    scatter for W.at[idxs].set (XLA pads scatter operands ~66×), and
    4+ dispatch round trips — measured 7.6 s → 1.3 s for the 50k-row
    CIFAR-shape fit."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, bs, axis=0)
    Kb = _gaussian_block(X, Xb, gamma)                       # (n, bs)
    Kbb = jax.lax.dynamic_slice_in_dim(Kb, start, bs, axis=0)
    W_old = jax.lax.dynamic_slice_in_dim(W, start, bs, axis=0)
    Yb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
    residual = Kb.T @ W - Kbb.T @ W_old
    W_new = solve_spd(Kbb, Yb - residual, lam)
    return jax.lax.dynamic_update_slice_in_dim(W, W_new, start, axis=0)


def _krr_block_step_cached_impl(Kb, Y, W, start, lam, *, bs):
    """Cached-kernel variant: same step minus the kernel generation."""
    Kbb = jax.lax.dynamic_slice_in_dim(Kb, start, bs, axis=0)
    W_old = jax.lax.dynamic_slice_in_dim(W, start, bs, axis=0)
    Yb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
    residual = Kb.T @ W - Kbb.T @ W_old
    W_new = solve_spd(Kbb, Yb - residual, lam)
    return jax.lax.dynamic_update_slice_in_dim(W, W_new, start, axis=0)


_krr_block_step_donating = jax.jit(
    _krr_block_step_impl, static_argnames=("bs",), donate_argnums=(2,)
)
_krr_block_step_plain = jax.jit(
    _krr_block_step_impl, static_argnames=("bs",)
)
_krr_block_step_cached_donating = jax.jit(
    _krr_block_step_cached_impl, static_argnames=("bs",), donate_argnums=(2,)
)
_krr_block_step_cached_plain = jax.jit(
    _krr_block_step_cached_impl, static_argnames=("bs",)
)


def _krr_block_step(*args, **kwargs):
    # CPU donation intermittently aborts (same workaround as linalg/bcd.py)
    if jax.default_backend() == "cpu":
        return _krr_block_step_plain(*args, **kwargs)
    return _krr_block_step_donating(*args, **kwargs)


def _krr_block_step_cached(*args, **kwargs):
    if jax.default_backend() == "cpu":
        return _krr_block_step_cached_plain(*args, **kwargs)
    return _krr_block_step_cached_donating(*args, **kwargs)


@partial(jax.jit, static_argnames=("bs",))
def _kernel_block_slice(X, start, gamma, bs):
    """K(X, X[start:start+bs]) with the block rows dynamic-sliced (never
    gathered) — the generation path for cached-kernel mode."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, bs, axis=0)
    return _gaussian_block(X, Xb, gamma)


class KernelRidgeRegression(LabelEstimator, CostModel):
    """Gauss-Seidel block-coordinate kernel ridge regression
    (parity: KernelRidgeRegression.scala:37-235). Per block B:
        (K_BB + λI) W_B ← y_B − (K_Bᵀ W − K_BBᵀ W_B_old)
    """

    def __init__(self, gamma: float, lam: float, block_size: int,
                 num_epochs: int, block_permuter: Optional[int] = None,
                 cache_kernel: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_interval: int = 25):
        self.gamma = gamma
        self.lam = lam
        self.block_size = block_size
        self.num_epochs = num_epochs
        self.block_permuter = block_permuter
        self.cache_kernel = cache_kernel
        # Solver-state checkpoint every N blocks — the TPU analogue of the
        # reference's truncateLineage/RDD.checkpoint call
        # (KernelRidgeRegression.scala:204-208, utils/MatrixUtils.scala:163-189):
        # there it bounds RDD lineage depth; here the model has no lineage,
        # so the surviving purpose is restart — a killed long fit resumes
        # from the last saved (epoch, step, W).
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_interval = checkpoint_interval

    def _ckpt_path(self) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        import os

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        return os.path.join(self.checkpoint_dir, "krr_state.npz")

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        # kernel generation n²·d once (cached) or per epoch; per epoch
        # every block pays the n×bs residual GEMM (n²·k total) and a bs³
        # Cholesky (n·bs² total); cached-kernel epochs re-stream n² floats
        bs = min(self.block_size, n)
        gen_epochs = 1 if self.cache_kernel else self.num_epochs
        return combine_cost(
            {
                "flops": (
                    gen_epochs * float(n) * n * d
                    + self.num_epochs * (float(n) * n * k + float(n) * bs * bs)
                ) / num_machines,
                "bytes": (
                    self.num_epochs * float(n) * n / num_machines
                    + float(n) * d
                ),
                "network": float(n) * k * self.num_epochs,
                "passes": self.num_epochs,
            },
            cpu_weight, mem_weight, network_weight,
        )

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        import os

        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        n, k = Y.shape
        bs = self.block_size
        kernel_cache: Dict[int, jnp.ndarray] = {}
        W = jnp.zeros((n, k), dtype=jnp.float32)

        num_blocks = -(-n // bs)
        rng = (
            np.random.default_rng(self.block_permuter)
            if self.block_permuter is not None
            else None
        )
        start_epoch, start_step = 0, 0
        ckpt = self._ckpt_path()
        if ckpt and os.path.exists(ckpt):
            saved = np.load(ckpt)
            if saved["W"].shape == (n, k):
                W = jnp.asarray(saved["W"])
                start_epoch = int(saved["epoch"])
                start_step = int(saved["step"])
        steps_done = 0
        for epoch in range(self.num_epochs):
            # the permutation stream must be identical across a resume, so
            # draw it per epoch regardless of where we restart
            order = list(range(num_blocks))
            if rng is not None:
                rng.shuffle(order)
            if epoch < start_epoch:
                continue
            for step, blk in enumerate(order):
                if epoch == start_epoch and step < start_step:
                    continue
                start = blk * bs
                size = min(bs, n - start)
                # ONE fused program per block (generation + residual +
                # Cholesky solve + in-place model update); phase table
                # keeps the per-block wall (parity: the reference's
                # per-block timing logs, KernelRidgeRegression.scala:
                # 216-224 — its four sub-phases are one XLA program here)
                with phase("krr.block_step") as out:
                    if self.cache_kernel:
                        Kb = kernel_cache.get(start)
                        if Kb is None:
                            Kb = _kernel_block_slice(
                                X, start, jnp.float32(self.gamma), size
                            )
                            kernel_cache[start] = Kb
                        W = _krr_block_step_cached(
                            Kb, Y, W, start, jnp.float32(self.lam),
                            bs=size,
                        )
                    else:
                        W = _krr_block_step(
                            X, Y, W, start, jnp.float32(self.gamma),
                            jnp.float32(self.lam), bs=size,
                        )
                    out.append(W)
                steps_done += 1
                if ckpt and steps_done % self.checkpoint_interval == 0:
                    np.savez(
                        ckpt,
                        W=np.asarray(jax.block_until_ready(W)),
                        epoch=epoch,
                        step=step + 1,
                    )
        if ckpt and os.path.exists(ckpt):
            os.remove(ckpt)  # complete fit: drop the restart state
        return KernelBlockLinearMapper(X, W, self.gamma, bs)


class ExactKernelRidge(LabelEstimator, CostModel):
    """Direct kernel ridge: materialize K block-by-block and solve
    (K + λI) W = Y with one Cholesky — exact, one shot, O(n²) memory and
    an n³/3 factorization. The cheap end of the KRR family when n is
    small enough that the full kernel fits and the cubic solve beats
    ``num_epochs`` Gauss-Seidel sweeps; prices out fast as n grows. Same
    fitted-model contract as the Gauss-Seidel solver
    (:class:`KernelBlockLinearMapper`), so the two are interchangeable
    physical implementations behind :class:`KernelRidgeEstimator`."""

    def __init__(self, gamma: float, lam: float, block_size: int):
        self.gamma = gamma
        self.lam = lam
        self.block_size = block_size

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        return combine_cost(
            {
                # generation + one Cholesky + the triangular solves
                "flops": (
                    float(n) * n * d + float(n) ** 3 / 3.0
                    + float(n) * n * k
                ) / num_machines,
                "bytes": float(n) * n / num_machines + float(n) * d,
                "network": float(n) * k,
                "passes": 1,
            },
            cpu_weight, mem_weight, network_weight,
        )

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        n = X.shape[0]
        bs = self.block_size
        with phase("krr.exact_solve") as out:
            cols = [
                _kernel_block_slice(
                    X, start, jnp.float32(self.gamma), min(bs, n - start)
                )
                for start in range(0, n, bs)
            ]
            K = jnp.concatenate(cols, axis=1)  # (n, n)
            W = solve_spd(K, Y, jnp.float32(self.lam))
            out.append(W)
        return KernelBlockLinearMapper(X, W, self.gamma, bs)


class KernelRidgeEstimator(
    LabelEstimator, AutoSolverFrontDoor, CostModel, Optimizable
):
    """Cost-model auto-selecting front door for kernel ridge regression:
    the exact full-kernel solve vs the Gauss-Seidel block solver — both
    produce a :class:`KernelBlockLinearMapper` for the same (γ, λ), so
    selection is purely a cost question (the cubic factorization wins at
    small n, the epoch-bounded block sweeps win once n³ dominates).
    Runs through :class:`keystone_tpu.cost.SolverChooser`: with a profile
    store configured the family earns learned ``op/`` seconds-per-unit
    profiles from traced fits, and borderline shapes are decided by
    predicted wall-clock instead of analytic units."""

    def __init__(self, gamma: float, lam: float, block_size: int,
                 num_epochs: int, block_permuter: Optional[int] = None,
                 cache_kernel: bool = True,
                 num_machines: Optional[int] = None,
                 cpu_weight: Optional[float] = None,
                 mem_weight: Optional[float] = None,
                 network_weight: Optional[float] = None):
        self.gamma = gamma
        self.lam = lam
        self.block_size = block_size
        self.num_epochs = num_epochs
        self.num_machines = num_machines
        self._init_chooser_weights(cpu_weight, mem_weight, network_weight)
        self.options: Sequence = [
            KernelRidgeRegression(
                gamma, lam, block_size, num_epochs,
                block_permuter=block_permuter, cache_kernel=cache_kernel,
            ),
            ExactKernelRidge(gamma, lam, block_size),
        ]
        self.default = self.options[0]

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        data = Dataset.of(data)
        labels = Dataset.of(labels)
        solver = self.sample_optimize(
            [data.take(24), labels.take(24)], len(data)
        )
        return solver.fit(data, labels)


class GaussianKernelGenerator(LabelEstimator):
    """Convenience estimator shape used by RandomPatchCifarKernel: fit KRR on
    Gaussian-kernel features (parity: GaussianKernelGenerator +
    KernelRidgeRegression composition, KernelGenerator.scala:36-84)."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def kernel_matrix(self, data: Dataset, cache: bool = True
                      ) -> BlockKernelMatrix:
        return BlockKernelMatrix(
            Dataset.of(data).to_array(), self.gamma, cache
        )
