"""Kernel methods: Gaussian kernel blocks + Gauss-Seidel kernel ridge
regression (arXiv:1602.05310 recipe).

Parity: nodes/learning/KernelGenerator.scala:36,84,138-206 (lazy column-block
kernel computation), KernelMatrix.scala:17,50 (block caching),
KernelRidgeRegression.scala:37,67,86-235 (blockwise Gauss-Seidel solve),
KernelBlockLinearMapper.scala:28 (test-time application).

Mesh-native shape: the n×n kernel matrix is never materialized — one n×b
column block at a time is computed as a single GEMM + elementwise exp
(row-sharded train data × replicated block), cached in HBM, and freed after
its solve; exactly the reference's streaming pattern with the
broadcast/treeReduce choreography replaced by XLA collectives.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...linalg.row_matrix import solve_spd
from ...utils.timing import phase
from ...utils.jit import nestable_jit
from ...workflow.transformer import LabelEstimator, Transformer


@nestable_jit
def _gaussian_block_xla(X, Xb, gamma):
    """exp(−γ‖x−y‖²) for all (row of X, row of Xb): (n, b)
    (parity: computeKernel, KernelGenerator.scala:138-206)."""
    xn = jnp.sum(X * X, axis=1, keepdims=True)
    bn = jnp.sum(Xb * Xb, axis=1)
    sq = xn - 2.0 * (X @ Xb.T) + bn
    return jnp.exp(-gamma * jnp.maximum(sq, 0.0))


def _gaussian_block(X, Xb, gamma):
    """Kernel-block front door: the fused Pallas kernel on TPU when the
    tile working set fits VMEM (ops/gaussian_kernel.py), identical-math
    XLA lowering otherwise."""
    from ...ops.gaussian_kernel import (
        gaussian_kernel_block_pallas,
        pallas_block_supported,
    )

    if pallas_block_supported(X.shape[0], X.shape[1], Xb.shape[0]):
        return gaussian_kernel_block_pallas(X, Xb, gamma)
    return _gaussian_block_xla(X, Xb, gamma)


class BlockKernelMatrix:
    """Lazily computed, cached n×b kernel column blocks
    (parity: BlockKernelMatrix, KernelMatrix.scala:50-90)."""

    def __init__(self, X, gamma: float, cache_blocks: bool = True):
        self.X = jnp.asarray(X, dtype=jnp.float32)
        self.gamma = gamma
        self.cache_blocks = cache_blocks
        self._cache: Dict[tuple, jnp.ndarray] = {}

    def block(self, idxs) -> jnp.ndarray:
        key = (int(idxs[0]), int(idxs[-1]))
        if key in self._cache:
            return self._cache[key]
        Kb = _gaussian_block(
            self.X, self.X[jnp.asarray(np.asarray(idxs))], self.gamma
        )
        if self.cache_blocks:
            self._cache[key] = Kb
        return Kb

    def diag_block(self, idxs) -> jnp.ndarray:
        Kb = self.block(idxs)
        return Kb[jnp.asarray(np.asarray(idxs))]

    def unpersist(self, idxs) -> None:
        self._cache.pop((int(idxs[0]), int(idxs[-1])), None)


class KernelBlockLinearMapper(Transformer):
    """Apply a kernel model: out = Σ_B K(test, train_B) · W_B
    (parity: KernelBlockLinearMapper.scala:28-90)."""

    # Never trace-fuse: train_X/W are dataset-sized, so baking them into a
    # fused XLA module as literals (or fetching them host-side) is exactly
    # the wrong trade. They stay device-resident; _gaussian_block takes them
    # as jit *arguments*.
    no_fuse = True

    def __init__(self, train_X, model_W, gamma: float, block_size: int):
        self.train_X = jnp.asarray(train_X, dtype=jnp.float32)
        self.W = jnp.asarray(model_W, dtype=jnp.float32)  # (n_train, k)
        self.gamma = gamma
        self.block_size = block_size

    def trace_batch(self, X):
        X = jnp.asarray(X, dtype=jnp.float32)
        n_train = self.train_X.shape[0]
        out = jnp.zeros((X.shape[0], self.W.shape[1]), dtype=jnp.float32)
        for start in range(0, n_train, self.block_size):
            end = min(start + self.block_size, n_train)
            Kb = _gaussian_block(X, self.train_X[start:end], self.gamma)
            out = out + Kb @ self.W[start:end]
        return out


def _krr_block_step_impl(X, Y, W, start, gamma, lam, *, bs):
    """One Gauss-Seidel block step as ONE fused program (kernel-block
    generation from a dynamic row slice, residual, SPD solve, in-place
    model update). The eager form paid four separate TPU sins per block:
    a row GATHER for X[idxs] (~20M elem/s on this part vs dense streaming),
    an LU factorization where Cholesky applies (K_BB + λI is SPD), a
    scatter for W.at[idxs].set (XLA pads scatter operands ~66×), and
    4+ dispatch round trips — measured 7.6 s → 1.3 s for the 50k-row
    CIFAR-shape fit."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, bs, axis=0)
    Kb = _gaussian_block(X, Xb, gamma)                       # (n, bs)
    Kbb = jax.lax.dynamic_slice_in_dim(Kb, start, bs, axis=0)
    W_old = jax.lax.dynamic_slice_in_dim(W, start, bs, axis=0)
    Yb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
    residual = Kb.T @ W - Kbb.T @ W_old
    W_new = solve_spd(Kbb, Yb - residual, lam)
    return jax.lax.dynamic_update_slice_in_dim(W, W_new, start, axis=0)


def _krr_block_step_cached_impl(Kb, Y, W, start, lam, *, bs):
    """Cached-kernel variant: same step minus the kernel generation."""
    Kbb = jax.lax.dynamic_slice_in_dim(Kb, start, bs, axis=0)
    W_old = jax.lax.dynamic_slice_in_dim(W, start, bs, axis=0)
    Yb = jax.lax.dynamic_slice_in_dim(Y, start, bs, axis=0)
    residual = Kb.T @ W - Kbb.T @ W_old
    W_new = solve_spd(Kbb, Yb - residual, lam)
    return jax.lax.dynamic_update_slice_in_dim(W, W_new, start, axis=0)


_krr_block_step_donating = jax.jit(
    _krr_block_step_impl, static_argnames=("bs",), donate_argnums=(2,)
)
_krr_block_step_plain = jax.jit(
    _krr_block_step_impl, static_argnames=("bs",)
)
_krr_block_step_cached_donating = jax.jit(
    _krr_block_step_cached_impl, static_argnames=("bs",), donate_argnums=(2,)
)
_krr_block_step_cached_plain = jax.jit(
    _krr_block_step_cached_impl, static_argnames=("bs",)
)


def _krr_block_step(*args, **kwargs):
    # CPU donation intermittently aborts (same workaround as linalg/bcd.py)
    if jax.default_backend() == "cpu":
        return _krr_block_step_plain(*args, **kwargs)
    return _krr_block_step_donating(*args, **kwargs)


def _krr_block_step_cached(*args, **kwargs):
    if jax.default_backend() == "cpu":
        return _krr_block_step_cached_plain(*args, **kwargs)
    return _krr_block_step_cached_donating(*args, **kwargs)


@partial(jax.jit, static_argnames=("bs",))
def _kernel_block_slice(X, start, gamma, bs):
    """K(X, X[start:start+bs]) with the block rows dynamic-sliced (never
    gathered) — the generation path for cached-kernel mode."""
    Xb = jax.lax.dynamic_slice_in_dim(X, start, bs, axis=0)
    return _gaussian_block(X, Xb, gamma)


class KernelRidgeRegression(LabelEstimator):
    """Gauss-Seidel block-coordinate kernel ridge regression
    (parity: KernelRidgeRegression.scala:37-235). Per block B:
        (K_BB + λI) W_B ← y_B − (K_Bᵀ W − K_BBᵀ W_B_old)
    """

    def __init__(self, gamma: float, lam: float, block_size: int,
                 num_epochs: int, block_permuter: Optional[int] = None,
                 cache_kernel: bool = True,
                 checkpoint_dir: Optional[str] = None,
                 checkpoint_interval: int = 25):
        self.gamma = gamma
        self.lam = lam
        self.block_size = block_size
        self.num_epochs = num_epochs
        self.block_permuter = block_permuter
        self.cache_kernel = cache_kernel
        # Solver-state checkpoint every N blocks — the TPU analogue of the
        # reference's truncateLineage/RDD.checkpoint call
        # (KernelRidgeRegression.scala:204-208, utils/MatrixUtils.scala:163-189):
        # there it bounds RDD lineage depth; here the model has no lineage,
        # so the surviving purpose is restart — a killed long fit resumes
        # from the last saved (epoch, step, W).
        self.checkpoint_dir = checkpoint_dir
        self.checkpoint_interval = checkpoint_interval

    def _ckpt_path(self) -> Optional[str]:
        if not self.checkpoint_dir:
            return None
        import os

        os.makedirs(self.checkpoint_dir, exist_ok=True)
        return os.path.join(self.checkpoint_dir, "krr_state.npz")

    def fit(self, data: Dataset, labels: Dataset) -> KernelBlockLinearMapper:
        import os

        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        n, k = Y.shape
        bs = self.block_size
        kernel_cache: Dict[int, jnp.ndarray] = {}
        W = jnp.zeros((n, k), dtype=jnp.float32)

        num_blocks = -(-n // bs)
        rng = (
            np.random.default_rng(self.block_permuter)
            if self.block_permuter is not None
            else None
        )
        start_epoch, start_step = 0, 0
        ckpt = self._ckpt_path()
        if ckpt and os.path.exists(ckpt):
            saved = np.load(ckpt)
            if saved["W"].shape == (n, k):
                W = jnp.asarray(saved["W"])
                start_epoch = int(saved["epoch"])
                start_step = int(saved["step"])
        steps_done = 0
        for epoch in range(self.num_epochs):
            # the permutation stream must be identical across a resume, so
            # draw it per epoch regardless of where we restart
            order = list(range(num_blocks))
            if rng is not None:
                rng.shuffle(order)
            if epoch < start_epoch:
                continue
            for step, blk in enumerate(order):
                if epoch == start_epoch and step < start_step:
                    continue
                start = blk * bs
                size = min(bs, n - start)
                # ONE fused program per block (generation + residual +
                # Cholesky solve + in-place model update); phase table
                # keeps the per-block wall (parity: the reference's
                # per-block timing logs, KernelRidgeRegression.scala:
                # 216-224 — its four sub-phases are one XLA program here)
                with phase("krr.block_step") as out:
                    if self.cache_kernel:
                        Kb = kernel_cache.get(start)
                        if Kb is None:
                            Kb = _kernel_block_slice(
                                X, start, jnp.float32(self.gamma), size
                            )
                            kernel_cache[start] = Kb
                        W = _krr_block_step_cached(
                            Kb, Y, W, start, jnp.float32(self.lam),
                            bs=size,
                        )
                    else:
                        W = _krr_block_step(
                            X, Y, W, start, jnp.float32(self.gamma),
                            jnp.float32(self.lam), bs=size,
                        )
                    out.append(W)
                steps_done += 1
                if ckpt and steps_done % self.checkpoint_interval == 0:
                    np.savez(
                        ckpt,
                        W=np.asarray(jax.block_until_ready(W)),
                        epoch=epoch,
                        step=step + 1,
                    )
        if ckpt and os.path.exists(ckpt):
            os.remove(ckpt)  # complete fit: drop the restart state
        return KernelBlockLinearMapper(X, W, self.gamma, bs)


class GaussianKernelGenerator(LabelEstimator):
    """Convenience estimator shape used by RandomPatchCifarKernel: fit KRR on
    Gaussian-kernel features (parity: GaussianKernelGenerator +
    KernelRidgeRegression composition, KernelGenerator.scala:36-84)."""

    def __init__(self, gamma: float):
        self.gamma = gamma

    def kernel_matrix(self, data: Dataset, cache: bool = True
                      ) -> BlockKernelMatrix:
        return BlockKernelMatrix(
            Dataset.of(data).to_array(), self.gamma, cache
        )
