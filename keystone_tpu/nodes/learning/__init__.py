from .cost import CostModel
from .classifiers import (
    LeastSquaresEstimator,
    LinearDiscriminantAnalysis,
    LogisticRegressionEstimator,
    LogisticRegressionModel,
    NaiveBayesEstimator,
    NaiveBayesModel,
)
from .kernel import (
    BlockKernelMatrix,
    ExactKernelRidge,
    GaussianKernelGenerator,
    KernelBlockLinearMapper,
    KernelRidgeEstimator,
    KernelRidgeRegression,
)
from .lbfgs import (
    DenseLBFGSwithL2,
    LocalLeastSquaresEstimator,
    SparseLBFGSwithL2,
)
from .weighted import (
    BlockWeightedLeastSquaresEstimator,
    PerClassWeightedLeastSquaresEstimator,
    ReWeightedLeastSquaresEstimator,
    WeightedLeastSquaresEstimator,
)
from .gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from .kmeans import KMeansModel, KMeansPlusPlusEstimator
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
    SparseLinearMapper,
    TSQRLeastSquaresEstimator,
)
from .pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from .zca import ZCAWhitener, ZCAWhitenerEstimator

__all__ = [
    "CostModel",
    "LeastSquaresEstimator",
    "LinearDiscriminantAnalysis",
    "LogisticRegressionEstimator",
    "LogisticRegressionModel",
    "NaiveBayesEstimator",
    "NaiveBayesModel",
    "BlockKernelMatrix",
    "ExactKernelRidge",
    "GaussianKernelGenerator",
    "KernelBlockLinearMapper",
    "KernelRidgeEstimator",
    "KernelRidgeRegression",
    "DenseLBFGSwithL2",
    "LocalLeastSquaresEstimator",
    "SparseLBFGSwithL2",
    "BlockWeightedLeastSquaresEstimator",
    "PerClassWeightedLeastSquaresEstimator",
    "ReWeightedLeastSquaresEstimator",
    "WeightedLeastSquaresEstimator",
    "GaussianMixtureModel",
    "GaussianMixtureModelEstimator",
    "KMeansModel",
    "KMeansPlusPlusEstimator",
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
    "SparseLinearMapper",
    "TSQRLeastSquaresEstimator",
    "ApproximatePCAEstimator",
    "BatchPCATransformer",
    "ColumnPCAEstimator",
    "DistributedColumnPCAEstimator",
    "DistributedPCAEstimator",
    "LocalColumnPCAEstimator",
    "PCAEstimator",
    "PCATransformer",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
]
