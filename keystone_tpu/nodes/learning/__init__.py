from .cost import CostModel
from .gmm import GaussianMixtureModel, GaussianMixtureModelEstimator
from .kmeans import KMeansModel, KMeansPlusPlusEstimator
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)
from .pca import (
    ApproximatePCAEstimator,
    BatchPCATransformer,
    ColumnPCAEstimator,
    DistributedColumnPCAEstimator,
    DistributedPCAEstimator,
    LocalColumnPCAEstimator,
    PCAEstimator,
    PCATransformer,
)
from .zca import ZCAWhitener, ZCAWhitenerEstimator

__all__ = [
    "CostModel",
    "GaussianMixtureModel",
    "GaussianMixtureModelEstimator",
    "KMeansModel",
    "KMeansPlusPlusEstimator",
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
    "ApproximatePCAEstimator",
    "BatchPCATransformer",
    "ColumnPCAEstimator",
    "DistributedColumnPCAEstimator",
    "DistributedPCAEstimator",
    "LocalColumnPCAEstimator",
    "PCAEstimator",
    "PCATransformer",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
]
