from .cost import CostModel
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)

__all__ = [
    "CostModel",
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
]
