from .cost import CostModel
from .zca import ZCAWhitener, ZCAWhitenerEstimator
from .linear import (
    BlockLeastSquaresEstimator,
    BlockLinearMapper,
    LinearMapEstimator,
    LinearMapper,
)

__all__ = [
    "CostModel",
    "BlockLeastSquaresEstimator",
    "BlockLinearMapper",
    "LinearMapEstimator",
    "LinearMapper",
    "ZCAWhitener",
    "ZCAWhitenerEstimator",
]
