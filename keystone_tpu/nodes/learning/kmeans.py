"""k-means++ (parity: nodes/learning/KMeansPlusPlus.scala:16,83).

One round = the k-means++ initialization; more rounds = Lloyd's algorithm.
Distance matrices, assignments and center updates are all batched matrix
algebra on-device; the sequential k-means++ seeding loop stays host-side
(it is inherently sequential and tiny: k draws).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param
from ...utils.jit import nestable_jit


@nestable_jit
def _sq_dists(X, means):
    """½‖x‖² − x·μ + ½‖μ‖² per (sample, center) — the reference's vectorized
    distance trick (KMeansPlusPlus.scala:34-39)."""
    xsq = 0.5 * jnp.sum(X * X, axis=1, keepdims=True)
    msq = 0.5 * jnp.sum(means * means, axis=1)
    return xsq - X @ means.T + msq


@nestable_jit
def _one_hot_assign(X, means):
    d = _sq_dists(X, means)
    idx = jnp.argmin(d, axis=1)
    return jax.nn.one_hot(idx, means.shape[0], dtype=X.dtype)


class KMeansModel(Transformer):
    """Maps each vector to its one-hot nearest-center assignment
    (parity: KMeansModel, KMeansPlusPlus.scala:16-78)."""

    def __init__(self, means):
        self.means = as_param(means)

    def trace_batch(self, X):
        return _one_hot_assign(X, self.means)


class KMeansPlusPlusEstimator(Estimator):
    """(parity: KMeansPlusPlusEstimator, KMeansPlusPlus.scala:83-181)."""

    def __init__(self, num_means: int, max_iterations: int,
                 stop_tolerance: float = 1e-3, seed: int = 0):
        self.num_means = num_means
        self.max_iterations = max_iterations
        self.stop_tolerance = stop_tolerance
        self.seed = seed

    def fit(self, data: Dataset) -> KMeansModel:
        return self.fit_matrix(Dataset.of(data).to_array())

    def fit_matrix(self, X) -> KMeansModel:
        X = jnp.asarray(X, dtype=jnp.float32)
        n, d = X.shape
        k = self.num_means
        rng = np.random.default_rng(self.seed)

        # -- k-means++ seeding (sequential, host-driven) ---------------
        centers = [int(rng.integers(0, n))]
        xsq_half = 0.5 * jnp.sum(X * X, axis=1)
        cur_sq = None
        for i in range(k - 1):
            c = X[centers[i]]
            sq_new = xsq_half - X @ c + 0.5 * jnp.dot(c, c)
            cur_sq = sq_new if cur_sq is None else jnp.minimum(cur_sq, sq_new)
            probs = np.maximum(np.asarray(cur_sq), 0.0)
            total = probs.sum()
            if total <= 0:
                centers.append(int(rng.integers(0, n)))
            else:
                centers.append(int(rng.choice(n, p=probs / total)))

        means = X[jnp.asarray(centers)]

        # -- Lloyd's iterations ---------------------------------------
        prev_cost = None
        for _ in range(self.max_iterations):
            dists = _sq_dists(X, means)
            cost = float(jnp.mean(jnp.min(dists, axis=1)))
            if prev_cost is not None and not (
                prev_cost - cost >= self.stop_tolerance * abs(prev_cost)
            ):
                break
            prev_cost = cost
            assign = jax.nn.one_hot(
                jnp.argmin(dists, axis=1), k, dtype=X.dtype
            )
            counts = assign.sum(axis=0)
            # keep empty clusters where they were (reference divides and gets
            # NaN only for empty clusters, which don't occur with k-means++
            # seeding on real data; guard anyway)
            new_means = (assign.T @ X) / jnp.maximum(counts, 1.0)[:, None]
            means = jnp.where(
                (counts > 0)[:, None], new_means, means
            )
        return KMeansModel(means)
