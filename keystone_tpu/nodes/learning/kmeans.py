"""k-means++ (parity: nodes/learning/KMeansPlusPlus.scala:16,83).

One round = the k-means++ initialization; more rounds = Lloyd's algorithm.
Everything — including the sequential D²-weighted seeding — runs as
compiled device programs: the seeding is one ``lax.scan`` over k−1 steps
with on-device categorical draws, and Lloyd's iterations are one
``lax.while_loop`` with the reference's stop-on-non-improving-cost
semantics. The first cut kept the seeding host-side ("inherently
sequential and tiny: k draws") — but each draw fetched an n-element
probability vector to the host, and through a tunneled transport those
k−1 blocking fetches cost 10-18 s at n=200k; as one program the whole
fit is a handful of dispatches.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param
from ...utils.jit import nestable_jit


@nestable_jit
def _sq_dists(X, means):
    """½‖x‖² − x·μ + ½‖μ‖² per (sample, center) — the reference's vectorized
    distance trick (KMeansPlusPlus.scala:34-39)."""
    xsq = 0.5 * jnp.sum(X * X, axis=1, keepdims=True)
    msq = 0.5 * jnp.sum(means * means, axis=1)
    return xsq - X @ means.T + msq


@nestable_jit
def _one_hot_assign(X, means):
    d = _sq_dists(X, means)
    idx = jnp.argmin(d, axis=1)
    return jax.nn.one_hot(idx, means.shape[0], dtype=X.dtype)


@functools.partial(jax.jit, static_argnames=("k",))
def _seed_plus_plus(X, key, k: int):
    """k-means++ seeding as ONE program: scan over k−1 D²-weighted draws
    (parity: the seeding loop of KMeansPlusPlusEstimator; the degenerate
    all-points-covered case falls back to a uniform draw, as the host
    version did)."""
    n = X.shape[0]
    xsq_half = 0.5 * jnp.sum(X * X, axis=1)
    k0, key = jax.random.split(key)
    c0 = X[jax.random.randint(k0, (), 0, n)]
    if k == 1:
        return c0[None]

    def step(carry, _):
        cur_sq, last_c, key = carry
        sq_new = xsq_half - X @ last_c + 0.5 * jnp.dot(last_c, last_c)
        cur_sq = jnp.minimum(cur_sq, sq_new)
        probs = jnp.maximum(cur_sq, 0.0)
        key, kw, ku = jax.random.split(key, 3)
        # log(0) = −inf excludes already-covered points from the draw
        idx_weighted = jax.random.categorical(kw, jnp.log(probs))
        idx_uniform = jax.random.randint(ku, (), 0, n)
        idx = jnp.where(jnp.sum(probs) > 0, idx_weighted, idx_uniform)
        new_c = X[idx]
        return (cur_sq, new_c, key), new_c

    init = (jnp.full((n,), jnp.inf, X.dtype), c0, key)
    _, rest = jax.lax.scan(step, init, None, length=k - 1)
    return jnp.concatenate([c0[None], rest], axis=0)


@functools.partial(
    jax.jit, static_argnames=("max_iterations", "stop_tolerance")
)
def _lloyd_loop(X, means, *, max_iterations: int, stop_tolerance: float):
    """Lloyd's iterations as ONE ``lax.while_loop`` program. Break
    semantics match the host loop exactly: when the cost stops improving,
    KEEP the current means (no final update); empty clusters stay where
    they were."""
    k = means.shape[0]

    def cond(carry):
        i, done, *_ = carry
        return (i < max_iterations) & ~done

    def body(carry):
        i, done, prev_cost, has_prev, means = carry
        dists = _sq_dists(X, means)
        cost = jnp.mean(jnp.min(dists, axis=1))
        stop = has_prev & ~(
            prev_cost - cost >= stop_tolerance * jnp.abs(prev_cost)
        )
        assign = jax.nn.one_hot(jnp.argmin(dists, axis=1), k, dtype=X.dtype)
        counts = assign.sum(axis=0)
        new_means = (assign.T @ X) / jnp.maximum(counts, 1.0)[:, None]
        new_means = jnp.where((counts > 0)[:, None], new_means, means)
        m2 = jnp.where(stop, means, new_means)
        return (i + 1, stop, cost, True, m2)

    init = (
        jnp.int32(0), jnp.bool_(False), jnp.float32(0.0), jnp.bool_(False),
        means,
    )
    *_, means = jax.lax.while_loop(cond, body, init)
    return means


class KMeansModel(Transformer):
    """Maps each vector to its one-hot nearest-center assignment
    (parity: KMeansModel, KMeansPlusPlus.scala:16-78)."""

    def __init__(self, means):
        self.means = as_param(means)

    def trace_batch(self, X):
        return _one_hot_assign(X, self.means)


class KMeansPlusPlusEstimator(Estimator):
    """(parity: KMeansPlusPlusEstimator, KMeansPlusPlus.scala:83-181)."""

    def __init__(self, num_means: int, max_iterations: int,
                 stop_tolerance: float = 1e-3, seed: int = 0):
        self.num_means = num_means
        self.max_iterations = max_iterations
        self.stop_tolerance = stop_tolerance
        self.seed = seed

    def fit(self, data: Dataset) -> KMeansModel:
        return self.fit_matrix(Dataset.of(data).to_array())

    def fit_matrix(self, X) -> KMeansModel:
        X = jnp.asarray(X, dtype=jnp.float32)
        means = _seed_plus_plus(
            X, jax.random.PRNGKey(self.seed), self.num_means
        )
        means = _lloyd_loop(
            X, means,
            max_iterations=self.max_iterations,
            stop_tolerance=self.stop_tolerance,
        )
        return KMeansModel(means)
