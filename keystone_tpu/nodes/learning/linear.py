"""Linear solvers: exact normal equations and block coordinate descent.

Parity: nodes/learning/LinearMapper.scala:18,69 (LinearMapper /
LinearMapEstimator) and nodes/learning/BlockLinearMapper.scala:22,199
(BlockLinearMapper / BlockLeastSquaresEstimator).

Semantics preserved from the reference:
  * features and labels are mean-centered before solving (StandardScaler with
    normalizeStdDev=false); the label mean becomes the intercept;
  * the block estimator centers each feature block independently;
  * ``num_iter=1`` is the one-pass BCD variant (solveOnePassL2).

TPU-native apply: the per-block GEMM+sum of the reference collapses into ONE
fused (n,d)×(d,k) MXU matmul over the concatenated model; block structure only
matters at fit time.
"""

from __future__ import annotations

import logging
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)

from ...data.dataset import Dataset
from ...linalg import solve_blockwise_l2, solve_least_squares
from ...parallel.mesh import shard_batch
from ...utils.params import as_param
from ...workflow.transformer import LabelEstimator, Transformer
from .cost import CostModel, combine_cost, label_dim_fitted_out_spec


class LinearMapper(Transformer):
    """out = (x − feature_mean) · W + b  (parity: LinearMapper.scala:18-63;
    scaling folded into the single GEMM)."""

    #: ``solver_state`` is refit bookkeeping (a snapshot-able
    #: GramSolverState), not part of the serve computation — W/b/mean fully
    #: determine trace_batch, so two mappers differing only in it must
    #: share AOT executables
    aot_fingerprint_exclude = ("solver_state",)

    def __init__(self, W, b=None, feature_mean=None, solver_state=None):
        self.W = as_param(W)
        self.b = as_param(b)
        self.feature_mean = as_param(feature_mean)
        #: optional :class:`~keystone_tpu.linalg.accumulators.GramSolverState`
        #: captured at fit time — what ``FittedPipeline.absorb`` folds
        #: appended chunks into (None when the fit didn't snapshot)
        self.solver_state = solver_state

    def trace_batch(self, X):
        if self.feature_mean is not None:
            X = X - self.feature_mean
        out = X @ self.W
        if self.b is not None:
            out = out + self.b
        return out


class LinearMapEstimator(LabelEstimator, CostModel):
    """Exact OLS via mesh normal equations
    (parity: LinearMapper.scala:69-100). Chunked inputs stream: a means
    pass, then centered (A, y) chunks through the laned Gram accumulator
    (``solve_least_squares_streaming``) — the exact solve never
    materializes the design matrix.

    ``snapshot=True`` fits through the raw-accumulator algebra
    (:class:`~keystone_tpu.linalg.accumulators.GramSolverState`: ΣAᵀA and
    ΣAᵀy with centering applied algebraically at the solve) and attaches
    the state to the fitted :class:`LinearMapper` — the handle
    ``FittedPipeline.absorb`` folds appended chunks into for an
    O(new chunks) incremental refit.

    ``checkpoint=dir`` makes a chunked fit RESUMABLE: the same
    accumulator state (plus a chunk/row cursor) persists atomically to
    ``dir`` every ``checkpoint_every`` chunks
    (:class:`~keystone_tpu.faults.FitCheckpoint`), so a killed fit
    re-run with the same arguments resumes from the last completed
    block — folding bit-identical solver state to an uninterrupted fit
    — instead of rescanning from chunk zero. The checkpoint is removed
    when the fit completes."""

    supports_streaming = True

    def __init__(
        self,
        lam: Optional[float] = None,
        snapshot: bool = False,
        checkpoint: Optional[str] = None,
        checkpoint_every: int = 1,
    ):
        self.lam = lam
        self.snapshot = snapshot
        self.checkpoint = checkpoint
        self.checkpoint_every = checkpoint_every

    def fitted_out_spec(self, fit_in, apply_in):
        return label_dim_fitted_out_spec(fit_in, apply_in)

    # -- sweep grid hooks (keystone_tpu/sweep/) -------------------------

    def grid_family(self):
        """Estimators of one sweep whose key matches fit as a group; λ is
        the swept axis, so it is excluded from the key. The checkpoint
        dir is part of the identity — a sweep's shared accumulation pass
        would otherwise silently drop a member's resume contract."""
        return ("gram_ne", bool(self.snapshot), self.checkpoint)

    @staticmethod
    def fit_lambda_grid(estimators: Sequence["LinearMapEstimator"],
                        data, labels: Dataset,
                        checkpoint: Optional[str] = None,
                        checkpoint_every: int = 1) -> List[LinearMapper]:
        """Fit a λ-only grid from ONE accumulation pass: the Gram and
        cross products don't depend on λ, so the grid costs
        O(prefix + n·d² + G·d³) instead of G full fits. Every returned
        mapper carries its own snapshot of the shared state (λ recorded),
        so any of them can later ``absorb`` appended chunks.

        With ``checkpoint``, the accumulation over a chunked ``data``
        persists ``(state, chunk cursor, row cursor)`` to that directory
        every ``checkpoint_every`` chunks and RESUMES from the last
        completed block on re-run — the fold is associative and the
        state is exact host float64, so the resumed accumulator is
        bit-identical to an uninterrupted pass."""
        from ...data.chunked import ChunkedDataset
        from ...linalg.accumulators import GramSolverState
        from ...utils.timing import phase

        state = GramSolverState()
        with phase("linear_map.grid_accumulate") as out:
            if isinstance(data, ChunkedDataset):
                y = jnp.asarray(
                    Dataset.of(labels).to_array(), dtype=jnp.float32
                )
                ckpt = None
                start_chunk = 0
                offset = 0
                if checkpoint is not None:
                    from ...faults import FitCheckpoint

                    lams = [float(e.lam or 0.0) for e in estimators]
                    key = (
                        f"gram_ne|n={len(data)}"
                        f"|y={tuple(int(s) for s in y.shape)}|lams={lams}"
                    )
                    ckpt = FitCheckpoint(checkpoint, key)
                    loaded = ckpt.load()
                    if loaded is not None:
                        state, start_chunk, offset = loaded
                        logger.info(
                            "fit checkpoint: resuming Gram accumulation "
                            "at chunk %d (row %d) from %s",
                            start_chunk, offset, ckpt.path,
                        )
                every = max(1, int(checkpoint_every))
                i = start_chunk
                for chunk in data.raw_chunks(skip=start_chunk):
                    rows = int(chunk.shape[0])
                    state.update(chunk, y[offset : offset + rows])
                    offset += rows
                    i += 1
                    if ckpt is not None and i % every == 0:
                        ckpt.save(state, i, offset)
                if offset != y.shape[0]:
                    raise ValueError(
                        f"chunked features have {offset} rows, labels "
                        f"{y.shape[0]}"
                    )
                if ckpt is not None:
                    ckpt.complete()
            else:
                state.update(
                    Dataset.of(data).to_array(),
                    Dataset.of(labels).to_array(),
                )
            out.append(state.gram)
        models = []
        for est in estimators:
            W, b, mean = state.solve(est.lam or 0.0)
            snap = state.snapshot()
            snap.lam = float(est.lam or 0.0)
            models.append(
                LinearMapper(W, b=b, feature_mean=mean, solver_state=snap)
            )
        return models

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        from ...data.chunked import ChunkedDataset

        if self.snapshot or self.checkpoint:
            # the checkpointed fit rides the same accumulator path the
            # snapshot fit uses — the state on disk IS the snapshot
            return LinearMapEstimator.fit_lambda_grid(
                [self], data, labels,
                checkpoint=self.checkpoint,
                checkpoint_every=self.checkpoint_every,
            )[0]
        if isinstance(data, ChunkedDataset):
            return self._fit_streaming(data, labels)
        A = shard_batch(data.to_array().astype(jnp.float32))
        b = shard_batch(labels.to_array().astype(jnp.float32))
        a_mean = jnp.mean(A, axis=0)
        b_mean = jnp.mean(b, axis=0)
        W = solve_least_squares(A - a_mean, b - b_mean, reg=self.lam or 0.0)
        return LinearMapper(W, b=b_mean, feature_mean=a_mean)

    def _fit_streaming(self, data, labels: Dataset) -> LinearMapper:
        """Out-of-core exact solve: one pass for column means, one laned
        Gram/cross pass over centered chunks (same two-pass shape as the
        streaming BCD path; collectives O(1) per scan)."""
        from ...linalg import solve_least_squares_streaming
        from ...linalg.bcd import stream_column_means
        from ...utils.timing import phase

        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        with phase("linear_map.stream_center") as out:
            a_mean, n = stream_column_means(data.raw_chunks)
            if n != y.shape[0]:
                raise ValueError(
                    f"chunked features have {n} rows, labels {y.shape[0]}"
                )
            y_mean = jnp.mean(y, axis=0)
            out.append(y_mean)

        def centered():
            offset = 0
            for chunk in data.raw_chunks():
                chunk = jnp.asarray(chunk, dtype=jnp.float32)
                rows = int(chunk.shape[0])
                yield (
                    chunk - a_mean,
                    y[offset : offset + rows] - y_mean,
                )
                offset += rows

        with phase("linear_map.stream_solve") as out:
            W = solve_least_squares_streaming(centered(), reg=self.lam or 0.0)
            out.append(W)
        return LinearMapper(W, b=y_mean, feature_mean=a_mean)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        # parity: LinearMapper.scala:100-117
        from ...linalg.normal_equations import cost_signature

        return combine_cost(
            cost_signature(n, d, k, num_machines),
            cpu_weight, mem_weight, network_weight,
        )


class BlockLinearMapper(Transformer):
    """Fused apply of a block-solved model: block weights are vertically
    concatenated and per-block means concatenated, so application is one
    GEMM (parity: BlockLinearMapper.scala:22-98, whose per-block RDD zip+sum
    is pure network choreography the MXU doesn't need)."""

    #: refit bookkeeping (a snapshot-able WeightedSolverState from the
    #: per-class weighted family), never part of the serve computation
    aot_fingerprint_exclude = ("solver_state",)

    def __init__(self, xs: Sequence, block_size: int, b=None,
                 feature_means: Optional[Sequence] = None,
                 solver_state=None):
        import numpy as np

        #: optional :class:`~keystone_tpu.linalg.weighted.
        #: WeightedSolverState` captured at fit time — what
        #: ``FittedPipeline.absorb`` folds appended chunks into
        self.solver_state = solver_state
        # One batched device fetch; parameters live on host (utils/params.py)
        xs, b, feature_means = jax.device_get((list(xs), b, feature_means))
        self.xs = [as_param(x) for x in xs]
        self.block_size = block_size
        self.b = as_param(b)
        self.feature_means = (
            None
            if feature_means is None
            else [as_param(m) for m in feature_means]
        )
        self._W = np.concatenate(self.xs, axis=0)
        self._mean = (
            None
            if self.feature_means is None
            else np.concatenate(self.feature_means, axis=0)
        )

    def trace_batch(self, X):
        if self._mean is not None:
            X = X - self._mean
        out = X @ self._W
        if self.b is not None:
            out = out + self.b
        return out

    def apply_blocks(self, blocks: Sequence) -> jnp.ndarray:
        """Apply to pre-split feature blocks (parity:
        BlockLinearMapper.scala:50-73)."""
        out = None
        for j, (Aj, Wj) in enumerate(zip(blocks, self.xs)):
            Aj = jnp.asarray(Aj)
            if self.feature_means is not None:
                Aj = Aj - self.feature_means[j]
            term = Aj @ Wj
            out = term if out is None else out + term
        if self.b is not None:
            out = out + self.b
        return out


class BlockLeastSquaresEstimator(LabelEstimator, CostModel):
    """Block-coordinate-descent least squares — the workhorse solver
    (parity: BlockLinearMapper.scala:199-283)."""

    supports_streaming = True

    def __init__(self, block_size: int, num_iter: int, lam: float = 0.0,
                 num_features: Optional[int] = None,
                 snapshot: bool = False):
        if snapshot:
            from ...linalg.accumulators import NotAbsorbable

            raise NotAbsorbable(
                "block-coordinate descent has no snapshot-able state: "
                "its iterates depend on block visitation order, so "
                "appended chunks cannot be folded in after the fact — "
                "fit with LinearMapEstimator(snapshot=True) (exact Gram "
                "family) for an absorbable model"
            )
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.num_features = num_features
        #: per-block starting weights for the next fit (a λ-sweep warm
        #: start from the nearest-λ neighbor's model); consumed and
        #: cleared by ``fit`` — never part of the estimator's identity
        self.warm_start_ws: Optional[Sequence] = None

    def fitted_out_spec(self, fit_in, apply_in):
        return label_dim_fitted_out_spec(fit_in, apply_in)

    # passes over the input, for the auto-cache planner
    # (parity: BlockLinearMapper.scala:204)
    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    # -- sweep grid hooks (keystone_tpu/sweep/) -------------------------

    def grid_family(self):
        return ("bcd", self.block_size, self.num_iter, self.num_features)

    @staticmethod
    def fit_lambda_grid(
        estimators: Sequence["BlockLeastSquaresEstimator"], data, labels,
        warm_start: bool = True,
    ) -> List["BlockLinearMapper"]:
        """Fit a λ grid of BCD members, each warm-started from the
        nearest-λ neighbor already solved (ascending λ order). BCD is
        iterative, so warm-started iterates differ from cold ones while
        descending the same objective — a sweep only takes this path when
        asked (``GridSweep(warm_start=True)``). Chunked inputs fall back
        to independent cold fits (the streaming prediction buffer has no
        cheap consistent warm initialization)."""
        import copy

        from ...data.chunked import ChunkedDataset

        order = sorted(
            range(len(estimators)), key=lambda i: estimators[i].lam or 0.0
        )
        models: List[Optional[BlockLinearMapper]] = [None] * len(estimators)
        prev: Optional[BlockLinearMapper] = None
        chunked = isinstance(data, ChunkedDataset)
        for i in order:
            est = copy.copy(estimators[i])
            est.warm_start_ws = (
                [w for w in prev.xs] if (warm_start and prev is not None
                                         and not chunked) else None
            )
            models[i] = est.fit(data, labels)
            prev = models[i]
        return models

    def fit(self, data, labels: Dataset) -> BlockLinearMapper:
        """``data`` is either a Dataset of (n, d) features (split internally,
        parity :251-257) or an already-split sequence of blocks (:212).

        A contiguous (n, d) matrix with d divisible by ``block_size`` solves
        through :func:`solve_blockwise_l2_scan` — the whole BCD pass is ONE
        compiled program (zero host round trips per block). Pre-split or
        ragged blocks take the per-block-dispatch path.
        """
        from ...data.chunked import ChunkedDataset
        from ...linalg.bcd import _block_means, solve_blockwise_l2_scan
        from ...utils.timing import phase

        warm = getattr(self, "warm_start_ws", None)  # pre-sweep pickles
        self.warm_start_ws = None
        if isinstance(data, ChunkedDataset):
            return self._fit_streaming(data, labels)

        X = None
        if isinstance(data, Dataset) and isinstance(data.payload, (list, tuple)):
            blocks = [jnp.asarray(p) for p in data.payload]
        elif isinstance(data, (list, tuple)):
            # stage pre-split blocks through the pipelined scan: block i+1
            # materializes (and its H2D transfer streams) while block i's
            # device placement completes, instead of a serial eager loop
            from ...data.pipeline_scan import scan_pipeline

            blocks = list(
                scan_pipeline(
                    (Dataset.of(d).to_array() for d in data),
                    label="block_ingest",
                )
            )
        else:
            X = Dataset.of(data).to_array()
            d = self.num_features or X.shape[-1]
            X = X[..., :d]
            blocks = None

        y = Dataset.of(labels).to_array().astype(jnp.float32)

        if X is not None and X.shape[-1] % self.block_size == 0:
            d = X.shape[-1]
            with phase("block_ls.center") as out:
                X = shard_batch(
                    X if X.dtype == jnp.float32 else X.astype(jnp.float32)
                )
                mean_vec = jnp.mean(X, axis=0)
                y_mean = jnp.mean(y, axis=0)
                out.append((mean_vec, y_mean))
            with phase("block_ls.solve") as out:
                init = None
                if warm is not None:
                    cat = jnp.concatenate(
                        [jnp.asarray(w) for w in warm], axis=0
                    )
                    if cat.shape == (d, y.shape[1]):
                        init = cat
                W = solve_blockwise_l2_scan(
                    X, shard_batch(y - y_mean), reg=self.lam,
                    block_size=self.block_size, num_iter=self.num_iter,
                    means=mean_vec, init=init,
                )
                out.append(W)
            ws = [
                W[i : i + self.block_size]
                for i in range(0, d, self.block_size)
            ]
            means = [
                mean_vec[i : i + self.block_size]
                for i in range(0, d, self.block_size)
            ]
            return BlockLinearMapper(
                ws, self.block_size, b=y_mean, feature_means=means
            )

        if blocks is None:
            d = X.shape[-1]
            blocks = [
                X[..., i : min(i + self.block_size, d)]
                for i in range(0, d, self.block_size)
            ]
        with phase("block_ls.center") as out:
            blocks = [
                shard_batch(b if b.dtype == jnp.float32 else b.astype(jnp.float32))
                for b in blocks
            ]
            # one program for every mean; centering itself is fused into the
            # per-block solve so centered copies never hit HBM
            means, y_mean = _block_means(blocks, y)
            out.append(y_mean)
        with phase("block_ls.solve"):
            init = None
            if warm is not None and len(warm) == len(blocks) and all(
                tuple(w.shape) == (int(b.shape[1]), int(y.shape[1]))
                for w, b in zip(warm, blocks)
            ):
                init = [jnp.asarray(w) for w in warm]
            ws = solve_blockwise_l2(
                blocks, shard_batch(y - y_mean), reg=self.lam,
                num_iter=self.num_iter, means=means, init=init,
            )
        return BlockLinearMapper(
            ws, self.block_size, b=y_mean, feature_means=means
        )

    def _fit_streaming(self, data, labels: Dataset) -> BlockLinearMapper:
        """Fit from a :class:`~keystone_tpu.data.chunked.ChunkedDataset`
        without ever materializing the featurized design matrix — the
        out-of-core path (parity: the reference's BCD scanning its cached
        featurized RDD per block step, BlockLinearMapper.scala:199-257 over
        ImageNet/TIMIT-scale training sets that exceed one machine).

        Scans the source num_iter × nblocks + 1 times (one centering pass;
        each block step fuses the previous block's prediction update)."""
        from ...linalg.bcd import (
            solve_blockwise_l2_streaming,
            stream_column_means,
        )
        from ...utils.timing import phase

        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)

        # raw (unpipelined) scans compose here: the streaming solvers wrap
        # chunk_scan() in scan_pipeline themselves, so exactly ONE
        # producer thread runs the whole chain per scan
        if self.num_features is not None:
            d = self.num_features
            base_scan = data.raw_chunks

            def chunk_scan():
                for chunk in base_scan():
                    yield chunk[..., :d]

        else:
            chunk_scan = data.raw_chunks

        with phase("block_ls.stream_center") as out:
            mean_vec, n = stream_column_means(chunk_scan)
            if n != y.shape[0]:
                raise ValueError(
                    f"chunked features have {n} rows, labels {y.shape[0]}"
                )
            y_mean = jnp.mean(y, axis=0)
            out.append(y_mean)
        with phase("block_ls.stream_solve") as out:
            ws = solve_blockwise_l2_streaming(
                chunk_scan, y - y_mean, reg=self.lam,
                block_size=self.block_size, num_iter=self.num_iter,
                means=mean_vec,
            )
            out.append(ws[-1])
        d = int(mean_vec.shape[0])
        means = [
            mean_vec[i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        ]
        return BlockLinearMapper(
            ws, self.block_size, b=y_mean, feature_means=means
        )

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        # parity: BlockLinearMapper.scala:268-282
        from ...linalg.bcd import cost_signature

        return combine_cost(
            cost_signature(
                n, d, k, self.block_size, self.num_iter, num_machines
            ),
            cpu_weight, mem_weight, network_weight,
        )


class TSQRLeastSquaresEstimator(LabelEstimator, CostModel):
    """Exact least squares via tall-skinny QR of the AUGMENTED design
    matrix — the numerically robust sibling of the normal equations.

    Parity root: mlmatrix's TSQR (DistributedPCA.scala:48 uses qrR); the
    reference never wires it into LeastSquaresEstimator's option set, but
    the factorization is the classic cure for the Gram route squaring the
    condition number. One QR of ``[A−μ | y−ν ; √λ·I | 0]`` yields an
    upper-triangular ``R`` whose blocks satisfy ``R₁₁ᵀR₁₁ = AᵀA + λI``
    and ``R₁₁ᵀR₁₂ = Aᵀy`` (centered), so the solution is ONE triangular
    solve ``W = R₁₁⁻¹R₁₂`` — no Gram matrix ever forms. Costs ~2× the
    Gram contraction in flops (see ``linalg.tsqr.cost_signature``): the
    cost model prefers it only when learned profiles or conditioning
    evidence say so.

    Chunked inputs stream through :func:`linalg.tsqr.tsqr_r_streaming`
    (per-lane R folds, one cross-mesh gather at finalize), so the exact
    QR solve is available out-of-core too.

    ``checkpoint=dir`` makes the chunked fit resumable: it runs the
    sequential :class:`~keystone_tpu.linalg.accumulators.TsqrRState`
    recurrence (restartable by construction) instead of the laned fold,
    persists the R state + column means + chunk cursor to ``dir`` every
    ``checkpoint_every`` chunks, and a killed fit re-run resumes from
    the last completed block — the means pass is checkpointed too, so
    resume re-reads NO already-folded chunk.
    """

    supports_streaming = True

    def __init__(
        self,
        lam: float = 0.0,
        checkpoint: Optional[str] = None,
        checkpoint_every: int = 1,
    ):
        self.lam = lam
        self.checkpoint = checkpoint
        self.checkpoint_every = checkpoint_every

    def fitted_out_spec(self, fit_in, apply_in):
        return label_dim_fitted_out_spec(fit_in, apply_in)

    # -- sweep grid hooks (keystone_tpu/sweep/) -------------------------

    def grid_family(self):
        return ("tsqr", self.checkpoint)

    @staticmethod
    def fit_lambda_grid(
        estimators: Sequence["TSQRLeastSquaresEstimator"], data, labels
    ) -> List[LinearMapper]:
        """Fit a λ-only grid from ONE factorization: the R factor of the
        UNregularized centered augmented matrix is λ-independent, and
        ``qr([A; B]).R == qr([qr(A).R; B]).R`` (up to row signs, which
        the triangular solve cancels) — so each member folds only its
        √λ·I rows into the shared R, an O((d+k)³) fold against one
        O(n·(d+k)²) factorization."""
        from ...data.chunked import ChunkedDataset
        from ...linalg.bcd import stream_column_means
        from ...linalg.tsqr import _qr_fold, tsqr_r, tsqr_r_streaming
        from ...utils.timing import phase

        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        chunked = isinstance(data, ChunkedDataset)
        with phase("tsqr_ls.grid_factorize") as out:
            if chunked:
                a_mean, n = stream_column_means(data.raw_chunks)
                if n != y.shape[0]:
                    raise ValueError(
                        f"chunked features have {n} rows, labels {y.shape[0]}"
                    )
                y_mean = jnp.mean(y, axis=0)
                d = int(a_mean.shape[0])

                def augmented():
                    offset = 0
                    for chunk in data.raw_chunks():
                        chunk = jnp.asarray(chunk, dtype=jnp.float32)
                        rows = int(chunk.shape[0])
                        yield jnp.concatenate(
                            [chunk - a_mean,
                             y[offset : offset + rows] - y_mean],
                            axis=1,
                        )
                        offset += rows

                R_base = tsqr_r_streaming(augmented)
            else:
                A = jnp.asarray(
                    Dataset.of(data).to_array(), dtype=jnp.float32
                )
                a_mean = jnp.mean(A, axis=0)
                y_mean = jnp.mean(y, axis=0)
                d = int(A.shape[1])
                R_base = tsqr_r(
                    jnp.concatenate([A - a_mean, y - y_mean], axis=1)
                )
            out.append(R_base)
        k = int(y.shape[1])
        models = []
        for est in estimators:
            reg = est._reg_rows(d, k)
            R = R_base if reg is None else _qr_fold(R_base, reg)
            W = TSQRLeastSquaresEstimator._solve_from_r(R, d)
            models.append(LinearMapper(W, b=y_mean, feature_mean=a_mean))
        return models

    @staticmethod
    def _solve_from_r(R, d: int):
        from jax.scipy.linalg import solve_triangular

        return solve_triangular(R[:d, :d], R[:d, d:], lower=False)

    def _reg_rows(self, d: int, k: int):
        if not self.lam:
            return None
        return jnp.concatenate(
            [
                jnp.sqrt(jnp.float32(self.lam)) * jnp.eye(d, dtype=jnp.float32),
                jnp.zeros((d, k), dtype=jnp.float32),
            ],
            axis=1,
        )

    def fit(self, data, labels: Dataset) -> LinearMapper:
        from ...data.chunked import ChunkedDataset
        from ...linalg.tsqr import tsqr_r

        if isinstance(data, ChunkedDataset):
            return self._fit_streaming(data, labels)
        A = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        a_mean = jnp.mean(A, axis=0)
        y_mean = jnp.mean(y, axis=0)
        d, k = A.shape[1], y.shape[1]
        aug = jnp.concatenate([A - a_mean, y - y_mean], axis=1)
        reg = self._reg_rows(d, k)
        if reg is not None:
            aug = jnp.concatenate([aug, reg], axis=0)
        W = self._solve_from_r(tsqr_r(aug), d)
        return LinearMapper(W, b=y_mean, feature_mean=a_mean)

    def _fit_streaming_checkpointed(self, data, labels: Dataset) -> LinearMapper:
        """The resumable out-of-core TSQR fit: sequential
        :class:`TsqrRState` fold (exactly the streaming recurrence, so
        restart-from-R is restart-from-the-math) with the column means
        and the chunk/row cursor persisted alongside the R factor. The
        √λ rows fold only at the end — they must never be inside a
        checkpointed prefix."""
        from ...faults import FitCheckpoint
        from ...linalg.accumulators import TsqrRState
        from ...linalg.bcd import stream_column_means
        from ...linalg.tsqr import _qr_fold
        from ...utils.timing import phase

        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        key = (
            f"tsqr|n={len(data)}|y={tuple(int(s) for s in y.shape)}"
            f"|lam={float(self.lam or 0.0)}"
        )
        ckpt = FitCheckpoint(self.checkpoint, key)
        loaded = ckpt.load()
        if loaded is not None:
            doc, start_chunk, offset = loaded
            a_mean = jnp.asarray(doc["a_mean"])
            y_mean = jnp.asarray(doc["y_mean"])
            state = doc["state"]
            logger.info(
                "fit checkpoint: resuming TSQR fold at chunk %d (row %d) "
                "from %s", start_chunk, offset, ckpt.path,
            )
        else:
            with phase("tsqr_ls.stream_center") as out:
                a_mean, n = stream_column_means(data.raw_chunks)
                if n != y.shape[0]:
                    raise ValueError(
                        f"chunked features have {n} rows, labels "
                        f"{y.shape[0]}"
                    )
                y_mean = jnp.mean(y, axis=0)
                out.append(y_mean)
            state = TsqrRState()
            start_chunk, offset = 0, 0
            # block 0's checkpoint carries the means: a fit killed during
            # the fold must not re-pay the centering pass on resume
            ckpt.save(self._ckpt_doc(a_mean, y_mean, state), 0, 0)
        d = int(a_mean.shape[0])
        k = int(y.shape[1])
        every = max(1, int(self.checkpoint_every))
        with phase("tsqr_ls.stream_solve") as out:
            i = start_chunk
            for chunk in data.raw_chunks(skip=start_chunk):
                chunk = jnp.asarray(chunk, dtype=jnp.float32)
                rows = int(chunk.shape[0])
                state.update(
                    jnp.concatenate(
                        [chunk - a_mean, y[offset : offset + rows] - y_mean],
                        axis=1,
                    )
                )
                offset += rows
                i += 1
                if i % every == 0:
                    ckpt.save(self._ckpt_doc(a_mean, y_mean, state), i, offset)
            if offset != y.shape[0]:
                raise ValueError(
                    f"chunked features have {offset} rows, labels "
                    f"{y.shape[0]}"
                )
            R = state.finalize()
            reg = self._reg_rows(d, k)
            if reg is not None:
                R = _qr_fold(R, reg)
            W = self._solve_from_r(R, d)
            out.append(W)
        ckpt.complete()
        return LinearMapper(W, b=y_mean, feature_mean=a_mean)

    @staticmethod
    def _ckpt_doc(a_mean, y_mean, state):
        import numpy as np

        return {
            "a_mean": np.asarray(a_mean),
            "y_mean": np.asarray(y_mean),
            "state": state.snapshot(),
        }

    def _fit_streaming(self, data, labels: Dataset) -> LinearMapper:
        """Means pass, then centered augmented chunks through the laned
        streaming TSQR; the √λ regularization rows ride as a final chunk
        (``qr([A; √λI])`` has the regularized Gram as RᵀR)."""
        from ...linalg.bcd import stream_column_means
        from ...linalg.tsqr import tsqr_r_streaming
        from ...utils.timing import phase

        if self.checkpoint:
            return self._fit_streaming_checkpointed(data, labels)

        y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        with phase("tsqr_ls.stream_center") as out:
            a_mean, n = stream_column_means(data.raw_chunks)
            if n != y.shape[0]:
                raise ValueError(
                    f"chunked features have {n} rows, labels {y.shape[0]}"
                )
            y_mean = jnp.mean(y, axis=0)
            out.append(y_mean)
        d = int(a_mean.shape[0])
        k = int(y.shape[1])
        reg = self._reg_rows(d, k)

        def augmented():
            offset = 0
            for chunk in data.raw_chunks():
                chunk = jnp.asarray(chunk, dtype=jnp.float32)
                rows = int(chunk.shape[0])
                yield jnp.concatenate(
                    [chunk - a_mean, y[offset : offset + rows] - y_mean],
                    axis=1,
                )
                offset += rows
            if reg is not None:
                yield reg

        with phase("tsqr_ls.stream_solve") as out:
            W = self._solve_from_r(tsqr_r_streaming(augmented), d)
            out.append(W)
        return LinearMapper(W, b=y_mean, feature_mean=a_mean)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        from ...linalg.tsqr import cost_signature

        return combine_cost(
            cost_signature(n, d, k, num_machines),
            cpu_weight, mem_weight, network_weight,
        )


class SparseLinearMapper(Transformer):
    """Apply a dense trained model to sparse input rows: xᵀ·W (+ b)
    (parity: SparseLinearMapper.scala:13-50).

    TPU path: ``SparseRows`` batches apply as an embedding-style gather
    (W[indices]·values, data/sparse.py) — no densification at any width.
    """

    def __init__(self, W, b=None):
        self.W = as_param(W)
        self.b = as_param(b)

    def apply_batch(self, data):
        from ...data.sparse import SparseRows

        data = Dataset.of(data)
        if isinstance(data.payload, SparseRows):
            out = data.payload.matmul(self.W)
            if self.b is not None:
                out = out + self.b
            return Dataset(out, batched=True)
        return data.map_batch(self.trace_batch)

    def trace_batch(self, X):
        out = jnp.asarray(X) @ self.W
        if self.b is not None:
            out = out + self.b
        return out

    def apply(self, x):
        from ...data.sparse import SparseRows

        sr = SparseRows.datum_from_pairs(x, self.W.shape[0])
        if sr is not None:
            x = sr
        if isinstance(x, SparseRows):
            out = x.matmul(self.W)
            out = out if self.b is None else out + self.b
            return out[0] if len(x) == 1 else out
        if hasattr(x, "nnz"):  # scipy sparse vector/matrix
            import numpy as np

            dense = jnp.asarray(np.asarray(x.todense()))
            if dense.ndim == 2 and dense.shape[0] > 1:
                return self.trace_batch(dense)  # r×d matrix → r×k batch
            x = dense.reshape(-1)
        return self.trace_batch(jnp.asarray(x)[None])[0]
