"""Distributed L-BFGS least squares.

Parity: nodes/learning/LBFGS.scala:14-281 (runLBFGS/CostFun/DenseLBFGSwithL2/
SparseLBFGSwithL2) + Gradient.scala:10-119. The reference computes
per-partition batched gradients, treeReduces them to the driver and drives
Breeze's LBFGS; here the ENTIRE optimization — gradients (per-shard GEMM +
psum over ICI for row-sharded data), two-loop recursion, line search, and
convergence test — is one compiled ``lax.while_loop`` program (see
:func:`minimize_lbfgs`).

Loss (CostFun, LBFGS.scala:69-123):
  f(W) = Σ ½‖AW − B‖² / n + ½·λ‖W‖²,  ∇f = Aᵀ(AW−B)/n + λW.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...parallel.mesh import shard_batch
from ...workflow.transformer import LabelEstimator
from .cost import CostModel
from .linear import LinearMapper


@jax.jit
def _ls_value_and_grad(W, A, B, lam):
    n = A.shape[0]
    axb = A @ W - B
    loss = 0.5 * jnp.sum(axb * axb) / n + 0.5 * lam * jnp.sum(W * W)
    grad = A.T @ axb / n + lam * W
    return loss, grad


def minimize_lbfgs(
    value_and_grad: Callable,
    w0,
    max_iterations: int = 100,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    vag_args: tuple = (),
):
    """L-BFGS with two-loop recursion + Armijo backtracking, as ONE
    compiled ``lax.while_loop`` program. ``value_and_grad(W) -> (f, g)``
    must be jax-traceable; it is inlined into the program, so every
    iteration — recursion, line search, convergence test — runs on
    device with ZERO host round trips. The first cut drove the loop from
    the host (the reference's shape: Breeze LBFGS on the driver,
    LBFGS.scala:69-123): through a tunneled transport, its 3-4 blocking
    scalar fetches per iteration put a ~0.5 s/iter floor under every
    solve regardless of problem size.

    The history lives in fixed (m, *W.shape) buffers rolled so the
    newest correction sits at index m−1; ``count`` masks unfilled (or
    memory-reset) entries. Semantics match the host loop: Armijo with
    c1=1e-4 and 20 halvings, non-descent directions reset the memory,
    line-search failure terminates, and convergence compares consecutive
    f values against ``convergence_tol``.
    """
    W0 = jnp.asarray(w0, dtype=jnp.float32)
    m = int(num_corrections)
    tol = jnp.float32(convergence_tol)

    # The data operands (vag_args) enter as JIT ARGUMENTS, never as
    # closures: a closed-over device array becomes an HLO constant, and
    # baking a GB-scale Gram/design matrix into the program meant
    # shipping it to the (tunneled) compile service on every trace —
    # observed as multi-minute "hangs" before the first iteration.
    def _run_body(st, vag):
        it, done, W, f, g, S, Y, count, prev_f = st

        # two-loop recursion over the masked circular history
        def bwd(i, qa):
            q, alphas = qa
            idx = m - 1 - i  # newest first
            valid = i < count
            s, y = S[idx], Y[idx]
            denom = jnp.vdot(y, s)
            rho = jnp.where(valid & (denom != 0), 1.0 / denom, 0.0)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            return q, alphas.at[idx].set(a)

        q, alphas = jax.lax.fori_loop(
            0, m, bwd, (g, jnp.zeros((m,), dtype=jnp.float32))
        )
        sy = jnp.vdot(S[m - 1], Y[m - 1])
        yy = jnp.vdot(Y[m - 1], Y[m - 1])
        gamma = jnp.where((count > 0) & (yy != 0), sy / yy, 1.0)
        q = gamma * q

        def fwd(i, q):
            valid = i >= (m - count)  # oldest first
            s, y = S[i], Y[i]
            denom = jnp.vdot(y, s)
            rho = jnp.where(valid & (denom != 0), 1.0 / denom, 0.0)
            b = rho * jnp.vdot(y, q)
            return q + (alphas[i] - b) * s

        direction = -jax.lax.fori_loop(0, m, fwd, q)
        gd = jnp.vdot(g, direction)
        # non-descent → steepest descent + memory reset
        reset = gd >= 0
        direction = jnp.where(reset, -g, direction)
        gd = jnp.where(reset, -jnp.vdot(g, g), gd)
        count = jnp.where(reset, 0, count)

        # Armijo backtracking, up to 20 halvings
        def ls_cond(ls):
            tries, _, ok, *_ = ls
            return (tries < 20) & ~ok

        def ls_body(ls):
            tries, step, ok, Wn, fn, gn = ls
            cand = W + step * direction
            cf, cg = vag(cand)
            good = cf <= f + 1e-4 * step * gd
            Wn = jnp.where(good, cand, Wn)
            fn = jnp.where(good, cf, fn)
            gn = jnp.where(good, cg, gn)
            return tries + 1, step * 0.5, ok | good, Wn, fn, gn

        _, _, ok, Wn, fn, gn = jax.lax.while_loop(
            ls_cond, ls_body,
            (jnp.int32(0), jnp.float32(1.0), jnp.bool_(False), W, f, g),
        )

        S2 = jnp.roll(S, -1, axis=0).at[m - 1].set(Wn - W)
        Y2 = jnp.roll(Y, -1, axis=0).at[m - 1].set(gn - g)
        count2 = jnp.minimum(count + 1, m)
        converged = jnp.abs(prev_f - fn) < tol * jnp.maximum(
            jnp.abs(fn), 1.0
        )
        done2 = ~ok | converged
        # line-search failure keeps the pre-step state
        W3 = jnp.where(ok, Wn, W)
        f3 = jnp.where(ok, fn, f)
        g3 = jnp.where(ok, gn, g)
        S3 = jnp.where(ok, S2, S)
        Y3 = jnp.where(ok, Y2, Y)
        c3 = jnp.where(ok, count2, count)
        return (it + 1, done2, W3, f3, g3, S3, Y3, c3, f3)

    @jax.jit
    def run(W, vag_args):
        def vag(w):
            f, g = value_and_grad(w, *vag_args)
            return jnp.asarray(f, dtype=jnp.float32), g

        def cond(st):
            it, done = st[0], st[1]
            return (it < max_iterations) & ~done

        f0, g0 = vag(W)
        S = jnp.zeros((m,) + W.shape, dtype=jnp.float32)
        Y = jnp.zeros_like(S)
        init = (
            jnp.int32(0), jnp.bool_(False), W, f0, g0, S, Y,
            jnp.int32(0), jnp.float32(jnp.inf),
        )
        return jax.lax.while_loop(
            cond, lambda st: _run_body(st, vag), init
        )[2]

    return run(W0, tuple(vag_args))


class DenseLBFGSwithL2(LabelEstimator, CostModel):
    """(parity: DenseLBFGSwithL2, LBFGS.scala:135-186)."""

    def __init__(self, convergence_tol: float = 1e-4,
                 num_iterations: int = 100, reg_param: float = 0.0,
                 num_corrections: int = 10):
        self.convergence_tol = convergence_tol
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.num_corrections = num_corrections

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = shard_batch(
            jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        )
        B = shard_batch(
            jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        )
        lam = jnp.float32(self.reg_param)
        W0 = jnp.zeros((A.shape[1], B.shape[1]), dtype=jnp.float32)
        W = minimize_lbfgs(
            _ls_value_and_grad,
            W0,
            max_iterations=self.num_iterations,
            num_corrections=self.num_corrections,
            convergence_tol=self.convergence_tol,
            vag_args=(A, B, lam),
        )
        return LinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        import math

        flops = n * d * k / num_machines
        bytes_scanned = n * d / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


def _streamed_gram(X, B):
    """G = AᵀA (d, d) and c = AᵀB, accumulated over dense row blocks:
    each block is one SMALL scatter (bounded padding) + one MXU GEMM,
    and is dropped before the next, so peak memory is G + one block."""
    d = X.shape[1]
    n, m = X.indices.shape
    # chunk rows by BOTH the scatter-update count (padding bound) and the
    # densified block's bytes (rows·d·4 — at d=16384 an update-count-only
    # bound allowed ~1.6 GB blocks, breaking the "G + one block" claim)
    block_budget_bytes = 256 << 20
    row_chunk = max(
        1,
        min((1 << 21) // max(m, 1), block_budget_bytes // max(4 * d, 1)),
    )
    G = jnp.zeros((d, d), dtype=jnp.float32)
    c = jnp.zeros((d,) + B.shape[1:], dtype=jnp.float32)
    for i in range(0, n, row_chunk):
        Ab = X.row_slice(i, min(i + row_chunk, n)).to_dense()
        G = G + jnp.matmul(Ab.T, Ab, precision="high")
        c = c + jnp.matmul(Ab.T, B[i : i + row_chunk], precision="high")
    return G, c


class SparseLBFGSwithL2(DenseLBFGSwithL2):
    """Sparse-input variant (parity: SparseLBFGSwithL2, LBFGS.scala:208).

    XLA has no dynamic sparsity, so sparse rows arrive as a padded-COO
    ``SparseRows`` batch. Two execution strategies, chosen by memory:

    * **precomputed-Gram quadratic** (default whenever the d×d Gram fits
      ``gram_budget_bytes``): the least-squares objective is a fixed
      quadratic, f(W) = (½WᵀGW − cᵀW + ½‖B‖²)/n + ½λ‖W‖² with G = AᵀA,
      so G and c = AᵀB are accumulated ONCE by streaming dense row
      blocks through the MXU (each block scattered small — XLA's TPU
      scatter pads its operands ~66×, so one huge scatter OOMs — then
      immediately contracted and discarded), after which every L-BFGS
      iteration is one d×d GEMV touching no data. TPU-first twice over:
      the MXU streams dense blocks 50-100× faster than the fine-grained
      gather path at text densities (~20M random elements/s measured on
      a v5e), and the iteration cost becomes data-size independent.
    * **gather/scatter** (the fallback, SURVEY §7's original decision):
      gather-matmul (A·W) + scatter-add (Aᵀ·residual), used when d² is
      too large for the Gram (``gram_budget_bytes=0`` forces it).

    Both run the same :func:`minimize_lbfgs` on the same objective —
    the strategies produce the same iterates up to f32 rounding
    (asserted by the strategy-agreement test). scipy.sparse inputs are
    converted to SparseRows first. Returns a SparseLinearMapper so the
    fitted model applies sparsely either way.
    """

    sparse_overhead = 10.0

    def __init__(self, *args, gram_budget_bytes: float = 2e9, **kwargs):
        super().__init__(*args, **kwargs)
        self.gram_budget_bytes = gram_budget_bytes

    def fit(self, data: Dataset, labels: Dataset):
        from ...data.sparse import SparseRows
        from .linear import SparseLinearMapper

        data = Dataset.of(data)
        X = None
        if isinstance(data.payload, SparseRows):
            X = data.payload
        elif not data.is_batched:
            import scipy.sparse as sp

            items = data.collect()
            if items and sp.issparse(items[0]):
                X = SparseRows.from_scipy(sp.vstack(items))
            else:
                return super().fit(Dataset.of(np.asarray(items)), labels)
        if X is None:
            return super().fit(data, labels)

        B = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        lam = jnp.float32(self.reg_param)
        n = B.shape[0]
        d = X.shape[1]

        # k=1 (binary) runs on 1-D vectors: XLA pads a minor dim of 1 to
        # 128, so every (n, 1) residual/carry in the compiled loop would
        # cost 128x its size — measured 10.8 GB of pure padding (an HBM
        # OOM) at the Amazon shape. Squeeze in, unsqueeze out.
        squeeze = B.ndim == 2 and B.shape[1] == 1
        if squeeze:
            B = B[:, 0]

        if 4.0 * d * d <= self.gram_budget_bytes:
            G, c = _streamed_gram(X, B)
            e = 0.5 * jnp.sum(B * B)

            def vag(W, G, c, e, lam):
                GW = jnp.matmul(G, W, precision="high")
                loss = (0.5 * jnp.vdot(W, GW) - jnp.vdot(c, W) + e) / n \
                    + 0.5 * lam * jnp.sum(W * W)
                grad = (GW - c) / n + lam * W
                return loss, grad

            vag_args = (G, c, e, lam)
        else:
            from ...data.sparse import SparseRows as _SR

            def vag(W, idx, vals, B, lam):
                Xa = _SR(idx, vals, d)
                W2 = W[:, None] if squeeze else W
                axb = Xa.matmul(W2) - (B[:, None] if squeeze else B)
                loss = 0.5 * jnp.sum(axb * axb) / n \
                    + 0.5 * lam * jnp.sum(W * W)
                grad = Xa.rmatmul(axb) / n + lam * W2
                return loss, (grad[:, 0] if squeeze else grad)

            vag_args = (X.indices, X.values, B, lam)

        W0 = jnp.zeros((d,) if squeeze else (d, B.shape[1]),
                       dtype=jnp.float32)
        W = minimize_lbfgs(
            vag,
            W0,
            max_iterations=self.num_iterations,
            num_corrections=self.num_corrections,
            convergence_tol=self.convergence_tol,
            vag_args=vag_args,
        )
        if squeeze:
            W = W[:, None]
        return SparseLinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        import math

        flops = n * sparsity * d * k / num_machines
        bytes_scanned = n * d * sparsity / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            self.sparse_overhead
            * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


class LocalLeastSquaresEstimator(LabelEstimator):
    """Dual-form OLS for d ≫ n: solve in the n×n Gram space
    (parity: LocalLeastSquaresEstimator.scala:16-61)."""

    def __init__(self, lam: float):
        self.lam = lam

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        B = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        a_mean = jnp.mean(A, axis=0)
        b_mean = jnp.mean(B, axis=0)
        Az = A - a_mean
        Bz = B - b_mean
        AAt = Az @ Az.T
        n = AAt.shape[0]
        inner = jnp.linalg.solve(
            AAt + self.lam * jnp.eye(n, dtype=A.dtype), Bz
        )
        W = Az.T @ inner
        return LinearMapper(W, b=b_mean, feature_mean=a_mean)
