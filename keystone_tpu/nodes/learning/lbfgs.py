"""Distributed L-BFGS least squares.

Parity: nodes/learning/LBFGS.scala:14-281 (runLBFGS/CostFun/DenseLBFGSwithL2/
SparseLBFGSwithL2) + Gradient.scala:10-119. The reference computes
per-partition batched gradients, treeReduces them to the driver and drives
Breeze's LBFGS; here the full gradient is one jit program (per-shard GEMM +
psum over ICI for row-sharded data) and the L-BFGS two-loop recursion +
backtracking line search run host-side on device arrays.

Loss (CostFun, LBFGS.scala:69-123):
  f(W) = Σ ½‖AW − B‖² / n + ½·λ‖W‖²,  ∇f = Aᵀ(AW−B)/n + λW.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...parallel.mesh import shard_batch
from ...workflow.transformer import LabelEstimator
from .cost import CostModel
from .linear import LinearMapper


@jax.jit
def _ls_value_and_grad(W, A, B, lam):
    n = A.shape[0]
    axb = A @ W - B
    loss = 0.5 * jnp.sum(axb * axb) / n + 0.5 * lam * jnp.sum(W * W)
    grad = A.T @ axb / n + lam * W
    return loss, grad


def minimize_lbfgs(
    value_and_grad: Callable,
    w0,
    max_iterations: int = 100,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
):
    """Standard L-BFGS with two-loop recursion + Armijo backtracking.
    ``value_and_grad(W) -> (f, g)`` must be a jit-compiled device function.
    Returns the final weights."""
    W = jnp.asarray(w0)
    f, g = value_and_grad(W)
    s_hist: List = []
    y_hist: List = []
    prev_f = None
    for _ in range(max_iterations):
        # two-loop recursion
        q = g
        alphas = []
        for s, y in reversed(list(zip(s_hist, y_hist))):
            rho = 1.0 / jnp.vdot(y, s)
            a = rho * jnp.vdot(s, q)
            q = q - a * y
            alphas.append((a, rho))
        if s_hist:
            s, y = s_hist[-1], y_hist[-1]
            gamma = jnp.vdot(s, y) / jnp.vdot(y, y)
            q = gamma * q
        for (a, rho), (s, y) in zip(reversed(alphas), zip(s_hist, y_hist)):
            b = rho * jnp.vdot(y, q)
            q = q + (a - b) * s
        direction = -q

        # backtracking line search (Armijo)
        step = 1.0
        gd = float(jnp.vdot(g, direction))
        if gd >= 0:  # not a descent direction — reset memory
            s_hist.clear()
            y_hist.clear()
            direction = -g
            gd = float(jnp.vdot(g, direction))
        f_val = float(f)
        new_W, new_f, new_g = None, None, None
        for _ in range(20):
            cand = W + step * direction
            cf, cg = value_and_grad(cand)
            if float(cf) <= f_val + 1e-4 * step * gd:
                new_W, new_f, new_g = cand, cf, cg
                break
            step *= 0.5
        if new_W is None:
            break
        s_hist.append(new_W - W)
        y_hist.append(new_g - g)
        if len(s_hist) > num_corrections:
            s_hist.pop(0)
            y_hist.pop(0)
        W, f, g = new_W, new_f, new_g
        if prev_f is not None and abs(prev_f - float(f)) < convergence_tol * max(
            abs(float(f)), 1.0
        ):
            break
        prev_f = float(f)
    return W


class DenseLBFGSwithL2(LabelEstimator, CostModel):
    """(parity: DenseLBFGSwithL2, LBFGS.scala:135-186)."""

    def __init__(self, convergence_tol: float = 1e-4,
                 num_iterations: int = 100, reg_param: float = 0.0,
                 num_corrections: int = 10):
        self.convergence_tol = convergence_tol
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.num_corrections = num_corrections

    @property
    def weight(self) -> int:
        return self.num_iterations + 1

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = shard_batch(
            jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        )
        B = shard_batch(
            jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        )
        lam = jnp.float32(self.reg_param)
        W0 = jnp.zeros((A.shape[1], B.shape[1]), dtype=jnp.float32)
        W = minimize_lbfgs(
            lambda w: _ls_value_and_grad(w, A, B, lam),
            W0,
            max_iterations=self.num_iterations,
            num_corrections=self.num_corrections,
            convergence_tol=self.convergence_tol,
        )
        return LinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        import math

        flops = n * d * k / num_machines
        bytes_scanned = n * d / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


class SparseLBFGSwithL2(DenseLBFGSwithL2):
    """Sparse-input variant (parity: SparseLBFGSwithL2, LBFGS.scala:208).

    XLA has no dynamic sparsity, so sparse rows arrive as a padded-COO
    ``SparseRows`` batch and the least-squares gradient runs as
    gather-matmul (A·W) + scatter-add (Aᵀ·residual) — never densified
    (the SURVEY §7 decision). scipy.sparse inputs are converted to
    SparseRows first. Returns a SparseLinearMapper so the fitted model also
    applies sparsely.
    """

    sparse_overhead = 10.0

    def fit(self, data: Dataset, labels: Dataset):
        from ...data.sparse import SparseRows
        from .linear import SparseLinearMapper

        data = Dataset.of(data)
        X = None
        if isinstance(data.payload, SparseRows):
            X = data.payload
        elif not data.is_batched:
            import scipy.sparse as sp

            items = data.collect()
            if items and sp.issparse(items[0]):
                X = SparseRows.from_scipy(sp.vstack(items))
            else:
                return super().fit(Dataset.of(np.asarray(items)), labels)
        if X is None:
            return super().fit(data, labels)

        B = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        lam = jnp.float32(self.reg_param)
        n = B.shape[0]

        @jax.jit
        def vag(W):
            axb = X.matmul(W) - B
            loss = 0.5 * jnp.sum(axb * axb) / n + 0.5 * lam * jnp.sum(W * W)
            grad = X.rmatmul(axb) / n + lam * W
            return loss, grad

        W0 = jnp.zeros((X.shape[1], B.shape[1]), dtype=jnp.float32)
        W = minimize_lbfgs(
            vag,
            W0,
            max_iterations=self.num_iterations,
            num_corrections=self.num_corrections,
            convergence_tol=self.convergence_tol,
        )
        return SparseLinearMapper(W)

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        import math

        flops = n * sparsity * d * k / num_machines
        bytes_scanned = n * d * sparsity / num_machines
        network = 2.0 * d * k * math.log2(max(num_machines, 2))
        return self.num_iterations * (
            self.sparse_overhead
            * max(cpu_weight * flops, mem_weight * bytes_scanned)
            + network_weight * network
        )


class LocalLeastSquaresEstimator(LabelEstimator):
    """Dual-form OLS for d ≫ n: solve in the n×n Gram space
    (parity: LocalLeastSquaresEstimator.scala:16-61)."""

    def __init__(self, lam: float):
        self.lam = lam

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        A = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        B = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        a_mean = jnp.mean(A, axis=0)
        b_mean = jnp.mean(B, axis=0)
        Az = A - a_mean
        Bz = B - b_mean
        AAt = Az @ Az.T
        n = AAt.shape[0]
        inner = jnp.linalg.solve(
            AAt + self.lam * jnp.eye(n, dtype=A.dtype), Bz
        )
        W = Az.T @ inner
        return LinearMapper(W, b=b_mean, feature_mean=a_mean)
