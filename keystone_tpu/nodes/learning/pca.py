"""PCA family: local SVD, distributed TSQR, randomized sketch, and the
cost-model chooser.

Parity: nodes/learning/PCA.scala:19,38,118-160,163-226 (PCATransformer,
BatchPCATransformer, ColumnPCAEstimator, PCAEstimator),
DistributedPCA.scala:20 (TSQR-based), ApproximatePCA.scala:22,58
(Halko/Martinsson/Tropp randomized range finder).

"Column" estimators treat each item — a (d, n_desc) descriptor matrix — as
n_desc separate d-vectors, matching the reference's matrixToColArray
flattening.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...linalg.tsqr import tsqr_r
from ...parallel.mesh import default_mesh
from ...workflow.node_optimization import Optimizable
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param
from .cost import (
    CostModel,
    DEFAULT_CPU_WEIGHT,
    DEFAULT_MEM_WEIGHT,
    DEFAULT_NETWORK_WEIGHT,
)


def enforce_matlab_sign_convention(pca):
    """Largest-|coefficient| element of each column gets a positive sign
    (parity: PCAEstimator.enforceMatlabPCASignConvention, PCA.scala:228-247)."""
    col_max = jnp.max(pca, axis=0)
    abs_col_max = jnp.max(jnp.abs(pca), axis=0)
    signs = jnp.where(col_max == abs_col_max, 1.0, -1.0)
    return pca * signs


class PCATransformer(Transformer):
    """x → pcaMatᵀ x for d-vectors (parity: PCATransformer, PCA.scala:19-30).
    ``pca_mat`` is (d, dims)."""

    def __init__(self, pca_mat):
        self.pca_mat = as_param(pca_mat)

    def trace_batch(self, X):
        return X @ self.pca_mat


class BatchPCATransformer(Transformer):
    """Per-item descriptor matrices (d, n_desc) → (dims, n_desc)
    (parity: BatchPCATransformer, PCA.scala:38-44)."""

    def __init__(self, pca_mat):
        self.pca_mat = as_param(pca_mat)

    def trace_batch(self, X):
        # X: (n, d, n_desc) → (n, dims, n_desc)
        return jnp.einsum("dk,ndm->nkm", self.pca_mat, X)

    def apply(self, x):
        return self.pca_mat.T @ jnp.asarray(x)


@jax.jit
def _pca_svd(X):
    means = jnp.mean(X, axis=0)
    _, _, vt = jnp.linalg.svd(X - means, full_matrices=False)
    return enforce_matlab_sign_convention(vt.T)


@jax.jit
def _pca_gram_eigh(X):
    """PCA directions via the d×d covariance eigendecomposition.

    XLA has no native tall-skinny SVD — jnp.linalg.svd of a 200k×128
    sample matrix measures ~12 s on a v5e, dominating the whole ImageNet
    PCA phase. For n ≫ d the right singular vectors are the eigenvectors
    of XᵀX: one MXU GEMM (precision=high, so the squared-condition worry
    stays below f32 noise for featurizer-scale conditioning) plus an eigh
    of a d×d matrix — milliseconds. The reference's own local path is f32
    sgesvd (PCA.scala:192-206); agreement is pinned by the PCA oracle
    tests."""
    means = jnp.mean(X, axis=0)
    Xc = X - means
    G = jnp.matmul(Xc.T, Xc, precision="high")
    _, vecs = jnp.linalg.eigh(G)  # ascending eigenvalues
    v = vecs[:, ::-1]  # descending, like svd's vt ordering
    return enforce_matlab_sign_convention(v)


def _pca_directions(X):
    """svd for small samples, Gram-eigh for tall ones (n ≥ 8·d)."""
    n, d = X.shape
    if n >= 8 * d:
        return _pca_gram_eigh(X)
    return _pca_svd(X)


class PCAEstimator(Estimator, CostModel):
    """Local SVD PCA over collected samples (parity: PCAEstimator,
    PCA.scala:163-226; the direct sgesvd call becomes jnp.linalg.svd in f32)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        return PCATransformer(self.compute_pca(X))

    def compute_pca(self, X):
        return _pca_directions(X)[:, : self.dims]

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        flops = n * d * d
        return max(cpu_weight * flops, mem_weight * n * d) \
            + network_weight * n * d


class DistributedPCAEstimator(Estimator, CostModel):
    """TSQR-based PCA: R factor over the mesh, then a d×d SVD of R
    (parity: DistributedPCAEstimator, DistributedPCA.scala:20-74; the
    per-partition QR + tree reduction becomes linalg.tsqr_r over ICI)."""

    def __init__(self, dims: int):
        self.dims = dims

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        means = jnp.mean(X, axis=0)
        R = tsqr_r(X - means, mesh=default_mesh())
        _, _, vt = jnp.linalg.svd(R, full_matrices=False)
        pca = enforce_matlab_sign_convention(vt.T)
        return PCATransformer(pca[:, : self.dims])

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        import math

        log2m = math.log2(max(num_machines, 2))
        flops = n * d * d / num_machines + d * d * d * log2m
        return max(cpu_weight * flops, mem_weight * n * d) \
            + network_weight * d * d * log2m


class ApproximatePCAEstimator(Estimator):
    """Randomized sketch PCA, HMT 2011 algorithms 4.4 + 5.1
    (parity: ApproximatePCAEstimator, ApproximatePCA.scala:22-105)."""

    def __init__(self, dims: int, q: int = 10, p: int = 5, seed: int = 0):
        self.dims = dims
        self.q = q
        self.p = p
        self.seed = seed

    def fit(self, data: Dataset) -> PCATransformer:
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        return PCATransformer(self._approximate_pca(X))

    def _approximate_pca(self, A):
        k, p, q = self.dims, self.p, self.q
        n, d = A.shape
        key = jax.random.PRNGKey(self.seed)
        omega = jax.random.normal(key, (d, k + p), dtype=A.dtype)
        means = jnp.mean(A, axis=0)
        A = A - means
        Q, _ = jnp.linalg.qr(A @ omega)
        for _ in range(q):
            Qh, _ = jnp.linalg.qr(A.T @ Q)
            Q, _ = jnp.linalg.qr(A @ Qh)
        B = Q.T @ A
        _, _, vt = jnp.linalg.svd(B, full_matrices=False)
        pca = enforce_matlab_sign_convention(vt.T)
        return pca[:, :k]


class _ColumnFit:
    """Mixin: flatten per-item (d, n_desc) matrices into sample rows."""

    @staticmethod
    def _collect_columns(data: Dataset):
        data = Dataset.of(data)
        if data.is_batched:
            X = jnp.asarray(data.to_array())
            # (n, d, m) → (n·m, d)
            return jnp.transpose(X, (0, 2, 1)).reshape(-1, X.shape[1])
        cols = [np.asarray(item).T for item in data]
        return jnp.asarray(np.concatenate(cols, axis=0), dtype=jnp.float32)


class LocalColumnPCAEstimator(Estimator, CostModel, _ColumnFit):
    """(parity: LocalColumnPCAEstimator, PCA.scala:52-73)."""

    def __init__(self, dims: int):
        self.dims = dims
        self._est = PCAEstimator(dims)

    def fit(self, data: Dataset) -> BatchPCATransformer:
        rows = self._collect_columns(data)
        return BatchPCATransformer(self._est.compute_pca(rows))

    def cost(self, *a):
        return self._est.cost(*a)


class DistributedColumnPCAEstimator(Estimator, CostModel, _ColumnFit):
    """(parity: DistributedColumnPCAEstimator, PCA.scala:81-103)."""

    def __init__(self, dims: int):
        self.dims = dims
        self._est = DistributedPCAEstimator(dims)

    def fit(self, data: Dataset) -> BatchPCATransformer:
        rows = self._collect_columns(data)
        t = self._est.fit(Dataset.of(rows))
        return BatchPCATransformer(t.pca_mat)

    def cost(self, *a):
        return self._est.cost(*a)


class ColumnPCAEstimator(Estimator, _ColumnFit, Optimizable):
    """Cost-model chooser between local and distributed column PCA
    (parity: ColumnPCAEstimator, PCA.scala:105-160). Falls back to the local
    estimator when no sample statistics are available. Participates in
    graph-level NodeOptimizationRule via ``sample_optimize``
    (parity: OptimizableNodes.scala:12-25)."""

    def __init__(
        self,
        dims: int,
        num_machines: Optional[int] = None,
        cpu_weight: float = DEFAULT_CPU_WEIGHT,
        mem_weight: float = DEFAULT_MEM_WEIGHT,
        network_weight: float = DEFAULT_NETWORK_WEIGHT,
    ):
        self.dims = dims
        self.num_machines = num_machines
        self.cpu_weight = cpu_weight
        self.mem_weight = mem_weight
        self.network_weight = network_weight
        self.local = LocalColumnPCAEstimator(dims)
        self.distributed = DistributedColumnPCAEstimator(dims)

    def sample_optimize(self, samples, num_items: int) -> Estimator:
        return self.optimize(samples[0], total_items=num_items)

    def optimize(self, sample: Dataset,
                 total_items: Optional[int] = None) -> Estimator:
        sample = Dataset.of(sample)
        # shapes only — no device→host materialization of the descriptors
        if sample.is_batched:
            shape = jax.tree_util.tree_leaves(sample.payload)[0].shape
            d, n = shape[1], shape[0] * shape[2]
            n_sample_items = shape[0]
        else:
            items = sample.payload
            d = items[0].shape[0]
            n = sum(item.shape[1] for item in items)
            n_sample_items = len(items)
        if total_items is not None and n_sample_items:
            # scale descriptor-column count from the sample to the full set
            n = int(n * total_items / n_sample_items)
        machines = self.num_machines or default_mesh().size
        args = (n, d, self.dims, 1.0, machines,
                self.cpu_weight, self.mem_weight, self.network_weight)
        if self.local.cost(*args) <= self.distributed.cost(*args):
            return self.local
        return self.distributed

    def fit(self, data: Dataset) -> BatchPCATransformer:
        return self.optimize(data).fit(data)
