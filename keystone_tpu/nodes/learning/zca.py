"""ZCA whitening (parity: nodes/learning/ZCAWhitener.scala:12,30).

The reference centers the sample matrix, takes a float32 SVD via a direct
LAPACK ``sgesvd`` call, and builds W = Vᵀ diag((σ²/(n−1) + ε)^−½) V. Here the
same algebra runs on-device through ``jnp.linalg.svd`` — f32 end to end, like
the reference's deliberate float path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param


class ZCAWhitener(Transformer):
    """x → (x − means) · W (parity: ZCAWhitener.scala:12-18)."""

    def __init__(self, whitener, means):
        self.whitener = as_param(whitener)
        self.means = as_param(means)

    def trace_batch(self, X):
        return (X - self.means) @ self.whitener

    # alias used by Convolver.build and host-side callers
    def transform(self, X):
        return (jnp.asarray(X) - self.means) @ self.whitener


@jax.jit
def _fit_zca(X, eps):
    means = jnp.mean(X, axis=0)
    Xc = (X - means).astype(jnp.float32)
    n = X.shape[0]
    _, s, vt = jnp.linalg.svd(Xc, full_matrices=False)
    scale = (s * s / (n - 1.0) + eps) ** -0.5
    W = vt.T @ (scale[:, None] * vt)
    return W, means


class ZCAWhitenerEstimator(Estimator):
    """Fit the whitening rotation from a sample matrix
    (parity: ZCAWhitener.scala:30-73)."""

    def __init__(self, eps: float = 0.1):
        self.eps = eps

    def fit(self, data: Dataset) -> ZCAWhitener:
        return self.fit_single(Dataset.of(data).to_array())

    def fit_single(self, X) -> ZCAWhitener:
        W, means = _fit_zca(
            jnp.asarray(X, dtype=jnp.float32), jnp.float32(self.eps)
        )
        return ZCAWhitener(W, means)
