"""Class-weighted block-coordinate least squares (the ImageNet FV solver).

Parity: nodes/learning/BlockWeightedLeastSquares.scala:36,86-321 and
PerClassWeightedLeastSquares.scala:31,63. Objective: per class c, ridge
regression under the mixture weighting that gives class-c examples total
weight ``w`` and the population weight ``1−w`` (Appendix of the KeystoneML
paper; jointXTX/jointXTR algebra preserved exactly).

Mesh-native mapping of the reference's choreography (SURVEY §2.7): the
"one class per partition" HashPartitioner trick becomes segment reductions
over the class-index vector — per-class means via one segment_sum, per-class
Grams via a chunked masked einsum — and the per-class executor-local solves
become a vmapped batched Cholesky. No resharding of the data ever happens.
"""

from __future__ import annotations

from functools import partial, wraps
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...linalg.row_matrix import solve_spd
from ...parallel.mesh import shard_classes
from ...utils.jit import nestable_jit
from ...workflow.node_optimization import Optimizable
from ...workflow.transformer import LabelEstimator
from .cost import AutoSolverFrontDoor, CostModel, combine_cost
from .linear import BlockLinearMapper


def _f32_true(fn):
    """Run a weighted-family solve with f32-true matmuls.

    The mixture normal matrices are regularized with λ as small as the
    reference's ImageNet 6e-5 (ImageNetSiftLcsFV.scala:146) — BELOW the
    noise floor of the TPU's default-bf16 matmul lowering (~1e-3·‖XᵀX‖).
    At default precision the λ-decided near-null directions of jointXTX
    come out noise-dominated and held-out predictions from BOTH the
    dense and dual paths are near-random (measured: 9% argmax agreement
    between two correct algorithms; 97% under f32-true). The reference
    solves in f64 Breeze; f32-true is the TPU analogue, and these GEMMs
    are a negligible share of pipeline compute."""

    @wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision("highest"):
            return fn(*args, **kwargs)

    return wrapped


@partial(jax.jit, static_argnames=("k",))
def _class_stats(A, y_idx, k):
    """Per-class counts (k,), means (k, d) via segment reductions."""
    onehot = jax.nn.one_hot(y_idx, k, dtype=A.dtype)  # (n, k)
    counts = jnp.sum(onehot, axis=0)
    sums = onehot.T @ A
    means = sums / jnp.maximum(counts, 1.0)[:, None]
    return counts, means


@partial(jax.jit, static_argnames=())
def _chunk_grams(A, mask_chunk):
    """Masked Grams for a chunk of classes: (C, d, d)."""
    return jnp.einsum("nd,nc,ne->cde", A, mask_chunk, A)


# batched per-class ridge solve — shared with the streaming solver body,
# which now lives at the linalg layer (K-lane mesh distribution included)
from ...linalg.weighted import _batched_solve, solve_weighted_streaming


@nestable_jit
def _dual_solve_chunk(Q, R, dvec, pm_proj, mu_proj, s3, rhs, lam):
    """Per-class solves in the SAMPLE-SPAN basis, vmapped over a class
    chunk — the few-shot/many-class regime (n ≪ d, e.g. the reference's
    1000-class ImageNet config) where the dense path factors a d×d
    system per class although every class covariance is rank ≤ n.

    With Aᵀ = QR (reduced QR, computed once per feature block) the
    per-class normal matrix lives entirely in span(Q):
        jointXTX_c + λI = λI + Q H_c Qᵀ,
        H_c = R diag(d_c) Rᵀ + Σⱼ s3ⱼ (Qᵀpⱼ)(Qᵀpⱼ)ᵀ,
    with d_c[i] = (1−w)/n + w·1[i∈c]/n_c (the diagonal of
    :func:`_class_sample_weights`) and pⱼ ∈ {pm, μ_c, μ_c−pm} — all in
    span(Aᵀ), so the projection is exact. The full inverse is
        x = Q (λI + H_c)⁻¹ Qᵀr + (r − QQᵀr)/λ,
    but the ⊥ term is IDENTICALLY ZERO here and must not be computed:
    rhs ∈ span(Q) by construction (jointXTR ∈ col(Aᵀ) and every Ws
    update is a previous output of this function, i.e. ∈ span(Q), by
    induction from Ws = 0) — so (r − QQᵀr) is pure rounding noise, and
    dividing that noise by the ImageNet-scale λ=6e-5 produced weights
    whose dominant component was noise orthogonal to the training rows:
    invisible on train predictions, near-random held-out (caught by the
    held-out assertion in the dual-vs-per-class test). The same 1/λ
    amplification killed the plain Woodbury form of this solve. Hence:
        x = Q (λI + H_c)⁻¹ Qᵀr,
    O(n³) per class instead of O(d³), with no 1/λ-amplified term at all.

    Q (d, n); R (n, n); dvec (C, n); pm_proj (n,) = Qᵀpm (projected ONCE
    per block — not per class); mu_proj (C, n) = μ_c Q; s3 (3,);
    rhs (C, d).
    """
    n = R.shape[0]
    eye = jnp.eye(n, dtype=R.dtype)

    # NOTE: no explicit precision= on any product here — an explicit
    # precision="high" would OVERRIDE the _f32_true("highest") context
    # the weighted family runs under (explicit args beat the context),
    # silently reintroducing bf16_3x rounding next to the λ floor.
    def one(dv, mu_p, r):
        Pp = jnp.stack([pm_proj, mu_p, mu_p - pm_proj])   # (3, n)
        H = jnp.matmul(R * dv[None, :], R.T)
        H = H + jnp.einsum("j,jm,jo->mo", s3, Pp, Pp)
        rp = jnp.matmul(Q.T, r)                           # (n,)
        z = jnp.linalg.solve(H + lam * eye, rp)
        return jnp.matmul(Q, z)

    return jax.vmap(one)(dvec, mu_proj, rhs)


class BlockWeightedLeastSquaresEstimator(LabelEstimator, CostModel):
    """(parity: BlockWeightedLeastSquaresEstimator,
    BlockWeightedLeastSquares.scala:36-84)."""

    supports_streaming = True

    def __init__(self, block_size: int, num_iter: int, lam: float,
                 mixture_weight: float,
                 num_features: Optional[int] = None,
                 class_chunk: int = 8,
                 snapshot: bool = False):
        if snapshot:
            from ...linalg.accumulators import NotAbsorbable

            raise NotAbsorbable(
                "the block-weighted BCD solver has no snapshot-able "
                "state: its iterates depend on block visitation order, "
                "so appended chunks cannot be folded in after the fact "
                "— fit with PerClassWeightedLeastSquaresEstimator("
                "snapshot=True) for an absorbable weighted model"
            )
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features
        self.class_chunk = class_chunk

    # passes over the data per iteration (parity: WeightedNode weight)
    @property
    def weight(self) -> int:
        return 3 * self.num_iter + 1

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        from ...linalg.weighted import cost_signature

        return combine_cost(
            cost_signature(
                n, self.num_features or d, k, self.block_size,
                self.num_iter, num_machines, self.class_chunk,
            ),
            cpu_weight, mem_weight, network_weight,
        )

    def fit(self, data, labels: Dataset) -> BlockLinearMapper:
        from ...data.chunked import ChunkedDataset

        if isinstance(data, ChunkedDataset):
            Y = jnp.asarray(
                Dataset.of(labels).to_array(), dtype=jnp.float32
            )
            # RDD-cache semantics (one scan): a chunked featurized set that
            # fits the HBM budget materializes and solves in-memory; anything
            # bigger streams with per-chunk Gram accumulation. Either way the
            # upstream featurizer chain ran chunk-by-chunk — the full-size
            # featurization intermediates never coexist in HBM.
            cached = data.cache()
            if not isinstance(cached, ChunkedDataset):
                X = jnp.asarray(cached.to_array(), dtype=jnp.float32)
                d = self.num_features or X.shape[-1]
                blocks = [
                    X[..., i : min(i + self.block_size, d)]
                    for i in range(0, d, self.block_size)
                ]
                return self.train_with_l2(blocks, Y)
            return self.train_streaming(cached, Y)
        if isinstance(data, Dataset) and isinstance(data.payload, (list, tuple)):
            blocks = [jnp.asarray(p, dtype=jnp.float32) for p in data.payload]
        elif isinstance(data, (list, tuple)):
            blocks = [
                jnp.asarray(Dataset.of(d).to_array(), dtype=jnp.float32)
                for d in data
            ]
        else:
            X = jnp.asarray(
                Dataset.of(data).to_array(), dtype=jnp.float32
            )
            d = self.num_features or X.shape[-1]
            blocks = [
                X[..., i : min(i + self.block_size, d)]
                for i in range(0, d, self.block_size)
            ]
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        return self.train_with_l2(blocks, Y)

    @_f32_true
    def train_with_l2(self, blocks: Sequence, Y) -> BlockLinearMapper:
        """(parity: trainWithL2, BlockWeightedLeastSquares.scala:102-321)."""
        w = self.mixture_weight
        lam = self.lam
        n, k = Y.shape
        y_idx = jnp.argmax(Y, axis=1)

        counts = jnp.sum(
            jax.nn.one_hot(y_idx, k, dtype=jnp.float32), axis=0
        )
        # jointLabelMean_c = 2w + 2(1−w)·n_c/n − 1  (ref :148-155)
        joint_label_mean = 2 * w + 2 * (1 - w) * counts / n - 1.0
        R = Y - joint_label_mean

        onehot = jax.nn.one_hot(y_idx, k, dtype=jnp.float32)  # (n, k)
        Ws: List[jnp.ndarray] = [
            jnp.zeros((b.shape[1], k), dtype=jnp.float32) for b in blocks
        ]
        stats = [None] * len(blocks)  # (pop_cov, pop_mean, joint_means)

        from ...utils.timing import phase

        for _ in range(self.num_iter):
            for j, A in enumerate(blocks):
                d = A.shape[1]
                # Strategy: dense primal (d×d per class) when classes are
                # well-populated; dual/Woodbury in sample space when
                # n + 3 < d — the few-shot/many-class regime where the
                # dense path would factor k rank-deficient d×d systems.
                # The cached per-block Gram is pop_cov (d×d) for the
                # dense path, AAᵀ (n×n) for the dual path — never both.
                use_dual = lam > 0 and (n + 3) < d
                if stats[j] is None:
                    pop_mean = jnp.mean(A, axis=0)
                    _, class_means = _class_stats(A, y_idx, k)
                    joint_means = w * class_means + (1 - w) * pop_mean
                    if use_dual:
                        gram = tuple(jnp.linalg.qr(A.T))  # (Q (d,n), R (n,n))
                    else:
                        gram = (A.T @ A) / n - jnp.outer(pop_mean, pop_mean)
                    stats[j] = (gram, pop_mean, joint_means)
                gram_j, pop_mean, joint_means = stats[j]
                pop_cov = gram_j  # dense path; dual path unpacks (Q, R)
                pop_xtr = (A.T @ R) / n  # (d, k)
                residual_mean = jnp.mean(R, axis=0)  # (k,)

                _, class_means = _class_stats(A, y_idx, k)
                # per-class residual-column stats: r_c over class-c rows
                class_r_sum = jnp.sum(onehot * R, axis=0)  # Σ_{i∈c} R[i, c]
                class_r_mean = class_r_sum / jnp.maximum(counts, 1.0)
                class_xtr = (A.T @ (onehot * R)) / jnp.maximum(
                    counts, 1.0
                )  # (d, k): A_cᵀ r_c / n_c per class

                if use_dual:
                    s3 = jnp.asarray(
                        [-(1 - w), -w, w * (1 - w)], dtype=jnp.float32
                    )
                    # constant per block — projected once, not per chunk
                    pm_proj = jnp.matmul(pop_mean, stats[j][0][0])
                    # dual systems are (n+3)² per class — far smaller than
                    # d² — so batch many more classes per dispatch (bound:
                    # ~256 MB of batched inner systems)
                    C = max(
                        1,
                        min(k, self.class_chunk * 8,
                            (1 << 26) // max((n + 3) ** 2, 1)),
                    )
                else:
                    C = max(1, self.class_chunk)
                delta_cols = []
                for c0 in range(0, k, C):
                    cs = slice(c0, min(c0 + C, k))
                    mu_c = class_means[cs]  # (C, d)
                    mean_diff = mu_c - pop_mean  # (C, d)
                    mean_mixture = (
                        (1 - w) * residual_mean[cs] + w * class_r_mean[cs]
                    )  # (C,)
                    jointXTR = (
                        (1 - w) * pop_xtr[:, cs].T
                        + w * class_xtr[:, cs].T
                        - joint_means[cs] * mean_mixture[:, None]
                    )  # (C, d)
                    rhs = jointXTR - lam * Ws[j][:, cs].T
                    if use_dual:
                        dvec = (1 - w) / n + w * onehot[:, cs].T \
                            / jnp.maximum(counts[cs], 1.0)[:, None]  # (C, n)
                        Qb, Rb = gram_j
                        mu_proj = jnp.matmul(mu_c, Qb)  # (C, n)
                        delta_cols.append(
                            _dual_solve_chunk(
                                Qb, Rb, shard_classes(dvec),
                                pm_proj, shard_classes(mu_proj), s3,
                                shard_classes(rhs), lam,
                            )
                        )
                        continue
                    # model-axis parallelism: the class dim of the masked
                    # Grams and the batched per-class solves shards over
                    # MODEL_AXIS (each model-device owns a slice of
                    # classes); a 1-wide model axis makes this a no-op
                    mask = shard_classes(onehot[:, cs], axis=1)  # (n, C)
                    grams = _chunk_grams(A, mask)  # (C, d, d)
                    cnt = counts[cs][:, None, None]
                    class_cov = grams / jnp.maximum(cnt, 1.0) - jnp.einsum(
                        "cd,ce->cde", mu_c, mu_c
                    )
                    jointXTX = (
                        (1 - w) * pop_cov
                        + w * class_cov
                        + w * (1 - w) * jnp.einsum(
                            "cd,ce->cde", mean_diff, mean_diff
                        )
                    )
                    delta_cols.append(
                        _batched_solve(
                            shard_classes(jointXTX), shard_classes(rhs), lam
                        )
                    )
                delta = jnp.concatenate(delta_cols, axis=0).T  # (d, k)
                Ws[j] = Ws[j] + delta
                # per-block phase (parity: the reference's per-block solve
                # timing logs, BlockWeightedLeastSquares.scala:177-313);
                # syncs only under KEYSTONE_PROFILE
                with phase("wls.block") as out:
                    R = R - A @ delta
                    out.append(R)

        # final intercept (ref :310-315)
        b = joint_label_mean - sum(
            jnp.einsum("cd,dc->c", stats[j][2], Ws[j])
            for j in range(len(blocks))
        )
        return BlockLinearMapper(Ws, self.block_size, b=b)

    def train_streaming(self, data, Y) -> BlockLinearMapper:
        """Out-of-core weighted solve: the featurized design matrix streams
        through in row chunks and NEVER materializes (parity: the
        reference's per-partition Gram iteration over the cached featurized
        RDD, BlockWeightedLeastSquares.scala:177-313 — Spark re-reads
        partitions from cluster RAM; here the chunked source recomputes
        them, lineage-style).

        The solver body lives at the linalg layer
        (:func:`~keystone_tpu.linalg.weighted.solve_weighted_streaming`),
        mesh-distributed across the data-axis scan lanes with per-lane
        partial accumulators reduced once per block. Resident state:
        labels/residual (n, k) — as per-lane slabs when laned — the
        per-block joint stats, one (C, bs, bs) masked-Gram accumulator,
        and one chunk. Scan count: num_iter × nblocks × (1 + ⌈k/C⌉) — the
        class-chunked Gram passes are the price of never holding the
        (k, bs, bs) per-class Grams; the reference pays the same shape as
        one shuffle of the full data to class-keyed partitions. The same
        delayed-residual-update trick as the streaming BCD fuses
        ``R −= A_prev·Δ_prev`` into the next block's accumulation scan."""
        n = Y.shape[0]
        if len(data) != n:
            raise ValueError(
                f"chunked features have {len(data)} rows, labels {n}"
            )
        # raw (unpipelined) scans compose here; the solver wraps them in
        # scan_pipeline so exactly ONE pipeline runs per scan
        if self.num_features is not None:
            dcap = self.num_features
            base_scan = data.raw_chunks

            def scan():
                for chunk in base_scan():
                    yield chunk[..., :dcap]

        else:
            scan = data.raw_chunks

        Ws, b = solve_weighted_streaming(
            scan, Y,
            block_size=self.block_size, num_iter=self.num_iter,
            lam=self.lam, mixture_weight=self.mixture_weight,
            class_chunk=self.class_chunk,
        )
        return BlockLinearMapper(Ws, self.block_size, b=b)


def _joint_weighted_stats(X, Y, w):
    """Shared mixture-weighting algebra of the per-class family (parity:
    computeJointFeatureMean / computeJointLabelMean / computeWeights,
    PerClassWeightedLeastSquares.scala:140-190). Returns
    (y_idx, counts, joint_label_mean (k,), joint_means (k, d))."""
    n, k = Y.shape
    y_idx = jnp.argmax(Y, axis=1)
    onehot = jax.nn.one_hot(y_idx, k, dtype=jnp.float32)
    counts = jnp.sum(onehot, axis=0)
    joint_label_mean = 2 * w + 2 * (1 - w) * counts / n - 1.0
    pop_mean = jnp.mean(X, axis=0)
    class_means = (onehot.T @ X) / jnp.maximum(counts, 1.0)[:, None]
    joint_means = w * class_means + (1 - w) * pop_mean  # (k, d)
    return y_idx, counts, joint_label_mean, joint_means


def _class_sample_weights(y_idx, counts, c, w, n):
    """diag(B) for class ``c``: (1−w)/n population term on every row plus
    w/n_c on class-c rows (class rows appear in both the population and
    the class statistics of the block solver)."""
    return (1 - w) / n + jnp.where(
        y_idx == c, w / jnp.maximum(counts[c], 1.0), 0.0
    )


class PerClassWeightedLeastSquaresEstimator(LabelEstimator, CostModel):
    """Same objective solved exactly, class-at-a-time, as a dense weighted
    ridge — the reference uses it as the agreement oracle for the block
    solver (parity: PerClassWeightedLeastSquares.scala:31-63;
    BlockWeightedLeastSquaresSuite.scala:115). Exact (non-iterative) when
    the full feature matrix fits; use for tests/small problems.

    ``snapshot=True`` fits through the per-class raw accumulators
    (:class:`~keystone_tpu.linalg.weighted.WeightedSolverState` — k
    per-class Grams plus label cross terms, all associative over row
    blocks) and attaches the state to the fitted mapper, so
    ``FittedPipeline.absorb`` can fold appended chunks into the weighted
    family exactly as it does the Gram family. The exact per-class
    solve is order-free, which is WHY this family absorbs while the
    BCD-iterated weighted solvers raise :class:`NotAbsorbable`."""

    def __init__(self, block_size: int, num_iter: int, lam: float,
                 mixture_weight: float,
                 num_features: Optional[int] = None,
                 snapshot: bool = False):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features
        self.snapshot = snapshot

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        # exact per-class dense ridge: every class pays the full weighted
        # Gram (2·n·d²) plus a d³ factorization, and re-reads X
        d = self.num_features or d
        return combine_cost(
            {
                "flops": k * (2.0 * n * d * d + d ** 3 / 3.0) / num_machines,
                "bytes": k * (n * d / num_machines + d * d),
                "network": d * (d + k),
                "passes": k,
            },
            cpu_weight, mem_weight, network_weight,
        )

    def _fit_snapshot(self, data, labels: Dataset) -> BlockLinearMapper:
        """The accumulator path: fold the data (chunked or not) into a
        :class:`~keystone_tpu.linalg.weighted.WeightedSolverState`, solve
        from the state, and attach the snapshot for later ``absorb``. The
        state solves in host float64, so this path is if anything MORE
        accurate than the f32 dense oracle it mirrors."""
        from ...data.chunked import ChunkedDataset
        from ...linalg.weighted import WeightedSolverState

        d_cap = self.num_features
        state = WeightedSolverState(
            lam=float(self.lam),
            mixture_weight=float(self.mixture_weight),
            block_size=int(self.block_size),
        )
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        if isinstance(data, ChunkedDataset):
            offset = 0
            for chunk in data.raw_chunks():
                chunk = jnp.asarray(chunk, dtype=jnp.float32)
                if d_cap is not None:
                    chunk = chunk[..., :d_cap]
                rows = int(chunk.shape[0])
                state.update(chunk, Y[offset : offset + rows])
                offset += rows
            if offset != int(Y.shape[0]):
                raise ValueError(
                    f"chunked features have {offset} rows, labels "
                    f"{Y.shape[0]}"
                )
        else:
            X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
            if d_cap is not None:
                X = X[:, :d_cap]
            state.update(X, Y)
        W, b = state.solve()
        d = int(W.shape[0])
        blocks = [
            W[i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        ]
        return BlockLinearMapper(
            blocks, self.block_size, b=b, solver_state=state.snapshot()
        )

    @_f32_true
    def fit(self, data, labels: Dataset) -> BlockLinearMapper:
        if self.snapshot:
            return self._fit_snapshot(data, labels)
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        w = self.mixture_weight
        n, k = Y.shape
        d = X.shape[1]
        y_idx, counts, joint_label_mean, joint_means = _joint_weighted_stats(
            X, Y, w
        )

        cols = []
        for c in range(k):
            b_i = _class_sample_weights(y_idx, counts, c, w, n)
            mu = joint_means[c]
            Xc = X - mu
            yc = Y[:, c] - joint_label_mean[c]
            G = Xc.T @ (Xc * b_i[:, None])
            rhs = Xc.T @ (yc * b_i)
            Wc = jnp.linalg.solve(
                G + self.lam * jnp.eye(d, dtype=X.dtype), rhs
            )
            cols.append(Wc)
        W = jnp.stack(cols, axis=1)  # (d, k)
        b = joint_label_mean - jnp.einsum("cd,dc->c", joint_means, W)
        blocks = [
            W[i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        ]
        return BlockLinearMapper(blocks, self.block_size, b=b)


@_f32_true
def solve_reweighted_l2(
    blocks: Sequence,
    y_zm,
    sample_weights,
    reg: float,
    num_iter: int = 1,
    means: Optional[Sequence] = None,
):
    """Iterative weighted BCD:  W = (Xᵀdiag(b)X + λI)⁻¹ Xᵀ(b∘y)  solved a
    feature block at a time (parity: the internal solver behind the
    per-class estimator, internal/ReWeightedLeastSquares.scala:18-150).

    blocks: list of (n, bs_j) feature blocks; ``y_zm`` (n, k) zero-meaned
    labels; ``sample_weights`` (n,) the diagonal of B; ``means`` optional
    per-block column means subtracted in-program (never materialized).

    Shape of the iteration, preserved from the reference: the weighted
    per-block Gram ``XⱼᵀBXⱼ`` is computed once on the first pass and cached
    (it never changes); the residual carries ``R = B∘(X·W)`` and each block
    update solves against ``Xⱼᵀ((B∘y) − (R − B∘(XⱼWⱼ)))``. The reference's
    map + treeReduce per term become one jitted program per block step.
    """
    y_zm = jnp.asarray(y_zm, dtype=jnp.float32)
    b = jnp.asarray(sample_weights, dtype=jnp.float32)
    if y_zm.ndim == 1:
        y_zm = y_zm[:, None]
    blocks = [jnp.asarray(a, dtype=jnp.float32) for a in blocks]
    if means is None:
        means = [jnp.zeros((a.shape[1],), dtype=jnp.float32) for a in blocks]
    k = y_zm.shape[1]
    Ws = [jnp.zeros((a.shape[1], k), dtype=jnp.float32) for a in blocks]
    R = jnp.zeros_like(y_zm)
    gram_cache: List[Optional[jax.Array]] = [None] * len(blocks)
    for it in range(num_iter):
        for j, Aj in enumerate(blocks):
            if gram_cache[j] is None:
                gram_cache[j] = _weighted_gram(Aj, means[j], b)
            Ws[j], R = _reweighted_block_update(
                Aj, means[j], gram_cache[j], Ws[j], R, y_zm, b,
                jnp.float32(reg),
            )
    return Ws


@nestable_jit
def _weighted_gram(Aj, mj, b):
    # no explicit precision= — the _f32_true context governs (an explicit
    # "high" would override it and keep this at bf16_3x)
    Ajc = Aj - mj
    return jnp.matmul(Ajc.T, Ajc * b[:, None])


@nestable_jit
def _reweighted_block_update(Aj, mj, G, Wj_old, R, y_zm, b, reg):
    Ajc = Aj - mj
    # remove this block's contribution from the weighted residual
    xw_old = jnp.matmul(Ajc, Wj_old)
    R_wo = R - xw_old * b[:, None]
    rhs = jnp.matmul(Ajc.T, y_zm * b[:, None] - R_wo)
    Wj = solve_spd(G, rhs, reg)
    R = R_wo + jnp.matmul(Ajc, Wj) * b[:, None]
    return Wj, R


class ReWeightedLeastSquaresEstimator(LabelEstimator, CostModel):
    """Per-class weighted least squares solved by the ITERATIVE reweighted
    BCD (parity: PerClassWeightedLeastSquares.scala:97-110 driving
    internal/ReWeightedLeastSquares.scala:18). Third agreement point for
    the weighted family next to the block solver and the exact per-class
    oracle — all three optimize the same objective, so they must agree."""

    def __init__(self, block_size: int, num_iter: int, lam: float,
                 mixture_weight: float,
                 num_features: Optional[int] = None):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        # per class: weighted per-block Grams once (n·d·bs), then
        # num_iter residual/solve sweeps (2·n·d GEMV-shaped + d·bs² solves)
        d = self.num_features or d
        bs = min(self.block_size, d)
        return combine_cost(
            {
                "flops": k * (
                    n * d * bs + self.num_iter * (2.0 * n * d + d * bs * bs)
                ) / num_machines,
                "bytes": k * self.num_iter * (n * d / num_machines + d),
                "network": d * (bs + k),
                "passes": k * self.num_iter,
            },
            cpu_weight, mem_weight, network_weight,
        )

    @_f32_true
    def fit(self, data, labels: Dataset) -> BlockLinearMapper:
        X = jnp.asarray(Dataset.of(data).to_array(), dtype=jnp.float32)
        Y = jnp.asarray(Dataset.of(labels).to_array(), dtype=jnp.float32)
        w = self.mixture_weight
        n, k = Y.shape
        d = self.num_features or X.shape[1]
        X = X[:, :d]
        y_idx, counts, joint_label_mean, joint_means = _joint_weighted_stats(
            X, Y, w
        )

        splits = list(range(0, d, self.block_size))
        # feature blocks are class-independent; slice once outside the loop
        blocks = [X[:, i : min(i + self.block_size, d)] for i in splits]
        cols = []
        for c in range(k):
            b_i = _class_sample_weights(y_idx, counts, c, w, n)
            mu = joint_means[c]
            mean_blocks = [
                mu[i : min(i + self.block_size, d)] for i in splits
            ]
            yc = Y[:, c] - joint_label_mean[c]
            ws_c = solve_reweighted_l2(
                blocks, yc, b_i, reg=self.lam, num_iter=self.num_iter,
                means=mean_blocks,
            )
            cols.append(jnp.concatenate([wj[:, 0] for wj in ws_c]))
        W = jnp.stack(cols, axis=1)  # (d, k)
        b = joint_label_mean - jnp.einsum("cd,dc->c", joint_means, W)
        ws = [
            W[i : min(i + self.block_size, d)] for i in splits
        ]
        return BlockLinearMapper(ws, self.block_size, b=b)


class WeightedLeastSquaresEstimator(
    LabelEstimator, AutoSolverFrontDoor, CostModel, Optimizable
):
    """Cost-model auto-selecting front door for the weighted family — the
    class-weighted analogue of ``LeastSquaresEstimator``. All three
    physical solvers optimize the same mixture objective (the agreement
    contract pinned by the weighted parity tests), so selection is purely
    a cost question: the block solver streams and shares per-block Grams
    across classes, the per-class oracle is exact but pays k dense d×d
    factorizations, the reweighted BCD sits between. Selection runs
    through :class:`keystone_tpu.cost.SolverChooser`, so with a profile
    store configured (``KEYSTONE_PROFILE_DIR``) the family earns learned
    ``op/`` seconds-per-unit profiles from traced fits and future choices
    rank by predicted wall-clock."""

    def __init__(self, block_size: int, num_iter: int, lam: float,
                 mixture_weight: float,
                 num_features: Optional[int] = None,
                 num_machines: Optional[int] = None,
                 cpu_weight: Optional[float] = None,
                 mem_weight: Optional[float] = None,
                 network_weight: Optional[float] = None):
        self.block_size = block_size
        self.num_iter = num_iter
        self.lam = lam
        self.mixture_weight = mixture_weight
        self.num_features = num_features
        self.num_machines = num_machines
        self._init_chooser_weights(cpu_weight, mem_weight, network_weight)
        args = (block_size, num_iter, lam, mixture_weight)
        self.options: Sequence = [
            BlockWeightedLeastSquaresEstimator(
                *args, num_features=num_features
            ),
            PerClassWeightedLeastSquaresEstimator(
                *args, num_features=num_features
            ),
            ReWeightedLeastSquaresEstimator(
                *args, num_features=num_features
            ),
        ]
        self.default = self.options[0]

    def fit(self, data, labels: Dataset) -> BlockLinearMapper:
        from ...data.chunked import ChunkedDataset

        if isinstance(data, (list, tuple)):
            # pre-split block list: only the block solver understands it
            # (the per-class/reweighted options stack a dense (n, d)), and
            # the list container would corrupt the shape signature
            # (n = block count, not rows) — skip the chooser
            return self.default.fit(data, labels)
        chunked = isinstance(data, ChunkedDataset)
        sample = data.take(24) if chunked else Dataset.of(data)
        solver = self.sample_optimize(
            [sample, Dataset.of(labels)],
            len(Dataset.of(data)), chunked=chunked,
        )
        return solver.fit(data if chunked else Dataset.of(data), labels)
