"""Solver cost models (parity: nodes/learning/CostModel.scala:6 and the
fitted cluster constants at LeastSquaresEstimator.scala:28-31).

The functional form max(cpu·flops, mem·bytes) + net·network carries over
unchanged; on TPU the three weights describe MXU throughput, HBM bandwidth
and ICI bandwidth instead of EC2 cores/RAM/Ethernet. Constants are
recalibrated by ``scripts/calibrate_cost_model.py`` output; defaults below
are v5e-order-of-magnitude estimates (flops ≈ 1/394e12 s, HBM ≈ 1/819e9 s,
ICI ≈ 1/4.5e10 s per element, relative units).
"""

from __future__ import annotations


class CostModel:
    """Estimated cost of fitting this solver on (n, d, k) data.

    ``cost`` returns analytic *units* in the reference's functional form;
    the ``keystone_tpu.cost`` subsystem converts units to predicted
    wall-clock seconds via learned per-class throughput (see
    ``cost/model.py``) and restricts chunked inputs to solvers that set
    ``supports_streaming``."""

    #: True when ``fit`` accepts a ChunkedDataset without materializing
    #: the full design matrix (the out-of-core / laned path)
    supports_streaming = False

    def cost(
        self,
        n: int,
        d: int,
        k: int,
        sparsity: float,
        num_machines: int,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
    ) -> float:
        raise NotImplementedError


def combine_cost(
    signature: dict,
    cpu_weight: float,
    mem_weight: float,
    network_weight: float,
) -> float:
    """``max(cpu·flops, mem·bytes) + net·network`` over one solver's work
    terms (see ``linalg.*.cost_signature``)."""
    return (
        max(
            cpu_weight * signature["flops"],
            mem_weight * signature["bytes"],
        )
        + network_weight * signature["network"]
    )


# Default weights, recalibratable on real hardware. Ratios matter, absolute
# scale does not (same as the reference's fitted constants).
DEFAULT_CPU_WEIGHT = 2.5e-12
DEFAULT_MEM_WEIGHT = 1.2e-9
DEFAULT_NETWORK_WEIGHT = 2.2e-11
