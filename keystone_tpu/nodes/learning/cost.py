"""Solver cost models (parity: nodes/learning/CostModel.scala:6 and the
fitted cluster constants at LeastSquaresEstimator.scala:28-31).

The functional form max(cpu·flops, mem·bytes) + net·network carries over
unchanged; on TPU the three weights describe MXU throughput, HBM bandwidth
and ICI bandwidth instead of EC2 cores/RAM/Ethernet. Constants are
recalibrated by ``scripts/calibrate_cost_model.py`` output; defaults below
are v5e-order-of-magnitude estimates (flops ≈ 1/394e12 s, HBM ≈ 1/819e9 s,
ICI ≈ 1/4.5e10 s per element, relative units).
"""

from __future__ import annotations


class CostModel:
    """Estimated cost of fitting this solver on (n, d, k) data.

    ``cost`` returns analytic *units* in the reference's functional form;
    the ``keystone_tpu.cost`` subsystem converts units to predicted
    wall-clock seconds via learned per-class throughput (see
    ``cost/model.py``) and restricts chunked inputs to solvers that set
    ``supports_streaming``."""

    #: True when ``fit`` accepts a ChunkedDataset without materializing
    #: the full design matrix (the out-of-core / laned path)
    supports_streaming = False

    def cost(
        self,
        n: int,
        d: int,
        k: int,
        sparsity: float,
        num_machines: int,
        cpu_weight: float,
        mem_weight: float,
        network_weight: float,
    ) -> float:
        raise NotImplementedError


def combine_cost(
    signature: dict,
    cpu_weight: float,
    mem_weight: float,
    network_weight: float,
) -> float:
    """``max(cpu·flops, mem·bytes) + net·network`` over one solver's work
    terms (see ``linalg.*.cost_signature``)."""
    return (
        max(
            cpu_weight * signature["flops"],
            mem_weight * signature["bytes"],
        )
        + network_weight * signature["network"]
    )


# Default weights, recalibratable on real hardware. Ratios matter, absolute
# scale does not (same as the reference's fitted constants).
DEFAULT_CPU_WEIGHT = 2.5e-12
DEFAULT_MEM_WEIGHT = 1.2e-9
DEFAULT_NETWORK_WEIGHT = 2.2e-11


def dense_shape_from_samples(samples, num_items: int, machines: int,
                             chunked: bool = False):
    """Distill (data, labels) dependency samples into the chooser's
    :class:`~keystone_tpu.cost.ShapeSignature` for a dense solve — the
    shared front half of every auto-solver's ``shape_from_samples`` (n is
    the FULL dataset size, d/k peeked from one sample item). Sparse-aware
    families (``LeastSquaresEstimator``) keep their own richer version."""
    import numpy as np

    from ...cost import ShapeSignature
    from ...data.dataset import Dataset

    sample = Dataset.of(samples[0])
    sample_labels = Dataset.of(samples[1])
    d = int(np.asarray(sample.first()).shape[-1])
    k = int(np.asarray(sample_labels.first()).shape[-1])
    n = num_items if num_items else len(sample)
    return ShapeSignature(
        n=int(n), d=d, k=k, chunked=bool(chunked), machines=int(machines)
    )


def label_dim_fitted_out_spec(fit_in, apply_in):
    """Shared ``fitted_out_spec`` declaration (see
    ``keystone_tpu/check/abstract.py``) for the label-estimator solver
    families: the fitted mapper sends one feature vector to one score per
    label column, so the output item spec IS the labels' item shape, in
    the solvers' float32. None when the labels spec is unknown."""
    labels = fit_in[1] if len(fit_in) > 1 else None
    if (
        not isinstance(labels, tuple) or len(labels) != 2
        or not isinstance(labels[1], str)
    ):
        return None
    shape, _ = labels
    return (tuple(shape), "float32")


class AutoSolverFrontDoor:
    """The cost-model front-door protocol shared by the auto-selecting
    estimator families (``LeastSquaresEstimator``,
    ``WeightedLeastSquaresEstimator``, ``KernelRidgeEstimator``): an
    ``options`` list of interchangeable physical solvers, selection
    through :class:`keystone_tpu.cost.SolverChooser`, and the
    graph-level ``sample_optimize`` hook.

    Subclass ``__init__`` must set ``self.options``, ``self.default``,
    ``self.num_machines``, and call :meth:`_init_chooser_weights`.
    ``shape_from_samples`` defaults to the dense signature; sparse-aware
    families override it. ``cost`` prices the front door as its cheapest
    option, so an un-resolved auto node ranks where its best member
    would."""

    def fitted_out_spec(self, fit_in, apply_in):
        return label_dim_fitted_out_spec(fit_in, apply_in)

    def _init_chooser_weights(self, cpu_weight, mem_weight, network_weight):
        self.cpu_weight = (
            DEFAULT_CPU_WEIGHT if cpu_weight is None else cpu_weight
        )
        self.mem_weight = (
            DEFAULT_MEM_WEIGHT if mem_weight is None else mem_weight
        )
        self.network_weight = (
            DEFAULT_NETWORK_WEIGHT if network_weight is None
            else network_weight
        )

    @property
    def weight(self) -> int:
        return self.default.weight

    def cost(self, n, d, k, sparsity, num_machines,
             cpu_weight, mem_weight, network_weight):
        return min(
            opt.cost(n, d, k, sparsity, num_machines,
                     cpu_weight, mem_weight, network_weight)
            for opt in self.options
        )

    def shape_from_samples(self, samples, num_items: int,
                           chunked: bool = False):
        from ...parallel.mesh import default_mesh

        return dense_shape_from_samples(
            samples, num_items,
            self.num_machines or default_mesh().size, chunked,
        )

    def choose_solver(self, shape, node_id=None):
        """Run the cost-model chooser over the option set; returns the
        full :class:`~keystone_tpu.cost.SolverChoice` (pricing table
        included) for the given shape signature."""
        from ...cost import SolverChooser

        return SolverChooser().choose(
            self.options, shape,
            self.cpu_weight, self.mem_weight, self.network_weight,
            node_id=node_id, owner_label=type(self).__name__,
        )

    def sample_optimize(self, samples, num_items: int, chunked: bool = False):
        shape = self.shape_from_samples(samples, num_items, chunked=chunked)
        return self.choose_solver(shape).chosen
