"""Probabilistic/discriminant classifiers: Naive Bayes, logistic regression,
linear discriminant analysis, and the cost-model auto-solver.

Parity: nodes/learning/NaiveBayesModel.scala:21,62 (multinomial NB, the MLlib
``NaiveBayes.train`` it wraps), LogisticRegressionModel.scala:19,42 (LBFGS
logistic GLM), LinearDiscriminantAnalysis.scala:17, and
LeastSquaresEstimator.scala:26-88 (cost-model solver selection).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...parallel.mesh import default_mesh, shard_batch
from ...workflow.node_optimization import Optimizable
from ...workflow.transformer import LabelEstimator, Transformer
from ...utils.params import as_param
from .cost import AutoSolverFrontDoor, CostModel
from .lbfgs import DenseLBFGSwithL2, SparseLBFGSwithL2, minimize_lbfgs
from .linear import (
    BlockLeastSquaresEstimator,
    LinearMapEstimator,
    LinearMapper,
    TSQRLeastSquaresEstimator,
)


class NaiveBayesModel(Transformer):
    """x → log-priors + log-likelihood matrix · x (parity:
    NaiveBayesModel.scala:21-60: pi + theta·x, both already logs)."""

    def __init__(self, pi, theta):
        self.pi = as_param(pi)          # (k,) log priors
        self.theta = as_param(theta)    # (k, d) log feature probs

    def trace_batch(self, X):
        return X @ self.theta.T + self.pi

    def apply_batch(self, data):
        from ...data.sparse import SparseRows

        data = Dataset.of(data)
        if isinstance(data.payload, SparseRows):
            return Dataset(
                data.payload.matmul(self.theta.T) + self.pi, batched=True
            )
        return super().apply_batch(data)

    def apply(self, x):
        from ...data.sparse import SparseRows

        sr = SparseRows.datum_from_pairs(x, self.theta.shape[1])
        if sr is not None:
            return (sr.matmul(self.theta.T) + self.pi)[0]
        return super().apply(x)


class NaiveBayesEstimator(LabelEstimator):
    """Multinomial NB with Laplace smoothing ``lambda`` (parity:
    NaiveBayesEstimator wrapping MLlib NaiveBayes.train,
    NaiveBayesModel.scala:62-69; the MLlib algorithm is the spec:
    pi_c = log((n_c + λ)/(n + kλ)), theta_cj = log((Σ_c x_j + λ)/(Σ_cj + dλ))."""

    def __init__(self, num_classes: int, lam: float = 1.0):
        self.num_classes = num_classes
        self.lam = lam

    def fit(self, data: Dataset, labels: Dataset) -> NaiveBayesModel:
        from ...data.sparse import SparseRows

        data = Dataset.of(data)
        y = jnp.asarray(
            Dataset.of(labels).to_array(), dtype=jnp.int32
        ).ravel()
        k = self.num_classes
        onehot = jax.nn.one_hot(y, k, dtype=jnp.float32)
        if isinstance(data.payload, SparseRows):
            X = data.payload
            n, d = X.shape
            # hard int labels: one (n, m)-element scatter-add instead of the
            # (n, m, k) soft-membership scatter class_sums would build
            feat_sums = X.label_sums(y, k)
        else:
            X = jnp.asarray(data.to_array(), dtype=jnp.float32)
            n, d = X.shape
            feat_sums = onehot.T @ X  # (k, d)
        n_c = onehot.sum(axis=0)
        pi = jnp.log(n_c + self.lam) - jnp.log(n + k * self.lam)
        theta = jnp.log(feat_sums + self.lam) - jnp.log(
            feat_sums.sum(axis=1, keepdims=True) + d * self.lam
        )
        return NaiveBayesModel(pi, theta)


@jax.jit
def _logistic_value_and_grad(W, A, y_onehot, lam):
    """Multinomial cross-entropy with L2 (binary case = 2-column softmax)."""
    n = A.shape[0]
    logits = A @ W
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.sum(y_onehot * log_probs) / n + 0.5 * lam * jnp.sum(W * W)
    grad = A.T @ (jax.nn.softmax(logits, axis=-1) - y_onehot) / n + lam * W
    return loss, grad


def _sparse_logistic_value_and_grad(W, X, y_onehot, lam):
    """Sparse-input multinomial cross-entropy: gather-matmul forward,
    scatter-add gradient (no densification)."""
    n = y_onehot.shape[0]
    logits = X.matmul(W)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.sum(y_onehot * log_probs) / n + 0.5 * lam * jnp.sum(W * W)
    resid = jax.nn.softmax(logits, axis=-1) - y_onehot
    grad = X.rmatmul(resid) / n + lam * W
    return loss, grad


class LogisticRegressionModel(Transformer):
    """Class prediction via argmax of logits (parity:
    LogisticRegressionModel.scala:19-40, which emits the predicted class)."""

    def __init__(self, W):
        self.W = as_param(W)

    def trace_batch(self, X):
        return jnp.argmax(X @ self.W, axis=-1)

    def apply_batch(self, data):
        from ...data.sparse import SparseRows

        data = Dataset.of(data)
        if isinstance(data.payload, SparseRows):
            return Dataset(
                jnp.argmax(data.payload.matmul(self.W), axis=-1),
                batched=True,
            )
        return super().apply_batch(data)

    def apply(self, x):
        from ...data.sparse import SparseRows

        sr = SparseRows.datum_from_pairs(x, self.W.shape[0])
        if sr is not None:
            return jnp.argmax(sr.matmul(self.W), axis=-1)[0]
        return super().apply(x)

    def scores(self, X):
        return jnp.asarray(X) @ self.W


class LogisticRegressionEstimator(LabelEstimator):
    """LBFGS-fit multinomial logistic regression (parity:
    LogisticRegressionEstimator wrapping MLlib's LogisticRegressionWithLBFGS,
    LogisticRegressionModel.scala:42-94)."""

    def __init__(self, num_classes: int, reg_param: float = 0.0,
                 num_iters: int = 100, convergence_tol: float = 1e-4):
        self.num_classes = num_classes
        self.reg_param = reg_param
        self.num_iters = num_iters
        self.convergence_tol = convergence_tol

    def fit(self, data: Dataset, labels: Dataset) -> LogisticRegressionModel:
        from ...data.sparse import SparseRows

        data = Dataset.of(data)
        y = jnp.asarray(
            Dataset.of(labels).to_array(), dtype=jnp.int32
        ).ravel()
        onehot = jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32)
        lam = jnp.float32(self.reg_param)
        if isinstance(data.payload, SparseRows):
            X = data.payload
            W0 = jnp.zeros((X.shape[1], self.num_classes), dtype=jnp.float32)
            # operands ride vag_args, not closures: a closed-over design
            # matrix becomes an HLO constant shipped to the compile
            # service (see minimize_lbfgs)
            num_features = X.num_features

            def vag(w, idx, vals, onehot, lam):
                return _sparse_logistic_value_and_grad(
                    w, SparseRows(idx, vals, num_features), onehot, lam
                )

            vag_args = (X.indices, X.values, onehot, lam)
        else:
            if not data.is_batched:
                import scipy.sparse as sp

                items = data.collect()
                if items and sp.issparse(items[0]):
                    X = jnp.asarray(
                        np.asarray(sp.vstack(items).todense()),
                        dtype=jnp.float32,
                    )
                else:
                    X = jnp.asarray(np.asarray(items), dtype=jnp.float32)
            else:
                X = jnp.asarray(data.to_array(), dtype=jnp.float32)
            X = shard_batch(X)
            onehot_dev = shard_batch(onehot)
            W0 = jnp.zeros((X.shape[1], self.num_classes), dtype=jnp.float32)
            vag = _logistic_value_and_grad
            vag_args = (X, onehot_dev, lam)
        W = minimize_lbfgs(
            vag,
            W0,
            max_iterations=self.num_iters,
            convergence_tol=self.convergence_tol,
            vag_args=vag_args,
        )
        return LogisticRegressionModel(W)


class LinearDiscriminantAnalysis(LabelEstimator):
    """Multi-class LDA: top eigenvectors of S_W⁻¹ S_B
    (parity: LinearDiscriminantAnalysis.scala:17-68)."""

    def __init__(self, num_dimensions: int):
        self.num_dimensions = num_dimensions

    def fit(self, data: Dataset, labels: Dataset) -> LinearMapper:
        X = np.asarray(Dataset.of(data).to_array(), dtype=np.float64)
        y = np.asarray(Dataset.of(labels).to_array()).ravel().astype(np.int64)
        classes = np.unique(y)
        total_mean = X.mean(axis=0)
        d = X.shape[1]
        sW = np.zeros((d, d))
        sB = np.zeros((d, d))
        for c in classes:
            Xc = X[y == c]
            mu = Xc.mean(axis=0)
            Z = Xc - mu
            sW += Z.T @ Z
            m = (mu - total_mean)[:, None]
            sB += Xc.shape[0] * (m @ m.T)
        evals, evecs = np.linalg.eig(np.linalg.inv(sW) @ sB)
        order = np.argsort(-np.abs(evals))[: self.num_dimensions]
        W = np.real(evecs[:, order])
        return LinearMapper(jnp.asarray(W, dtype=jnp.float32))


class LeastSquaresEstimator(
    LabelEstimator, AutoSolverFrontDoor, CostModel, Optimizable
):
    """Cost-model auto-selecting least squares solver
    (parity: LeastSquaresEstimator.scala:26-88; option set preserved —
    dense LBFGS, sparse LBFGS, block solver (1000, 3), exact normal
    equations — plus the augmented-TSQR exact solver). Participates in
    graph-level NodeOptimizationRule via ``sample_optimize`` (parity:
    OptimizableNodes.scala:27-40).

    Selection runs through :class:`keystone_tpu.cost.SolverChooser`: cold
    it ranks by each option's analytic ``cost`` units (identical to the
    reference's argmin); with a profile store configured
    (``KEYSTONE_PROFILE_DIR``) units are converted to predicted seconds
    via learned per-class throughput, and chunked (out-of-core) inputs
    restrict the field to solvers with a streaming fit path."""

    def __init__(self, lam: float = 0.0, num_machines: Optional[int] = None,
                 cpu_weight: float = 3.8e-4, mem_weight: float = 2.9e-1,
                 network_weight: float = 1.32):
        self.lam = lam
        self.num_machines = num_machines
        self.cpu_weight = cpu_weight
        self.mem_weight = mem_weight
        self.network_weight = network_weight
        self.options: Sequence = [
            DenseLBFGSwithL2(reg_param=lam, num_iterations=20),
            SparseLBFGSwithL2(reg_param=lam, num_iterations=20),
            BlockLeastSquaresEstimator(1000, 3, lam=lam),
            LinearMapEstimator(lam=lam),
            TSQRLeastSquaresEstimator(lam=lam),
        ]
        self.default = self.options[0]

    def sample_optimize(self, samples, num_items: int, chunked: bool = False):
        """Graph-level entry: pick the concrete solver from dependency
        samples + the full dataset size."""
        data_sample, label_sample = samples[0], samples[1]
        return self.optimize(
            data_sample, label_sample, total_n=num_items, chunked=chunked
        )

    def shape_from_samples(
        self, samples, num_items: int, chunked: bool = False
    ):
        """Distill dependency samples into the chooser's shape signature
        (n is the FULL dataset size — selecting on the raw sample size
        skews toward small-n regimes; the reference uses
        numPerPartition × machines, LeastSquaresEstimator.scala:63-66)."""
        from ...cost import ShapeSignature
        from ...data.sparse import SparseRows

        sample = Dataset.of(samples[0])
        sample_labels = Dataset.of(samples[1])
        if isinstance(sample.payload, SparseRows):
            sparsity = sample.payload.density()
            d = sample.payload.num_features
        else:
            first = sample.first()
            if hasattr(first, "nnz"):  # scipy sparse
                items = sample.collect()
                sparsity = float(
                    np.mean([i.nnz / np.prod(i.shape) for i in items])
                )
                d = first.shape[-1]
            else:
                sparsity = 1.0
                d = np.asarray(first).shape[-1]
        n = num_items if num_items else len(sample)
        k = np.asarray(sample_labels.first()).shape[-1]
        return ShapeSignature(
            n=int(n), d=int(d), k=int(k), sparsity=float(sparsity),
            chunked=bool(chunked),
            machines=int(self.num_machines or default_mesh().size),
        )

    def optimize(self, sample: Dataset, sample_labels: Dataset,
                 total_n: Optional[int] = None,
                 chunked: bool = False) -> LabelEstimator:
        shape = self.shape_from_samples(
            [sample, sample_labels],
            total_n if total_n is not None else len(Dataset.of(sample)),
            chunked=chunked,
        )
        return self.choose_solver(shape).chosen

    def fit(self, data: Dataset, labels: Dataset):
        from ...data.chunked import ChunkedDataset

        chunked = isinstance(data, ChunkedDataset)
        sample = data.take(24) if chunked else Dataset.of(data)
        solver = self.optimize(
            sample, Dataset.of(labels), total_n=len(Dataset.of(data)),
            chunked=chunked,
        )
        return solver.fit(data if chunked else Dataset.of(data),
                          Dataset.of(labels))
