"""Glue nodes (parity: ``nodes/util/`` — ClassLabelIndicators.scala:15,38,
VectorSplitter.scala:10, VectorCombiner.scala, MaxClassifier.scala,
TopKClassifier.scala, Cacher.scala:15, Shuffler.scala:15, Densify/Sparsify,
FloatToDouble, MatrixVectorizer)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Transformer


class ClassLabelIndicators(Transformer):
    """Int label → ±1 indicator vector: −1 everywhere, +1 at the class index
    (parity: ClassLabelIndicatorsFromIntLabels, ClassLabelIndicators.scala:15-30).
    The ±1 (not 0/1) coding is what makes plain least squares a classifier."""

    def __init__(self, num_classes: int):
        if num_classes <= 1:
            raise ValueError("num_classes must be > 1")
        self.num_classes = num_classes

    def trace_batch(self, y):
        y = y.astype(jnp.int32)
        return 2.0 * jax.nn.one_hot(y, self.num_classes, dtype=jnp.float32) - 1.0


class MultiClassLabelIndicators(Transformer):
    """Variable-length label sets → ±1 multi-hot vector (parity:
    ClassLabelIndicatorsFromIntArrayLabels, ClassLabelIndicators.scala:38-58).
    Per-item host path: label sets are ragged."""

    def __init__(self, num_classes: int):
        if num_classes <= 1:
            raise ValueError("num_classes must be > 1")
        self.num_classes = num_classes

    def apply(self, labels):
        out = np.full((self.num_classes,), -1.0, dtype=np.float32)
        out[np.asarray(labels, dtype=np.int64)] = 1.0
        return jnp.asarray(out)

    def out_spec(self, labels_spec=None):
        # ragged per-item host path: not abstractly evaluable, but the
        # output spec is fully determined by construction
        return ((self.num_classes,), "float32")


class MaxClassifier(Transformer):
    """argmax over the score vector (parity: MaxClassifier.scala)."""

    def trace_batch(self, X):
        return jnp.argmax(X, axis=-1)


class TopKClassifier(Transformer):
    """Indices of the k largest scores, descending
    (parity: TopKClassifier.scala)."""

    def __init__(self, k: int):
        self.k = k

    def trace_batch(self, X):
        _, idx = jax.lax.top_k(X, self.k)
        return idx


class VectorCombiner(Transformer):
    """Concatenate the gathered branch outputs feature-wise
    (parity: VectorCombiner.scala vertcat over Seq[DenseVector])."""

    def trace_batch(self, Xs):
        # Input is the gather node's tuple of branch outputs.
        if isinstance(Xs, (tuple, list)):
            return jnp.concatenate([jnp.asarray(x) for x in Xs], axis=-1)
        return jnp.asarray(Xs)

    def apply(self, xs: Sequence) -> jnp.ndarray:
        return jnp.concatenate([jnp.asarray(x) for x in xs], axis=-1)

    def apply_batch(self, data: Dataset) -> Dataset:
        from ...data.chunked import ChunkedDataset

        data = Dataset.of(data)
        if isinstance(data, ChunkedDataset):
            # zipped gather chunks are tuples — concat lazily per chunk
            return data.map_batch(self.trace_batch)
        if data.is_batched and isinstance(data.payload, (list, tuple)):
            # gather output: a tuple of (n, d_i) arrays — concat on device.
            return Dataset(
                jnp.concatenate(
                    [jnp.asarray(p) for p in data.payload], axis=-1
                ),
                batched=True,
            )
        return data.map(self.apply)


class VectorSplitter(Transformer):
    """Split (n, d) features into ceil(d/block_size) column blocks
    (parity: VectorSplitter.scala:10-37). Output is the list of blocks —
    consumed by the block solvers; mesh-native layout note in SURVEY §2.7."""

    def __init__(self, block_size: int, num_features: Optional[int] = None):
        self.block_size = block_size
        self.num_features = num_features

    def out_spec(self, in_spec=None):
        # block list: not abstractly evaluable (list output), but fully
        # determined by the input width. An unknown input spec stays
        # unknown — fabricating a dtype would let the checker "guess",
        # which its no-false-positives contract forbids.
        if in_spec is None:
            return None
        shape, dtype = in_spec
        if not shape:
            raise ValueError("VectorSplitter needs a feature axis")
        d = self.num_features or int(shape[-1])
        lead = tuple(shape[:-1])
        return tuple(
            (lead + (min(self.block_size, d - i),), dtype)
            for i in range(0, d, self.block_size)
        )

    def split_batch(self, X) -> List[jnp.ndarray]:
        X = jnp.asarray(X)
        d = self.num_features or X.shape[-1]
        return [
            X[..., i : min(i + self.block_size, d)]
            for i in range(0, d, self.block_size)
        ]

    def apply(self, x):
        return self.split_batch(x)

    def apply_batch(self, data: Dataset) -> Dataset:
        X = Dataset.of(data).to_array()
        return Dataset(tuple(self.split_batch(X)), batched=True)


class Cacher(Transformer):
    """Materialize and hold the upstream result (parity: Cacher.scala:15 —
    the node the AutoCacheRule inserts). On TPU this pins the array in HBM.

    Inside a fused traced program (FittedPipeline.trace_fn) caching is
    meaningless — XLA holds intermediates — so the traced form is identity;
    this keeps serve chains containing Cachers one-jaxpr compilable."""

    def __init__(self, name: Optional[str] = None):
        self.name = name

    def trace_batch(self, X):
        return X

    def apply(self, x):
        return x

    def apply_batch(self, data: Dataset) -> Dataset:
        return Dataset.of(data).cache()


class Shuffler(Transformer):
    """Deterministic-seed row shuffle (parity: Shuffler.scala:15)."""

    def __init__(self, seed: int = 42):
        self.seed = seed

    def out_spec(self, in_spec=None):
        return in_spec  # a permutation is spec-preserving

    def apply(self, x):
        return x

    def apply_batch(self, data: Dataset) -> Dataset:
        data = Dataset.of(data)
        n = len(data)
        perm = np.random.default_rng(self.seed).permutation(n)
        if data.is_batched:
            return Dataset(
                jax.tree_util.tree_map(
                    lambda a: a[jnp.asarray(perm)], data.payload
                ),
                batched=True,
            )
        items = data.collect()
        return Dataset.from_items([items[i] for i in perm])


class FloatToDouble(Transformer):
    """dtype widening (parity: FloatToDouble.scala). On TPU f64 is emulated
    and slow; this exists for numerical-parity experiments on CPU."""

    def trace_batch(self, X):
        return X.astype(jnp.float64)


class DoubleToFloat(Transformer):
    def trace_batch(self, X):
        return X.astype(jnp.float32)


class MatrixVectorizer(Transformer):
    """Flatten each matrix item column-major into a vector (parity:
    MatrixVectorizer.scala; breeze toDenseVector is column-major)."""

    def trace_batch(self, X):
        # X: (n, r, c) → (n, r*c) in column-major (Fortran) order.
        return jnp.transpose(X, (0, 2, 1)).reshape(X.shape[0], -1)


class Densify(Transformer):
    """Sparse→dense passthrough: arrays are already dense on TPU; accepts
    scipy.sparse items for API parity (Densify.scala)."""

    def apply(self, x):
        if hasattr(x, "todense"):
            return jnp.asarray(np.asarray(x.todense()).squeeze())
        return jnp.asarray(x)


class Sparsify(Transformer):
    """Dense→scipy CSR per item (Sparsify.scala). Host-side only — XLA has no
    dynamic sparsity; used at the text-featurization boundary."""

    def apply(self, x):
        import scipy.sparse as sp

        return sp.csr_matrix(np.asarray(x))
