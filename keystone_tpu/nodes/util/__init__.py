from .core import (
    Cacher,
    ClassLabelIndicators,
    Densify,
    DoubleToFloat,
    FloatToDouble,
    MatrixVectorizer,
    MaxClassifier,
    MultiClassLabelIndicators,
    Shuffler,
    Sparsify,
    TopKClassifier,
    VectorCombiner,
    VectorSplitter,
)
from .sparse_features import (
    AllSparseFeatures,
    CommonSparseFeatures,
    SparseFeatureVectorizer,
)

__all__ = [
    "AllSparseFeatures",
    "CommonSparseFeatures",
    "SparseFeatureVectorizer",
    "Cacher",
    "ClassLabelIndicators",
    "Densify",
    "DoubleToFloat",
    "FloatToDouble",
    "MatrixVectorizer",
    "MaxClassifier",
    "MultiClassLabelIndicators",
    "Shuffler",
    "Sparsify",
    "TopKClassifier",
    "VectorCombiner",
    "VectorSplitter",
]
