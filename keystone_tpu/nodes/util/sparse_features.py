"""Sparse feature-space selection and vectorization.

Parity: nodes/util/CommonSparseFeatures.scala:19-67,
AllSparseFeatures.scala:15-28, SparseFeatureVectorizer.scala:7-21.

This is the SURVEY §7 "sparse text features" decision point. The reference
emits breeze SparseVectors; here the vectorizer emits a padded-COO
``SparseRows`` batch (data/sparse.py) whose consumers run as dense
gathers/scatters on the MXU. Top-K selection bounds the feature space, so
rows keep a small static capacity and XLA never sees dynamic sparsity.

Deterministic ordering parity: features are ranked by (count desc, first
appearance asc) exactly like the reference's (frequency, uniqueId) ordering
(CommonSparseFeatures.scala:21-44); AllSparseFeatures orders by first
appearance (AllSparseFeatures.scala:20-26).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

from ...data.dataset import Dataset
from ...data.sparse import SparseRows
from ...workflow.transformer import Estimator, Transformer


class SparseFeatureVectorizer(Transformer):
    """Map (feature, value) pair lists into the fitted feature space
    (parity: SparseFeatureVectorizer.scala:7-21)."""

    def __init__(self, feature_space: Dict):
        self.feature_space = dict(feature_space)

    @property
    def num_features(self) -> int:
        return len(self.feature_space)

    def apply(self, pairs: Sequence[Tuple]) -> List[Tuple[int, float]]:
        fs = self.feature_space
        out = [(fs[f], float(v)) for f, v in pairs if f in fs]
        out.sort()
        return out

    def apply_batch(self, data) -> Dataset:
        data = Dataset.of(data)
        rows = [self.apply(doc) for doc in data]
        return Dataset(
            SparseRows.from_pairs(rows, self.num_features), batched=True
        )


class CommonSparseFeatures(Estimator):
    """Keep the ``num_features`` most frequently observed features
    (parity: CommonSparseFeatures.scala:19-67)."""

    def __init__(self, num_features: int):
        self.num_features = num_features

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        data = Dataset.of(data)
        counts: Dict = {}
        first_seen: Dict = {}
        uid = 0
        for doc in data:
            for feature, _value in doc:
                counts[feature] = counts.get(feature, 0) + 1
                if feature not in first_seen:
                    first_seen[feature] = uid
                uid += 1
        ranked = sorted(
            counts.keys(), key=lambda f: (-counts[f], first_seen[f])
        )[: self.num_features]
        return SparseFeatureVectorizer(
            {f: i for i, f in enumerate(ranked)}
        )


class AllSparseFeatures(Estimator):
    """Keep every observed feature, ordered by first appearance
    (parity: AllSparseFeatures.scala:15-28)."""

    def fit(self, data: Dataset) -> SparseFeatureVectorizer:
        data = Dataset.of(data)
        feature_space: Dict = {}
        for doc in data:
            for feature, _value in doc:
                if feature not in feature_space:
                    feature_space[feature] = len(feature_space)
        return SparseFeatureVectorizer(feature_space)
