"""Image operator nodes, batched NXYC.

Parity targets: nodes/images/ — Convolver.scala:20,48, Pooler.scala:21,
SymmetricRectifier.scala:7, Windower.scala:13, GrayScaler.scala:9,
PixelScaler.scala:10, ImageVectorizer.scala:12, Cropper.scala:18,
RandomPatcher.scala:16, CenterCornerPatcher.scala:18.

Image representation: a batch is one ``(n, X, Y, C)`` float array in HBM —
the reference's five per-image storage layouts (utils/images/Image.scala)
collapse into this single canonical dense layout; loaders do the
transposition once at ingest. ``x``/``y`` follow the reference's
``Image.get(x, y, c)`` coordinates. The canonical *vectorized* layout is the
reference's channel-major order ``c + x*C + y*X*C``.

The Convolver is the showpiece mapping: the reference's im2col + GEMM over
patches (Convolver.scala:128-203) with per-patch mean/variance normalization
and ZCA whitening becomes ONE ``lax.conv_general_dilated`` (MXU) plus two
``reduce_window`` moment sums and elementwise algebra — the normalization
never materializes the patch matrix.
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Transformer
from ...utils.params import as_param

_DIMNUMS = ("NHWC", "HWIO", "NHWC")  # H≡x, W≡y throughout


def vectorize_images(X):
    """(n, X, Y, C) → (n, X*Y*C) in channel-major order c + x*C + y*X*C
    (parity: ImageVectorizer.scala:12 flattening ChannelMajor storage)."""
    n = X.shape[0]
    return jnp.transpose(X, (0, 2, 1, 3)).reshape(n, -1)


def images_from_vectors(V, x_dim: int, y_dim: int, channels: int):
    """Inverse of :func:`vectorize_images`."""
    n = V.shape[0]
    return jnp.transpose(
        V.reshape(n, y_dim, x_dim, channels), (0, 2, 1, 3)
    )


class ImageVectorizer(Transformer):
    def trace_batch(self, X):
        return vectorize_images(X)


class PixelScaler(Transformer):
    """byte pixels → [0,1] doubles (parity: PixelScaler.scala:10)."""

    def trace_batch(self, X):
        return X.astype(jnp.float32) / 255.0


class GrayScaler(Transformer):
    """Luminance per the reference's human-eye weights
    (parity: GrayScaler.scala:9 via ImageUtils.toGrayScale:73-113)."""

    def trace_batch(self, X):
        # uint8 ingestion: images ride to HBM as bytes (4x less transfer
        # than f32); entry ops cast on device
        X = X.astype(jnp.float32)
        # reference weights: 0.299 R + 0.587 G + 0.114 B
        w = jnp.array([0.299, 0.587, 0.114], dtype=X.dtype)
        if X.shape[-1] == 3:
            return (X * w).sum(axis=-1, keepdims=True)
        return X.mean(axis=-1, keepdims=True)


class SymmetricRectifier(Transformer):
    """Channel-doubling rectification [max(v, x−α); max(v, −x−α)]
    (parity: SymmetricRectifier.scala:7-32)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def trace_batch(self, X):
        return jnp.concatenate(
            [
                jnp.maximum(self.max_val, X - self.alpha),
                jnp.maximum(self.max_val, -X - self.alpha),
            ],
            axis=-1,
        )


def pack_filter_images(filters):
    """(k, S, S, C) filter images → (k, S·S·C) rows in the canonical patch
    layout c + px·C + py·C·S (parity: Convolver.packFilters:99-127)."""
    filters = jnp.asarray(filters)
    k = filters.shape[0]
    return jnp.transpose(filters, (0, 2, 1, 3)).reshape(k, -1)


class Convolver(Transformer):
    """Filter-bank convolution with optional per-patch normalization and ZCA
    whitening (parity: Convolver.scala:20-223).

    ``filters``: (k, S²·C) rows in patch layout c + px·C + py·C·S, already
    whitened by the caller when a whitener is used (the reference does the
    same: Convolver.scala:75-81 folds W·Wᵀ into the filters).

    out(x,y,k) = p̂(x,y)·f_k − means·f_k where p̂ is the
    mean/variance-normalized patch; computed as conv + window moments:

        p̂·f = (conv(img, f) − μ_patch · Σf) / sd_patch
    """

    def __init__(
        self,
        filters,
        img_x: int,
        img_y: int,
        img_channels: int,
        whitener=None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
    ):
        self.filters = as_param(filters, dtype='float32')
        self.img_x = img_x
        self.img_y = img_y
        self.img_channels = img_channels
        self.whitener = whitener
        self.normalize_patches = normalize_patches
        self.var_constant = var_constant
        self.conv_size = int(
            math.isqrt(self.filters.shape[1] // img_channels)
        )
        if self.conv_size ** 2 * img_channels != self.filters.shape[1]:
            raise ValueError("filters must be square patches")

    def trace_batch(self, X):
        S, C = self.conv_size, self.img_channels
        K = self.filters.shape[0]
        m = S * S * C
        X = X.astype(jnp.float32)

        # kernel[pox, poy, c, k] from row layout c + pox*C + poy*C*S
        kernel = jnp.transpose(
            self.filters.reshape(K, S, S, C), (2, 1, 3, 0)
        )
        conv = jax.lax.conv_general_dilated(
            X, kernel, window_strides=(1, 1), padding="VALID",
            dimension_numbers=_DIMNUMS,
        )  # (n, resX, resY, K)

        if self.normalize_patches:
            ones_spec = (1, S, S, C)  # window over the whole patch
            p_sum = jax.lax.reduce_window(
                X, 0.0, jax.lax.add, ones_spec, (1, 1, 1, 1), "valid"
            ).sum(axis=-1, keepdims=True)
            p_sumsq = jax.lax.reduce_window(
                X * X, 0.0, jax.lax.add, ones_spec, (1, 1, 1, 1), "valid"
            ).sum(axis=-1, keepdims=True)
            mu = p_sum / m
            var = (p_sumsq - p_sum * mu) / (m - 1)
            sd = jnp.sqrt(var + self.var_constant)
            f_sum = self.filters.sum(axis=1)  # (K,)
            conv = (conv - mu * f_sum) / sd

        if self.whitener is not None:
            bias = self.whitener.means @ self.filters.T  # (K,)
            conv = conv - bias
        return conv

    @staticmethod
    def build(
        filter_images,
        img_x: int,
        img_y: int,
        img_channels: int,
        whitener=None,
        normalize_patches: bool = True,
        var_constant: float = 10.0,
        flip_filters: bool = False,
    ) -> "Convolver":
        """From (k, S, S, C) filter images, whitening them if a whitener is
        given (parity: Convolver.apply:61-91)."""
        f = jnp.asarray(filter_images)
        if flip_filters:
            f = f[:, ::-1, ::-1, :]
        packed = pack_filter_images(f)
        if whitener is not None:
            packed = whitener.transform(packed) @ whitener.whitener.T
        return Convolver(
            packed, img_x, img_y, img_channels, whitener,
            normalize_patches, var_constant,
        )


class Pooler(Transformer):
    """Strided window pooling (parity: Pooler.scala:21-84). Pool centers
    start at poolSize/2 and step by ``stride``; windows clip at the image
    edge. ``pixel_fn`` maps pixels before pooling; ``pool_fn`` is 'sum',
    'max' or 'mean'."""

    def __init__(
        self,
        stride: int,
        pool_size: int,
        pixel_fn: Optional[Callable] = None,
        pool_fn: str = "sum",
    ):
        self.stride = stride
        self.pool_size = pool_size
        self.pixel_fn = pixel_fn
        if pool_fn not in ("sum", "max", "mean"):
            raise ValueError(f"unsupported pool_fn {pool_fn}")
        self.pool_fn = pool_fn

    def trace_batch(self, X):
        ps, st = self.pool_size, self.stride
        start = ps // 2
        # The reference window is [x−ps/2, x+ps/2) with integer division —
        # 2·(ps//2) wide, NOT ps wide for odd ps (Pooler.scala:56-59).
        w = 2 * (ps // 2)
        n, xd, yd, c = X.shape
        if self.pixel_fn is not None:
            X = self.pixel_fn(X)
        npx = max(1, -(-(xd - start) // st))  # ceil
        npy = max(1, -(-(yd - start) // st))
        # pad so every (possibly clipped) window fits; identity element pad
        ext_x = (npx - 1) * st + w
        ext_y = (npy - 1) * st + w
        init = -jnp.inf if self.pool_fn == "max" else 0.0
        X = jnp.pad(
            X,
            ((0, 0), (0, max(0, ext_x - xd)), (0, max(0, ext_y - yd)), (0, 0)),
            constant_values=init if self.pool_fn == "max" else 0.0,
        )
        op = jax.lax.max if self.pool_fn == "max" else jax.lax.add
        out = jax.lax.reduce_window(
            X, init, op, (1, w, w, 1), (1, st, st, 1), "valid"
        )
        out = out[:, :npx, :npy, :]
        if self.pool_fn == "mean":
            out = out / (ps * ps)
        return out


class Windower(Transformer):
    """All windowSize×windowSize patches stepping by ``stride``; a batch of n
    images becomes a batch of n·numWindows patch images
    (parity: Windower.scala:13-55)."""

    def __init__(self, stride: int, window_size: int):
        self.stride = stride
        self.window_size = window_size

    def trace_batch(self, X):
        n, xd, yd, c = X.shape
        w, st = self.window_size, self.stride
        nx = len(range(0, xd - w + 1, st))
        ny = len(range(0, yd - w + 1, st))
        # w² shifted slices instead of nx·ny dynamic ones:
        # shifted[dx][dy][n, xi, yi, c] = X[n, xi·st+dx, yi·st+dy, c]
        rows = []
        for dx in range(w):
            cols = []
            for dy in range(w):
                cols.append(
                    X[:, dx : dx + (nx - 1) * st + 1 : st,
                      dy : dy + (ny - 1) * st + 1 : st, :]
                )
            rows.append(jnp.stack(cols, axis=-2))  # (n, nx, ny, w(dy), c)
        patches = jnp.stack(rows, axis=-3)  # (n, nx, ny, w(dx), w(dy), c)
        # reference emission order: per image, for x, for y
        return patches.reshape(n * nx * ny, w, w, c)


class Cropper(Transformer):
    """Fixed crop [startX,endX)×[startY,endY)
    (parity: Cropper.scala:18)."""

    def __init__(self, start_x: int, start_y: int, end_x: int, end_y: int):
        self.start_x, self.start_y = start_x, start_y
        self.end_x, self.end_y = end_x, end_y

    def trace_batch(self, X):
        return X[:, self.start_x : self.end_x, self.start_y : self.end_y, :]


class RandomPatcher(Transformer):
    """``num_patches`` random windows per image, fresh randomness per batch
    (parity: RandomPatcher.scala:16-47)."""

    def __init__(
        self, num_patches: int, patch_size_x: int, patch_size_y: int,
        seed: int = 0,
    ):
        self.num_patches = num_patches
        self.patch_size_x = patch_size_x
        self.patch_size_y = patch_size_y
        self._rng = np.random.default_rng(seed)

    def apply_batch(self, data: Dataset) -> Dataset:
        X = Dataset.of(data).to_array()
        n, xd, yd, c = X.shape
        px, py = self.patch_size_x, self.patch_size_y
        out = []
        for _ in range(self.num_patches):
            xs = self._rng.integers(0, xd - px + 1, size=n)
            ys = self._rng.integers(0, yd - py + 1, size=n)
            idx_x = xs[:, None] + np.arange(px)[None, :]
            idx_y = ys[:, None] + np.arange(py)[None, :]
            patch = X[
                jnp.arange(n)[:, None, None],
                jnp.asarray(idx_x)[:, :, None],
                jnp.asarray(idx_y)[:, None, :],
                :,
            ]
            out.append(patch)
        # per-image grouping (reference emits numPatches per image in turn:
        # RandomPatcher.scala:34)
        stacked = jnp.stack(out, axis=1)  # (n, num_patches, px, py, c)
        return Dataset(
            stacked.reshape(-1, px, py, X.shape[-1]), batched=True
        )


class CenterCornerPatcher(Transformer):
    """Center + four corner crops, optionally with horizontal flips
    (parity: CenterCornerPatcher.scala:18-60)."""

    def __init__(self, patch_size_x: int, patch_size_y: int,
                 horizontal_flips: bool = False):
        self.px = patch_size_x
        self.py = patch_size_y
        self.horizontal_flips = horizontal_flips

    def trace_batch(self, X):
        n, xd, yd, c = X.shape
        px, py = self.px, self.py
        starts = [
            (0, 0),
            (xd - px, 0),
            (0, yd - py),
            (xd - px, yd - py),
            ((xd - px) // 2, (yd - py) // 2),
        ]
        crops = [X[:, sx : sx + px, sy : sy + py, :] for sx, sy in starts]
        if self.horizontal_flips:
            # reference emits (crop, flipped-crop) pairs per image
            # (CenterCornerPatcher.scala:41-42)
            crops = [
                v for cr in crops for v in (cr, jnp.flip(cr, axis=2))
            ]
        # per-image grouping: img0's crops first, then img1's …
        stacked = jnp.stack(crops, axis=1)  # (n, ncrops, px, py, c)
        return stacked.reshape(-1, self.px, self.py, X.shape[-1])


class RandomImageTransformer(Transformer):
    """Random horizontal flip per image (parity:
    RandomImageTransformer.scala:16 — the reference's only stock transform is
    flip with probability 0.5)."""

    def __init__(self, flip_chance: float = 0.5, seed: int = 0):
        self.flip_chance = flip_chance
        self._rng = np.random.default_rng(seed)

    def apply_batch(self, data: Dataset) -> Dataset:
        X = Dataset.of(data).to_array()
        flips = self._rng.random(X.shape[0]) < self.flip_chance
        flipped = jnp.flip(X, axis=2)
        mask = jnp.asarray(flips)[:, None, None, None]
        return Dataset(jnp.where(mask, flipped, X), batched=True)


class LabelExtractor(Transformer):
    """(label, image) item → label (parity: LabeledImageExtractors.scala:9-18).
    Loaders here usually hand out LabeledData directly; these extractors keep
    the reference's RDD[LabeledImage] composition style available."""

    def apply(self, item):
        return item[0]


class ImageExtractor(Transformer):
    """(label, image) item → image (parity: LabeledImageExtractors.scala:20-24)."""

    def apply(self, item):
        return item[1]


class MultiLabelExtractor(Transformer):
    """(label_set, image) item → label set
    (parity: LabeledImageExtractors.scala:26-32)."""

    def apply(self, item):
        return item[0]
