"""Local Color Statistics descriptors, batched.

Parity: nodes/images/LCSExtractor.scala:25-130 — per-channel box-filter means
and standard deviations of subPatchSize² windows, sampled at a neighborhood
grid around each keypoint; values interleaved (mean, std) per neighbor per
channel. The per-pixel loops become two box convs and static gathers.

Output per image: (numLCSValues, numPoolsX·numPoolsY) with descriptor index
x_idx · numPoolsY + y_idx, matching the reference layout.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...workflow.transformer import Transformer
from .daisy import _sep_conv_same


class LCSExtractor(Transformer):
    def __init__(self, stride: int, stride_start: int, sub_patch_size: int):
        self.stride = stride
        self.stride_start = stride_start
        self.sub_patch_size = sub_patch_size

    def trace_batch(self, X):
        """(n, X, Y, C) → (n, numLCSValues, numDesc)."""
        X = jnp.asarray(X).astype(jnp.float32)
        n, xd, yd, nc = X.shape
        sp = self.sub_patch_size
        ones = np.full(sp, 1.0 / sp)

        kx = np.arange(self.stride_start, xd - self.stride_start, self.stride)
        ky = np.arange(self.stride_start, yd - self.stride_start, self.stride)
        npx, npy = len(kx), len(ky)

        # neighborhood offsets (LCSExtractor.scala:41-47)
        start = -2 * sp + sp // 2 - 1
        end = sp + sp // 2 - 1
        offsets = list(range(start, end + 1, sp))

        # box means/stds per channel: (n, X, Y)
        means_c, stds_c = [], []
        for c in range(nc):
            ch = X[..., c]
            m = _sep_conv_same(ch, ones, ones)
            sq = _sep_conv_same(ch * ch, ones, ones)
            sd = jnp.sqrt(jnp.maximum(sq - m * m, 0.0))
            means_c.append(m)
            stds_c.append(sd)

        cols = []  # feature rows in lcsIdx order: c slow, (nx, ny), (mean,std)
        for c in range(nc):
            for nx in offsets:
                for ny in offsets:
                    xs = jnp.asarray(np.clip(kx + nx, 0, xd - 1))
                    ys = jnp.asarray(np.clip(ky + ny, 0, yd - 1))
                    m = means_c[c][:, xs, :][:, :, ys].reshape(n, npx * npy)
                    s = stds_c[c][:, xs, :][:, :, ys].reshape(n, npx * npy)
                    cols.append(m)
                    cols.append(s)
        return jnp.stack(cols, axis=1)  # (n, numLCSValues, numDesc)

    def apply(self, x):
        return self.trace_batch(jnp.asarray(x)[None])[0]
