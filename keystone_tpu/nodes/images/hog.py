"""Histogram-of-gradients features (Felzenszwalb voc-release5 variant).

Parity: nodes/images/HogExtractor.scala:27-296 (itself a port of
voc-dpm features.cc). The per-pixel loops become batched array ops: the
bilinear scatter into cells exploits that cell indices and bilinear weights
depend only on pixel *position* (static), while only the orientation snap and
magnitude are data-dependent — so the histogram build is four
``segment_sum``s over static segment ids.

Output per image: (numXCells−2)·(numYCells−2) rows × 32 features, row index
y + x·numYCellsWithFeatures — the reference's layout.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ...workflow.transformer import Transformer

_EPS = 1e-4
_UU = np.array([1.0, 0.9397, 0.766, 0.5, 0.1736,
                -0.1736, -0.5, -0.766, -0.9397])
_VV = np.array([0.0, 0.342, 0.6428, 0.866, 0.9848,
                0.9848, 0.866, 0.6428, 0.342])


class HogExtractor(Transformer):
    def __init__(self, bin_size: int):
        self.bin_size = bin_size

    def trace_batch(self, X):
        """(n, X, Y, C) → (n, numCellsWithFeatures, 32)."""
        X = jnp.asarray(X).astype(jnp.float32)
        n, xd, yd, nc = X.shape
        b = self.bin_size
        # round half UP like Scala math.round (Python round() is banker's)
        n_x = int(math.floor(xd / b + 0.5))
        n_y = int(math.floor(yd / b + 0.5))
        vis_x, vis_y = n_x * b, n_y * b

        # pixel grid 1..vis-2 (the reference's loop bounds)
        pxs = np.arange(1, vis_x - 1)
        pys = np.arange(1, vis_y - 1)
        P = len(pxs) * len(pys)

        sub = X[:, : min(vis_x, xd), : min(vis_y, yd), :]
        # pad if rounding made the visible area larger than the image
        if vis_x > xd or vis_y > yd:
            sub = jnp.pad(
                sub,
                ((0, 0), (0, max(0, vis_x - xd)), (0, max(0, vis_y - yd)),
                 (0, 0)),
                mode="edge",
            )

        dx = (sub[:, 2:, :, :] - sub[:, :-2, :, :])[:, :, 1:-1, :]
        dy = (sub[:, :, 2:, :] - sub[:, :, :-2, :])[:, 1:-1, :, :]
        mag_sq = dx * dx + dy * dy
        best_c = jnp.argmax(mag_sq, axis=-1)  # ties: lowest idx (ref scans
        # channels high→low with strict >, i.e. lowest wins ties too)
        dx = jnp.take_along_axis(dx, best_c[..., None], axis=-1)[..., 0]
        dy = jnp.take_along_axis(dy, best_c[..., None], axis=-1)[..., 0]
        mag = jnp.sqrt(jnp.take_along_axis(
            mag_sq, best_c[..., None], axis=-1)[..., 0])

        uu = jnp.asarray(_UU, dtype=X.dtype)
        vv = jnp.asarray(_VV, dtype=X.dtype)
        dots = dy[..., None] * uu + dx[..., None] * vv  # (n, px, py, 9)
        both = jnp.concatenate([dots, -dots], axis=-1)  # o and o+9
        o_idx = jnp.argmax(both, axis=-1)               # (n, px, py)

        # weighted orientation one-hots, flattened over pixels
        contrib = jax.nn.one_hot(o_idx, 18, dtype=X.dtype) * mag[..., None]
        contrib = contrib.reshape(n, P, 18)

        # static bilinear geometry per pixel position
        xp = (pxs + 0.5) / b - 0.5
        yp = (pys + 0.5) / b - 0.5
        ixp = np.floor(xp).astype(np.int64)
        iyp = np.floor(yp).astype(np.int64)
        vx0 = (xp - ixp)[:, None] * np.ones((1, len(pys)))
        vy0 = np.ones((len(pxs), 1)) * (yp - iyp)[None, :]
        IX = ixp[:, None] * np.ones((1, len(pys)), dtype=np.int64)
        IY = np.ones((len(pxs), 1), dtype=np.int64) * iyp[None, :]

        hist = jnp.zeros((n, n_x * n_y, 18), dtype=X.dtype)
        corners = [
            (IX, IY, (1 - vx0) * (1 - vy0)),
            (IX, IY + 1, (1 - vx0) * vy0),
            (IX + 1, IY, vx0 * (1 - vy0)),
            (IX + 1, IY + 1, vx0 * vy0),
        ]
        for cx, cy, w in corners:
            valid = (cx >= 0) & (cx < n_x) & (cy >= 0) & (cy < n_y)
            seg = np.where(valid, cx + cy * n_x, n_x * n_y)  # invalid → bin
            seg_flat = jnp.asarray(seg.reshape(P))
            w_flat = jnp.asarray(
                (w * valid).reshape(P, 1), dtype=X.dtype
            )
            summed = jax.ops.segment_sum(
                jnp.einsum("npo,p->npo", contrib, w_flat[:, 0]).swapaxes(0, 1),
                seg_flat,
                num_segments=n_x * n_y + 1,
            )  # (cells+1, n, 18)
            hist = hist + jnp.swapaxes(summed[:-1], 0, 1)

        # cell energies: sum over 9 of (h_o + h_{o+9})²
        energy = jnp.sum(
            (hist[..., :9] + hist[..., 9:]) ** 2, axis=-1
        ).reshape(n, n_y, n_x)  # index [y, x] to mirror x + y·n_x layout

        nxf, nyf = max(n_x - 2, 0), max(n_y - 2, 0)
        if nxf == 0 or nyf == 0:
            return jnp.zeros((n, 0, 32), dtype=X.dtype)

        # block norms: 1/sqrt of 2×2 neighborhoods of cell energies
        e2 = (
            energy[:, :-1, :-1] + energy[:, :-1, 1:]
            + energy[:, 1:, :-1] + energy[:, 1:, 1:]
        )  # (n, n_y−1, n_x−1): sum of 2×2 block anchored at (y, x)
        inv = 1.0 / jnp.sqrt(e2 + _EPS)
        # n1..n4 for output cell (x, y) — anchored per the reference offsets
        n1 = inv[:, 1 : 1 + nyf, 1 : 1 + nxf]
        n2 = inv[:, 1 : 1 + nyf, 0:nxf]
        n3 = inv[:, 0:nyf, 1 : 1 + nxf]
        n4 = inv[:, 0:nyf, 0:nxf]

        hist_g = hist.reshape(n, n_y, n_x, 18)
        hcell = hist_g[:, 1 : 1 + nyf, 1 : 1 + nxf, :]  # (n, nyf, nxf, 18)

        h1 = jnp.minimum(hcell * n1[..., None], 0.2)
        h2 = jnp.minimum(hcell * n2[..., None], 0.2)
        h3 = jnp.minimum(hcell * n3[..., None], 0.2)
        h4 = jnp.minimum(hcell * n4[..., None], 0.2)
        contrast_sensitive = 0.5 * (h1 + h2 + h3 + h4)
        t1 = jnp.sum(h1, axis=-1)
        t2 = jnp.sum(h2, axis=-1)
        t3 = jnp.sum(h3, axis=-1)
        t4 = jnp.sum(h4, axis=-1)

        hsum = hcell[..., :9] + hcell[..., 9:]
        i1 = jnp.minimum(hsum * n1[..., None], 0.2)
        i2 = jnp.minimum(hsum * n2[..., None], 0.2)
        i3 = jnp.minimum(hsum * n3[..., None], 0.2)
        i4 = jnp.minimum(hsum * n4[..., None], 0.2)
        contrast_insensitive = 0.5 * (i1 + i2 + i3 + i4)

        texture = 0.2357 * jnp.stack([t1, t2, t3, t4], axis=-1)
        zeros = jnp.zeros_like(t1)[..., None]
        feats = jnp.concatenate(
            [contrast_sensitive, contrast_insensitive, texture, zeros],
            axis=-1,
        )  # (n, nyf, nxf, 32)
        # row index y + x·nyf → transpose to (x, y) then flatten
        return jnp.swapaxes(feats, 1, 2).reshape(n, nxf * nyf, 32)

    def apply(self, x):
        return self.trace_batch(jnp.asarray(x)[None])[0]
