"""DAISY dense descriptors (Tola, Lepetit, Fua; PAMI 2010), batched.

Parity: nodes/images/DaisyExtractor.scala:28-201. The per-image loops —
separable gradient convs, H rectified directional-gradient maps, a cascade of
Q Gaussian blurs, ring-sample histograms on a keypoint grid — become batched
XLA convs and static gathers; the whole extractor is one traceable function.

Output per image: (H·(T·Q+1), numDesc) float matrix, column layout matching
the reference (center histogram first, then angle-major ring histograms),
descriptor index = x_idx · resultWidth + y_idx.
"""

from __future__ import annotations

import math
from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from ...workflow.transformer import Transformer

_DN = ("NHWC", "HWIO", "NHWC")


def _sep_conv_same(X, xf: np.ndarray, yf: np.ndarray):
    """Zero-padded 'same' separable conv of (n, X, Y) maps (parity:
    ImageUtils.conv2D:226-344, which zero-pads and keeps the input size)."""
    Xp = X[..., None]
    kx = jnp.asarray(xf, dtype=X.dtype).reshape(-1, 1, 1, 1)
    ky = jnp.asarray(yf, dtype=X.dtype).reshape(1, -1, 1, 1)
    px = (len(xf) - 1) // 2, len(xf) - 1 - (len(xf) - 1) // 2
    py = (len(yf) - 1) // 2, len(yf) - 1 - (len(yf) - 1) // 2
    out = jax.lax.conv_general_dilated(
        Xp, kx, (1, 1), [px, (0, 0)], dimension_numbers=_DN
    )
    out = jax.lax.conv_general_dilated(
        out, ky, (1, 1), [(0, 0), py], dimension_numbers=_DN
    )
    return out[..., 0]


class DaisyExtractor(Transformer):
    """(parity: DaisyExtractor.scala:28; defaults match)."""

    def __init__(self, daisy_t: int = 8, daisy_q: int = 3, daisy_r: int = 7,
                 daisy_h: int = 8, pixel_border: int = 16, stride: int = 4,
                 patch_size: int = 24):
        self.T = daisy_t
        self.Q = daisy_q
        self.R = daisy_r
        self.H = daisy_h
        self.pixel_border = pixel_border
        self.stride = stride
        self.patch_size = patch_size
        self.feature_threshold = 1e-8
        conv_threshold = 1e-6

        # blur cascade σ² increments (DaisyExtractor.scala:40-55)
        sigma_sq = [
            (self.R * n / (2.0 * self.Q)) ** 2 for n in range(self.Q + 1)
        ]
        diffs = [b - a for a, b in zip(sigma_sq, sigma_sq[1:])]
        self.g: List[np.ndarray] = []
        for t in diffs:
            rad = int(
                math.ceil(
                    math.sqrt(
                        -2 * t * math.log(conv_threshold)
                        - t * math.log(2 * math.pi * t)
                    )
                )
            )
            xs = np.arange(-rad, rad + 1, dtype=np.float64)
            self.g.append(
                (np.exp(-(xs ** 2) / (2 * t)) / math.sqrt(2 * math.pi * t))
                .astype(np.float32)
            )

    @property
    def feature_size(self) -> int:
        return self.H * (self.T * self.Q + 1)

    def trace_batch(self, X):
        """(n, X, Y, 1) grayscale batch → (n, featureSize, numDesc)."""
        gray = jnp.asarray(X)[..., 0].astype(jnp.float32)
        n, xd, yd = gray.shape
        f1 = np.array([1.0, 0.0, -1.0])
        f2 = np.array([1.0, 2.0, 1.0])
        ix = _sep_conv_same(gray, f1, f2)
        iy = _sep_conv_same(gray, f2, f1)

        # H rectified directional-gradient maps, then the Q-blur cascade
        layers = []  # layers[l][a]: (n, X, Y)
        first = []
        for a in range(self.H):
            ang = 2 * math.pi * a / self.H
            m = jnp.maximum(math.cos(ang) * ix + math.sin(ang) * iy, 0.0)
            first.append(_sep_conv_same(m, self.g[0], self.g[0]))
        layers.append(first)
        for l in range(1, self.Q):
            layers.append(
                [
                    _sep_conv_same(prev, self.g[l], self.g[l])
                    for prev in layers[l - 1]
                ]
            )

        kx = np.arange(self.pixel_border, xd - self.pixel_border, self.stride)
        ky = np.arange(self.pixel_border, yd - self.pixel_border, self.stride)
        rh, rw = len(kx), len(ky)

        # stack each level once — hist_at is called 1 + Q·T times
        level_stacks = [
            jnp.stack(layers[l], axis=-1) for l in range(self.Q)
        ]  # each (n, X, Y, H)

        def hist_at(level: int, dx: int, dy: int):
            """(n, rh, rw, H) histograms sampled at grid + offset."""
            xs = jnp.asarray(np.clip(kx + dx, 0, xd - 1))
            ys = jnp.asarray(np.clip(ky + dy, 0, yd - 1))
            return level_stacks[level][:, xs, :, :][:, :, ys, :]

        def norm_hist(h):
            nrm = jnp.linalg.norm(h, axis=-1, keepdims=True)
            return jnp.where(
                nrm > self.feature_threshold, h / jnp.maximum(nrm, 1e-30), 0.0
            )

        ndesc = rh * rw
        out = jnp.zeros((n, ndesc, self.feature_size), dtype=jnp.float32)
        center = norm_hist(hist_at(0, 0, 0)).reshape(n, ndesc, self.H)
        out = out.at[:, :, : self.H].set(center)

        for l in range(self.Q):
            cur_rad = self.R * (1.0 + l) / self.Q
            for a in range(self.T):
                theta = 2 * math.pi * (a - 1) / self.T  # note the −1 (ref :77)
                dx = int(round(cur_rad * math.sin(theta)))
                dy = int(round(cur_rad * math.cos(theta)))
                h = norm_hist(hist_at(l, dx, dy)).reshape(n, ndesc, self.H)
                col = self.H + a * self.Q * self.H + l * self.H
                out = out.at[:, :, col : col + self.H].set(h)

        return jnp.swapaxes(out, 1, 2)  # (n, featureSize, numDesc)

    def apply(self, x):
        return self.trace_batch(jnp.asarray(x)[None])[0]
