from .daisy import DaisyExtractor
from .fisher_vector import FisherVector, GMMFisherVectorEstimator
from .hog import HogExtractor
from .lcs import LCSExtractor
from .sift import SIFTExtractor
from .core import (
    CenterCornerPatcher,
    ImageExtractor,
    LabelExtractor,
    MultiLabelExtractor,
    Convolver,
    Cropper,
    GrayScaler,
    ImageVectorizer,
    PixelScaler,
    Pooler,
    RandomImageTransformer,
    RandomPatcher,
    SymmetricRectifier,
    Windower,
    images_from_vectors,
    pack_filter_images,
    vectorize_images,
)

__all__ = [
    "DaisyExtractor",
    "FisherVector",
    "GMMFisherVectorEstimator",
    "HogExtractor",
    "LCSExtractor",
    "SIFTExtractor",
    "CenterCornerPatcher",
    "ImageExtractor",
    "LabelExtractor",
    "MultiLabelExtractor",
    "Convolver",
    "Cropper",
    "GrayScaler",
    "ImageVectorizer",
    "PixelScaler",
    "Pooler",
    "RandomImageTransformer",
    "RandomPatcher",
    "SymmetricRectifier",
    "Windower",
    "images_from_vectors",
    "pack_filter_images",
    "vectorize_images",
]
