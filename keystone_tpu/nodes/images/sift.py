"""Dense multi-scale SIFT as a batched convolution stack.

Parity target: the reference's native path — utils/external/VLFeat.scala:18 →
src/main/cpp/VLFeat.cxx:40-210 (per-scale VlDsiftFilter with flat window,
windowSize=1.5, magnif=6, contrast threshold 0.005, ×512 short quantization)
wrapped by nodes/images/external/SIFTExtractor.scala:16.

The JNI/C++ pipeline becomes pure XLA: per scale —
Gaussian smooth (separable conv, σ = binSize/6) → central-difference
gradients → magnitude-weighted linear interpolation into 8 orientation maps →
4×4 spatial bins of side binSize pooled with a flat (box) window → sample the
keypoint grid (step) → L2 normalize, clamp 0.2, renormalize → zero
low-contrast descriptors → quantize (×512, clamp 255). Everything batched
over images on the MXU; no per-image native calls.

Descriptor layout matches vl_dsift: element (t, i, j) at t + 8·i + 32·j for
orientation t, x-bin i, y-bin j. Output per image: (128, N) float matrix, the
same shape external.SIFTExtractor emits.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Transformer

_NBP = 4      # spatial bins per side
_NBO = 8      # orientation bins
_MAGNIF = 6.0
_CONTRAST_THRESHOLD = 0.005
_WINDOW_SIZE = 1.5


def _gaussian_kernel1d(sigma: float) -> np.ndarray:
    radius = max(1, int(math.ceil(4.0 * sigma)))
    x = np.arange(-radius, radius + 1)
    k = np.exp(-0.5 * (x / sigma) ** 2)
    return (k / k.sum()).astype(np.float32)


def _smooth(X, sigma: float):
    """Separable Gaussian blur of (n, X, Y) maps via two 1-D convs
    (σ=0 → identity); edge-replicated padding like vl_imsmooth."""
    if sigma <= 0:
        return X
    k = jnp.asarray(_gaussian_kernel1d(sigma))
    r = k.shape[0] // 2
    Xp = jnp.pad(X, [(0, 0), (r, r), (r, r)], mode="edge")[..., None]
    kx = k.reshape(-1, 1, 1, 1)  # (H, W, I, O)
    ky = k.reshape(1, -1, 1, 1)
    dn = ("NHWC", "HWIO", "NHWC")
    out = jax.lax.conv_general_dilated(
        Xp, kx, (1, 1), "VALID", dimension_numbers=dn
    )
    out = jax.lax.conv_general_dilated(
        out, ky, (1, 1), "VALID", dimension_numbers=dn
    )
    return out[..., 0]


def _orientation_maps(X):
    """(n, X, Y) grayscale → (n, X, Y, 8) magnitude-weighted orientation
    histogram maps with linear interpolation between adjacent bins."""
    gx = (jnp.roll(X, -1, axis=1) - jnp.roll(X, 1, axis=1)) * 0.5
    gy = (jnp.roll(X, -1, axis=2) - jnp.roll(X, 1, axis=2)) * 0.5
    # replicate edges (roll wraps; fix borders with one-sided differences)
    gx = gx.at[:, 0, :].set(X[:, 1, :] - X[:, 0, :])
    gx = gx.at[:, -1, :].set(X[:, -1, :] - X[:, -2, :])
    gy = gy.at[:, :, 0].set(X[:, :, 1] - X[:, :, 0])
    gy = gy.at[:, :, -1].set(X[:, :, -1] - X[:, :, -2])

    mag = jnp.sqrt(gx * gx + gy * gy)
    theta = jnp.arctan2(gy, gx) % (2.0 * math.pi)
    t = theta / (2.0 * math.pi) * _NBO
    t0 = jnp.floor(t)
    frac = t - t0
    t0 = t0.astype(jnp.int32) % _NBO
    t1 = (t0 + 1) % _NBO
    w0 = mag * (1.0 - frac)
    w1 = mag * frac
    maps = (
        jax.nn.one_hot(t0, _NBO, dtype=X.dtype) * w0[..., None]
        + jax.nn.one_hot(t1, _NBO, dtype=X.dtype) * w1[..., None]
    )
    return maps


def _box_pool(maps, width: int):
    """Box-sum each orientation map over width×width windows ('flat window')
    → (n, X-w+1, Y-w+1, 8). Separable: two 1-D passes cost 2·W adds per
    output instead of the 2-D window's W²."""
    out = jax.lax.reduce_window(
        maps, 0.0, jax.lax.add, (1, width, 1, 1), (1, 1, 1, 1), "valid"
    )
    return jax.lax.reduce_window(
        out, 0.0, jax.lax.add, (1, 1, width, 1), (1, 1, 1, 1), "valid"
    )


@partial(jax.jit, static_argnames=("bin_size", "step"))
def _sift_one_scale(gray, bin_size: int, step: int):
    """Descriptors for one scale over the keypoint grid.

    gray: (n, X, Y) already smoothed. Returns (n, nkx·nky, 128) float
    descriptors (un-normalized binning already weighted), plus norms.
    """
    n, xd, yd = gray.shape
    maps = _orientation_maps(gray)
    window = max(1, int(round(bin_size * _WINDOW_SIZE)))
    pooled = _box_pool(maps, window)  # value at p = sum over box anchored at p

    # Descriptor geometry: 4×4 bins of side bin_size; descriptor extent
    # 4·bin_size. Anchor descriptors at top-left corner positions.
    extent = _NBP * bin_size
    max_x = xd - extent
    max_y = yd - extent
    if max_x < 0 or max_y < 0:
        return jnp.zeros((n, 0, _NBP * _NBP * _NBO)), jnp.zeros((n, 0))
    kx = list(range(0, max_x + 1, step))
    ky = list(range(0, max_y + 1, step))

    # bin (i, j) of descriptor at (x, y) pools the box anchored at
    # (x + i·bin − (window−bin)//2, …) — centered flat window per bin.
    # NOTE: these advanced-index gathers were once rewritten as edge-pad
    # + stride-`step` slices (27% less HBM traffic by XLA's own count) —
    # and ran 1.5× SLOWER: stride-3 slices on the second-minor dim defeat
    # the TPU's vectorized loads worse than the gathers do. Measured,
    # reverted; don't repeat.
    off = (window - bin_size) // 2
    px_max = pooled.shape[1] - 1
    py_max = pooled.shape[2] - 1

    feats = []
    for j in range(_NBP):        # y bins slow
        for i in range(_NBP):    # x bins
            xs = np.clip(np.asarray(kx) + i * bin_size - off, 0, px_max)
            ys = np.clip(np.asarray(ky) + j * bin_size - off, 0, py_max)
            block = pooled[:, jnp.asarray(xs), :, :][:, :, jnp.asarray(ys), :]
            feats.append(block)  # (n, nkx, nky, 8)
    # layout: t + 8·i + 32·j  → stack bins in (j, i) order then interleave o
    desc = jnp.stack(feats, axis=3)  # (n, nkx, nky, 16, 8)
    desc = desc.reshape(n, len(kx) * len(ky), _NBP * _NBP * _NBO)

    norms = jnp.linalg.norm(desc, axis=-1)
    # vl_dsift norm semantics: norm before clamping used for the contrast test
    normed = desc / jnp.maximum(norms[..., None], 1e-12)
    normed = jnp.minimum(normed, 0.2)
    n2 = jnp.linalg.norm(normed, axis=-1, keepdims=True)
    normed = normed / jnp.maximum(n2, 1e-12)
    return normed, norms


class SIFTExtractor(Transformer):
    """Dense multi-scale SIFT over grayscale images (interface parity:
    SIFTExtractor.scala:10 / external/SIFTExtractor.scala:16).

    Input: (n, X, Y, 1) grayscale batch in [0, 1]. Output: list of (128, N)
    float matrices (N = Σ grid points over scales), scaled like the
    reference's short quantization (×512, clamp 255).
    """

    def __init__(self, step: int = 3, bin_size: int = 4,
                 num_scales: int = 4, scale_step: int = 0):
        self.step = step
        self.bin_size = bin_size
        self.num_scales = num_scales
        self.scale_step = scale_step

    def descriptors_batch(self, X) -> jnp.ndarray:
        """(n, X, Y, 1) → (n, N, 128) quantized descriptors."""
        gray = jnp.asarray(X)[..., 0].astype(jnp.float32)
        all_desc = []
        for scale in range(self.num_scales):
            bin_size = self.bin_size + 2 * scale  # VLFeat.cxx:71
            sigma = bin_size / _MAGNIF            # VLFeat.cxx:85
            smoothed = _smooth(gray, sigma)
            step = self.step + scale * self.scale_step
            desc, norms = _sift_one_scale(smoothed, bin_size, step)
            # zero low-contrast descriptors (VLFeat.cxx:62,146)
            desc = jnp.where(
                (norms > _CONTRAST_THRESHOLD)[..., None], desc, 0.0
            )
            # short quantization: ×512, clamp 255 (VLFeat.cxx:237-249)
            desc = jnp.minimum(jnp.floor(desc * 512.0), 255.0)
            all_desc.append(desc)
        return jnp.concatenate(all_desc, axis=1)

    def trace_batch(self, X):
        # (n, N, 128) → (n, 128, N): the reference's column-major descriptor
        # matrix shape (external/SIFTExtractor.scala:27-33)
        return jnp.swapaxes(self.descriptors_batch(X), 1, 2)

    def apply(self, x):
        return self.trace_batch(jnp.asarray(x)[None])[0]
