"""Fisher Vector encoding (parity: nodes/images/FisherVector.scala:21-94 and
the native enceval path external/FisherVector.scala:17 — the formula from
Sanchez et al. IJCV'13; the JNI fast path is subsumed by running the same
matrix algebra on the MXU).

Input items are (d, n_desc) descriptor matrices; output (d, 2k) — first- and
second-order statistics per mixture component.

Note: the reference's fv2 line (FisherVector.scala:47) carries a stray ``.t``
on the ``(μ²−σ²)·diag(s0)`` term that only type-checks when d == k; the
published Sanchez et al. formula (and the enceval native implementation the
reference validates against) scale per column by s0 — implemented as intended
here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.jit import nestable_jit
from ..learning.gmm import (
    GaussianMixtureModel,
    GaussianMixtureModelEstimator,
    _posteriors,
)


@nestable_jit
def _fisher_vector(X, means, variances, weights, weight_threshold):
    """X: (n, d, m) batch of descriptor matrices; means/variances (d, k);
    weights (k,). Returns (n, d, 2k)."""
    n_desc = X.shape[-1]
    # posteriors per descriptor: (n, m, k)
    Xt = jnp.swapaxes(X, 1, 2)  # (n, m, d)
    q = jax.vmap(
        lambda xt: _posteriors(
            xt, means.T, variances.T, weights, weight_threshold
        )
    )(Xt)
    s0 = jnp.mean(q, axis=1)                       # (n, k)
    # precision=high like the GMM contractions (see gmm.py _PREC): the fv2
    # term subtracts products of these statistics, so bf16 GEMM noise there
    # is visible after the ±cancellation
    s1 = jnp.einsum("ndm,nmk->ndk", X, q, precision="high") / n_desc
    s2 = jnp.einsum("ndm,nmk->ndk", X * X, q, precision="high") / n_desc

    fv1 = (s1 - means * s0[:, None, :]) / (
        jnp.sqrt(variances) * jnp.sqrt(weights)
    )
    fv2 = (
        s2
        - 2.0 * means * s1
        + (means * means - variances) * s0[:, None, :]
    ) / (variances * jnp.sqrt(2.0 * weights))
    return jnp.concatenate([fv1, fv2], axis=-1)


class FisherVector(Transformer):
    """FV encoding transformer (parity: FisherVector, FisherVector.scala:21-55)."""

    def __init__(self, gmm: GaussianMixtureModel):
        self.gmm = gmm

    def trace_batch(self, X):
        return _fisher_vector(
            X.astype(jnp.float32),
            self.gmm.means.astype(jnp.float32),
            self.gmm.variances.astype(jnp.float32),
            self.gmm.weights.astype(jnp.float32),
            self.gmm.weight_threshold,
        )

    def apply(self, x):
        return self.trace_batch(jnp.asarray(x)[None])[0]


class GMMFisherVectorEstimator(Estimator):
    """Fit a GMM on descriptor columns, emit the FV transformer (parity:
    ScalaGMMFisherVectorEstimator / GMMFisherVectorEstimator,
    FisherVector.scala:66-94; the k≥32 native-vs-scala choice point vanishes —
    there is one on-device implementation)."""

    def __init__(self, k: int, **gmm_kwargs):
        self.k = k
        self.gmm_kwargs = gmm_kwargs

    def fit(self, data: Dataset) -> FisherVector:
        from ...utils.timing import phase

        data = Dataset.of(data)
        if data.is_batched:
            X = jnp.asarray(data.to_array())
            cols = jnp.transpose(X, (0, 2, 1)).reshape(-1, X.shape[1])
        else:
            import numpy as np

            cols = jnp.asarray(
                np.concatenate([np.asarray(i).T for i in data], axis=0)
            )
        with phase("gmm_fv.em_fit") as out:
            gmm = GaussianMixtureModelEstimator(
                self.k, **self.gmm_kwargs
            ).fit_matrix(cols)
            out.append(gmm.means)
        return FisherVector(gmm)
