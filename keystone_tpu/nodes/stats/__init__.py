from .core import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
    TermFrequency,
)

__all__ = [
    "TermFrequency",
    "ColumnSampler",
    "CosineRandomFeatures",
    "LinearRectifier",
    "NormalizeRows",
    "PaddedFFT",
    "RandomSignNode",
    "Sampler",
    "SignedHellingerMapper",
    "StandardScaler",
    "StandardScalerModel",
]
