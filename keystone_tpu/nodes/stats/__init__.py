from .core import (
    ColumnSampler,
    CosineRandomFeatures,
    LinearRectifier,
    NormalizeRows,
    PaddedFFT,
    RandomSignNode,
    Sampler,
    SignedHellingerMapper,
    StandardScaler,
    StandardScalerModel,
)

__all__ = [
    "ColumnSampler",
    "CosineRandomFeatures",
    "LinearRectifier",
    "NormalizeRows",
    "PaddedFFT",
    "RandomSignNode",
    "Sampler",
    "SignedHellingerMapper",
    "StandardScaler",
    "StandardScalerModel",
]
