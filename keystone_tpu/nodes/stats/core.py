"""Statistics / random-feature nodes.

Parity targets: ``nodes/stats/`` in the reference — PaddedFFT.scala:13,
CosineRandomFeatures.scala:19,49, RandomSignNode.scala:11,
StandardScaler.scala:16,38, LinearRectifier.scala:12, NormalizeRows.scala:10,
SignedHellingerMapper.scala:12,18, Sampling.scala:12,28.

Every numeric node here is a pure ``trace_batch`` over the stacked (n, d)
array: elementwise ops fuse into neighbouring matmuls under jit, the
random-feature GEMM rides the MXU, and the fit-side reductions (mean/var)
lower to psum over the mesh when the input is sharded.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...data.dataset import Dataset
from ...workflow.transformer import Estimator, Transformer
from ...utils.params import as_param


class PaddedFFT(Transformer):
    """Zero-pad each vector to the next power of two and return the real part
    of the first half of its FFT (parity: PaddedFFT.scala:13-21). d →
    2^ceil(log2 d) / 2 output features; rfft keeps XLA from computing the
    redundant conjugate half."""

    def trace_batch(self, X):
        d = X.shape[-1]
        padded = 1 << max(0, (d - 1)).bit_length()
        X = jnp.pad(X, [(0, 0)] * (X.ndim - 1) + [(0, padded - d)])
        # rfft returns padded/2+1 bins; the reference keeps bins [0, padded/2).
        return jnp.fft.rfft(X, axis=-1).real[..., : padded // 2]


class RandomSignNode(Transformer):
    """Elementwise multiply by a fixed random ±1 vector
    (parity: RandomSignNode.scala:11,19-24)."""

    def __init__(self, signs):
        self.signs = as_param(signs)

    @staticmethod
    def create(size: int, seed: int = 0) -> "RandomSignNode":
        signs = 2.0 * jax.random.bernoulli(
            jax.random.PRNGKey(seed), 0.5, (size,)
        ).astype(jnp.float32) - 1.0
        return RandomSignNode(signs)

    def trace_batch(self, X):
        return X * self.signs


class LinearRectifier(Transformer):
    """max(maxVal, x − alpha) (parity: LinearRectifier.scala:12-17)."""

    def __init__(self, max_val: float = 0.0, alpha: float = 0.0):
        self.max_val = max_val
        self.alpha = alpha

    def trace_batch(self, X):
        return jnp.maximum(self.max_val, X - self.alpha)


class NormalizeRows(Transformer):
    """Scale each row to unit L2 norm (zero rows pass through unchanged)."""

    def trace_batch(self, X):
        norm = jnp.linalg.norm(X, axis=-1, keepdims=True)
        return X / jnp.where(norm == 0, 1.0, norm)


class SignedHellingerMapper(Transformer):
    """x → sign(x)·√|x| (parity: SignedHellingerMapper.scala:12-16)."""

    def trace_batch(self, X):
        return jnp.sign(X) * jnp.sqrt(jnp.abs(X))


class TermFrequency(Transformer):
    """Seq of terms → (unique term, weighting(count)) pairs
    (parity: TermFrequency.scala:18-21). ``fun`` maps the raw count, e.g.
    ``TermFrequency(lambda x: math.log(x) + 1)``; defaults to identity."""

    def __init__(self, fun=None):
        self.fun = fun

    def apply(self, terms):
        from collections import Counter

        fun = self.fun or (lambda x: x)
        counts = Counter(
            tuple(t) if isinstance(t, list) else t for t in terms
        )
        return [(term, float(fun(c))) for term, c in counts.items()]


class CosineRandomFeatures(Transformer):
    """Random Fourier features cos(x Wᵀ + b)
    (parity: CosineRandomFeatures.scala:19-44; batched GEMM is the reference's
    mapPartitions + BLAS3 path, here one MXU matmul).

    W: (num_output_features, num_input_features); b: (num_output_features,).
    """

    def __init__(self, W, b):
        self.W = as_param(W)
        self.b = as_param(b)
        if self.b.shape[0] != self.W.shape[0]:
            raise ValueError("rows of W and size of b must match")

    @staticmethod
    def create(
        num_input_features: int,
        num_output_features: int,
        gamma: float,
        seed: int = 0,
    ) -> "CosineRandomFeatures":
        """Gaussian W scaled by gamma, uniform b in [0, 2π)
        (parity: CosineRandomFeatures.scala:49-61)."""
        kw, kb = jax.random.split(jax.random.PRNGKey(seed))
        W = gamma * jax.random.normal(
            kw, (num_output_features, num_input_features), dtype=jnp.float32
        )
        b = 2 * math.pi * jax.random.uniform(
            kb, (num_output_features,), dtype=jnp.float32
        )
        return CosineRandomFeatures(W, b)

    def trace_batch(self, X):
        return jnp.cos(X @ self.W.T + self.b)


@jax.jit
def _column_stats(X):
    # Sample variance (ddof=1), matching MultivariateOnlineSummarizer.
    return jnp.mean(X, axis=0), jnp.var(X, axis=0, ddof=1)


@jax.jit
def _chunk_center_stats(X):
    """One chunk's (column mean, CENTERED sum of squares) — the
    numerically-stable merge inputs for the streaming StandardScaler."""
    mean = jnp.mean(X, axis=0)
    diff = X - mean
    return mean, jnp.sum(diff * diff, axis=0)


def _chan_merge(a, b):
    """Chan/Welford merge of two (n, mean, M2) column-stat triples."""
    na, ma, sa = a
    nb, mb, sb = b
    tot = na + nb
    delta = mb - ma
    mean = ma + delta * (nb / tot)
    m2 = sa + sb + delta * delta * (na * nb / tot)
    return tot, mean, m2


class StandardScalerModel(Transformer):
    """(x − mean) / std; std of None means center-only
    (parity: StandardScaler.scala:16-32)."""

    def __init__(self, mean, std=None):
        self.mean = as_param(mean)
        self.std = as_param(std)

    def trace_batch(self, X):
        out = X - self.mean
        if self.std is not None:
            out = out / self.std
        return out


class StandardScaler(Estimator):
    """Fit column mean/std; degenerate stds (0/NaN/inf) become 1.0
    (parity: StandardScaler.scala:38-61). The treeAggregate summarizer
    collapses to jnp.mean/var — psum over the mesh when sharded."""

    def __init__(self, normalize_std_dev: bool = True, eps: float = 1e-12):
        self.normalize_std_dev = normalize_std_dev
        self.eps = eps

    def fitted_out_spec(self, fit_in, apply_in):
        # the fitted model is (x - mean)/std: spec-preserving
        return apply_in[0] if apply_in else None

    def fit(self, data: Dataset) -> StandardScalerModel:
        from ...data.chunked import ChunkedDataset

        if isinstance(data, ChunkedDataset):
            mean, var = self._streaming_stats(data)
        else:
            mean, var = _column_stats(data.to_array())
        if not self.normalize_std_dev:
            return StandardScalerModel(mean, None)
        std = jnp.sqrt(var)
        bad = jnp.isnan(std) | jnp.isinf(std) | (jnp.abs(std) < self.eps)
        std = jnp.where(bad, 1.0, std)
        return StandardScalerModel(mean, std)

    @staticmethod
    def _streaming_stats(data):
        """Column mean/var(ddof=1) of a chunked set in ONE pipelined scan
        — per-chunk centered statistics merged Chan/Welford-style (the
        raw sum-of-squares form cancels catastrophically in f32 when
        |mean| ≫ std) instead of materializing via ``to_array()``. Host
        chunk production overlaps the device reductions.

        Mesh-distributed like the streaming solvers: chunks round-robin
        across the data-axis lanes, each lane folds its own Chan triple
        (n, mean, M2) on its own device, and the lane triples merge across
        the mesh ONCE at finalize — O(1) collectives per scan. A 1-lane
        mesh runs the original sequential merge, bit-identical."""
        from ...parallel.lanes import gather_lane_partials, scan_lanes

        lanes = scan_lanes()
        it = data.chunks(lanes=lanes)
        lanes = getattr(it, "lanes", lanes)
        parts = [None] * lanes  # per-lane (n, mean, m2) Chan triples
        for i, chunk in enumerate(it):
            X = jnp.asarray(chunk)
            nc = int(X.shape[0])
            mc, m2c = _chunk_center_stats(X)
            lane = i % lanes
            if parts[lane] is None:
                parts[lane] = (nc, mc, m2c)
            else:
                parts[lane] = _chan_merge(parts[lane], (nc, mc, m2c))
        live = [p for p in parts if p is not None]
        if not live:
            raise ValueError("empty chunked dataset")
        # device partials hop to one chip (counts stay host), then the
        # same Chan merge combines the lanes in deterministic lane order
        gathered = gather_lane_partials(
            [(mc, m2c) for _, mc, m2c in live], scan=it
        )
        n, mean, m2 = (live[0][0],) + tuple(gathered[0])
        for (nc, _, _), (mc, m2c) in zip(live[1:], gathered[1:]):
            n, mean, m2 = _chan_merge((n, mean, m2), (nc, mc, m2c))
        # sample variance (ddof=1), matching _column_stats; n==1 yields a
        # zero m2 whose std the degenerate guard maps to 1.0
        var = m2 / max(n - 1, 1)
        return mean, var


class Sampler(Transformer):
    """Deterministic-seed sample of ``size`` rows without replacement
    (parity: Sampling.scala:28-33 takeSample). Operates dataset→dataset."""

    def __init__(self, size: int, seed: int = 42):
        self.size = size
        self.seed = seed

    def apply_batch(self, data: Dataset) -> Dataset:
        data = Dataset.of(data)
        n = len(data)
        k = min(self.size, n)
        idx = np.random.default_rng(self.seed).choice(n, size=k, replace=False)
        if data.is_batched:
            X = data.to_array()
            return Dataset(X[jnp.asarray(np.sort(idx))], batched=True)
        items = data.collect()
        return Dataset.from_items([items[i] for i in np.sort(idx)])

    def apply(self, x):
        return x


class ColumnSampler(Transformer):
    """Sample ``num_samples`` random columns of each (d, m) matrix item
    (parity: Sampling.scala:12-20). Used to subsample descriptor matrices
    before PCA/GMM estimation.

    A batched (n, d, m) descriptor stack samples in ONE device gather
    (take_along_axis with per-item column draws) instead of n per-item
    dispatches — through a tunneled transport the per-item loop was the
    dominant cost of the ImageNet fit's sampling phases (round 3: ~50 s
    per branch at 300 images for ~0.1 s of gather work)."""

    def __init__(self, num_samples_per_matrix: int, seed: int = 0):
        self.num_samples = num_samples_per_matrix
        self.seed = seed
        self._rng = np.random.default_rng(seed)

    def apply(self, x):
        x = jnp.asarray(x)
        cols = self._rng.integers(0, x.shape[1], size=self.num_samples)
        return x[:, jnp.asarray(cols)]

    def apply_batch(self, data):
        from ...data.chunked import ChunkedDataset

        data = Dataset.of(data)
        if not data.is_batched:
            return data.map(self.apply)
        if isinstance(data, ChunkedDataset):
            # per-chunk device gather, lazily — the sampled set is small and
            # materializes at the consumer; the descriptor stack never does.
            # raw_chunks: this factory COMPOSES into a downstream scan, which
            # pipelines the whole chain once at its consumer
            parent = data.raw_chunks

            def factory():
                for i, chunk in enumerate(parent()):
                    yield self.sample_chunk(chunk, i)

            return ChunkedDataset(factory, len(data), label="col_sample")
        return Dataset(self._sample_batch(data.to_array()), batched=True)

    def sample_chunk(self, X, chunk_index: int):
        """Sample one chunk of a chunked scan. Column draws key on
        (seed, chunk index), NOT the stateful rng: a lazy chunked chain
        re-runs on every scan, and the lineage contract requires identical
        chunks each time. Shared by the chunked ``apply_batch`` path and
        callers that drive one combined scan themselves (the ImageNet FV
        branch builder draws PCA + GMM samples in a single featurize pass)."""
        return self._sample_batch(
            X, np.random.default_rng((self.seed, chunk_index))
        )

    def _sample_batch(self, X, rng=None):
        rng = self._rng if rng is None else rng
        n, _, m = X.shape
        cols = rng.integers(0, m, size=(n, self.num_samples))
        return jnp.take_along_axis(
            X, jnp.asarray(cols)[:, None, :], axis=2
        )
