"""AOT compilation of fitted pipelines: load an executable, or trace once
and export it for every future process.

:class:`AotDispatcher` is the per-shape compile engine both
``FittedPipeline.compile`` and the serving engine's private jit ride when
an executable cache is configured. For each distinct input signature
``(shape, dtype)`` it resolves a callable exactly once:

* **hit** — the cache holds a ``jax.export`` artifact for (pipeline
  fingerprint, signature, environment): deserialize the StableHLO and
  wrap it in ``jax.jit``. ZERO traces of the pipeline function — the
  whole featurize→predict chain never runs under a jax tracer in this
  process. The wrapper's XLA compile is keyed by the serialized module,
  identical to the one the exporting process paid, so with jax's
  persistent compilation cache layered underneath (see
  ``compile.configure``) even that compile is a disk lookup.
* **miss** — trace ONCE via ``jax.export.export`` (the trace-count hook
  fires here, exactly as a legacy ``jax.jit`` first call would), persist
  the serialized artifact, and execute through the very same exported
  module. Cold and warm boots therefore run byte-identical StableHLO —
  the acceptance bit-equality invariant is structural, not incidental.
* **export unavailable** (an unexportable primitive, a serialization
  failure) — fall back to a plain per-signature ``jax.jit``; the failure
  is logged once and the process behaves exactly as before this layer
  existed.

Obs spans (when a tracer is installed): ``aot.load`` (bytes,
seconds_saved = the producer's measured trace+export cost), ``aot.miss``
and ``aot.export`` (bytes, trace_seconds) — a trace of a warm boot shows
loads and no exports; a cold boot shows the misses it paid.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from ..obs.tracer import current as _trace_current
from .cache import ExecutableCache
from .fingerprint import entry_key, environment_key

logger = logging.getLogger(__name__)

#: input signature: (shape tuple, canonical dtype string)
Signature = Tuple[Tuple[int, ...], str]


def signature_of(x: Any) -> Signature:
    return (tuple(int(d) for d in x.shape), str(x.dtype))


class AotDispatcher:
    """Resolves one callable per input signature, cache-first.

    ``fn`` is the pure stacked-array pipeline function
    (``FittedPipeline.trace_fn()``). ``on_trace(sig)`` fires once per
    pipeline trace actually paid (the compile-accounting hook);
    ``on_load(sig)`` fires once per executable loaded instead of traced.
    Thread-safe: the serving engine's caller thread warms buckets while
    the worker thread may resolve a late signature.
    """

    def __init__(
        self,
        fn: Callable,
        fingerprint_digest: str,
        cache: ExecutableCache,
        *,
        on_trace: Optional[Callable[[Signature], None]] = None,
        on_load: Optional[Callable[[Signature], None]] = None,
        label: str = "",
        expected_exportable: Optional[bool] = None,
    ):
        self._fn = fn
        self._digest = fingerprint_digest
        self._cache = cache
        self._on_trace = on_trace
        self._on_load = on_load
        self._label = label
        #: the static checker's export verdict (keystone_tpu/check/),
        #: when the caller ran one — the dynamic path asserts against it
        self._expected_exportable = expected_exportable
        self._env = environment_key()
        self._by_sig: Dict[Signature, Callable] = {}
        self._lock = threading.Lock()
        self._loaded = 0
        self._traced = 0
        # the persistent compile ledger lives next to the cache entries:
        # every trace/export/load lands with duration + bytes (the
        # residency-budget evidence; appends never raise)
        from ..obs.ledger import CompileLedger

        self._ledger = CompileLedger.for_cache_root(cache.root)

    # -- introspection --------------------------------------------------

    @property
    def digest(self) -> str:
        """The pipeline fingerprint this dispatcher compiles for — the
        manifest key a booting fleet uses to pre-warm every previously
        exported signature."""
        return self._digest

    @property
    def loaded_count(self) -> int:
        """Signatures resolved from the cache (zero traces paid)."""
        return self._loaded

    @property
    def traced_count(self) -> int:
        """Signatures that paid a live pipeline trace."""
        return self._traced

    # -- the hot path ---------------------------------------------------

    def __call__(self, x):
        sig = signature_of(x)
        call = self._by_sig.get(sig)
        if call is None:
            call = self._resolve(sig)
        return call(x)

    # -- resolution -----------------------------------------------------

    def _resolve(self, sig: Signature) -> Callable:
        with self._lock:
            call = self._by_sig.get(sig)
            if call is not None:
                return call
            call = self._load(sig)
            if call is None:
                call = self._trace_and_export(sig)
            self._by_sig[sig] = call
            return call

    def _load(self, sig: Signature) -> Optional[Callable]:
        import jax
        from jax import export as jax_export

        key = entry_key(self._digest, sig[0], sig[1], self._env)
        t0 = time.perf_counter()
        entry = self._cache.load(key, expect_env=self._env)
        if entry is None:
            return None
        try:
            exported = jax_export.deserialize(bytearray(entry.payload))
            call = jax.jit(exported.call)
        except Exception:
            logger.warning(
                "aot: undeserializable entry for %s %s — falling back to live "
                "compile", self._label or key, sig, exc_info=True,
            )
            self._cache._discard(entry.path, "undeserializable")
            return None
        self._loaded += 1
        load_seconds = time.perf_counter() - t0
        self._ledger.record(
            "load",
            key=key,
            label=self._label,
            shape=list(sig[0]),
            dtype=sig[1],
            nbytes=entry.nbytes,
            seconds=load_seconds,
            saved_s=entry.header.get("trace_seconds"),
        )
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(
                "aot.load",
                op_type="AotDispatcher",
                key=key,
                label=self._label,
                shape=list(sig[0]),
                dtype=sig[1],
                bytes=entry.nbytes,
                load_seconds=round(load_seconds, 4),
                seconds_saved=entry.header.get("trace_seconds"),
            )
        logger.info(
            "aot: loaded %s %s from cache (%d bytes, saved ~%ss of tracing)",
            self._label or key, sig, entry.nbytes,
            entry.header.get("trace_seconds", "?"),
        )
        if self._on_load is not None:
            self._on_load(sig)
        return call

    def _trace_and_export(self, sig: Signature) -> Callable:
        import jax
        import numpy as np
        from jax import export as jax_export

        tracer = _trace_current()
        key = entry_key(self._digest, sig[0], sig[1], self._env)
        if tracer is not None:
            tracer.instant(
                "aot.miss", op_type="AotDispatcher", key=key,
                label=self._label, shape=list(sig[0]), dtype=sig[1],
            )
        fired = []

        def traced(x):
            # runs only under a jax trace — exactly once per compile paid
            fired.append(sig)
            if self._on_trace is not None and len(fired) == 1:
                self._on_trace(sig)
            return self._fn(x)

        spec = jax.ShapeDtypeStruct(sig[0], np.dtype(sig[1]))
        t0 = time.perf_counter()
        try:
            exported = jax_export.export(jax.jit(traced))(spec)
            call = jax.jit(exported.call)
        except Exception:
            logger.warning(
                "aot: export failed for %s %s — serving via plain jit "
                "(no cross-process caching for this signature)",
                self._label or key, sig, exc_info=True,
            )
            if self._expected_exportable:
                # static-vs-dynamic disagreement: the checker's lattice
                # said this chain exports. A verdict bug — make it loud
                # so the classifier gets fixed, not papered over.
                logger.error(
                    "aot: STATIC CHECK DISAGREEMENT — the traceability "
                    "lattice classified %s as exportable but jax.export "
                    "refused it; report this pipeline's node set",
                    self._label or key,
                )
            self._traced += 1
            if fired:
                return jax.jit(self._fn)  # already counted by the export try
            return jax.jit(traced)
        trace_seconds = time.perf_counter() - t0
        self._traced += 1
        self._ledger.record(
            "trace",
            key=key,
            label=self._label,
            shape=list(sig[0]),
            dtype=sig[1],
            seconds=trace_seconds,
        )
        try:
            payload = bytes(exported.serialize())
            self._cache.store(
                key,
                payload,
                {
                    "env": self._env,
                    "pipeline": self._digest,
                    "shape": list(sig[0]),
                    "dtype": sig[1],
                    "label": self._label,
                    "trace_seconds": round(trace_seconds, 4),
                    "created_unix": time.time(),
                },
            )
            # index the export in the bucket-signature manifest so a
            # fresh replica can pre-warm every signature at deploy time
            from . import manifest as _manifest

            _manifest.record_export(self._cache, self._digest, sig[0], sig[1])
        except Exception:
            logger.warning(
                "aot: could not persist %s %s — executable still serves "
                "live", self._label or key, sig, exc_info=True,
            )
            payload = b""
        if payload:
            self._ledger.record(
                "export",
                key=key,
                label=self._label,
                shape=list(sig[0]),
                dtype=sig[1],
                nbytes=len(payload),
                seconds=trace_seconds,
            )
        if tracer is not None:
            tracer.instant(
                "aot.export",
                op_type="AotDispatcher",
                key=key,
                label=self._label,
                shape=list(sig[0]),
                dtype=sig[1],
                bytes=len(payload),
                trace_seconds=round(trace_seconds, 4),
            )
        return call
