"""Cold-start probe: boot a serving engine against an AOT cache dir and
report what warm-up cost, as one JSON line on stdout.

This is the measurement half of the ``serve_cold_start`` bench section
and of ``bin/serve-smoke.sh``'s second boot: the driver runs this module
in a FRESH subprocess twice against the same ``--cache`` dir — the first
boot traces and exports every bucket (cold), the second must load every
bucket and pay zero traces (warm). Everything process-local that could
mask the effect (jax's in-memory jit cache, the backend) is fresh by
construction because the process is.

The probe also verifies correctness, not just speed: a handful of
predictions served through the (possibly cache-loaded) engine must be
bit-equal to ``FittedPipeline.apply`` on the same rows — a cache that
boots fast but serves a different model must fail here, loudly.

Usage::

    python -m keystone_tpu.compile.coldstart --cache /tmp/aot [--buckets 8,32]

Output (one line)::

    {"construct_seconds": ..., "warmup_seconds": ..., "compiles": N,
     "aot_loads": M, "buckets": [...], "outputs_match": true, ...}
"""

from __future__ import annotations

import argparse
import json
import time
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser("keystone-tpu coldstart probe")
    p.add_argument("--cache", required=True, help="AOT executable cache dir")
    p.add_argument("--buckets", default="8,32")
    p.add_argument("--numFFTs", type=int, default=2)
    p.add_argument("--blockSize", type=int, default=512)
    p.add_argument("--nTrain", type=int, default=512)
    p.add_argument("--requests", type=int, default=16)
    args = p.parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    t_proc0 = time.perf_counter()
    from ..utils.obs import configure

    configure(aot_cache=args.cache)

    import numpy as np

    from ..serving.demo import build_demo_fitted
    from ..serving.engine import ServingEngine

    # the fit is deterministic but NOT what this probe measures — serving
    # replicas load a fitted model; they don't refit it
    fitted, test_data = build_demo_fitted(
        num_ffts=args.numFFTs, block_size=args.blockSize,
        n_train=args.nTrain, n_test=args.requests,
    )

    t0 = time.perf_counter()
    engine = ServingEngine(fitted, buckets=buckets)
    construct_seconds = time.perf_counter() - t0

    t0 = time.perf_counter()
    warmed = engine.warm_up(required=True)
    warmup_seconds = time.perf_counter() - t0

    data = test_data[: args.requests]
    engine.start(warmup=False)  # already warm; don't re-run (nor re-count)
    try:
        preds = [engine.predict(row, timeout=60.0) for row in data]
    finally:
        engine.shutdown()
    expected = np.asarray(fitted.apply(data).to_array())
    outputs_match = bool(
        np.array_equal(np.asarray(preds).ravel(), expected.ravel())
    )

    counters = engine.metrics.snapshot()["counters"]
    print(
        json.dumps(
            {
                "construct_seconds": round(construct_seconds, 4),
                "warmup_seconds": round(warmup_seconds, 4),
                "buckets_warmed": warmed,
                "buckets": list(engine.policy.batch_sizes),
                "compiles": counters.get("compiles", 0),
                "aot_loads": counters.get("aot_loads", 0),
                "requests": len(data),
                "outputs_match": outputs_match,
                "process_seconds": round(time.perf_counter() - t_proc0, 4),
            }
        )
    )
    return 0 if outputs_match else 1


if __name__ == "__main__":
    raise SystemExit(main())
