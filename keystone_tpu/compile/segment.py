"""Segment compilation: lower a planned traceable segment into ONE jitted
function and dispatch it through the AOT export/cache/manifest plane.

``check/segments.py`` (PR 13) partitions the optimized DAG into maximal
traceable segments between materialization barriers. This module is the
payoff: :func:`lower_segment` composes the member operators'
``trace_batch`` bodies in topo order into a single function over the
segment's pinned ``inputs`` → ``outputs`` tuple, and
:class:`SegmentDispatcher` resolves one executable per input-signature
tuple exactly the way :class:`~keystone_tpu.compile.aot.AotDispatcher`
does for serving buckets — cache hit ⇒ deserialize, zero traces; miss ⇒
trace once via ``jax.export``, persist, index in the segment manifest so
a warm boot (``ServingFleet.start()``, cluster workers) pre-warms it.
A warm FIT therefore boots zero-trace.

:class:`SegmentBinding` is the executor-facing handle: it owns the
lowered steps, the content digest, and the three runtime paths —

* **compiled** — all-batched array inputs dispatch the whole segment as
  one program (one Python dispatch for N nodes);
* **chunked** — a single-output segment over chunked data rides the
  out-of-core scan per chunk through :class:`ChunkPadder` (ragged final
  chunks pad to the bucket ladder, results slice back);
* **fallback** — anything else (item-list inputs, batch-coupled members
  over chunks, multi-output chunked segments, a runtime failure) degrades
  to exact per-node semantics: same operators, same order, same answers.

Adaptive boundaries close the loop through ``cost/segments.py``: each
compile and each run is recorded under the profile store's
``plan/segment/`` namespace, and a segment whose observed compile cost
swamps its cumulative dispatch savings is demoted back to node dispatch
on the next fit. ``KEYSTONE_SEGMENT_COMPILE=0`` kill-switches the whole
layer (read per pull by the executor, not here).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.tracer import current as _trace_current
from .aot import Signature, signature_of
from .cache import ExecutableCache
from .fingerprint import (
    FingerprintError,
    environment_key,
    segment_entry_key,
    segment_fingerprint,
)

logger = logging.getLogger(__name__)

#: lowered step: (operator, input slots into the segment value vector)
Step = Tuple[Any, Tuple[int, ...]]


def lower_segment(graph: Any, segment: Any) -> Tuple[Callable, List[Step], Tuple[int, ...]]:
    """Compose ``segment``'s member ``trace_batch`` bodies into one
    function ``fn(*inputs) -> outputs tuple``.

    The index space is positional over ``segment.inputs`` followed by
    ``segment.nodes`` — the same space :func:`segment_fingerprint` hashes,
    so two processes that agree on the digest agree on the signature.
    Returns ``(fn, steps, out_slots)``; ``steps``/``out_slots`` also
    drive the exact-semantics fallback path.
    """
    inputs = list(segment.inputs)
    members = list(segment.nodes)
    pos: Dict[Any, int] = {d: i for i, d in enumerate(inputs)}
    for j, n in enumerate(members):
        pos[n] = len(inputs) + j
    steps: List[Step] = [
        (
            graph.get_operator(n),
            tuple(pos[d] for d in graph.get_dependencies(n)),
        )
        for n in members
    ]
    out_slots = tuple(pos[o] for o in segment.outputs)

    def fn(*xs):
        values = list(xs)
        for op, slots in steps:
            values.append(op.trace_batch(*[values[s] for s in slots]))
        return tuple(values[s] for s in out_slots)

    return fn, steps, out_slots


class SegmentDispatcher:
    """One executable per input-signature tuple, cache-first — the
    segment-graph sibling of :class:`~keystone_tpu.compile.aot.AotDispatcher`.

    With no cache configured every signature resolves to a structural
    ``jax.jit`` (still one program per segment, just not exported). Inputs
    that have no array signature (tuple payloads out of a gather join)
    also ride the structural jit: jit handles pytrees natively, only the
    AOT export plane needs flat array signatures.
    """

    def __init__(
        self,
        fn: Callable,
        digest: str,
        cache: Optional[ExecutableCache],
        *,
        label: str = "",
        n_nodes: int = 1,
    ):
        self._fn = fn
        self._digest = digest
        self._cache = cache
        self._label = label
        self._n_nodes = n_nodes
        self._env = environment_key() if cache is not None else None
        self._by_sig: Dict[Tuple[Signature, ...], Callable] = {}
        self._structural: Optional[Callable] = None
        self._lock = threading.Lock()
        self._loaded = 0
        self._traced = 0
        self._ledger = None
        if cache is not None:
            from ..obs.ledger import CompileLedger

            self._ledger = CompileLedger.for_cache_root(cache.root)

    @property
    def digest(self) -> str:
        return self._digest

    @property
    def loaded_count(self) -> int:
        """Signature tuples resolved from the cache (zero traces paid)."""
        return self._loaded

    @property
    def traced_count(self) -> int:
        """Signature tuples that paid a live trace."""
        return self._traced

    def __call__(self, *xs):
        try:
            sigs = tuple(signature_of(x) for x in xs)
        except (AttributeError, TypeError):
            # non-array input (e.g. a gather join's tuple payload): jit
            # dispatches pytrees fine, only AOT export needs flat arrays
            return self._structural_jit()(*xs)
        call = self._by_sig.get(sigs)
        if call is None:
            call = self._resolve(sigs)
        return call(*xs)

    def _structural_jit(self) -> Callable:
        call = self._structural
        if call is None:
            import jax

            with self._lock:
                if self._structural is None:
                    self._structural = jax.jit(self._fn)
                call = self._structural
        return call

    def _resolve(self, sigs: Tuple[Signature, ...]) -> Callable:
        with self._lock:
            call = self._by_sig.get(sigs)
            if call is not None:
                return call
            if self._cache is None:
                import jax

                if self._structural is None:
                    self._structural = jax.jit(self._fn)
                call = self._structural
            else:
                call = self._load(sigs)
                if call is None:
                    call = self._trace_and_export(sigs)
            self._by_sig[sigs] = call
            return call

    def _load(self, sigs: Tuple[Signature, ...]) -> Optional[Callable]:
        import jax
        from jax import export as jax_export

        key = segment_entry_key(self._digest, sigs, self._env)
        t0 = time.perf_counter()
        entry = self._cache.load(key, expect_env=self._env)
        if entry is None:
            return None
        try:
            exported = jax_export.deserialize(bytearray(entry.payload))
            call = jax.jit(exported.call)
        except Exception:
            logger.warning(
                "segment: undeserializable entry for %s — falling back to "
                "live compile", self._label or key, exc_info=True,
            )
            self._cache._discard(entry.path, "undeserializable")
            return None
        self._loaded += 1
        load_seconds = time.perf_counter() - t0
        if self._ledger is not None:
            self._ledger.record(
                "load",
                key=key,
                label=self._label,
                kind="segment",
                inputs=len(sigs),
                nbytes=entry.nbytes,
                seconds=load_seconds,
                saved_s=entry.header.get("trace_seconds"),
            )
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(
                "aot.load",
                op_type="SegmentDispatcher",
                key=key,
                label=self._label,
                inputs=len(sigs),
                bytes=entry.nbytes,
                load_seconds=round(load_seconds, 4),
                seconds_saved=entry.header.get("trace_seconds"),
            )
        logger.info(
            "segment: loaded %s from cache (%d bytes, saved ~%ss of "
            "tracing)", self._label or key, entry.nbytes,
            entry.header.get("trace_seconds", "?"),
        )
        return call

    def _trace_and_export(self, sigs: Tuple[Signature, ...]) -> Callable:
        import jax
        import numpy as np
        from jax import export as jax_export

        from ..cost import segments as seg_cost

        tracer = _trace_current()
        key = segment_entry_key(self._digest, sigs, self._env)
        if tracer is not None:
            tracer.instant(
                "aot.miss", op_type="SegmentDispatcher", key=key,
                label=self._label, inputs=len(sigs),
            )
        specs = [jax.ShapeDtypeStruct(s, np.dtype(d)) for s, d in sigs]
        t0 = time.perf_counter()
        try:
            exported = jax_export.export(jax.jit(self._fn))(*specs)
            call = jax.jit(exported.call)
        except Exception:
            logger.warning(
                "segment: export failed for %s — dispatching via plain jit "
                "(no cross-process caching for this signature)",
                self._label or key, exc_info=True,
            )
            self._traced += 1
            seg_cost.record_compile(
                self._digest, time.perf_counter() - t0,
                exported=False, n_nodes=self._n_nodes,
            )
            return jax.jit(self._fn)
        trace_seconds = time.perf_counter() - t0
        self._traced += 1
        if self._ledger is not None:
            self._ledger.record(
                "trace",
                key=key,
                label=self._label,
                kind="segment",
                inputs=len(sigs),
                seconds=trace_seconds,
            )
        try:
            payload = bytes(exported.serialize())
            self._cache.store(
                key,
                payload,
                {
                    "env": self._env,
                    "segment": self._digest,
                    "inputs": [[list(s), d] for s, d in sigs],
                    "label": self._label,
                    "trace_seconds": round(trace_seconds, 4),
                    "created_unix": time.time(),
                },
            )
            from . import manifest as _manifest

            _manifest.record_segment(self._cache, self._digest, sigs)
        except Exception:
            logger.warning(
                "segment: could not persist %s — executable still serves "
                "live", self._label or key, exc_info=True,
            )
            payload = b""
        if payload and self._ledger is not None:
            self._ledger.record(
                "export",
                key=key,
                label=self._label,
                kind="segment",
                inputs=len(sigs),
                nbytes=len(payload),
                seconds=trace_seconds,
            )
        if tracer is not None:
            tracer.instant(
                "aot.export",
                op_type="SegmentDispatcher",
                key=key,
                label=self._label,
                inputs=len(sigs),
                bytes=len(payload),
                trace_seconds=round(trace_seconds, 4),
            )
        seg_cost.record_compile(
            self._digest, trace_seconds, exported=bool(payload),
            n_nodes=self._n_nodes,
        )
        return call


# ---------------------------------------------------------------------------
# Process-wide dispatcher registry: one SegmentDispatcher per (digest,
# cache root), so two executors pulling the same fitted graph share
# resolved executables instead of re-tracing. Bounded LRU — digests churn
# across unrelated pipelines in a long-lived process.
# ---------------------------------------------------------------------------

_DISPATCHERS: "OrderedDict[Tuple[str, Optional[str]], SegmentDispatcher]" = OrderedDict()
_MAX_DISPATCHERS = 128
_dispatchers_lock = threading.Lock()


def dispatcher_for(
    digest: str, fn_factory: Callable[[], Callable], *, label: str = "",
    n_nodes: int = 1,
) -> SegmentDispatcher:
    """The shared dispatcher for ``digest`` against the currently
    configured cache. The cache is re-fetched per call (it may be
    configured after a binding was built), so bindings must not memoize
    the dispatcher they get back."""
    from . import get_cache

    cache = get_cache()
    key = (digest, cache.root if cache is not None else None)
    with _dispatchers_lock:
        disp = _DISPATCHERS.get(key)
        if disp is not None:
            _DISPATCHERS.move_to_end(key)
            return disp
        disp = SegmentDispatcher(
            fn_factory(), digest, cache, label=label, n_nodes=n_nodes
        )
        _DISPATCHERS[key] = disp
        while len(_DISPATCHERS) > _MAX_DISPATCHERS:
            _DISPATCHERS.popitem(last=False)
        return disp


def reset_dispatchers() -> None:
    """Drop every registered dispatcher (test hygiene)."""
    with _dispatchers_lock:
        _DISPATCHERS.clear()


# ---------------------------------------------------------------------------
# SegmentBinding: the executor-facing handle
# ---------------------------------------------------------------------------


class SegmentBinding:
    """One plannable segment, lowered and ready to dispatch.

    ``run(datasets)`` takes the materialized input Datasets (positional
    over the segment's pinned ``inputs`` order) and returns
    ``(outputs, path)`` — one Dataset per segment output plus which
    runtime path served it (``compiled`` / ``chunked`` / ``fallback``).
    Any runtime failure demotes the binding permanently (this process)
    and re-runs through exact node semantics — segment dispatch must
    never change answers or surface new errors.
    """

    def __init__(
        self,
        *,
        index: int,
        inputs: List[Any],
        outputs: List[Any],
        fn: Callable,
        steps: List[Step],
        out_slots: Tuple[int, ...],
        digest: str,
        label: str,
        node_ids: List[str],
        batch_coupled: bool,
    ):
        self.index = index
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.fn = fn
        self.steps = steps
        self.out_slots = out_slots
        self.digest = digest
        self.label = label
        self.node_ids = list(node_ids)
        self.batch_coupled = batch_coupled
        self._demoted = False

    def __len__(self) -> int:
        return len(self.steps)

    def _dispatcher(self) -> SegmentDispatcher:
        return dispatcher_for(
            self.digest, lambda: self.fn, label=self.label,
            n_nodes=len(self.steps),
        )

    def run(self, datasets: List[Any]) -> Tuple[Tuple[Any, ...], str]:
        if self._demoted:
            return self._fallback(datasets), "fallback"
        try:
            return self._run(datasets)
        except Exception as e:
            self._demote(f"runtime failure: {e!r}")
            return self._fallback(datasets), "fallback"

    def _run(self, datasets: List[Any]) -> Tuple[Tuple[Any, ...], str]:
        from ..data.chunked import ChunkedDataset, align_and_zip
        from ..data.dataset import Dataset
        from ..data.pipeline_scan import ChunkPadder

        # ChunkedDataset reports is_batched=True — check it FIRST
        if any(isinstance(ds, ChunkedDataset) for ds in datasets):
            if self.batch_coupled or len(self.out_slots) != 1:
                # batch-coupled members must see whole batches, and a
                # multi-output chunked segment would rescan the source
                # once per output — node semantics handle both exactly
                return self._fallback(datasets), "fallback"
            disp = self._dispatcher()
            if len(datasets) == 1:
                out = datasets[0].map_batch(
                    ChunkPadder(lambda c: disp(c)[0], shard=True)
                )
            else:
                out = align_and_zip(list(datasets)).map_batch(
                    ChunkPadder(lambda t: disp(*t)[0], shard=True)
                )
            return (out,), "chunked"
        if datasets and all(ds.is_batched for ds in datasets):
            from ..cost import segments as seg_cost

            disp = self._dispatcher()
            t0 = time.perf_counter()
            raw = disp(*[ds.to_array() for ds in datasets])
            seg_cost.record_run(
                self.digest, time.perf_counter() - t0,
                n_nodes=len(self.steps),
            )
            return (
                tuple(Dataset(o, batched=True) for o in raw),
                "compiled",
            )
        # item-list inputs: per-node dispatch is the honest semantics
        return self._fallback(datasets), "fallback"

    def _fallback(self, datasets: List[Any]) -> Tuple[Any, ...]:
        """Exact node semantics: same operators, same topo order, same
        execute() paths the node executor would have run."""
        from ..workflow.expressions import DatasetExpression

        values: List[Any] = list(datasets)
        for op, slots in self.steps:
            deps = [DatasetExpression.now(values[s]) for s in slots]
            values.append(op.execute(deps).get())
        return tuple(values[s] for s in self.out_slots)

    def _demote(self, why: str) -> None:
        if self._demoted:
            return
        self._demoted = True
        logger.warning(
            "segment %s (%s): %s — demoted to node dispatch",
            self.index, self.label, why, exc_info=True,
        )
        try:
            from ..cost import segments as seg_cost

            seg_cost.record_failure(self.digest, why="runtime")
        except Exception:
            logger.debug("segment: could not record demotion", exc_info=True)


def bind_segment(
    graph: Any, segment: Any, *, annotations: Optional[Dict[Any, str]] = None
) -> Optional[SegmentBinding]:
    """Lower ``segment`` into a dispatchable binding, or None when it is
    not worth (or not safe to) segment-dispatch:

    * empty, or a singleton whose operator is not already a fused chain —
      a single plain node gains nothing over its node thunk, but a
      singleton :class:`FusedTransformerOperator` IS eligible: that is
      how an optimizer-fused fit graph gets whole-chain AOT export;
    * any member annotated for the pipeline env whose value would NOT
      surface (interior annotated nodes must materialize individually);
    * any member without a traceable ``trace_batch`` (defense in depth —
      the planner's lattice should have barriered these already);
    * the segment fingerprint is uncomputable (unhashable operator
      state);
    * the cost model demoted this digest (compile cost exceeded observed
      dispatch savings — the adaptive-boundary split).
    """
    from ..workflow.fusion import FusedTransformerOperator
    from ..workflow.graph import NodeId
    from ..workflow.operators import TransformerOperator

    members = list(segment.nodes)
    if not members:
        return None
    ops = []
    for n in members:
        op = graph.get_operator(n)
        if not isinstance(op, TransformerOperator):
            return None
        if not callable(getattr(op, "trace_batch", None)):
            return None
        ops.append(op)
    if len(members) == 1 and not isinstance(ops[0], FusedTransformerOperator):
        return None
    out_set = set(segment.outputs)
    if annotations:
        for n in members:
            if n in annotations and n not in out_set:
                return None
    for d in segment.inputs:
        if not isinstance(d, NodeId):
            return None
    # convexity: a member → barrier → member path makes an INPUT of the
    # lowered function transitively depend on one of its OUTPUTS (e.g. a
    # shared prefix feeding both a host node and a traceable chain the
    # host node rejoins). Such a group is not one compilation unit.
    mset = set(members)
    stack: List[Any] = []
    for d in segment.inputs:
        stack.extend(graph.get_dependencies(d))
    seen_anc = set()
    while stack:
        a = stack.pop()
        if a in seen_anc:
            continue
        seen_anc.add(a)
        if a in mset:
            return None
        if isinstance(a, NodeId) and a in graph.operators:
            stack.extend(graph.get_dependencies(a))
    try:
        digest = segment_fingerprint(graph, segment)
    except FingerprintError:
        logger.debug(
            "segment %s: unfingerprintable — node dispatch", segment.index,
            exc_info=True,
        )
        return None
    from ..cost import segments as seg_cost

    if not seg_cost.should_compile(digest, len(members)):
        logger.info(
            "segment %s: demoted by cost model — node dispatch",
            segment.index,
        )
        return None
    fn, steps, out_slots = lower_segment(graph, segment)
    labels = [op.label for op in ops]
    label = "+".join(labels)
    if len(label) > 96:
        label = label[:93] + "..."
    return SegmentBinding(
        index=segment.index,
        inputs=list(segment.inputs),
        outputs=list(segment.outputs),
        fn=fn,
        steps=steps,
        out_slots=out_slots,
        digest=digest,
        label=label,
        node_ids=[str(n.id) for n in members],
        batch_coupled=any(
            bool(getattr(op, "batch_coupled", False)) for op in ops
        ),
    )


# ---------------------------------------------------------------------------
# Warm boot: pre-warm every manifest-indexed segment executable
# ---------------------------------------------------------------------------


def prewarm_segment_artifacts(
    cache: ExecutableCache, *, limit: int = 64, max_elements: int = 1 << 22
) -> int:
    """Deserialize + compile + execute-once every segment executable the
    manifest indexes — the fit-side analogue of the serving fleet's bucket
    pre-warm, called from ``ServingFleet.start()`` so a warm fit after a
    warm serve boot loads and never traces. Returns the number warmed.
    Best-effort throughout: a missing/evicted/foreign entry is skipped,
    never a boot failure. ``max_elements`` bounds the dummy-input bytes a
    boot will allocate per signature tuple."""
    import jax
    import numpy as np
    from jax import export as jax_export

    from . import manifest as _manifest

    env = environment_key()
    warmed = 0
    for digest in _manifest.segment_digests(cache):
        if warmed >= limit:
            break
        for sigs in _manifest.segment_signatures(cache, digest):
            if warmed >= limit:
                break
            try:
                elements = sum(
                    int(np.prod(shape)) if shape else 1 for shape, _ in sigs
                )
                if elements > max_elements:
                    logger.info(
                        "segment prewarm: skipping %s (%d elements over "
                        "budget)", digest[:16], elements,
                    )
                    continue
                key = segment_entry_key(digest, sigs, env)
                entry = cache.load(key, expect_env=env)
                if entry is None:
                    continue
                exported = jax_export.deserialize(bytearray(entry.payload))
                call = jax.jit(exported.call)
                args = [
                    jax.numpy.zeros(shape, np.dtype(dtype))
                    for shape, dtype in sigs
                ]
                jax.block_until_ready(call(*args))
                warmed += 1
            except Exception:
                logger.warning(
                    "segment prewarm: could not warm %s — skipped",
                    digest[:16], exc_info=True,
                )
    if warmed:
        logger.info("segment prewarm: %d executable(s) warmed", warmed)
    return warmed
