"""Canonical, process-stable fingerprinting of fitted pipelines.

The AOT executable cache (``compile/cache.py``) keys entries by *what the
compiled program computes*, and a wrong key is silent model corruption:
two different fitted pipelines colliding would serve one model's
executable for the other. So the fingerprint here is a content digest of
everything that determines the traced program:

* **graph topology** — nodes relabeled to their topological-linearization
  index (so the digest is invariant to the arbitrary integer ids graph
  splicing assigns) plus each node's dependency edges and the sink edge;
* **operator identities** — fully-qualified class names;
* **fitted parameters** — every attribute of every operator, canonicalized
  by content: scalars/strings verbatim, numpy and jax arrays as
  shape+dtype+sha256-of-bytes, containers recursively, nested operators
  (the optimizer's ``FusedTransformerOperator`` holds its steps as state)
  recursively, plain Python functions as code+constants+closure digests.

Anything whose content cannot be proven stable across processes (bound
native objects, jitted callables, lazy datasets) raises
:class:`FingerprintError` — the caller falls back to a live compile
rather than risking a bogus cache key. Derived/memo state a class
declares in ``aot_fingerprint_exclude`` (e.g. ``FusedTransformerOperator._jit``)
is skipped: a warm operator must fingerprint identically to a fresh one.

The digest is pure content — no ``hash()`` (PYTHONHASHSEED), no ``id()``,
no ``repr`` of objects — so it is stable across processes and machines,
which is what lets a serving replica boot from executables another
process exported. Environment compatibility (jax/jaxlib versions,
backend, device kind) is deliberately NOT part of the pipeline
fingerprint; :func:`environment_key` captures it separately so the cache
can report "same pipeline, stale toolchain" distinctly from a plain miss.
"""

from __future__ import annotations

import hashlib
import types
from typing import Any, Dict, Tuple

FORMAT_VERSION = 1


class FingerprintError(ValueError):
    """The pipeline holds state with no content-stable canonical form and
    therefore cannot be cache-keyed. Carries the offending path so logs
    name the blocking attribute."""


# ---------------------------------------------------------------------------
# content feeding
# ---------------------------------------------------------------------------


def _feed_bytes(h, tag: bytes, payload: bytes) -> None:
    # length-prefixed so adjacent fields can never alias across a boundary
    h.update(tag)
    h.update(b"%d:" % len(payload))
    h.update(payload)


def _feed(h, value: Any, path: str) -> None:
    """Feed one value's canonical content into the hash. ``path`` is a
    human-readable attribute trail for error messages only."""
    import numpy as np

    if value is None:
        h.update(b"N;")
    elif isinstance(value, bool):
        h.update(b"B1;" if value else b"B0;")
    elif isinstance(value, int):
        _feed_bytes(h, b"I", str(value).encode())
    elif isinstance(value, float):
        # repr() is the shortest round-trip form: bit-stable across processes
        _feed_bytes(h, b"F", repr(value).encode())
    elif isinstance(value, complex):
        _feed_bytes(h, b"C", repr(value).encode())
    elif isinstance(value, str):
        _feed_bytes(h, b"S", value.encode())
    elif isinstance(value, bytes):
        _feed_bytes(h, b"Y", value)
    elif isinstance(value, np.generic):
        _feed_bytes(h, b"G", str(value.dtype).encode())
        _feed(h, value.item(), path)
    elif isinstance(value, np.ndarray):
        _feed_bytes(h, b"A", str(value.shape).encode())
        _feed_bytes(h, b"a", str(value.dtype).encode())
        if value.dtype.hasobject:
            # tobytes() on an object array serializes PyObject POINTERS —
            # process-unstable garbage; recurse into the elements instead
            # (raises FingerprintError if they have no stable form)
            _feed(h, value.tolist(), path)
        else:
            _feed_bytes(
                h, b"d",
                hashlib.sha256(np.ascontiguousarray(value).tobytes()).digest(),
            )
    elif isinstance(value, (list, tuple)):
        h.update(b"L(" if isinstance(value, list) else b"T(")
        for i, item in enumerate(value):
            _feed(h, item, f"{path}[{i}]")
        h.update(b");")
    elif isinstance(value, dict):
        h.update(b"D(")
        try:
            keys = sorted(value)
        except TypeError as e:
            raise FingerprintError(f"{path}: unsortable dict keys ({e})") from e
        for k in keys:
            _feed(h, k, path)
            _feed(h, value[k], f"{path}[{k!r}]")
        h.update(b");")
    elif isinstance(value, (set, frozenset)):
        # order-canonical by each element's own content digest — sorting by
        # str(x) would embed memory addresses for object reprs, breaking
        # cross-process stability
        h.update(b"Z(")
        digests = []
        for item in value:
            sub = hashlib.sha256()
            _feed(sub, item, path)
            digests.append(sub.digest())
        for d in sorted(digests):
            _feed_bytes(h, b"z", d)
        h.update(b");")
    elif isinstance(value, np.dtype):
        _feed_bytes(h, b"t", str(value).encode())
    elif isinstance(value, types.FunctionType):
        _feed_function(h, value, path)
    elif isinstance(value, types.MethodType):
        h.update(b"M(")
        _feed_function(h, value.__func__, path)
        _feed(h, value.__self__, f"{path}.__self__")
        h.update(b");")
    else:
        _feed_object(h, value, path)


def _feed_code(h, code: types.CodeType, path: str) -> None:
    """Bytecode + constants, recursing into nested code objects (inner
    lambdas/defs live in co_consts — skipping them would let two functions
    differing only in an inner function's body collide)."""
    _feed_bytes(h, b"c", code.co_code)
    _feed(
        h,
        tuple(c for c in code.co_consts if not isinstance(c, types.CodeType)),
        f"{path}.co_consts",
    )
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            _feed_code(h, const, f"{path}.{const.co_name}")
    _feed(h, code.co_names, f"{path}.co_names")


def _global_names(code: types.CodeType) -> set:
    names = set(code.co_names)
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            names |= _global_names(const)
    return names


def _feed_function(h, fn: types.FunctionType, path: str) -> None:
    """A plain function/lambda canonicalizes as its compiled code plus the
    content of everything it feeds on: closure cells, defaults, AND the
    values of module globals it references — ``def f(X): return X * SCALE``
    must digest differently when ``SCALE`` changes, or a stale executable
    would load for the edited model. Referenced modules digest by name
    (their contents are the environment key's business), classes by
    qualified name, functions recursively; a referenced global with no
    content-stable form raises (→ live compile) rather than keying on it
    blindly."""
    _feed_bytes(h, b"f", f"{fn.__module__}.{fn.__qualname__}".encode())
    _feed_code(h, fn.__code__, path)
    if fn.__defaults__:
        _feed(h, fn.__defaults__, f"{path}.__defaults__")
    if fn.__kwdefaults__:
        _feed(h, fn.__kwdefaults__, f"{path}.__kwdefaults__")
    if fn.__closure__:
        for i, cell in enumerate(fn.__closure__):
            _feed(h, cell.cell_contents, f"{path}.closure[{i}]")
    fn_globals = fn.__globals__
    for name in sorted(_global_names(fn.__code__)):
        # co_names also lists attribute/builtin names; only names actually
        # bound in the module feed content (extra matches are harmless —
        # they add sensitivity, never instability)
        if name not in fn_globals:
            continue
        value = fn_globals[name]
        _feed_bytes(h, b"g", name.encode())
        if isinstance(value, types.ModuleType):
            _feed_bytes(h, b"m", value.__name__.encode())
        elif isinstance(value, type):
            _feed_bytes(
                h, b"k", f"{value.__module__}.{value.__qualname__}".encode()
            )
        else:
            _feed(h, value, f"{path}.globals[{name}]")


def _feed_object(h, value: Any, path: str) -> None:
    """Non-primitive objects: operators recurse by state; jax arrays and
    batched datasets digest by content; anything else is unprovable."""
    from ..workflow.operators import Operator

    if isinstance(value, Operator):
        _feed_operator_state(h, value, path)
        return
    if isinstance(value, types.ModuleType):
        # same rule as module GLOBALS: digest by name (a module's
        # contents are the environment key's business). Function-local
        # imports are idiomatic here, and they land in closure cells.
        _feed_bytes(h, b"m", value.__name__.encode())
        return
    try:
        import jax

        if isinstance(value, jax.Array):
            import numpy as np

            _feed(h, np.asarray(jax.device_get(value)), path)
            return
    except ImportError:  # pragma: no cover - jax is a hard dep of this repo
        pass
    import numpy as np

    if isinstance(value, np.ufunc):
        _feed_bytes(h, b"u", value.__name__.encode())
        return
    if isinstance(value, (types.BuiltinFunctionType, types.BuiltinMethodType)):
        # library-provided callables digest by identity; their behavior
        # moves with library versions, which is the environment key's job
        _feed_bytes(
            h, b"u",
            f"{getattr(value, '__module__', '')}.{value.__qualname__}".encode(),
        )
        return
    from ..data.dataset import Dataset

    if isinstance(value, Dataset):
        payload = value.payload if value.is_batched else None
        if payload is not None and hasattr(payload, "shape"):
            h.update(b"DS(")
            _feed(h, payload, path)
            h.update(b");")
            return
        raise FingerprintError(
            f"{path}: unmaterialized dataset has no content-stable form"
        )
    raise FingerprintError(
        f"{path}: {type(value).__qualname__} has no content-stable canonical form"
    )


def _feed_operator_state(h, op: Any, path: str) -> None:
    cls = type(op)
    _feed_bytes(h, b"O", f"{cls.__module__}.{cls.__qualname__}".encode())
    exclude = frozenset(getattr(cls, "aot_fingerprint_exclude", ()))
    state: Dict[str, Any] = vars(op)
    for key in sorted(state):
        if key in exclude:
            continue
        _feed(h, key, path)
        _feed(h, state[key], f"{path}.{key}")


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def pipeline_fingerprint(fitted) -> str:
    """Hex sha256 of a :class:`~keystone_tpu.workflow.pipeline.FittedPipeline`'s
    content — topology + operator identities + fitted-parameter digests.
    Raises :class:`FingerprintError` when any operator state has no
    content-stable form (the caller should fall back to a live compile)."""
    from ..workflow import analysis
    from ..workflow.graph import NodeId

    graph = fitted.graph
    h = hashlib.sha256()
    _feed_bytes(h, b"V", str(FORMAT_VERSION).encode())
    order = analysis.linearize(graph)
    index = {gid: i for i, gid in enumerate(order)}
    for gid in order:
        if not isinstance(gid, NodeId) or gid not in graph.operators:
            _feed_bytes(h, b"s", str(index[gid]).encode())  # source slot
            continue
        op = graph.get_operator(gid)
        _feed_bytes(h, b"n", str(index[gid]).encode())
        _feed_operator_state(h, op, op.label)
        _feed(
            h,
            tuple(index[d] for d in graph.get_dependencies(gid)),
            f"{op.label}.deps",
        )
    sink_dep = graph.get_sink_dependency(fitted._sink)
    _feed_bytes(h, b"K", str(index[sink_dep]).encode())
    return h.hexdigest()


def segment_fingerprint(graph, segment) -> str:
    """Hex sha256 of one :class:`~keystone_tpu.check.segments.Segment`'s
    content: member operator states + the segment-local dependency wiring
    + the output slots. The index space is positional over
    ``segment.inputs`` followed by ``segment.nodes`` (both pinned to
    topological order by the planner), so the digest is invariant to the
    arbitrary integer ids graph splicing assigns — two processes planning
    the same fitted pipeline produce the same segment digests, which is
    what lets a warm fit load another process's exported segment
    executables. Raises :class:`FingerprintError` when any member state
    has no content-stable form (the caller falls back to node dispatch)."""
    h = hashlib.sha256()
    _feed_bytes(h, b"V", f"seg{FORMAT_VERSION}".encode())
    pos: Dict[Any, int] = {d: i for i, d in enumerate(segment.inputs)}
    for j, n in enumerate(segment.nodes):
        pos[n] = len(segment.inputs) + j
    for n in segment.nodes:
        op = graph.get_operator(n)
        _feed_bytes(h, b"n", str(pos[n]).encode())
        _feed_operator_state(h, op, op.label)
        _feed(
            h,
            tuple(pos[d] for d in graph.get_dependencies(n)),
            f"{op.label}.deps",
        )
    _feed(h, tuple(pos[o] for o in segment.outputs), "outputs")
    return h.hexdigest()


def environment_key() -> Dict[str, str]:
    """What must match for a cached executable to be loadable: jax/jaxlib
    versions, the backend, and the device kind. Initializes the backend
    (any AOT compile needs it anyway)."""
    import jax
    import jaxlib

    devices = jax.devices()
    return {
        "format": str(FORMAT_VERSION),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": jax.default_backend(),
        "device_kind": devices[0].device_kind if devices else "unknown",
    }


def entry_key(
    pipeline_digest: str, shape: Tuple[int, ...], dtype: str, env: Dict[str, str]
) -> str:
    """Cache-entry key for one (pipeline, input signature, environment):
    ``<pipeline digest prefix>-<signature+env digest>``. The pipeline
    prefix keeps one pipeline's bucket entries adjacent on disk (and
    greppable); the second component separates shapes, dtypes, and
    toolchains."""
    h = hashlib.sha256()
    _feed_bytes(h, b"P", pipeline_digest.encode())
    _feed(h, tuple(int(d) for d in shape), "shape")
    _feed_bytes(h, b"y", str(dtype).encode())
    _feed(h, {str(k): str(v) for k, v in env.items()}, "env")
    return f"{pipeline_digest[:32]}-{h.hexdigest()[:24]}"


def segment_entry_key(
    segment_digest: str,
    signatures: Tuple[Tuple[Tuple[int, ...], str], ...],
    env: Dict[str, str],
) -> str:
    """Cache-entry key for one (segment, input-signature tuple,
    environment). The multi-input analogue of :func:`entry_key`: a
    segment function takes one array per segment input, so the key feeds
    every ``(shape, dtype)`` positionally."""
    h = hashlib.sha256()
    _feed_bytes(h, b"G", segment_digest.encode())
    for shape, dtype in signatures:
        _feed(h, tuple(int(d) for d in shape), "shape")
        _feed_bytes(h, b"y", str(dtype).encode())
    _feed(h, {str(k): str(v) for k, v in env.items()}, "env")
    return f"{segment_digest[:32]}-{h.hexdigest()[:24]}"
