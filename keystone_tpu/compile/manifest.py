"""The bucket-signature manifest: which input signatures a pipeline has
ever exported to the AOT cache.

The executable cache itself is keyed — a process must already know the
exact ``(pipeline, shape, dtype, environment)`` to look an entry up. That
is fine for the engine's own buckets, but a fresh serving replica booting
against a shared cache directory wants the inverse query: *"what
signatures does this pipeline serve?"* — so it can pre-compile every one
of them BEFORE admitting traffic, instead of discovering bucket shapes
one cold first-request at a time. The manifest is that index: one tiny
JSON file per (pipeline digest, signature), written whenever an export
lands, listed by :func:`exported_signatures` at deploy time
(``ServingFleet.start()`` pre-warms every entry per replica).

One file per signature — not one mutable list per pipeline — so
concurrent exporters (N replicas, N processes) never read-modify-write
each other's entries: writes are create-if-absent with the same atomic
tmp-then-rename discipline as the cache proper, and a corrupt or foreign
file degrades to "signature unknown", never a crash. Entries are advisory
(a manifest signature whose cache entry was evicted simply warms via a
live trace), so no invalidation protocol is needed.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import tempfile
import time
from typing import List, Tuple

from .cache import ExecutableCache

logger = logging.getLogger(__name__)

Signature = Tuple[Tuple[int, ...], str]


def _manifest_dir(cache: ExecutableCache, digest: str) -> str:
    return os.path.join(cache.root, "manifest", digest)


def _sig_name(shape: Tuple[int, ...], dtype: str) -> str:
    raw = json.dumps([list(shape), dtype]).encode()
    return hashlib.sha256(raw).hexdigest()[:24] + ".json"


def record_export(
    cache: ExecutableCache, digest: str, shape, dtype: str
) -> None:
    """Note that ``digest`` exported an executable for ``(shape, dtype)``.
    Best-effort: a manifest that cannot be written must never fail the
    export that still serves live."""
    try:
        d = _manifest_dir(cache, digest)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, _sig_name(tuple(shape), dtype))
        if os.path.exists(path):  # signature already recorded
            return
        payload = json.dumps(
            {
                "shape": [int(x) for x in shape],
                "dtype": str(dtype),
                "created_unix": time.time(),
            },
            sort_keys=True,
        ).encode()
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        logger.warning(
            "aot manifest: could not record %s %s", digest, shape,
            exc_info=True,
        )


# ---------------------------------------------------------------------------
# Segment-artifact manifest: which input-signature TUPLES each compiled
# segment has exported. Same one-file-per-record create-if-absent
# discipline as the bucket manifest above, in a sibling namespace
# (``manifest-segments/<segment digest>/``) so a booting fleet can
# pre-warm segment executables (the warm-FIT artifacts) alongside its
# serving buckets — see ``compile/segment.py::prewarm_segment_artifacts``.
# ---------------------------------------------------------------------------

#: one compiled segment's input signatures: one (shape, dtype) per input
SegmentSignature = Tuple[Signature, ...]


def _segment_manifest_root(cache: ExecutableCache) -> str:
    return os.path.join(cache.root, "manifest-segments")


def _segment_dir(cache: ExecutableCache, digest: str) -> str:
    return os.path.join(_segment_manifest_root(cache), digest)


def _segment_sig_name(sigs: SegmentSignature) -> str:
    raw = json.dumps([[list(s), d] for s, d in sigs]).encode()
    return hashlib.sha256(raw).hexdigest()[:24] + ".json"


def record_segment(
    cache: ExecutableCache, digest: str, signatures: SegmentSignature
) -> None:
    """Note that segment ``digest`` exported an executable for the input
    signature tuple ``signatures``. Best-effort, like
    :func:`record_export`: a manifest that cannot be written must never
    fail the export that still serves live."""
    try:
        sigs = tuple((tuple(int(x) for x in s), str(d)) for s, d in signatures)
        d = _segment_dir(cache, digest)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, _segment_sig_name(sigs))
        if os.path.exists(path):  # signature tuple already recorded
            return
        payload = json.dumps(
            {
                "inputs": [[list(s), dt] for s, dt in sigs],
                "created_unix": time.time(),
            },
            sort_keys=True,
        ).encode()
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    except Exception:
        logger.warning(
            "aot manifest: could not record segment %s %s", digest,
            signatures, exc_info=True,
        )


def segment_signatures(
    cache: ExecutableCache, digest: str
) -> List[SegmentSignature]:
    """Every input-signature tuple the segment ``digest`` has ever
    exported, deterministic order (sorted). Corrupt files are skipped."""
    d = _segment_dir(cache, digest)
    try:
        names = os.listdir(d)
    except OSError:
        return []
    sigs = set()
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name), "rb") as f:
                rec = json.loads(f.read().decode())
            parsed = tuple(
                (tuple(int(x) for x in shape), str(dtype))
                for shape, dtype in rec["inputs"]
            )
        except Exception:
            logger.warning(
                "aot manifest: skipping unreadable segment entry %s/%s",
                d, name,
            )
            continue
        sigs.add(parsed)
    return sorted(sigs)


def segment_digests(cache: ExecutableCache) -> List[str]:
    """Every segment digest with at least one manifest record (sorted) —
    the iteration root for fleet warm boot pre-warming."""
    try:
        names = os.listdir(_segment_manifest_root(cache))
    except OSError:
        return []
    return sorted(n for n in names if not n.startswith("."))


def exported_signatures(
    cache: ExecutableCache, digest: str
) -> List[Signature]:
    """Every ``(shape, dtype)`` the pipeline ``digest`` has ever exported,
    deterministic order (sorted). Corrupt or foreign files are skipped."""
    d = _manifest_dir(cache, digest)
    try:
        names = os.listdir(d)
    except OSError:
        return []
    sigs = set()
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(d, name), "rb") as f:
                rec = json.loads(f.read().decode())
            shape = tuple(int(x) for x in rec["shape"])
            dtype = str(rec["dtype"])
        except Exception:
            logger.warning(
                "aot manifest: skipping unreadable entry %s/%s", d, name
            )
            continue
        sigs.add((shape, dtype))
    return sorted(sigs)
