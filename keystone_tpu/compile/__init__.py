"""AOT pipeline compilation with a persistent executable cache.

The Julia→TPU paper (PAPERS.md #4) compiles whole programs to one
offline XLA artifact; a fitted KeystoneML pipeline is exactly that shape.
This package makes a :class:`~keystone_tpu.workflow.pipeline.FittedPipeline`
boot like one: the first process to compile a (pipeline, input-signature)
pair exports the traced program via ``jax.export`` into an on-disk cache,
and every later process — a restarted service, a new serving replica —
loads the executable instead of re-paying the trace. Warm boots are
milliseconds of deserialization instead of tens of seconds of tracing
and XLA compilation.

Layout of a cache directory::

    <dir>/entries/<pipeline-digest>-<signature-digest>.aot   # exported StableHLO
    <dir>/xla/                                               # layered jax compilation cache

Knobs: ``KEYSTONE_AOT_CACHE=<dir>`` (or ``--aot-cache`` on the CLI, or
``utils.obs.configure(aot_cache=...)``), ``KEYSTONE_AOT_CACHE_BYTES``
for the LRU size bound. See the README's "AOT executable cache" section
for the invalidation rules.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Optional

from .aot import AotDispatcher, signature_of
from .cache import CacheEntry, ExecutableCache
from .fingerprint import (
    FingerprintError,
    entry_key,
    environment_key,
    pipeline_fingerprint,
    segment_entry_key,
    segment_fingerprint,
)
from .manifest import (
    exported_signatures,
    record_export,
    record_segment,
    segment_digests,
    segment_signatures,
)
from .segment import (
    SegmentBinding,
    SegmentDispatcher,
    bind_segment,
    lower_segment,
    prewarm_segment_artifacts,
)

__all__ = [
    "AotDispatcher",
    "CacheEntry",
    "ExecutableCache",
    "FingerprintError",
    "SegmentBinding",
    "SegmentDispatcher",
    "bind_segment",
    "configure",
    "entry_key",
    "environment_key",
    "exported_signatures",
    "get_cache",
    "lower_segment",
    "pipeline_fingerprint",
    "prewarm_segment_artifacts",
    "record_export",
    "record_segment",
    "reset",
    "segment_digests",
    "segment_entry_key",
    "segment_fingerprint",
    "segment_signatures",
    "signature_of",
]

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_cache: Optional[ExecutableCache] = None
_initialized = False  # False => next get_cache() reads KEYSTONE_AOT_CACHE
#: jax config values overwritten by _layer_jax_compilation_cache, so
#: reset() can put them back: {config_name: prior_value}
_prior_jax_config: Optional[dict] = None
#: the XLA dir the layering itself installed — a later configure(other_dir)
#: may relocate it again (it is ours, not operator-chosen)
_layered_xla_dir: Optional[str] = None


def configure(
    path: Optional[str] = None, max_bytes: Optional[int] = None
) -> Optional[ExecutableCache]:
    """Install the process-wide executable cache.

    ``path=None`` follows ``KEYSTONE_AOT_CACHE`` (unset or empty ⇒ AOT
    caching disabled). Installing a cache also layers jax's persistent
    compilation cache underneath at ``<dir>/xla`` — so even a code path
    that re-lowers (an export round trip, a fallback live compile) hits
    a warm XLA cache on the second boot — unless the process already
    configured ``jax_compilation_cache_dir`` itself, which is respected.
    """
    global _cache, _initialized
    with _lock:
        _initialized = True
        if path is None:
            from ..utils import env_str

            path = env_str("KEYSTONE_AOT_CACHE")
        if not path:
            _cache = None
            return None
        try:
            _cache = ExecutableCache(path, max_bytes=max_bytes)
        except Exception:
            # an unwritable/invalid dir must degrade to AOT-off, not crash
            # a service that booted fine without the cache
            logger.warning(
                "aot: cache dir %r unusable — AOT caching disabled", path,
                exc_info=True,
            )
            _cache = None
            return None
        _layer_jax_compilation_cache(_cache)
        return _cache


def get_cache() -> Optional[ExecutableCache]:
    """The installed cache, or None (AOT caching off). Lazily honors
    ``KEYSTONE_AOT_CACHE`` so library callers that never touch
    ``configure`` still get caching when the environment asks for it."""
    if not _initialized:
        return configure()
    return _cache


def reset() -> None:
    """Forget the installed cache AND the env memo, and restore any jax
    config knobs :func:`configure` overwrote (test hygiene)."""
    global _cache, _initialized, _prior_jax_config, _layered_xla_dir
    with _lock:
        _cache = None
        _initialized = False
        _layered_xla_dir = None
        prior, _prior_jax_config = _prior_jax_config, None
    if prior:
        import jax

        for name, value in prior.items():
            try:
                jax.config.update(name, value)
            except Exception:  # pragma: no cover - knob absent in this jax
                logger.debug("could not restore jax config %s", name,
                             exc_info=True)


def _layer_jax_compilation_cache(cache: ExecutableCache) -> None:
    """Point jax's own persistent compilation cache under the AOT cache
    dir, so the XLA compile of a deserialized (or re-lowered) module is a
    disk lookup on warm boots — and the whole warm-boot state lives in
    ONE directory an operator can mount into a fresh replica. The package
    import-time DEFAULT (``~/.cache/keystone_tpu/xla``) is relocated here;
    a dir the operator chose (``JAX_COMPILATION_CACHE_DIR`` /
    ``KEYSTONE_COMPILE_CACHE``, or their own ``jax.config``) is kept.
    The persistence thresholds are zeroed either way: serve programs
    compile in well under the default minimum compile time, which would
    skip exactly the entries a warm boot needs."""
    global _prior_jax_config, _layered_xla_dir
    try:
        import jax

        import keystone_tpu as _pkg

        prior = _prior_jax_config if _prior_jax_config is not None else {}

        def _set(name, value):
            prior.setdefault(name, getattr(jax.config, name))
            jax.config.update(name, value)

        current_dir = jax.config.jax_compilation_cache_dir
        relocatable = (
            not current_dir
            or current_dir == getattr(_pkg, "_default_xla_cache_dir", None)
            or current_dir == _layered_xla_dir  # a previous configure()'s
        )
        if relocatable:
            os.makedirs(cache.xla_cache_dir, exist_ok=True)
            _set("jax_compilation_cache_dir", cache.xla_cache_dir)
            _layered_xla_dir = cache.xla_cache_dir
        _set("jax_persistent_cache_min_compile_time_secs", 0.0)
        _set("jax_persistent_cache_min_entry_size_bytes", -1)
        _prior_jax_config = prior
    except Exception:
        logger.warning(
            "aot: could not layer the jax persistent compilation cache",
            exc_info=True,
        )
