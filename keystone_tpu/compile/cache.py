"""The persistent executable cache: content-keyed blobs on disk.

One entry = one file = one serialized AOT executable for one
(pipeline fingerprint, input signature, environment) key. The file is
self-validating so every failure mode degrades to a cache miss, never a
crash or a wrong program:

* **atomic writes** — entries are written to a same-directory temp file
  and ``os.replace``d into place, so a concurrent reader sees either the
  old entry, the new entry, or a miss; never a torn file.
* **corruption tolerance** — magic, length framing, a sha256 payload
  checksum, and a JSON header are all validated on load; any mismatch
  (truncation, bit rot, a foreign file) logs, best-effort deletes the
  entry, and reports a miss so the caller live-compiles.
* **version invalidation** — the header records the producing
  environment (jax/jaxlib versions, backend, device kind). Entry keys
  already include the environment digest, so a toolchain upgrade simply
  misses; header validation is the belt-and-braces for hand-copied or
  doctored files.
* **LRU size bound** — loads bump the entry's mtime; stores evict
  oldest-mtime entries beyond ``max_bytes`` (``KEYSTONE_AOT_CACHE_BYTES``,
  default 1 GiB), never the entry just written. Deletion races with
  concurrent processes are benign (``FileNotFoundError`` ignored; POSIX
  keeps an open file readable after unlink).

This module is deliberately jax-free: it stores and validates bytes.
What the bytes *are* (``jax.export`` StableHLO artifacts) and how they
become callables is ``compile/aot.py``'s business.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import struct
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

_MAGIC = b"KSAOT001"
_LEN = struct.Struct("<Q")
_SUFFIX = ".aot"

DEFAULT_MAX_BYTES = 1 << 30  # 1 GiB


@dataclass
class CacheEntry:
    """A successfully loaded + validated entry."""

    key: str
    header: Dict[str, object]
    payload: bytes
    path: str

    @property
    def nbytes(self) -> int:
        return len(self.payload)


class ExecutableCache:
    """Size-bounded, multi-process-safe blob cache rooted at one directory."""

    def __init__(self, root: str, max_bytes: Optional[int] = None):
        self.root = os.path.abspath(os.path.expanduser(root))
        if max_bytes is None:
            from ..utils import env_int

            max_bytes = env_int(
                "KEYSTONE_AOT_CACHE_BYTES", DEFAULT_MAX_BYTES, minimum=0
            )
        self.max_bytes = int(max_bytes)
        os.makedirs(self.entries_dir, exist_ok=True)
        # the compile ledger shares the cache root: cache-layer movements
        # (hit/store/evict) interleave with the dispatcher's
        # trace/export/load events in one accounting stream
        from ..obs.ledger import CompileLedger

        self._ledger = CompileLedger.for_cache_root(self.root)

    @property
    def ledger(self):
        """The :class:`~keystone_tpu.obs.ledger.CompileLedger` riding
        this cache root (``compile-ledger.ndjson``)."""
        return self._ledger

    @property
    def entries_dir(self) -> str:
        return os.path.join(self.root, "entries")

    @property
    def xla_cache_dir(self) -> str:
        """Where the layered jax persistent compilation cache lives (see
        :func:`keystone_tpu.compile.configure`)."""
        return os.path.join(self.root, "xla")

    def entry_path(self, key: str) -> str:
        if os.sep in key or not key:
            raise ValueError(f"invalid cache key {key!r}")
        return os.path.join(self.entries_dir, key + _SUFFIX)

    # -- store ----------------------------------------------------------

    def store(self, key: str, payload: bytes, header: Dict[str, object]) -> str:
        """Atomically persist one entry; evicts beyond the size bound.
        Returns the entry path. IO failures propagate — callers treat a
        failed store as non-fatal (the executable still runs live)."""
        path = self.entry_path(key)
        header = dict(header)
        header["key"] = key
        header["payload_bytes"] = len(payload)
        header_bytes = json.dumps(header, sort_keys=True).encode()
        fd, tmp = tempfile.mkstemp(
            dir=self.entries_dir, prefix=".tmp-" + key[:16] + "-"
        )
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(_MAGIC)
                f.write(_LEN.pack(len(header_bytes)))
                f.write(header_bytes)
                f.write(_LEN.pack(len(payload)))
                f.write(payload)
                f.write(hashlib.sha256(payload).digest())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic on POSIX: readers see old XOR new
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        self._ledger.record("store", key=key, nbytes=len(payload))
        self._evict(keep=key)
        return path

    # -- load -----------------------------------------------------------

    def load(
        self, key: str, expect_env: Optional[Dict[str, str]] = None
    ) -> Optional[CacheEntry]:
        """Load + validate one entry. Returns None on miss, corruption,
        or environment mismatch — never raises for on-disk problems. A
        hit bumps the entry's mtime (the LRU recency signal)."""
        from ..faults import AOT_READ, fault_point, is_transient

        try:
            # the chaos seam for cache reads: a transient fault here is
            # exactly a flaky filesystem, and the recovery is the one the
            # cache already has — degrade to a miss (the caller traces
            # live and re-exports), never fail the serving boot
            fault_point(AOT_READ, key=key)
        except Exception as e:
            if is_transient(e):
                logger.warning(
                    "aot cache: transient read fault for %s — degrading "
                    "to a miss", key,
                )
                # the recovery instant for the aot.read fault site (lint
                # rule 4): the degrade-to-miss verdict must be visible in
                # a flight dump / trace, not only in the log stream
                from ..obs import flight as _flight
                from ..obs.tracer import current as _trace_current

                _flight.record_instant("aot.read_degraded", key=key)
                tracer = _trace_current()
                if tracer is not None:
                    tracer.instant(
                        "aot.read_degraded", op_type="AotCache", key=key
                    )
                return None
            raise
        path = self.entry_path(key)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            logger.warning("aot cache: unreadable entry %s", path, exc_info=True)
            return None
        entry = self._parse(key, data, path)
        if entry is None:
            self._discard(path, "corrupt")
            return None
        if expect_env is not None:
            got = entry.header.get("env")
            if got != dict(expect_env):
                # a different toolchain's artifact — stale, not corrupt
                logger.info(
                    "aot cache: environment mismatch for %s (entry %s, want %s)",
                    key, got, dict(expect_env),
                )
                return None
        try:
            os.utime(path)  # LRU recency; racing an eviction is benign
        except OSError:
            pass
        self._ledger.record("hit", key=key, nbytes=entry.nbytes)
        return entry

    def _parse(self, key: str, data: bytes, path: str) -> Optional[CacheEntry]:
        try:
            if data[: len(_MAGIC)] != _MAGIC:
                return None
            off = len(_MAGIC)
            (hlen,) = _LEN.unpack_from(data, off)
            off += _LEN.size
            header = json.loads(data[off : off + hlen].decode())
            off += hlen
            (plen,) = _LEN.unpack_from(data, off)
            off += _LEN.size
            payload = data[off : off + plen]
            digest = data[off + plen : off + plen + 32]
            if len(payload) != plen or len(digest) != 32:
                return None  # truncated
            if hashlib.sha256(payload).digest() != digest:
                return None  # bit rot / torn copy
            if header.get("key") != key:
                return None  # renamed / foreign file
            return CacheEntry(key=key, header=header, payload=payload, path=path)
        except Exception:
            # unreadable/corrupt entry degrades to a miss by contract
            logger.debug("aot cache: unreadable entry %s", path,
                         exc_info=True)
            return None

    def _discard(self, path: str, why: str) -> None:
        logger.warning("aot cache: discarding %s entry %s", why, path)
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- maintenance ----------------------------------------------------

    def entries(self) -> List[Tuple[str, int, float]]:
        """``(key, bytes, mtime)`` for every present entry, oldest first."""
        rows = []
        try:
            names = os.listdir(self.entries_dir)
        except OSError:
            return []
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            try:
                st = os.stat(os.path.join(self.entries_dir, name))
            except OSError:
                continue  # evicted by a concurrent process mid-listing
            rows.append((name[: -len(_SUFFIX)], st.st_size, st.st_mtime))
        rows.sort(key=lambda r: r[2])
        return rows

    def total_bytes(self) -> int:
        return sum(size for _, size, _ in self.entries())

    def _evict(self, keep: Optional[str] = None) -> int:
        """Drop oldest-mtime entries until under ``max_bytes``; never the
        ``keep`` key (the entry just written). Returns entries removed."""
        rows = self.entries()
        total = sum(size for _, size, _ in rows)
        removed = 0
        for key, size, _ in rows:
            if total <= self.max_bytes:
                break
            if key == keep:
                continue
            try:
                os.unlink(self.entry_path(key))
            except OSError:
                continue
            self._ledger.record("evict", key=key, nbytes=size)
            total -= size
            removed += 1
        if removed:
            logger.info(
                "aot cache: evicted %d entr%s (size bound %d bytes)",
                removed, "y" if removed == 1 else "ies", self.max_bytes,
            )
        return removed
