"""Native (C++) host-runtime accelerators, ctypes-loaded, always optional.

The TPU compute path is XLA/Pallas; this package is the native runtime
around it for host-side hot loops that cannot ride the device — the
counterpart of the reference's native layer (its `src/main/cpp` JNI
wrappers front VLFeat/enceval image code, which THIS framework subsumes
on-device; what remains host-bound here is text featurization's
per-character hashing). Design rules:

* built lazily with ``g++`` on first use, cached next to the source
  keyed by a source hash; no build system, no pybind11 — plain
  ``extern "C"`` + ctypes;
* bit-exact with the pure-Python implementations (asserted in
  tests/nodes/test_native_hashing.py) — the Python path is the spec,
  the native path is the speed;
* every caller falls back to pure Python when the toolchain or build is
  unavailable (``KEYSTONE_NO_NATIVE=1`` forces the fallback).
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import threading
from typing import List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    src = os.path.join(_HERE, "hashing.cpp")
    with open(src, "rb") as f:
        digest = hashlib.sha1(f.read()).hexdigest()[:12]
    build_dir = os.path.join(_HERE, "_build")
    so_path = os.path.join(build_dir, f"libkshash-{digest}.so")
    if not os.path.exists(so_path):
        os.makedirs(build_dir, exist_ok=True)
        tmp = so_path + f".tmp{os.getpid()}"
        cmd = ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, src]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            logger.warning(
                "native hashing build failed (falling back to Python): %s",
                proc.stderr[-500:],
            )
            return None
        os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    lib = ctypes.CDLL(so_path)
    lib.ks_java_string_hash_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
    ]
    lib.ks_java_string_hash_batch.restype = None
    lib.ks_ngram_hash_features_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
        ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.ks_ngram_hash_features_batch.restype = ctypes.c_int64
    lib.ks_text_frontend.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.ks_text_frontend.restype = ctypes.c_int64
    lib.ks_packed_grams_unique.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.ks_packed_grams_unique.restype = ctypes.c_int64
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded native library, or None (no toolchain / disabled)."""
    global _LIB, _LIB_FAILED
    from ..utils import env_flag

    if env_flag("KEYSTONE_NO_NATIVE", False):
        return None
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _LOCK:
        if _LIB is None and not _LIB_FAILED:
            try:
                _LIB = _build_and_load()
            except Exception as e:  # toolchain quirks → Python fallback
                logger.warning("native hashing unavailable: %s", e)
                _LIB = None
            if _LIB is None:
                _LIB_FAILED = True
    return _LIB


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def java_string_hash_batch(tokens: Sequence[str]) -> Optional[np.ndarray]:
    """(n,) int32 java hashCodes of ``tokens``, or None if native is
    unavailable. Bit-exact with hashing.java_string_hash (which matches
    the ord()-codepoint semantics of the Python loop)."""
    lib = get_lib()
    if lib is None:
        return None
    lens = np.fromiter(
        (len(t) for t in tokens), dtype=np.int64, count=len(tokens)
    )
    offsets = np.zeros(len(tokens) + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    blob = "".join(tokens)
    try:
        encoded = blob.encode("utf-32-le")
    except UnicodeEncodeError:
        # lone surrogates (e.g. surrogateescape-decoded bytes) cannot be
        # UTF-32-encoded; decline so callers take the ord()-based Python
        # path, which handles them
        return None
    cps = np.frombuffer(encoded, dtype=np.uint32)
    out = np.empty(len(tokens), dtype=np.int32)
    lib.ks_java_string_hash_batch(
        _ptr(cps), _ptr(offsets), len(tokens), _ptr(out)
    )
    return out


def text_frontend_batch(
    docs: Sequence[str],
    vocab_tokens: Sequence[str],
    grow: bool,
    trim: bool = True,
    lower: bool = True,
):
    """Fused trim→lowercase→tokenize→token-id pass over a raw-string corpus
    (spec: Trim/LowerCase/Tokenizer in nodes/nlp/text.py followed by
    packed_features._token_ids). Returns ``(ids int64, tok_doc_offsets
    int64, new_tokens list[str])`` — per-doc id slices delimited by the
    offsets, new vocabulary entries in first-seen order starting at
    ``len(vocab_tokens)`` — or None when native is unavailable or the
    corpus/vocab is not pure ASCII (the Python path's unicode ``\\w``
    semantics then apply)."""
    lib = get_lib()
    if lib is None:
        return None
    try:
        blob = "".join(docs).encode("ascii")
        vblob = "".join(vocab_tokens).encode("ascii")
    except UnicodeEncodeError:
        return None
    n_docs = len(docs)
    doc_off = np.zeros(n_docs + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter((len(d) for d in docs), dtype=np.int64, count=n_docs),
        out=doc_off[1:],
    )
    v_off = np.zeros(len(vocab_tokens) + 1, dtype=np.int64)
    np.cumsum(
        np.fromiter(
            (len(t) for t in vocab_tokens), dtype=np.int64,
            count=len(vocab_tokens),
        ),
        out=v_off[1:],
    )
    text_len = int(doc_off[-1])
    cap = text_len + n_docs + 1
    ids = np.empty(cap, dtype=np.int64)
    tok_off = np.zeros(n_docs + 1, dtype=np.int64)
    new_bytes = np.empty(max(text_len, 1), dtype=np.uint8)
    new_off = np.zeros(cap, dtype=np.int64)
    new_count = np.zeros(1, dtype=np.int64)
    tbuf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(1, np.uint8)
    vbuf = np.frombuffer(vblob, dtype=np.uint8) if vblob else np.zeros(1, np.uint8)
    ntok = lib.ks_text_frontend(
        _ptr(tbuf), _ptr(doc_off), n_docs,
        int(trim), int(lower),
        _ptr(vbuf), _ptr(v_off), len(vocab_tokens),
        int(grow),
        _ptr(ids), _ptr(tok_off),
        _ptr(new_bytes), _ptr(new_off), _ptr(new_count),
    )
    if ntok < 0:  # pragma: no cover - defensive
        return None
    nc = int(new_count[0])
    nb = new_bytes[: int(new_off[nc])].tobytes().decode("ascii")
    new_tokens = [
        nb[int(new_off[i]) : int(new_off[i + 1])] for i in range(nc)
    ]
    return ids[:ntok], tok_off, new_tokens


def packed_grams_unique(
    ids_list: Sequence[np.ndarray], orders: Sequence[int]
):
    """Per-(doc, gram) unique counts over packed n-grams — the native form
    of packed_features._corpus_grams + _per_doc_unique (doc-local sorts
    instead of a corpus lexsort). Returns ``(d_u, g_u, counts)`` in the
    same doc-major / first-emission order, or None if native is
    unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n_docs = len(ids_list)
    lens = np.fromiter(
        (len(a) for a in ids_list), dtype=np.int64, count=n_docs
    )
    tok_off = np.zeros(n_docs + 1, dtype=np.int64)
    np.cumsum(lens, out=tok_off[1:])
    flat = (
        np.ascontiguousarray(np.concatenate(ids_list), dtype=np.int64)
        if int(tok_off[-1])
        else np.zeros(1, dtype=np.int64)
    )
    orders_arr = np.asarray(orders, dtype=np.int32)
    cap = 0
    for o in orders:
        cap += int(np.maximum(lens - o + 1, 0).sum())
    cap = max(cap, 1)
    d_u = np.empty(cap, dtype=np.int64)
    g_u = np.empty(cap, dtype=np.int64)
    counts = np.empty(cap, dtype=np.int64)
    m = lib.ks_packed_grams_unique(
        _ptr(flat), _ptr(tok_off), n_docs,
        _ptr(orders_arr), len(orders_arr),
        _ptr(d_u), _ptr(g_u), _ptr(counts),
    )
    if m < 0:  # unsupported order: let the numpy path raise its error
        return None
    return d_u[:m], g_u[:m], counts[:m]


def ngram_hash_features_batch(
    token_hashes: np.ndarray,
    doc_offsets: np.ndarray,
    min_order: int,
    max_order: int,
    num_features: int,
    seq_seed: int,
):
    """Rolling n-gram feature indices (NGramsHashingTF's inner loops) as
    ``(flat_features int32, out_offsets int64)`` with out_offsets
    delimiting each doc's slice — or None if native is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    token_hashes = np.ascontiguousarray(token_hashes, dtype=np.int32)
    doc_offsets = np.ascontiguousarray(doc_offsets, dtype=np.int64)
    n_docs = len(doc_offsets) - 1
    doc_lens = np.diff(doc_offsets)
    # features per doc: Σ_i (min(max_order, n−i) − min_order + 1) over
    # valid starts — closed form via counts of each achievable order
    counts = np.zeros(n_docs, dtype=np.int64)
    for order in range(min_order, max_order + 1):
        counts += np.maximum(doc_lens - order + 1, 0)
    out_offsets = np.zeros(n_docs + 1, dtype=np.int64)
    np.cumsum(counts, out=out_offsets[1:])
    out = np.empty(int(out_offsets[-1]), dtype=np.int32)
    written = lib.ks_ngram_hash_features_batch(
        _ptr(token_hashes), _ptr(doc_offsets), n_docs,
        min_order, max_order, num_features,
        ctypes.c_uint32(seq_seed & 0xFFFFFFFF), _ptr(out_offsets), _ptr(out),
    )
    if written != len(out):  # pragma: no cover - count model mismatch
        raise AssertionError(
            f"native n-gram feature count {written} != expected {len(out)}"
        )
    return out, out_offsets
