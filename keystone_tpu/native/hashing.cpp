// Native batch hashing for the text featurization host path.
//
// Bit-exact counterparts of keystone_tpu/nodes/nlp/hashing.py (which is
// itself bit-exact with the reference's Scala `.##` / MurmurHash3.seqHash
// — HashingTF.scala:15-32, NGramsHashingTF.scala:25-146). The Python
// loops hash per character / per n-gram position in the interpreter; the
// corpus-level batch forms here do the same arithmetic over flat arrays.
// Strings arrive as UTF-32 codepoint arrays (matching the Python
// implementation's ord()-based loop).
//
// Built by keystone_tpu/native/__init__.py with g++ at first use and
// loaded via ctypes; everything stays available in pure Python when no
// compiler is present.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

inline uint32_t rotl32(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

inline uint32_t mix(uint32_t h, uint32_t k) {
  k *= 0xCC9E2D51u;
  k = rotl32(k, 15);
  k *= 0x1B873593u;
  h ^= k;
  h = rotl32(h, 13);
  return h * 5u + 0xE6546B64u;
}

inline int32_t finalize(uint32_t h, uint32_t length) {
  h ^= length;
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return (int32_t)h;
}

inline int32_t non_negative_mod(int32_t x, int32_t mod) {
  int32_t r = x % mod;
  return r < 0 ? r + mod : r;
}

}  // namespace

extern "C" {

// java.lang.String.hashCode over n strings packed as UTF-32 codepoints.
// offsets has n+1 entries delimiting each string in cps.
void ks_java_string_hash_batch(const uint32_t* cps, const int64_t* offsets,
                               int64_t n, int32_t* out) {
  for (int64_t s = 0; s < n; ++s) {
    uint32_t h = 0;
    for (int64_t i = offsets[s]; i < offsets[s + 1]; ++i) {
      h = h * 31u + cps[i];
    }
    out[s] = (int32_t)h;
  }
}

// The rolling n-gram feature stream of NGramsHashingTF.apply: for every
// start position, hash the min_order-gram, then extend one token at a
// time up to max_order, emitting non_negative_mod(finalize(h, order), F)
// at each order. doc_offsets (n_docs+1) delimits token_hashes per doc;
// out_offsets (n_docs+1) delimits the (precomputed) per-doc output
// counts. Returns total features written.
int64_t ks_ngram_hash_features_batch(
    const int32_t* token_hashes, const int64_t* doc_offsets, int64_t n_docs,
    int32_t min_order, int32_t max_order, int32_t num_features,
    uint32_t seq_seed, const int64_t* out_offsets, int32_t* out) {
  int64_t written = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int32_t* th = token_hashes + doc_offsets[d];
    const int64_t n = doc_offsets[d + 1] - doc_offsets[d];
    int32_t* w = out + out_offsets[d];
    for (int64_t i = 0; i + min_order <= n; ++i) {
      uint32_t h = seq_seed;
      for (int64_t j = i; j < i + min_order; ++j) {
        h = mix(h, (uint32_t)th[j]);
      }
      *w++ = non_negative_mod(finalize(h, (uint32_t)min_order),
                              num_features);
      for (int32_t order = min_order + 1;
           order <= max_order && i + order <= n; ++order) {
        h = mix(h, (uint32_t)th[i + order - 1]);
        *w++ = non_negative_mod(finalize(h, (uint32_t)order), num_features);
      }
    }
    written += w - (out + out_offsets[d]);
  }
  return written;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused text frontend: trim -> lowercase -> tokenize -> first-seen vocab ids,
// one pass over the concatenated ASCII corpus. Semantics are pinned to the
// Python chain Trim -> LowerCase -> Tokenizer(r"[^\w]+") -> _token_ids
// (nodes/nlp/text.py + packed_features.py), which remains the spec and the
// fallback; the Python caller guarantees pure-ASCII input (non-ASCII corpora
// take the Python path, where re's unicode \w applies).
//
// Tokenizer parity details reproduced exactly:
//   * split on runs of non-[A-Za-z0-9_];
//   * a doc starting with a separator contributes one leading EMPTY token
//     (Java String.split keeps it; trailing empties are dropped);
//   * an empty (or all-whitespace, post-trim) doc contributes no tokens;
//   * ids are assigned in first-seen order over the concatenated stream
//     (grow=1), or looked up with -1 for unknowns (grow=0).

namespace {

inline bool is_word_ascii(unsigned char c) {
  return (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
         (c >= '0' && c <= '9') || c == '_';
}

inline bool is_space_ascii(unsigned char c) {
  // str.strip() whitespace, ASCII subset: \t-\r, the \x1c-\x1f
  // file/group/record/unit separators, and space
  return c == ' ' || (c >= '\t' && c <= '\r') || (c >= 0x1c && c <= 0x1f);
}

inline uint64_t fnv1a(const char* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < n; ++i) {
    h ^= (unsigned char)p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// open-addressing token table: slot -> entry index + 1 (0 = empty)
struct TokenMap {
  struct Entry {
    const char* ptr;
    int64_t len;
    int64_t id;
    uint64_t hash;
  };
  std::vector<int64_t> slots;
  std::vector<Entry> entries;
  uint64_t mask;

  explicit TokenMap(int64_t expected) {
    int64_t cap = 16;
    while (cap < expected * 2) cap <<= 1;
    slots.assign(cap, 0);
    mask = (uint64_t)cap - 1;
  }

  void rehash() {
    int64_t cap = (int64_t)slots.size() * 2;
    slots.assign(cap, 0);
    mask = (uint64_t)cap - 1;
    for (int64_t i = 0; i < (int64_t)entries.size(); ++i) {
      uint64_t s = entries[i].hash & mask;
      while (slots[s]) s = (s + 1) & mask;
      slots[s] = i + 1;
    }
  }

  // returns id, or -1 when absent and insert_id < 0
  int64_t lookup_or_insert(const char* p, int64_t n, int64_t insert_id,
                           bool* inserted) {
    uint64_t h = fnv1a(p, n);
    uint64_t s = h & mask;
    while (slots[s]) {
      const Entry& e = entries[slots[s] - 1];
      if (e.hash == h && e.len == n && std::memcmp(e.ptr, p, n) == 0) {
        *inserted = false;
        return e.id;
      }
      s = (s + 1) & mask;
    }
    if (insert_id < 0) {
      *inserted = false;
      return -1;
    }
    entries.push_back({p, n, insert_id, h});
    slots[s] = (int64_t)entries.size();
    *inserted = true;
    if ((uint64_t)entries.size() * 3 > slots.size() * 2) rehash();
    return insert_id;
  }
};

}  // namespace

extern "C" {

// Returns the total token count (<= 0 on error). Buffers sized by caller:
// ids_out: text_len + n_docs entries; tok_doc_off_out: n_docs + 1;
// new_bytes_out: text_len bytes; new_off_out: text_len + n_docs + 1
// (offsets, first entry 0); new_count_out: 1.
int64_t ks_text_frontend(
    const char* text, const int64_t* doc_off, int64_t n_docs,
    int32_t do_trim, int32_t do_lower,
    const char* vocab_bytes, const int64_t* vocab_off, int64_t vocab_n,
    int32_t grow,
    int64_t* ids_out, int64_t* tok_doc_off_out,
    char* new_bytes_out, int64_t* new_off_out, int64_t* new_count_out) {
  const int64_t text_len = doc_off[n_docs];
  // lowercased working copy (token entries point into it, so it must
  // outlive the map — new-token bytes are copied to new_bytes_out before
  // return, making the map/table disposable)
  std::vector<char> buf(text, text + text_len);
  if (do_lower) {
    for (int64_t i = 0; i < text_len; ++i) {
      unsigned char c = (unsigned char)buf[i];
      if (c >= 'A' && c <= 'Z') buf[i] = (char)(c + 32);
    }
  }
  TokenMap map(vocab_n + 1024);
  for (int64_t v = 0; v < vocab_n; ++v) {
    bool ins;
    map.lookup_or_insert(vocab_bytes + vocab_off[v],
                         vocab_off[v + 1] - vocab_off[v], v, &ins);
  }
  int64_t next_id = vocab_n;
  int64_t ntok = 0;
  int64_t new_count = 0;
  int64_t new_bytes = 0;
  new_off_out[0] = 0;
  tok_doc_off_out[0] = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const char* s = buf.data() + doc_off[d];
    const char* e = buf.data() + doc_off[d + 1];
    if (do_trim) {
      while (s < e && is_space_ascii((unsigned char)*s)) ++s;
      while (e > s && is_space_ascii((unsigned char)e[-1])) --e;
    }
    const char* p = s;
    // a leading separator run yields one empty token, but ONLY if a word
    // token follows (otherwise Python's trailing-empty pop removes it too:
    // "++--++" tokenizes to nothing) — emit it lazily before the first word
    bool pending_empty = (p < e && !is_word_ascii((unsigned char)*p));
    while (true) {
      while (p < e && !is_word_ascii((unsigned char)*p)) ++p;
      if (p >= e) break;
      const char* t0 = p;
      while (p < e && is_word_ascii((unsigned char)*p)) ++p;
      for (int emit_empty = pending_empty ? 1 : 0; emit_empty >= 0;
           --emit_empty) {
        const char* tp = emit_empty ? s : t0;
        const int64_t tlen = emit_empty ? 0 : p - t0;
        bool inserted;
        int64_t id =
            map.lookup_or_insert(tp, tlen, grow ? next_id : -1, &inserted);
        if (inserted) {
          std::memcpy(new_bytes_out + new_bytes, tp, tlen);
          new_bytes += tlen;
          new_off_out[++new_count] = new_bytes;
          ++next_id;
        }
        ids_out[ntok++] = id;
      }
      pending_empty = false;
    }
    tok_doc_off_out[d + 1] = ntok;
  }
  *new_count_out = new_count;
  return ntok;
}

// Packed n-gram emission + per-doc uniquing, fused — the native form of
// packed_features._corpus_grams + _per_doc_unique. The numpy form pays a
// corpus-wide lexsort over every (doc, gram) pair; grams never cross doc
// boundaries, so doc-local sorts of ~tens of entries do the same work in
// cache. Bit-packing replicates NaiveBitPackIndexer.pack_batch exactly
// (20-bit ids, control bits 1<<60 / 1<<61); grams containing a -1 OOV id
// are dropped; output pairs are doc-major, within-doc ordered by FIRST
// EMISSION (position-major, then order ascending) — the uid order the
// selection tie-break depends on. Returns the unique-pair count.
int64_t ks_packed_grams_unique(
    const int64_t* ids, const int64_t* tok_off, int64_t n_docs,
    const int32_t* orders, int32_t n_orders,
    int64_t* d_u, int64_t* g_u, int64_t* counts_out) {
  struct Gram {
    int64_t packed;
    int64_t emit;
  };
  for (int32_t oi = 0; oi < n_orders; ++oi) {
    if (orders[oi] < 1 || orders[oi] > 3) return -1;  // wrapper falls back
  }
  std::vector<Gram> grams;
  std::vector<Gram> uniq;
  int64_t written = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int64_t* t = ids + tok_off[d];
    const int64_t n = tok_off[d + 1] - tok_off[d];
    grams.clear();
    for (int32_t oi = 0; oi < n_orders; ++oi) {
      const int32_t order = orders[oi];
      for (int64_t i = 0; i + order <= n; ++i) {
        int64_t packed;
        if (order == 1) {
          if (t[i] < 0) continue;
          packed = t[i] << 40;
        } else if (order == 2) {
          if (t[i] < 0 || t[i + 1] < 0) continue;
          packed = (t[i + 1] << 20) | (t[i] << 40) | (int64_t(1) << 60);
        } else {
          if (t[i] < 0 || t[i + 1] < 0 || t[i + 2] < 0) continue;
          packed = t[i + 2] | (t[i + 1] << 20) | (t[i] << 40) |
                   (int64_t(1) << 61);
        }
        grams.push_back({packed, i * n_orders + oi});
      }
    }
    std::sort(grams.begin(), grams.end(), [](const Gram& a, const Gram& b) {
      return a.packed != b.packed ? a.packed < b.packed : a.emit < b.emit;
    });
    uniq.clear();
    int64_t i = 0;
    while (i < (int64_t)grams.size()) {
      int64_t j = i + 1;
      while (j < (int64_t)grams.size() &&
             grams[j].packed == grams[i].packed) {
        ++j;
      }
      // grams[i].emit is the min emit key of the run (sorted tie-break)
      uniq.push_back({grams[i].packed, grams[i].emit});
      counts_out[written + (int64_t)uniq.size() - 1] = j - i;
      i = j;
    }
    // counts were written in gram order; reorder all three outputs by
    // first-emission via an index sort over the doc's unique entries
    std::vector<int64_t> order_idx(uniq.size());
    for (size_t x = 0; x < uniq.size(); ++x) order_idx[x] = (int64_t)x;
    std::sort(order_idx.begin(), order_idx.end(),
              [&](int64_t a, int64_t b) { return uniq[a].emit < uniq[b].emit; });
    std::vector<int64_t> counts_tmp(uniq.size());
    for (size_t x = 0; x < uniq.size(); ++x) {
      counts_tmp[x] = counts_out[written + order_idx[x]];
    }
    for (size_t x = 0; x < uniq.size(); ++x) {
      d_u[written + (int64_t)x] = d;
      g_u[written + (int64_t)x] = uniq[order_idx[x]].packed;
      counts_out[written + (int64_t)x] = counts_tmp[x];
    }
    written += (int64_t)uniq.size();
  }
  return written;
}

}  // extern "C"
