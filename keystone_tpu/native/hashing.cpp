// Native batch hashing for the text featurization host path.
//
// Bit-exact counterparts of keystone_tpu/nodes/nlp/hashing.py (which is
// itself bit-exact with the reference's Scala `.##` / MurmurHash3.seqHash
// — HashingTF.scala:15-32, NGramsHashingTF.scala:25-146). The Python
// loops hash per character / per n-gram position in the interpreter; the
// corpus-level batch forms here do the same arithmetic over flat arrays.
// Strings arrive as UTF-32 codepoint arrays (matching the Python
// implementation's ord()-based loop).
//
// Built by keystone_tpu/native/__init__.py with g++ at first use and
// loaded via ctypes; everything stays available in pure Python when no
// compiler is present.

#include <cstdint>

namespace {

inline uint32_t rotl32(uint32_t x, int n) {
  return (x << n) | (x >> (32 - n));
}

inline uint32_t mix(uint32_t h, uint32_t k) {
  k *= 0xCC9E2D51u;
  k = rotl32(k, 15);
  k *= 0x1B873593u;
  h ^= k;
  h = rotl32(h, 13);
  return h * 5u + 0xE6546B64u;
}

inline int32_t finalize(uint32_t h, uint32_t length) {
  h ^= length;
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  h ^= h >> 16;
  return (int32_t)h;
}

inline int32_t non_negative_mod(int32_t x, int32_t mod) {
  int32_t r = x % mod;
  return r < 0 ? r + mod : r;
}

}  // namespace

extern "C" {

// java.lang.String.hashCode over n strings packed as UTF-32 codepoints.
// offsets has n+1 entries delimiting each string in cps.
void ks_java_string_hash_batch(const uint32_t* cps, const int64_t* offsets,
                               int64_t n, int32_t* out) {
  for (int64_t s = 0; s < n; ++s) {
    uint32_t h = 0;
    for (int64_t i = offsets[s]; i < offsets[s + 1]; ++i) {
      h = h * 31u + cps[i];
    }
    out[s] = (int32_t)h;
  }
}

// The rolling n-gram feature stream of NGramsHashingTF.apply: for every
// start position, hash the min_order-gram, then extend one token at a
// time up to max_order, emitting non_negative_mod(finalize(h, order), F)
// at each order. doc_offsets (n_docs+1) delimits token_hashes per doc;
// out_offsets (n_docs+1) delimits the (precomputed) per-doc output
// counts. Returns total features written.
int64_t ks_ngram_hash_features_batch(
    const int32_t* token_hashes, const int64_t* doc_offsets, int64_t n_docs,
    int32_t min_order, int32_t max_order, int32_t num_features,
    uint32_t seq_seed, const int64_t* out_offsets, int32_t* out) {
  int64_t written = 0;
  for (int64_t d = 0; d < n_docs; ++d) {
    const int32_t* th = token_hashes + doc_offsets[d];
    const int64_t n = doc_offsets[d + 1] - doc_offsets[d];
    int32_t* w = out + out_offsets[d];
    for (int64_t i = 0; i + min_order <= n; ++i) {
      uint32_t h = seq_seed;
      for (int64_t j = i; j < i + min_order; ++j) {
        h = mix(h, (uint32_t)th[j]);
      }
      *w++ = non_negative_mod(finalize(h, (uint32_t)min_order),
                              num_features);
      for (int32_t order = min_order + 1;
           order <= max_order && i + order <= n; ++order) {
        h = mix(h, (uint32_t)th[i + order - 1]);
        *w++ = non_negative_mod(finalize(h, (uint32_t)order), num_features);
      }
    }
    written += w - (out + out_offsets[d]);
  }
  return written;
}

}  // extern "C"
