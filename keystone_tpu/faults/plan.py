"""Deterministic, seeded fault injection: the plan and its hook.

Distributed ML programs die not in the math but in the failure modes
around it — stragglers, lost workers, restarted jobs (the Spark-perf
study, PAPERS.md #3). This module is the chaos seam that lets the repo
TEST those modes deterministically: named :func:`fault_point` hooks are
instrumented into the hot paths that talk to the outside world (chunk
production, H2D staging, replica batch execution, AOT cache reads), and
a :class:`FaultPlan` — installed in code or parsed from the
``KEYSTONE_FAULTS`` environment variable — decides which invocations of
which sites raise which typed error.

With no plan installed and ``KEYSTONE_FAULTS`` unset, every fault point
is a no-op: one None-returning lookup, no locks, no logging — the hot
path pays nothing.

Plan grammar (``KEYSTONE_FAULTS``)::

    plan    := clause (';' clause)*
    clause  := site ['#' match] '=' kind ['@' hits]
    kind    := 'transient' | 'fatal' | 'kill'
    hits    := index (',' index)*            # exact 0-based invocation
                                             # indices at that site
             | 'p' RATE ['x' LIMIT] ['s' SEED]   # seeded Bernoulli per
                                             # invocation, at most LIMIT
                                             # faults, from SEED

``site`` names an instrumented hook (see the constants below). ``#match``
restricts the clause to invocations whose ``replica=`` context attribute
equals ``match`` (e.g. ``replica.batch#0`` faults only replica 0's
batches); each clause counts its MATCHING invocations independently,
so indices are deterministic per clause. Omitting ``@hits`` means
``@0`` — the first matching invocation.

Kinds:

* ``transient`` raises :class:`FaultInjected` (a :class:`TransientError`)
  — what the retry/requeue machinery recovers from;
* ``fatal`` raises :class:`FatalFaultInjected` — never retried, the
  "kill this fit so resume can be tested" error;
* ``kill`` raises :class:`ReplicaKilled` (a ``BaseException`` subclass,
  like ``KeyboardInterrupt``) — it deliberately punches through
  ``except Exception`` backstops to simulate a worker thread dying
  mid-loop; only the fleet's supervisor catches it.

Examples::

    KEYSTONE_FAULTS="scan.chunk=transient@2,5"       # chunks 2 and 5 fault once each
    KEYSTONE_FAULTS="scan.stage=transient@p0.2x3s7"  # ~20% of stagings, at most 3, seed 7
    KEYSTONE_FAULTS="replica.batch#1=kill@3"         # replica 1's 4th batch kills its thread
    KEYSTONE_FAULTS="aot.read=transient@0;scan.chunk=fatal@8"
"""

from __future__ import annotations

import logging
import os
import random
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# -- the instrumented sites --------------------------------------------------

#: chunk production inside the pipelined scan's producer thread
SCAN_CHUNK = "scan.chunk"
#: H2D staging of a produced chunk onto its lane device
SCAN_STAGE = "scan.stage"
#: one replica micro-batch execution (context attr ``replica=index``)
REPLICA_BATCH = "replica.batch"
#: one AOT executable-cache read (degrades to a miss on transient fault)
AOT_READ = "aot.read"
#: one cluster worker-process spawn attempt (router side, before fork —
#: transient => the router's spawn retry/restart budget absorbs it)
WORKER_SPAWN = "worker.spawn"
#: one trainer-daemon tail of the append-only chunk source (transient =>
#: the daemon's bounded ingest retry; kill => the daemon supervisor)
TRAINER_INGEST = "trainer.ingest"
#: one chunk folded by a trainer absorb (fires per folded chunk, INSIDE
#: the checkpointed fold — a kill here leaves the last completed block
#: on disk, so the retried absorb resumes instead of rescanning)
TRAINER_ABSORB = "trainer.absorb"
#: one trainer canary attempt, before the fleet swap is entered
#: (transient => counted as canary evidence failure: rollback + bounded
#: batch retry, old model keeps serving)
TRAINER_CANARY = "trainer.canary"
#: one autoscaler scale-up apply, AFTER the new slot is spawned and
#: BEFORE it reports ready — a kill here is a worker dying mid-scale-up:
#: the scaler reaps the half-born slot (``scale.abort`` instant) and the
#: next post-cooldown tick converges the fleet back to policy bounds
SCALE_SPAWN = "scale.spawn"
#: one autoscaler scale-down apply, after the drain begins — a kill here
#: is a worker dying mid-drain: the scaler force-retires it and the
#: router's down-handler requeues its in-flight work, deadlines intact
SCALE_DRAIN = "scale.drain"

_KINDS = ("transient", "fatal", "kill")


# -- typed errors ------------------------------------------------------------


class TransientError(Exception):
    """Classification base for failures worth retrying: the operation is
    expected to succeed if re-executed (flaky I/O, a dropped connection,
    an injected chaos fault). The recovery machinery retries ONLY errors
    classified transient; everything else propagates untouched."""


class FaultInjected(TransientError):
    """A ``transient``-kind fault raised by :func:`fault_point`."""

    def __init__(self, site: str, invocation: int):
        super().__init__(
            f"injected transient fault at {site} (invocation {invocation})"
        )
        self.site = site
        self.invocation = invocation


class FatalFaultInjected(RuntimeError):
    """A ``fatal``-kind fault: never classified transient, never retried
    — the way a chaos schedule kills a fit so resume can be tested."""

    def __init__(self, site: str, invocation: int):
        super().__init__(
            f"injected fatal fault at {site} (invocation {invocation})"
        )
        self.site = site
        self.invocation = invocation


class ReplicaDown(BaseException):
    """Base of the worker-death signals. A ``BaseException`` on purpose:
    it must punch through the ``except Exception`` backstops between a
    batch loop and the fleet supervisor, exactly like a real thread
    death would bypass them. ``pending`` carries the requests the dying
    worker leaves unanswered, for the supervisor to requeue."""

    def __init__(self, message: str):
        super().__init__(message)
        self.pending: Optional[list] = None


class ReplicaKilled(ReplicaDown):
    """A ``kill``-kind fault: the replica's thread dies here."""


def is_transient(exc: BaseException) -> bool:
    """The retry classification: our typed :class:`TransientError` plus
    the stdlib families that mean "the world hiccuped" rather than "the
    program is wrong"."""
    return isinstance(exc, (TransientError, ConnectionError, TimeoutError))


# -- plan --------------------------------------------------------------------


@dataclass
class FaultSpec:
    """One parsed clause: which invocations of ``site`` fault, and how."""

    site: str
    kind: str
    #: exact 0-based matching-invocation indices (None = probabilistic)
    at: Optional[frozenset] = None
    rate: float = 0.0
    limit: Optional[int] = None
    seed: int = 0
    #: restrict to invocations whose ``replica`` context attr equals this
    match: Optional[int] = None
    # runtime state (reset()-able)
    count: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)
    _rng: Optional[random.Random] = field(default=None, compare=False,
                                          repr=False)

    def reset(self) -> None:
        self.count = 0
        self.fired = 0
        self._rng = None

    def _hit(self) -> bool:
        i = self.count
        self.count += 1
        if self.at is not None:
            if i in self.at:
                self.fired += 1
                return True
            return False
        if self.limit is not None and self.fired >= self.limit:
            return False
        if self._rng is None:
            self._rng = random.Random(self.seed)
        if self._rng.random() < self.rate:
            self.fired += 1
            return True
        return False


class FaultPlan:
    """A parsed fault schedule. Thread-safe; each clause counts its own
    matching invocations, so two concurrent consumers of one plan see a
    deterministic global fault schedule (the interleaving decides which
    consumer draws each faulting invocation, but the total count and the
    per-clause indices are fixed)."""

    def __init__(self, specs: List[FaultSpec]):
        self._lock = threading.Lock()
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._by_site.setdefault(s.site, []).append(s)
        #: injected-fault counts per site, for tests and reports
        self.injected: Dict[str, int] = {}

    @property
    def sites(self) -> Tuple[str, ...]:
        return tuple(self._by_site)

    def reset(self) -> None:
        """Zero every clause's invocation/fired counters and re-seed."""
        with self._lock:
            for specs in self._by_site.values():
                for s in specs:
                    s.reset()
            self.injected.clear()

    def check(self, site: str, attrs: dict) -> Optional[str]:
        """Count one invocation of ``site``; return the fault kind to
        raise, or None. The no-plan-for-this-site path takes no lock."""
        specs = self._by_site.get(site)
        if specs is None:
            return None
        with self._lock:
            for s in specs:
                if s.match is not None and attrs.get("replica") != s.match:
                    continue
                if s._hit():
                    self.injected[site] = self.injected.get(site, 0) + 1
                    return s.kind
        return None


def parse_plan(text: str) -> FaultPlan:
    """Parse the ``KEYSTONE_FAULTS`` grammar (module docstring). Raises
    :class:`ValueError` naming the offending clause — a typo'd chaos
    schedule must fail loudly, not silently inject nothing."""
    specs: List[FaultSpec] = []
    for clause in text.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        try:
            site_part, _, rhs = clause.partition("=")
            if not _ or not site_part or not rhs:
                raise ValueError("expected site=kind[@hits]")
            site_part = site_part.strip()
            match: Optional[int] = None
            if "#" in site_part:
                site_part, m = site_part.split("#", 1)
                match = int(m)
            kind, _, hits = rhs.strip().partition("@")
            kind = kind.strip()
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown kind {kind!r} (use {'|'.join(_KINDS)})"
                )
            spec = FaultSpec(site=site_part, kind=kind, match=match)
            hits = hits.strip()
            if not hits:
                spec.at = frozenset((0,))
            elif hits.startswith("p"):
                body = hits[1:]
                seed = 0
                limit: Optional[int] = None
                if "s" in body:
                    body, s = body.split("s", 1)
                    seed = int(s)
                if "x" in body:
                    body, x = body.split("x", 1)
                    limit = int(x)
                rate = float(body)
                if not 0.0 < rate <= 1.0:
                    raise ValueError(f"rate {rate} outside (0, 1]")
                spec.rate, spec.limit, spec.seed = rate, limit, seed
            else:
                spec.at = frozenset(int(i) for i in hits.split(","))
            specs.append(spec)
        except ValueError as e:
            raise ValueError(
                f"bad KEYSTONE_FAULTS clause {clause!r}: {e}"
            ) from None
    if not specs:
        raise ValueError(f"empty fault plan: {text!r}")
    return FaultPlan(specs)


# -- installation + the hook -------------------------------------------------

_installed: Optional[FaultPlan] = None
_env_plan: Optional[FaultPlan] = None
_env_raw: Optional[str] = None


def install(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` process-wide (wins over ``KEYSTONE_FAULTS``)."""
    global _installed
    _installed = plan
    return plan


def clear() -> None:
    """Remove any installed plan AND forget the cached env parse (so a
    test that mutated ``KEYSTONE_FAULTS`` starts the next schedule with
    fresh invocation counters)."""
    global _installed, _env_plan, _env_raw
    _installed = None
    _env_plan = None
    _env_raw = None


def active_plan() -> Optional[FaultPlan]:
    """The plan in force: the installed one, else the ``KEYSTONE_FAULTS``
    parse (cached on the raw string, so invocation counters persist for
    the life of the value — the determinism contract)."""
    if _installed is not None:
        return _installed
    from ..utils import env_str

    raw = env_str("KEYSTONE_FAULTS")
    if not raw:
        return None
    global _env_plan, _env_raw
    if raw != _env_raw:
        _env_plan = parse_plan(raw)
        _env_raw = raw
        logger.warning(
            "fault injection ACTIVE: KEYSTONE_FAULTS=%r (sites: %s)",
            raw, ", ".join(_env_plan.sites),
        )
    return _env_plan


def fault_point(site: str, **attrs) -> None:
    """THE hook: a no-op without a plan; with one, raises the scheduled
    typed error for this invocation of ``site``. ``attrs`` is matching
    context (``replica=index``) and lands on the ``fault.inject`` trace
    instant."""
    plan = active_plan()
    if plan is None:
        return
    kind = plan.check(site, attrs)
    if kind is None:
        return
    invocation = plan.injected.get(site, 1) - 1
    logger.warning(
        "fault injected: site=%s kind=%s attrs=%s", site, kind, attrs
    )
    try:
        from ..obs import flight as _flight
        from ..obs.tracer import current as _trace_current

        # the always-on flight ring gets every injection — a post-mortem
        # dump must show the chaos schedule's hits even with tracing off
        _flight.record_instant(
            "fault.inject", site=site, kind=kind, **attrs
        )
        tracer = _trace_current()
        if tracer is not None:
            tracer.instant(
                "fault.inject", op_type="FaultPlan",
                site=site, kind=kind, **attrs,
            )
    except Exception:
        # trace emission must never change fault semantics
        logger.debug("fault.inject instant not recorded", exc_info=True)
    if kind == "kill":
        raise ReplicaKilled(f"injected kill at {site}")
    if kind == "fatal":
        raise FatalFaultInjected(site, invocation)
    raise FaultInjected(site, invocation)
