"""Fault-tolerant execution: deterministic fault injection and the
recovery machinery behind it.

Three pieces, used together by the chaos tests and the
``fault_tolerance`` bench extra:

* :mod:`~keystone_tpu.faults.plan` — seeded, deterministic fault
  injection (``KEYSTONE_FAULTS`` / :func:`install`) through named
  :func:`fault_point` hooks in the scan pipeline, the serving replicas,
  and the AOT cache; typed errors (:class:`TransientError`,
  :class:`ReplicaKilled`) classify what recovery applies.
* :mod:`~keystone_tpu.faults.retry` — per-scan bounded-backoff retry of
  transient failures (``KEYSTONE_SCAN_RETRIES``, off by default).
* :mod:`~keystone_tpu.faults.checkpoint` — atomic on-disk snapshots of
  the streaming-fit accumulators, so ``fit(checkpoint=dir)`` resumes a
  killed out-of-core fit from the last completed block.

Replica supervision (restart/requeue/quarantine) lives with the fleet in
:mod:`keystone_tpu.serving.fleet`; it consumes the typed errors here.
"""

from .checkpoint import FitCheckpoint
from .plan import (
    AOT_READ,
    REPLICA_BATCH,
    SCALE_DRAIN,
    SCALE_SPAWN,
    SCAN_CHUNK,
    SCAN_STAGE,
    TRAINER_ABSORB,
    TRAINER_CANARY,
    TRAINER_INGEST,
    WORKER_SPAWN,
    FatalFaultInjected,
    FaultInjected,
    FaultPlan,
    FaultSpec,
    ReplicaDown,
    ReplicaKilled,
    TransientError,
    active_plan,
    clear,
    fault_point,
    install,
    is_transient,
    parse_plan,
)
from .retry import RetryBudget, retry_call

__all__ = [
    "AOT_READ",
    "WORKER_SPAWN",
    "REPLICA_BATCH",
    "SCALE_DRAIN",
    "SCALE_SPAWN",
    "SCAN_CHUNK",
    "SCAN_STAGE",
    "TRAINER_ABSORB",
    "TRAINER_CANARY",
    "TRAINER_INGEST",
    "FatalFaultInjected",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "FitCheckpoint",
    "ReplicaDown",
    "ReplicaKilled",
    "RetryBudget",
    "TransientError",
    "active_plan",
    "clear",
    "fault_point",
    "install",
    "is_transient",
    "parse_plan",
    "retry_call",
]
