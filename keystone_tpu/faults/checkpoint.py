"""Resumable-fit checkpoints: snapshot-able accumulator state on disk.

A long out-of-core fit folds chunks into small sufficient statistics
(:mod:`keystone_tpu.linalg.accumulators`: a Gram/cross pair, a TSQR R
factor, Chan/Welford moments). Those states are tiny (O(d²)) and exact,
so a fit can persist ``(state, chunk_cursor, row_cursor)`` at block
boundaries and a killed fit can RESUME from the last completed block
instead of rescanning — the recovery the ROADMAP's mid-fit re-planning
item also needs.

Write discipline mirrors :class:`~keystone_tpu.cost.store.ProfileStore`:
one self-validating file per fit key (magic + sha256 over the pickled
payload), atomic tmp-then-rename so readers see the old checkpoint XOR
the new one, never a torn write. Loads degrade: a missing file is a
fresh fit, a corrupt file is deleted (WARNING) and the fit starts over,
a checkpoint written under a DIFFERENT fit key is left alone and
ignored — resuming someone else's fit would silently fold wrong data.

The state payload is pickled, which is exact for the host-numpy
accumulators (float64 arrays round-trip bit-for-bit) — the basis of the
resume-parity guarantee: a killed-and-resumed fit folds the identical
state an uninterrupted fit would have.
"""

from __future__ import annotations

import hashlib
import logging
import os
import pickle
import tempfile
from typing import Any, Optional, Tuple

logger = logging.getLogger(__name__)

_MAGIC = b"KSFITCKPT1\n"


class FitCheckpoint:
    """One fit's resumable state under ``root``, keyed by ``key`` (the
    fit's logical identity: solver family, λ grid, data shape/length —
    anything that would make resuming wrong if it differed)."""

    def __init__(self, root: str, key: str):
        self.root = str(root)
        self.key = str(key)
        os.makedirs(self.root, exist_ok=True)
        digest = hashlib.sha256(self.key.encode()).hexdigest()[:16]
        self.path = os.path.join(self.root, f"fitckpt-{digest}.pkl")

    # -- write -----------------------------------------------------------

    def save(self, state: Any, chunk_cursor: int, row_cursor: int) -> None:
        """Persist one completed-block snapshot atomically. ``state`` is
        any picklable accumulator (or dict of them); ``chunk_cursor`` is
        the number of chunks fully folded; ``row_cursor`` the rows they
        covered (so resume can slice labels without re-measuring skipped
        chunks)."""
        doc = {
            "key": self.key,
            "chunk": int(chunk_cursor),
            "rows": int(row_cursor),
            "state": state,
        }
        payload = pickle.dumps(doc, protocol=4)
        blob = _MAGIC + hashlib.sha256(payload).digest() + payload
        fd, tmp = tempfile.mkstemp(dir=self.root, prefix=".tmp-ckpt-")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path)  # atomic: old XOR new, never torn
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- read ------------------------------------------------------------

    def load(self) -> Optional[Tuple[Any, int, int]]:
        """``(state, chunk_cursor, row_cursor)`` of the last completed
        block, or None (missing / corrupt / foreign key). Never raises
        for on-disk problems."""
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        except OSError:
            logger.warning(
                "fit checkpoint: unreadable %s — starting fresh",
                self.path, exc_info=True,
            )
            return None
        doc = self._parse(blob)
        if doc is None:
            self._discard("corrupt")
            return None
        if doc.get("key") != self.key:
            # hash-prefix collision or a caller pointing two different
            # fits at one dir: never resume a foreign fit's state
            logger.warning(
                "fit checkpoint: %s belongs to a different fit key — "
                "ignoring it and starting fresh", self.path,
            )
            return None
        return doc["state"], int(doc["chunk"]), int(doc["rows"])

    def _parse(self, blob: bytes) -> Optional[dict]:
        if not blob.startswith(_MAGIC):
            return None
        body = blob[len(_MAGIC):]
        if len(body) < 32:
            return None
        digest, payload = body[:32], body[32:]
        if hashlib.sha256(payload).digest() != digest:
            return None
        try:
            doc = pickle.loads(payload)
        except Exception:
            # undecodable payload degrades to a fresh fit by contract
            logger.debug("checkpoint: undecodable payload", exc_info=True)
            return None
        if not isinstance(doc, dict) or "state" not in doc:
            return None
        return doc

    def _discard(self, why: str) -> None:
        logger.warning(
            "fit checkpoint: %s entry at %s — deleting and starting fresh",
            why, self.path,
        )
        try:
            os.unlink(self.path)
        except OSError:
            pass

    # -- lifecycle -------------------------------------------------------

    def complete(self) -> None:
        """The fit finished: remove the checkpoint so the NEXT fit under
        this key starts fresh instead of resuming a finished pass."""
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        except OSError:
            logger.warning(
                "fit checkpoint: could not remove completed %s", self.path,
                exc_info=True,
            )

    def exists(self) -> bool:
        return os.path.exists(self.path)
