"""Bounded-backoff retry of transient failures, with a per-scan budget.

Each scan layer owns ONE :class:`RetryBudget`, bounded per scan: the
chunk-fault seam and the H2D staging ring share a single budget (the
pipeline adopts the seam's), and a re-callable source's per-index
regeneration (``ChunkedDataset.from_chunk_fn``) draws its own — so a
scan whose source is genuinely broken cannot retry forever; exhaustion
re-raises the ORIGINAL exception with its original traceback, exactly
what the un-retried path propagated before this module existed.

Off by default: the budget reads ``KEYSTONE_SCAN_RETRIES`` (0 = no
retries, today's fail-fast behavior). ``KEYSTONE_SCAN_RETRY_BACKOFF``
sets the base backoff in seconds (default 0.05); each attempt doubles
it, capped at :data:`MAX_BACKOFF_S`. Every retry logs a rate-limited
WARNING and lands a ``retry.attempt`` instant in the trace.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Optional

from .plan import fault_point, is_transient

logger = logging.getLogger(__name__)

MAX_BACKOFF_S = 2.0


def retry_budget_from_env() -> int:
    """KEYSTONE_SCAN_RETRIES: transient retries allowed per scan
    (default 0 — recovery is opt-in)."""
    from ..utils import env_int

    return env_int("KEYSTONE_SCAN_RETRIES", 0, minimum=0)


def retry_backoff_from_env() -> float:
    from ..utils import env_float

    return env_float("KEYSTONE_SCAN_RETRY_BACKOFF", 0.05)


class RetryBudget:
    """A thread-safe bounded retry pool shared by every stage of one
    scan (the producer thread and the consumer's staging ring both draw
    from it)."""

    def __init__(
        self,
        budget: Optional[int] = None,
        backoff_s: Optional[float] = None,
        label: str = "scan",
    ):
        self.budget = retry_budget_from_env() if budget is None else budget
        self.backoff_s = (
            retry_backoff_from_env() if backoff_s is None else backoff_s
        )
        self.label = label
        self.attempts = 0  # total retries consumed (the span counter)
        self._lock = threading.Lock()

    def consume(self, exc: BaseException, site: str) -> Optional[float]:
        """One retry decision: returns the backoff delay in seconds when
        ``exc`` is transient and budget remains, else None (caller
        re-raises the original)."""
        if not is_transient(exc):
            return None
        with self._lock:
            if self.attempts >= self.budget:
                return None
            self.attempts += 1
            attempt = self.attempts
        delay = min(self.backoff_s * (2 ** (attempt - 1)), MAX_BACKOFF_S)
        from ..utils.obs import every

        if every(f"faults.retry:{site}", 10.0):
            logger.warning(
                "%s: transient failure at %s — retry %d/%d in %.3fs (%s)",
                self.label, site, attempt, self.budget, delay, exc,
            )
        try:
            from ..obs.tracer import current as _trace_current

            tracer = _trace_current()
            if tracer is not None:
                tracer.instant(
                    "retry.attempt", op_type="RetryBudget",
                    site=site, attempt=attempt, budget=self.budget,
                    delay_s=round(delay, 4), label=self.label,
                )
        except Exception:
            # trace emission must never change retry semantics
            logger.debug("retry.attempt instant not recorded", exc_info=True)
        return delay


def retry_call(
    fn: Callable[[], Any],
    budget: RetryBudget,
    site: str,
    inject: bool = True,
    **attrs,
) -> Any:
    """Run ``fn`` under the transient-retry discipline: an injected fault
    at ``site`` (when ``inject``) or a transient error from ``fn`` itself
    retries with backoff while the scan's budget lasts; anything else —
    and exhaustion — re-raises the original exception with its original
    traceback. ``fn`` MUST be safe to re-execute (idempotent production:
    a chunk_fn(i) regeneration, a device_put)."""
    while True:
        try:
            if inject:
                fault_point(site, **attrs)
            return fn()
        except StopIteration:
            raise
        except Exception as e:
            delay = budget.consume(e, site)
            if delay is None:
                raise
            if delay:
                time.sleep(delay)
