"""Profile-guided materialization planning (parity: ``workflow/AutoCacheRule.scala``).

In the reference, RDDs are recomputed per action unless a ``Cacher`` node
persists them, and AutoCacheRule decides which to cache under a memory budget.
Here the default executor memoizes every node's result in HBM, so the planner's
job inverts: decide which intermediates are *worth retaining* versus dropping
and recomputing under HBM pressure. This module currently implements node
profiling (wall time + result bytes at sample scales) and the greedy
runs-x-saved-time selection; the eviction hook lands with the materialization
planner (see ``docs/ROADMAP.md``).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ..data.dataset import Dataset
from .executor import GraphExecutor
from .graph import Graph, NodeId
from .node_optimization import _sampled_graph
from .rules import Annotations, Rule
from . import analysis

logger = logging.getLogger(__name__)


@dataclass
class Profile:
    """Per-node cost estimate (parity: ``AutoCacheRule.scala:12``)."""

    ns: float  # nanoseconds to compute
    mem_bytes: float  # size of the materialized result

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.ns + other.ns, self.mem_bytes + other.mem_bytes)


def _result_bytes(value) -> float:
    if isinstance(value, Dataset):
        if value.is_batched:
            return float(
                sum(np.prod(a.shape) * a.dtype.itemsize for a in jax.tree_util.tree_leaves(value.payload))
            )
        return float(sum(getattr(np.asarray(x), "nbytes", 64) for x in value.collect()))
    return 64.0


def profile_nodes(graph: Graph, sample_size: int = 24) -> Dict[NodeId, Profile]:
    """Execute a leaf-sampled copy of the graph, timing each node and sizing
    its result (the reference fits linear scale models over several sample
    fractions; one sample scale + linear extrapolation is used here)."""
    sampled = _sampled_graph(graph, sample_size)
    executor = GraphExecutor(sampled, optimize=False)
    profiles: Dict[NodeId, Profile] = {}
    for gid in analysis.linearize(sampled):
        if not isinstance(gid, NodeId):
            continue
        try:
            t0 = time.perf_counter_ns()
            value = executor.execute(gid).get()
            elapsed = time.perf_counter_ns() - t0
        except Exception as e:
            logger.debug("profiling skipped %s: %s", gid, e)
            continue
        profiles[gid] = Profile(float(elapsed), _result_bytes(value))
    return profiles


def estimate_runs(graph: Graph, weights: Dict[NodeId, int], cached: set) -> Dict[NodeId, int]:
    """Times each node runs given which nodes are cached: a node reruns once
    per (weighted) downstream consumer path that is not cut by a cached node
    (parity: ``AutoCacheRule.getRuns``)."""
    runs: Dict[NodeId, int] = {}

    def runs_of(gid) -> int:
        if gid in runs:
            return runs[gid]
        children = analysis.get_children(graph, gid)
        if not children:
            total = 1
        else:
            total = 0
            for c in children:
                if isinstance(c, NodeId):
                    w = weights.get(c, 1)
                    total += w * (1 if c in cached else runs_of(c))
                else:  # sink
                    total += 1
        runs[gid] = max(total, 1)
        return runs[gid]

    for n in graph.nodes:
        runs_of(n)
    return runs


class AutoCacheRule(Rule):
    """Greedy cache selection under a byte budget; currently selection is
    advisory (executor memoizes everything) and is logged for inspection."""

    def __init__(self, strategy: str = "greedy", mem_budget_bytes: Optional[int] = None):
        self.strategy = strategy
        self.mem_budget_bytes = mem_budget_bytes

    def apply(self, graph: Graph, annotations: Annotations) -> Tuple[Graph, Annotations]:
        profiles = profile_nodes(graph)
        weights = {
            n: getattr(graph.get_operator(n), "weight", 1) for n in graph.nodes
        }
        budget = self.mem_budget_bytes or (4 << 30)
        cached: set = set()
        if self.strategy == "aggressive":
            cached = {n for n in graph.nodes if len(analysis.get_children(graph, n)) > 1}
        else:
            spent = 0.0
            while True:
                runs = estimate_runs(graph, weights, cached)
                best, best_save = None, 0.0
                for n, p in profiles.items():
                    if n in cached or spent + p.mem_bytes > budget:
                        continue
                    save = (runs[n] - 1) * p.ns
                    if save > best_save:
                        best, best_save = n, save
                if best is None:
                    break
                cached.add(best)
                spent += profiles[best].mem_bytes
        if cached:
            logger.info(
                "auto-cache: would retain %d nodes (%s)",
                len(cached),
                ", ".join(graph.get_operator(n).label for n in sorted(cached)),
            )
        return graph, annotations
