"""Profile-guided cache insertion (parity: ``workflow/AutoCacheRule.scala``).

In the reference, RDDs recompute per action unless a ``Cacher`` node persists
them; AutoCacheRule profiles nodes at several sample scales, fits linear
time/memory-vs-scale models (``generalizeProfiles``,
AutoCacheRule.scala:104-135), estimates per-node run counts from downstream
weights (``getRuns`` :57-81), and inserts Cacher nodes — either around
everything reused (``aggressiveCache`` :503-518) or greedily maximizing saved
time under a memory budget (``greedyCache`` :559-602).

Here the same algorithm runs over HBM: the executor retains only results
under a Cacher (plus datasets/fitted estimators) across pulls once this rule
has run — see ``GraphExecutor`` — so the budget genuinely bounds resident
bytes, and uncached intermediates recompute exactly like unpersisted RDDs.
The budget defaults to 75%% of free device memory when the platform reports
it (parity: 0.75 × cluster free storage, AutoCacheRule.scala:572-585).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data.dataset import Dataset
from ..obs import tracer as obs_tracer
from .executor import GraphExecutor
from .graph import Graph, NodeId
from .node_optimization import _sampled_graph
from .operators import DatasetOperator
from .rules import Annotations, Rule
from . import analysis

logger = logging.getLogger(__name__)

#: string key in the annotations dict marking that cache planning ran (the
#: executor switches from memoize-everything to Cacher-only retention).
AUTOCACHE_ACTIVE = "autocache_active"


@dataclass
class Profile:
    """Per-node cost estimate (parity: ``AutoCacheRule.scala:12``)."""

    ns: float  # nanoseconds to compute
    mem_bytes: float  # size of the materialized result

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.ns + other.ns, self.mem_bytes + other.mem_bytes)


def _result_bytes(value) -> float:
    from ..obs.span import cheap_nbytes

    if isinstance(value, Dataset) and not value.is_batched:
        # profiling MAY force materialization (that's its job, unlike the
        # tracer's no-side-effect sizing): item lists collect and sum
        return float(
            sum(getattr(np.asarray(x), "nbytes", 64) for x in value.collect())
        )
    n = cheap_nbytes(value)
    return 64.0 if n is None else float(n)


def _profile_at_scale(graph: Graph, sample_size: int) -> Dict[NodeId, Profile]:
    sampled = _sampled_graph(graph, sample_size)
    # parallel=False: the fitted time-vs-scale model needs each node's own
    # wall-clock — sibling branches running on other cores during a timed
    # pull would inflate (contention) or hide (overlap) per-node cost.
    # Production pulls still run concurrently; retention is unchanged
    # (uncached intermediates stay in the per-pull transient table and the
    # scheduler drops each expression as it completes), though peak
    # transient memory under concurrency can reach worker-count in-flight
    # branches' intermediates at once — KEYSTONE_EXEC_WORKERS bounds it.
    executor = GraphExecutor(sampled, optimize=False, parallel=False)
    profiles: Dict[NodeId, Profile] = {}
    # profiling pulls run at sampled scale over a TRUNCATED graph whose
    # node ids collide with the production graph's — suspend tracing so
    # they can't pollute the real span registry / audit observations
    with obs_tracer.suspended():
        for gid in analysis.linearize(sampled):
            if not isinstance(gid, NodeId):
                continue
            try:
                t0 = time.perf_counter_ns()
                value = executor.execute(gid).get()
                elapsed = time.perf_counter_ns() - t0
            except Exception as e:
                logger.debug("profiling skipped %s: %s", gid, e)
                continue
            profiles[gid] = Profile(float(elapsed), _result_bytes(value))
    return profiles


def profile_nodes(
    graph: Graph,
    sample_sizes: Sequence[int] = (8, 16, 24),
    full_size: Optional[int] = None,
) -> Dict[NodeId, Profile]:
    """Profile at several sample scales and fit a linear model per node,
    extrapolated to the full input size (parity: ``generalizeProfiles``,
    AutoCacheRule.scala:104-135 — same least-squares-in-scale idea, with
    jit warmup noise damped by taking the *minimum* time per scale)."""
    input_size = _full_input_size(graph)
    # the truncated leaf size actually run: requested scale capped by the
    # real dataset size (otherwise the fitted slope uses a wrong Δx)
    scales = sorted({min(s, input_size) for s in sample_sizes})
    per_scale = [(s, _profile_at_scale(graph, s)) for s in scales]
    nodes = set().union(*[set(p.keys()) for _, p in per_scale]) if per_scale else set()
    out: Dict[NodeId, Profile] = {}
    for n in nodes:
        xs, ts, bs = [], [], []
        for s, profs in per_scale:
            if n in profs:
                xs.append(float(s))
                ts.append(profs[n].ns)
                bs.append(profs[n].mem_bytes)
        if not xs:
            continue
        target = float(full_size if full_size is not None else max(xs))
        if len(xs) >= 2 and len(set(xs)) >= 2:
            A = np.stack([np.ones(len(xs)), np.asarray(xs)], axis=1)
            t_coef, *_ = np.linalg.lstsq(A, np.asarray(ts), rcond=None)
            b_coef, *_ = np.linalg.lstsq(A, np.asarray(bs), rcond=None)
            ns = max(t_coef[0] + t_coef[1] * target, min(ts))
            mem = max(b_coef[0] + b_coef[1] * target, 0.0)
        else:
            scale = target / xs[-1]
            ns, mem = ts[-1] * scale, bs[-1] * scale
        out[n] = Profile(float(ns), float(mem))
    return out


def estimate_runs(
    graph: Graph, weights: Dict[NodeId, int], cached: set
) -> Dict[NodeId, int]:
    """Times each node runs given which nodes are cached: a node reruns once
    per (weighted) downstream consumer path that is not cut by a cached node
    (parity: ``AutoCacheRule.getRuns``)."""
    runs: Dict[NodeId, int] = {}

    def runs_of(gid) -> int:
        if gid in runs:
            return runs[gid]
        children = analysis.get_children(graph, gid)
        if not children:
            total = 1
        else:
            total = 0
            for c in children:
                if isinstance(c, NodeId):
                    w = weights.get(c, 1)
                    total += w * (1 if c in cached else runs_of(c))
                else:  # sink
                    total += 1
        runs[gid] = max(total, 1)
        return runs[gid]

    for n in graph.nodes:
        runs_of(n)
    return runs


def _device_budget_bytes() -> int:
    """75% of free device memory when the backend reports it, else 4 GiB."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if limit:
            return int(0.75 * (limit - in_use))
    except Exception:
        pass
    return 4 << 30


def _is_cacher(op) -> bool:
    from ..nodes.util.core import Cacher

    return isinstance(op, Cacher)


def insert_cachers(graph: Graph, nodes: Sequence[NodeId]) -> Graph:
    """Splice a Cacher after each selected node, rerouting every consumer
    (parity: ``addCachesToPipeline``, AutoCacheRule.scala:492-501)."""
    from ..nodes.util.core import Cacher

    for n in nodes:
        children = analysis.get_children(graph, n)
        existing = [
            c for c in children
            if isinstance(c, NodeId) and _is_cacher(graph.get_operator(c))
        ]
        if existing:
            # reuse the existing Cacher: reroute any consumer that bypasses it
            cacher = existing[0]
        else:
            graph, cacher = graph.add_node(Cacher(), [n])
        for c in children:
            if c == cacher:
                continue
            if isinstance(c, NodeId):
                if _is_cacher(graph.get_operator(c)):
                    continue  # a second cacher; leave it alone
                deps = [
                    cacher if d == n else d for d in graph.get_dependencies(c)
                ]
                graph = graph.set_dependencies(c, deps)
            else:  # SinkId
                graph = graph.set_sink_dependency(c, cacher)
    return graph


class AutoCacheRule(Rule):
    """Insert Cacher nodes by the aggressive or greedy policy; the executor
    then retains only cached results across pulls."""

    def __init__(
        self,
        strategy: str = "greedy",
        mem_budget_bytes: Optional[int] = None,
        profiles: Optional[Dict[NodeId, Profile]] = None,
    ):
        self.strategy = strategy
        self.mem_budget_bytes = mem_budget_bytes
        self.profiles = profiles  # injectable for tests (parity: suite)

    def _select_aggressive(self, graph: Graph) -> set:
        """Cache every node whose result is consumed along >1 downstream
        path (parity: ``aggressiveCache``, AutoCacheRule.scala:503-518)."""
        return {
            n
            for n in graph.nodes
            if len(analysis.get_children(graph, n)) > 1
            and not _is_cacher(graph.get_operator(n))
        }

    def _select_greedy(
        self, graph: Graph, profiles: Dict[NodeId, Profile], budget: float
    ) -> set:
        weights = {
            n: getattr(graph.get_operator(n), "weight", 1) for n in graph.nodes
        }
        # Existing Cacher nodes already cut recomputation: seed the run
        # estimator with them so their upstreams' savings aren't double
        # counted (parity: the reference seeds getRuns with cached nodes).
        preexisting = {
            n for n in graph.nodes if _is_cacher(graph.get_operator(n))
        }
        cached: set = set(preexisting)
        spent = 0.0
        while True:
            runs = estimate_runs(graph, weights, cached)
            best, best_save = None, 0.0
            for n, p in profiles.items():
                if n not in graph.nodes or n in cached:
                    continue
                if _is_cacher(graph.get_operator(n)):
                    continue
                if spent + p.mem_bytes > budget:
                    continue
                save = (runs[n] - 1) * p.ns
                if save > best_save:
                    best, best_save = n, save
            if best is None:
                break
            cached.add(best)
            spent += profiles[best].mem_bytes
        return cached - preexisting

    def apply(
        self, graph: Graph, annotations: Annotations
    ) -> Tuple[Graph, Annotations]:
        profiles: Optional[Dict[NodeId, Profile]] = None
        if self.strategy == "aggressive":
            selected = self._select_aggressive(graph)
        else:
            profiles = self.profiles
            if profiles is None:
                profiles = profile_nodes(
                    graph, full_size=_full_input_size(graph)
                )
            budget = (
                self.mem_budget_bytes
                if self.mem_budget_bytes is not None
                else _device_budget_bytes()
            )
            selected = self._select_greedy(graph, profiles, float(budget))
        self._record_plan(graph, profiles, selected)
        if selected:
            logger.info(
                "auto-cache (%s): inserting Cacher after %d nodes (%s)",
                self.strategy,
                len(selected),
                ", ".join(
                    graph.get_operator(n).label for n in sorted(selected)
                ),
            )
            graph = insert_cachers(graph, sorted(selected))
        annotations = dict(annotations)
        annotations[AUTOCACHE_ACTIVE] = True  # type: ignore[index]
        return graph, annotations

    @staticmethod
    def _record_plan(
        graph: Graph,
        profiles: Optional[Dict[NodeId, Profile]],
        selected: set,
    ) -> None:
        """Log the planner's per-node estimates into the trace so the
        estimate-vs-observed audit (obs/audit.py) can close the
        profile-guided-caching feedback loop after execution. Node ids are
        recorded BEFORE Cacher insertion (insert_cachers preserves the
        planned nodes' ids) and match the executor's span ``node`` field
        as long as later rewrites (trace fusion) leave the node in place —
        the audit flags the ones that disappear."""
        tracer = obs_tracer.current()
        if tracer is None:
            return
        estimated = set(profiles or ())
        for n in estimated | set(selected):
            if n not in graph.nodes:
                continue
            p = (profiles or {}).get(n)
            tracer.record_node_estimate(
                str(n.id),
                graph.get_operator(n).label,
                est_seconds=None if p is None else p.ns / 1e9,
                est_bytes=None if p is None else p.mem_bytes,
                cacher=n in selected,
            )


def _full_input_size(graph: Graph) -> int:
    n = 1
    for node in graph.nodes:
        op = graph.get_operator(node)
        if isinstance(op, DatasetOperator):
            n = max(n, len(op.dataset))
    return n
