"""Profile-guided cache insertion (parity: ``workflow/AutoCacheRule.scala``).

In the reference, RDDs recompute per action unless a ``Cacher`` node persists
them; AutoCacheRule profiles nodes at several sample scales, fits linear
time/memory-vs-scale models (``generalizeProfiles``,
AutoCacheRule.scala:104-135), estimates per-node run counts from downstream
weights (``getRuns`` :57-81), and inserts Cacher nodes — either around
everything reused (``aggressiveCache`` :503-518) or greedily maximizing saved
time under a memory budget (``greedyCache`` :559-602).

Here the same algorithm runs over HBM: the executor retains only results
under a Cacher (plus datasets/fitted estimators) across pulls once this rule
has run — see ``GraphExecutor`` — so the budget genuinely bounds resident
bytes, and uncached intermediates recompute exactly like unpersisted RDDs.
The budget defaults to 75%% of free device memory when the platform reports
it (parity: 0.75 × cluster free storage, AutoCacheRule.scala:572-585).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data.dataset import Dataset
from ..obs import tracer as obs_tracer
from .executor import GraphExecutor
from .graph import Graph, NodeId
from .node_optimization import _sampled_graph
from .operators import DatasetOperator
from .rules import Annotations, Rule
from . import analysis

logger = logging.getLogger(__name__)

#: string key in the annotations dict marking that cache planning ran (the
#: executor switches from memoize-everything to Cacher-only retention).
AUTOCACHE_ACTIVE = "autocache_active"


@dataclass
class Profile:
    """Per-node cost estimate (parity: ``AutoCacheRule.scala:12``)."""

    ns: float  # nanoseconds to compute
    mem_bytes: float  # size of the materialized result

    def __add__(self, other: "Profile") -> "Profile":
        return Profile(self.ns + other.ns, self.mem_bytes + other.mem_bytes)


def _result_bytes(value) -> float:
    from ..obs.span import cheap_nbytes

    if isinstance(value, Dataset) and not value.is_batched:
        # profiling MAY force materialization (that's its job, unlike the
        # tracer's no-side-effect sizing): item lists collect and sum
        return float(
            sum(getattr(np.asarray(x), "nbytes", 64) for x in value.collect())
        )
    n = cheap_nbytes(value)
    return 64.0 if n is None else float(n)


def _profile_at_scale(graph: Graph, sample_size: int) -> Dict[NodeId, Profile]:
    sampled = _sampled_graph(graph, sample_size)
    # parallel=False: the fitted time-vs-scale model needs each node's own
    # wall-clock — sibling branches running on other cores during a timed
    # pull would inflate (contention) or hide (overlap) per-node cost.
    # Production pulls still run concurrently; retention is unchanged
    # (uncached intermediates stay in the per-pull transient table and the
    # scheduler drops each expression as it completes), though peak
    # transient memory under concurrency can reach worker-count in-flight
    # branches' intermediates at once — KEYSTONE_EXEC_WORKERS bounds it.
    executor = GraphExecutor(sampled, optimize=False, parallel=False)
    profiles: Dict[NodeId, Profile] = {}
    from .. import cost as cost_mod

    # profiling pulls run at sampled scale over a TRUNCATED graph whose
    # node ids collide with the production graph's — suspend tracing so
    # they can't pollute the real span registry / audit observations
    with obs_tracer.suspended():
        for gid in analysis.linearize(sampled):
            if not isinstance(gid, NodeId):
                continue
            try:
                t0 = time.perf_counter_ns()
                cost_mod.count_sampling("autocache")
                value = executor.execute(gid).get()
                elapsed = time.perf_counter_ns() - t0
            except Exception as e:
                logger.debug("profiling skipped %s: %s", gid, e)
                continue
            profiles[gid] = Profile(float(elapsed), _result_bytes(value))
    return profiles


def profile_nodes(
    graph: Graph,
    sample_sizes: Sequence[int] = (8, 16, 24),
    full_size: Optional[int] = None,
    calibration: Optional[Dict[NodeId, float]] = None,
) -> Dict[NodeId, Profile]:
    """Profile at several sample scales and fit a linear model per node,
    extrapolated to the full input size (parity: ``generalizeProfiles``,
    AutoCacheRule.scala:104-135 — same least-squares-in-scale idea, with
    jit warmup noise damped by taking the *minimum* time per scale).

    ``calibration`` holds per-node observed/estimated seconds ratios
    measured by a previous traced run of the same pipeline
    (``cost.replan.stored_calibration``): each node's extrapolation is
    scaled by ITS OWN measured sample-to-full ratio rather than trusting
    one global linear-in-n factor — nodes whose per-item cost shifts
    between the 24-item sample scale and the real run (compile overhead
    amortization, cache effects, batching cliffs) were the audit's worst
    estimate-vs-observed ratios. Ratios are clamped to [1/64, 64] so one
    corrupt observation cannot zero out or explode a plan."""
    input_size = _full_input_size(graph)
    # the truncated leaf size actually run: requested scale capped by the
    # real dataset size (otherwise the fitted slope uses a wrong Δx)
    scales = sorted({min(s, input_size) for s in sample_sizes})
    per_scale = [(s, _profile_at_scale(graph, s)) for s in scales]
    nodes = set().union(*[set(p.keys()) for _, p in per_scale]) if per_scale else set()
    out: Dict[NodeId, Profile] = {}
    for n in nodes:
        xs, ts, bs = [], [], []
        for s, profs in per_scale:
            if n in profs:
                xs.append(float(s))
                ts.append(profs[n].ns)
                bs.append(profs[n].mem_bytes)
        if not xs:
            continue
        target = float(full_size if full_size is not None else max(xs))
        if len(xs) >= 2 and len(set(xs)) >= 2:
            A = np.stack([np.ones(len(xs)), np.asarray(xs)], axis=1)
            t_coef, *_ = np.linalg.lstsq(A, np.asarray(ts), rcond=None)
            b_coef, *_ = np.linalg.lstsq(A, np.asarray(bs), rcond=None)
            ns = max(t_coef[0] + t_coef[1] * target, min(ts))
            mem = max(b_coef[0] + b_coef[1] * target, 0.0)
        else:
            scale = target / xs[-1]
            ns, mem = ts[-1] * scale, bs[-1] * scale
        ratio = (calibration or {}).get(n)
        if ratio is not None:
            ns *= float(np.clip(ratio, 1.0 / 64.0, 64.0))
        out[n] = Profile(float(ns), float(mem))
    return out


def estimate_runs(
    graph: Graph, weights: Dict[NodeId, int], cached: set
) -> Dict[NodeId, int]:
    """Times each node runs given which nodes are cached: a node reruns once
    per (weighted) downstream consumer path that is not cut by a cached node
    (parity: ``AutoCacheRule.getRuns``)."""
    runs: Dict[NodeId, int] = {}

    def runs_of(gid) -> int:
        if gid in runs:
            return runs[gid]
        children = analysis.get_children(graph, gid)
        if not children:
            total = 1
        else:
            total = 0
            for c in children:
                if isinstance(c, NodeId):
                    w = weights.get(c, 1)
                    total += w * (1 if c in cached else runs_of(c))
                else:  # sink
                    total += 1
        runs[gid] = max(total, 1)
        return runs[gid]

    for n in graph.nodes:
        runs_of(n)
    return runs


def _device_budget_bytes() -> int:
    """75% of free device memory when the backend reports it, else 4 GiB."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        in_use = stats.get("bytes_in_use", 0)
        if limit:
            return int(0.75 * (limit - in_use))
    except Exception:
        logger.debug(
            "device memory_stats unavailable; using the 4 GiB default "
            "cache budget", exc_info=True,
        )
    return 4 << 30


def _is_cacher(op) -> bool:
    from ..nodes.util.core import Cacher

    return isinstance(op, Cacher)


def insert_cachers(graph: Graph, nodes: Sequence[NodeId]) -> Graph:
    """Splice a Cacher after each selected node, rerouting every consumer
    (parity: ``addCachesToPipeline``, AutoCacheRule.scala:492-501)."""
    from ..nodes.util.core import Cacher

    for n in nodes:
        children = analysis.get_children(graph, n)
        existing = [
            c for c in children
            if isinstance(c, NodeId) and _is_cacher(graph.get_operator(c))
        ]
        if existing:
            # reuse the existing Cacher: reroute any consumer that bypasses it
            cacher = existing[0]
        else:
            graph, cacher = graph.add_node(Cacher(), [n])
        for c in children:
            if c == cacher:
                continue
            if isinstance(c, NodeId):
                if _is_cacher(graph.get_operator(c)):
                    continue  # a second cacher; leave it alone
                deps = [
                    cacher if d == n else d for d in graph.get_dependencies(c)
                ]
                graph = graph.set_dependencies(c, deps)
            else:  # SinkId
                graph = graph.set_sink_dependency(c, cacher)
    return graph


class AutoCacheRule(Rule):
    """Insert Cacher nodes by the aggressive or greedy policy; the executor
    then retains only cached results across pulls."""

    def __init__(
        self,
        strategy: str = "greedy",
        mem_budget_bytes: Optional[int] = None,
        profiles: Optional[Dict[NodeId, Profile]] = None,
    ):
        self.strategy = strategy
        self.mem_budget_bytes = mem_budget_bytes
        self.profiles = profiles  # injectable for tests (parity: suite)

    def _select_aggressive(self, graph: Graph) -> set:
        """Cache every node whose result is consumed along >1 downstream
        path (parity: ``aggressiveCache``, AutoCacheRule.scala:503-518)."""
        return {
            n
            for n in graph.nodes
            if len(analysis.get_children(graph, n)) > 1
            and not _is_cacher(graph.get_operator(n))
        }

    def _select_greedy(
        self, graph: Graph, profiles: Dict[NodeId, Profile], budget: float
    ) -> set:
        weights = {
            n: getattr(graph.get_operator(n), "weight", 1) for n in graph.nodes
        }
        # Existing Cacher nodes already cut recomputation: seed the run
        # estimator with them so their upstreams' savings aren't double
        # counted (parity: the reference seeds getRuns with cached nodes).
        preexisting = {
            n for n in graph.nodes if _is_cacher(graph.get_operator(n))
        }
        cached: set = set(preexisting)
        spent = 0.0
        while True:
            runs = estimate_runs(graph, weights, cached)
            best, best_save = None, 0.0
            for n, p in profiles.items():
                if n not in graph.nodes or n in cached:
                    continue
                if _is_cacher(graph.get_operator(n)):
                    continue
                if spent + p.mem_bytes > budget:
                    continue
                save = (runs[n] - 1) * p.ns
                if save > best_save:
                    best, best_save = n, save
            if best is None:
                break
            cached.add(best)
            spent += profiles[best].mem_bytes
        return cached - preexisting

    def apply(
        self, graph: Graph, annotations: Annotations
    ) -> Tuple[Graph, Annotations]:
        from .. import cost as cost_mod
        from ..cost import replan as cost_replan

        store = cost_mod.get_store()
        # fingerprint/topo-index once per apply: stored_profiles,
        # calibration, persistence, and the pending-plan deposit all
        # address the same graph identity
        fp = cost_mod.graph_fingerprint(graph) if store is not None else None
        index = (
            cost_replan.topo_node_index(graph) if store is not None else None
        )
        plan_rec = (
            cost_replan.load_plan_record(store, fp)
            if store is not None else None
        )
        profiles: Optional[Dict[NodeId, Profile]] = None
        source = "none"
        budget: Optional[float] = None
        if self.strategy == "aggressive":
            selected = self._select_aggressive(graph)
        else:
            full_n = _full_input_size(graph)
            profiles = self.profiles
            source = "injected" if profiles is not None else source
            if profiles is None and store is not None:
                # a previous traced run of this pipeline left per-node
                # OBSERVED costs — plan from evidence, zero sampling
                profiles = cost_replan.stored_profiles(
                    store, graph, full_n, fp=fp, index=index, rec=plan_rec
                )
                if profiles is not None:
                    source = "profiles"
                    logger.info(
                        "auto-cache: planning %d nodes from stored "
                        "profiles (no sampling)", len(profiles),
                    )
            if profiles is None:
                calibration = cost_replan.stored_calibration(
                    store, graph, fp=fp, index=index, rec=plan_rec
                )
                profiles = profile_nodes(
                    graph, full_size=full_n, calibration=calibration
                )
                source = "sampled+calibrated" if calibration else "sampled"
                self._fill_from_class_throughput(graph, profiles, full_n)
                if store is not None:
                    # persist the sampled estimates NOW: graphs optimized
                    # outside a fit (a prefix spliced at construction, an
                    # apply-path plan) never reach the re-plan hook, and
                    # without a record they would re-sample on every run.
                    # A traced fit of the same graph later overwrites this
                    # with observed evidence (cost/replan.py).
                    self._persist_sampled_plan(
                        store, graph, profiles, full_n, source, fp, index
                    )
            budget = float(
                self.mem_budget_bytes
                if self.mem_budget_bytes is not None
                else _device_budget_bytes()
            )
            selected = self._select_greedy(graph, profiles, budget)
        self._record_plan(graph, profiles, selected)
        self._record_pending(
            graph, profiles, selected, source, budget, fp, index
        )
        self._record_estimate_span(graph, profiles, selected, source)
        if selected:
            logger.info(
                "auto-cache (%s): inserting Cacher after %d nodes (%s)",
                self.strategy,
                len(selected),
                ", ".join(
                    graph.get_operator(n).label for n in sorted(selected)
                ),
            )
            graph = insert_cachers(graph, sorted(selected))
        annotations = dict(annotations)
        annotations[AUTOCACHE_ACTIVE] = True  # type: ignore[index]
        return graph, annotations

    @staticmethod
    def _record_plan(
        graph: Graph,
        profiles: Optional[Dict[NodeId, Profile]],
        selected: set,
    ) -> None:
        """Log the planner's per-node estimates into the trace so the
        estimate-vs-observed audit (obs/audit.py) can close the
        profile-guided-caching feedback loop after execution. Node ids are
        recorded BEFORE Cacher insertion (insert_cachers preserves the
        planned nodes' ids) and match the executor's span ``node`` field
        as long as later rewrites (trace fusion) leave the node in place —
        the audit flags the ones that disappear."""
        tracer = obs_tracer.current()
        if tracer is None:
            return
        estimated = set(profiles or ())
        for n in estimated | set(selected):
            if n not in graph.nodes:
                continue
            p = (profiles or {}).get(n)
            tracer.record_node_estimate(
                str(n.id),
                graph.get_operator(n).label,
                est_seconds=None if p is None else p.ns / 1e9,
                est_bytes=None if p is None else p.mem_bytes,
                cacher=n in selected,
            )

    @staticmethod
    def _fill_from_class_throughput(
        graph: Graph, profiles: Dict[NodeId, Profile], full_n: int
    ) -> None:
        """Price nodes the sampled profiling skipped (an upstream failure
        at sample scale, an estimator that cannot run truncated) from the
        store's per-operator-class throughput records — measured evidence
        from OTHER pipelines on this backend/device kind."""
        from .. import cost as cost_mod

        estimator = cost_mod.get_estimator()
        for n in graph.nodes:
            if n in profiles:
                continue
            op = graph.get_operator(n)
            if isinstance(op, DatasetOperator) or _is_cacher(op):
                continue
            priced = estimator.node_profile_ns(type(op).__name__, full_n)
            if priced is not None:
                profiles[n] = Profile(priced[0], priced[1])
                logger.info(
                    "auto-cache: priced unprofiled %s from class "
                    "throughput evidence", op.label,
                )

    @staticmethod
    def _persist_sampled_plan(
        store, graph: Graph, profiles: Dict[NodeId, Profile],
        full_n: int, source: str, fp: str, index: Dict[NodeId, int],
    ) -> None:
        from ..cost.replan import PLAN_VERSION

        nodes = {}
        for n in graph.nodes:
            p = profiles.get(n)
            if p is None:
                continue
            op = graph.get_operator(n)
            nodes[str(index[n])] = {
                "idx": index[n],
                "label": op.label,
                "op_class": type(op).__name__,
                "n": max(int(full_n), 1),
                "observed": False,
                "seconds": round(p.ns / 1e9, 9),
                "bytes": float(p.mem_bytes),
            }
        if len(nodes) != len(graph.nodes):
            return  # partial coverage would force a re-sample anyway
        store.update(
            f"plan/{fp}",
            lambda rec: {
                "version": PLAN_VERSION,
                "strategy": "greedy",
                "budget": None,
                "full_n": max(int(full_n), 1),
                "source": source,
                "nodes": nodes,
            },
        )

    @staticmethod
    def _record_pending(
        graph: Graph,
        profiles: Optional[Dict[NodeId, Profile]],
        selected: set,
        source: str,
        budget: Optional[float],
        fp: Optional[str],
        index: Optional[Dict[NodeId, int]],
    ) -> None:
        """Deposit the cache plan into the pending re-plan (see
        ``cost/replan.py``): graph identity, budget, every node's estimate
        and the selection — what `finalize` joins against observations."""
        from .. import cost as cost_mod
        from ..cost.replan import topo_node_index

        plan = cost_mod.current_plan()
        # first deposit wins — see NodeOptimizationRule: a sub-pipeline
        # optimized while the outer fit executes must not replace the
        # outer fit's plan
        if plan is None or plan.autocache is not None:
            return
        if index is None:
            index = topo_node_index(graph)
        nodes = {}
        for n in graph.nodes:
            op = graph.get_operator(n)
            p = (profiles or {}).get(n)
            nodes[str(n.id)] = {
                "idx": index[n],
                "label": op.label,
                "op_class": type(op).__name__,
                "est_ns": None if p is None else p.ns,
                "est_bytes": None if p is None else p.mem_bytes,
                "cacher": n in selected,
                "leaf": isinstance(op, DatasetOperator),
            }
        plan.autocache = {
            "fp": fp if fp is not None else cost_mod.graph_fingerprint(graph),
            "graph": graph,
            "strategy": "greedy" if budget is not None else "aggressive",
            "budget": budget if budget is not None else 0.0,
            "full_n": _full_input_size(graph),
            "selected": set(selected),
            "source": source,
            "nodes": nodes,
        }

    @staticmethod
    def _record_estimate_span(
        graph: Graph,
        profiles: Optional[Dict[NodeId, Profile]],
        selected: set,
        source: str,
    ) -> None:
        tracer = obs_tracer.current()
        if tracer is None:
            return
        with tracer.span(
            "cost.estimate",
            op_type="AutoCacheRule",
            source=source,
            nodes=0 if profiles is None else len(profiles),
            cachers=len(selected),
        ):
            pass


def _full_input_size(graph: Graph) -> int:
    n = 1
    for node in graph.nodes:
        op = graph.get_operator(node)
        if isinstance(op, DatasetOperator):
            n = max(n, len(op.dataset))
    return n
