"""Untyped execution units that live at graph nodes.

Parity target: ``workflow/Operator.scala`` in the reference. Each operator's
``execute`` consumes the lazy :class:`Expression`s of its dependencies and
returns a lazy expression of its own result, so that graph execution builds a
web of thunks the executor memoizes.
"""

from __future__ import annotations

from typing import Any, Callable, List, Sequence

from ..data.dataset import Dataset
from .expressions import (
    DatasetExpression,
    DatumExpression,
    Expression,
    TransformerExpression,
)


class Operator:
    """Base of all graph operators. Identity-based equality (two separately
    constructed operators are distinct nodes even with equal parameters);
    the CSE rule merges structurally-equal ones via :func:`structural_key`."""

    @property
    def label(self) -> str:
        return type(self).__name__

    def execute(self, deps: Sequence[Expression]) -> Expression:
        raise NotImplementedError


class _Uncanonical(Exception):
    """Raised when an operator's state has no content-based canonical form."""


def _canon(v):
    """Canonicalize one parameter value into a hashable content digest."""
    import numpy as np

    if v is None or isinstance(v, (bool, int, float, complex, str, bytes)):
        return v
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        import hashlib

        return (
            "ndarray", v.shape, str(v.dtype),
            hashlib.sha1(np.ascontiguousarray(v).tobytes()).hexdigest(),
        )
    if isinstance(v, (list, tuple)):
        return (type(v).__name__, tuple(_canon(x) for x in v))
    if isinstance(v, dict):
        return ("dict", tuple(sorted((k, _canon(x)) for k, x in v.items())))
    if isinstance(v, (set, frozenset)):
        return ("set", tuple(sorted(map(repr, v))))
    # Callables, datasets, device arrays, arbitrary objects: two separately
    # constructed values cannot be proven equal — bail to identity.
    raise _Uncanonical(type(v).__name__)


def structural_key(op: "Operator"):
    """Content-based identity for CSE (parity: the reference's Scala case
    classes give ``EquivalentNodeMergeRule.scala:13`` structural equality
    for free — two separately-constructed equal nodes merge).

    Returns ``(type, canonical-params)`` when every attribute of the
    operator canonicalizes (scalars, strings, tuples, numpy arrays by
    content digest — ``utils/params.py`` keeps fitted parameters as numpy,
    so fitted transformers canonicalize too). Operators defining their own
    ``__eq__`` (Dataset/Datum leaves) and operators holding closures or
    arbitrary objects fall back to the operator instance itself, i.e.
    object identity — conservative, never merges wrongly."""
    cls = type(op)
    if cls.__eq__ is not object.__eq__:
        return op  # operator defines its own (payload-identity) equality
    try:
        return (cls, _canon(vars(op)))
    except _Uncanonical:
        return op


class Cacheable:
    """Marker mixin: nodes of this operator are saveable prefixes — the
    executor persists their result in the global state table (the role the
    ``Cacher`` node plays for ``ExtractSaveablePrefixes`` in the reference)."""


class DatasetOperator(Operator):
    """A leaf wrapping an already-materialized dataset (the reference wraps an
    RDD the same way, ``Operator.scala:25``)."""

    def __init__(self, dataset: Dataset):
        self.dataset = Dataset.of(dataset)

    # Two DatasetOperators wrapping the same payload are the same logical leaf
    # (the reference's DatasetOperator follows its RDD reference the same way);
    # this is what lets prefixes from separate with_data() calls on the same
    # data hit the fit-once state table.
    def __eq__(self, other: object) -> bool:
        return isinstance(other, DatasetOperator) and other.dataset.payload is self.dataset.payload

    def __hash__(self) -> int:
        return hash(("DatasetOperator", id(self.dataset.payload)))

    @property
    def label(self) -> str:
        return f"Dataset[n={len(self.dataset)}]"

    def execute(self, deps: Sequence[Expression]) -> DatasetExpression:
        if deps:
            raise ValueError("DatasetOperator takes no dependencies")
        return DatasetExpression.now(self.dataset)


class DatumOperator(Operator):
    """A leaf wrapping a single datum."""

    def __init__(self, datum: Any):
        self.datum = datum

    def __eq__(self, other: object) -> bool:
        return isinstance(other, DatumOperator) and other.datum is self.datum

    def __hash__(self) -> int:
        return hash(("DatumOperator", id(self.datum)))

    @property
    def label(self) -> str:
        return f"Datum[{type(self.datum).__name__}]"

    def execute(self, deps: Sequence[Expression]) -> DatumExpression:
        if deps:
            raise ValueError("DatumOperator takes no dependencies")
        return DatumExpression.now(self.datum)


class TransformerOperator(Operator):
    """An operator that maps inputs to outputs, itself a first-class value
    (it can flow through the graph as the result of an estimator fit)."""

    def single_transform(self, inputs: Sequence[DatumExpression]) -> Any:
        raise NotImplementedError

    def batch_transform(self, inputs: Sequence[DatasetExpression]) -> Dataset:
        raise NotImplementedError

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if not deps:
            raise ValueError("TransformerOperator requires at least one dependency")
        if all(isinstance(d, DatasetExpression) for d in deps):
            return DatasetExpression(lambda: self.batch_transform(deps))
        if all(isinstance(d, DatumExpression) for d in deps):
            return DatumExpression(lambda: self.single_transform(deps))
        raise ValueError("TransformerOperator dependencies must be all-dataset or all-datum")


class EstimatorOperator(Operator):
    """An operator whose result is a fitted :class:`TransformerOperator`.

    Subclasses implement ``fit(*datasets)``; the expression-level plumbing
    lives in ``fit_expressions``/``execute``.
    """

    def fit(self, *datasets: Dataset) -> TransformerOperator:
        raise NotImplementedError

    def fit_expressions(self, inputs: Sequence[DatasetExpression]) -> TransformerOperator:
        return self.fit(*[d.get() for d in inputs])

    def execute(self, deps: Sequence[Expression]) -> TransformerExpression:
        for d in deps:
            if not isinstance(d, DatasetExpression):
                raise ValueError("EstimatorOperator dependencies must be datasets")
        return TransformerExpression(lambda: self.fit_expressions(deps))


class DelegatingOperator(Operator):
    """Applies the transformer produced by its first dependency to the rest
    (parity: ``Operator.scala:135-164``). This is the node an estimator's
    ``with_data`` splices in so the fitted model can be applied downstream."""

    def execute(self, deps: Sequence[Expression]) -> Expression:
        if len(deps) < 2:
            raise ValueError("DelegatingOperator requires a transformer dep plus data deps")
        t_expr, *data = deps
        if not isinstance(t_expr, TransformerExpression):
            raise ValueError("first dependency must be a TransformerExpression")
        if all(isinstance(d, DatasetExpression) for d in data):
            return DatasetExpression(lambda: t_expr.get().batch_transform(data))
        if all(isinstance(d, DatumExpression) for d in data):
            return DatumExpression(lambda: t_expr.get().single_transform(data))
        raise ValueError("DelegatingOperator data dependencies must be all-dataset or all-datum")


class ExpressionOperator(Operator):
    """A leaf wrapping an already-computed expression — how saved state is
    spliced back into a graph (parity: ``Operator.scala:172``)."""

    def __init__(self, expression: Expression):
        self.expression = expression

    @property
    def label(self) -> str:
        return f"Saved[{type(self.expression).__name__}]"

    def execute(self, deps: Sequence[Expression]) -> Expression:
        return self.expression


class GatherTransformerOperator(TransformerOperator):
    """Zip-concatenates N dependency branches into one per-item sequence
    (parity: ``GatherTransformerOperator.scala:9``). Downstream nodes such as
    ``VectorCombiner`` turn the per-item sequence into one feature vector."""

    def single_transform(self, inputs: Sequence[DatumExpression]) -> Any:
        return [d.get() for d in inputs]

    def batch_transform(self, inputs: Sequence[DatasetExpression]) -> Dataset:
        from ..data.chunked import ChunkedDataset, align_and_zip

        datasets = [d.get() for d in inputs]
        if any(isinstance(ds, ChunkedDataset) for ds in datasets):
            # chunked branches zip per-chunk and stay lazy; materialized
            # branches are sliced at the chunked boundaries as the scan runs
            return align_and_zip(datasets)
        if all(ds.is_batched for ds in datasets):
            # keep branches as a tuple-of-arrays batched payload
            return Dataset(tuple(ds.payload for ds in datasets), batched=True)
        first, *rest = datasets
        return first.zip(*rest)
