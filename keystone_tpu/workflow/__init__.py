"""Workflow core: the typed pipeline API over an untyped, optimizable DAG."""

from .graph import Graph, GraphError, NodeId, SinkId, SourceId
from .operators import (
    Cacheable,
    DatasetOperator,
    DatumOperator,
    DelegatingOperator,
    EstimatorOperator,
    ExpressionOperator,
    GatherTransformerOperator,
    Operator,
    TransformerOperator,
)
from .expressions import (
    DatasetExpression,
    DatumExpression,
    Expression,
    TransformerExpression,
)
from .env import PipelineEnv
from .executor import GraphExecutor
from .pipeline import (
    Chainable,
    FittedPipeline,
    Pipeline,
    PipelineDataset,
    PipelineDatum,
    PipelineResult,
)
from .transformer import (
    Estimator,
    FunctionNode,
    Identity,
    LabelEstimator,
    Transformer,
)
from .node_optimization import Optimizable
from .optimizers import AutoCachingOptimizer, DefaultOptimizer, Optimizer
from .prefix import Prefix, find_prefix
from .rules import (
    Batch,
    EquivalentNodeMergeRule,
    ExtractSaveablePrefixes,
    Rule,
    RuleExecutor,
    SavedStateLoadRule,
    Strategy,
    UnusedBranchRemovalRule,
)

__all__ = [
    "Graph",
    "GraphError",
    "NodeId",
    "SinkId",
    "SourceId",
    "Operator",
    "Cacheable",
    "DatasetOperator",
    "DatumOperator",
    "DelegatingOperator",
    "EstimatorOperator",
    "ExpressionOperator",
    
    "GatherTransformerOperator",
    "TransformerOperator",
    "Expression",
    "DatasetExpression",
    "DatumExpression",
    "TransformerExpression",
    "PipelineEnv",
    "GraphExecutor",
    "Chainable",
    "Pipeline",
    "PipelineResult",
    "PipelineDataset",
    "PipelineDatum",
    "FittedPipeline",
    "Transformer",
    "Estimator",
    "LabelEstimator",
    "FunctionNode",
    "Identity",
    "Optimizable",
    "Optimizer",
    "DefaultOptimizer",
    "AutoCachingOptimizer",
    "Prefix",
    "find_prefix",
    "Rule",
    "RuleExecutor",
    "Batch",
    "Strategy",
    "EquivalentNodeMergeRule",
    "UnusedBranchRemovalRule",
    "ExtractSaveablePrefixes",
    "SavedStateLoadRule",
]
