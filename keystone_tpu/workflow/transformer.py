"""Typed Transformer / Estimator / LabelEstimator.

Parity targets: ``workflow/Transformer.scala``, ``Estimator.scala``,
``LabelEstimator.scala``. A Transformer is simultaneously (a) a chainable
pipeline stage and (b) the untyped operator that executes at its node — same
dual role as the reference.

TPU contract: numeric nodes implement ``trace_batch(x)``, a *pure jax*
function over the stacked array (leading batch dim). That single method gives
them: vectorized batch application, participation in whole-pipeline jit
fusion (see ``FittedPipeline.compile``), and mesh-sharded execution (the
stacked array may be sharded over devices; XLA inserts the collectives).
``apply(x)`` is the per-item fallback for host-side/ragged work.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from ..data.dataset import Dataset
from .expressions import DatasetExpression, DatumExpression
from .graph import Graph
from .operators import (
    DelegatingOperator,
    EstimatorOperator,
    TransformerOperator,
)
from .pipeline import Chainable, Pipeline, attach_data

# re-exported for operator implementors
__all__ = [
    "Transformer",
    "Estimator",
    "LabelEstimator",
    "FunctionNode",
    "Identity",
]


class Transformer(Chainable, TransformerOperator):
    """A deterministic per-item function, batched on TPU.

    Implement at least one of:
      * ``trace_batch(X)`` — pure jax over the stacked array (preferred), or
      * ``apply(x)`` — per-item host function.
    """

    #: override in subclasses whose trace_batch is pure jax
    trace_batch: Optional[Callable] = None

    #: set True on transformers whose trace_batch couples rows (batch
    #: statistics, whole-batch normalization, ...). ``apply_chunked``
    #: refuses such chains — its pad-and-slice tail would silently change
    #: their output — and routes callers to ``apply`` instead.
    batch_coupled: bool = False

    def apply(self, x: Any) -> Any:
        if self.trace_batch is not None:
            import jax.numpy as jnp

            return self.trace_batch(jnp.asarray(x)[None])[0]
        raise NotImplementedError(f"{type(self).__name__} implements neither apply nor trace_batch")

    def apply_batch(self, data: Dataset) -> Dataset:
        # Eager per-op dispatch here is deliberate: per-node jit costs one
        # XLA compile per node *instance* (measured slower end-to-end than
        # eager on TPU). Whole-chain fusion happens at the pipeline level
        # (FittedPipeline.compile), where one program covers every node.
        data = Dataset.of(data)
        if self.batch_coupled and getattr(data, "is_chunked", False):
            raise ValueError(
                f"{type(self).__name__} is batch-coupled: running it "
                "per-chunk would compute batch statistics per chunk, "
                "silently diverging from whole-batch output — "
                "materialize the dataset (e.g. .cache()) first"
            )
        if self.trace_batch is not None and data.is_batched:
            return data.map_batch(self.trace_batch)
        return data.map(self.apply)

    # -- operator-level glue -------------------------------------------

    def single_transform(self, inputs: Sequence[DatumExpression]) -> Any:
        (x,) = [d.get() for d in inputs]
        return self.apply(x)

    def batch_transform(self, inputs: Sequence[DatasetExpression]) -> Dataset:
        (ds,) = [d.get() for d in inputs]
        return self.apply_batch(ds)

    # -- chainable glue -------------------------------------------------

    def to_pipeline(self) -> Pipeline:
        graph = Graph()
        graph, source = graph.add_source()
        graph, node = graph.add_node(self, [source])
        graph, sink = graph.add_sink(node)
        return Pipeline(graph, source, sink)

    def __call__(self, data: Any):
        return self.to_pipeline().apply(data)


class FunctionNode(Transformer):
    """Wrap plain functions as a transformer: ``FunctionNode(item_fn=...)`` or
    ``FunctionNode(batch_fn=...)`` (batch_fn must be pure jax)."""

    def __init__(self, item_fn: Callable = None, batch_fn: Callable = None, label: str = None):
        if item_fn is None and batch_fn is None:
            raise ValueError("need item_fn or batch_fn")
        self._item_fn = item_fn
        self._label = label
        if batch_fn is not None:
            self.trace_batch = batch_fn

    @property
    def label(self) -> str:
        return self._label or getattr(
            self._item_fn or self.trace_batch, "__name__", type(self).__name__
        )

    def apply(self, x: Any) -> Any:
        if self._item_fn is not None:
            return self._item_fn(x)
        return super().apply(x)


class Identity(Transformer):
    """Pass-through (parity: ``workflow/Identity.scala``)."""

    def trace_batch(self, X):
        return X

    def apply(self, x: Any) -> Any:
        return x


class Estimator(Chainable, EstimatorOperator):
    """Fits on a dataset, producing a Transformer.

    Implement ``fit(data: Dataset) -> Transformer``.
    Use via ``est.with_data(data)`` or ``pipeline.and_then(est, data)``.
    """

    def fit(self, data: Dataset) -> Transformer:
        raise NotImplementedError

    def with_data(self, data: Any) -> Pipeline:
        """A pipeline that fits this estimator on ``data`` (lazily, once) and
        applies the fitted transformer to the pipeline input
        (parity: ``Estimator.scala:29-46``)."""
        graph = Graph()
        graph, source = graph.add_source()
        graph, data_id = attach_data(graph, data)
        graph, est_node = graph.add_node(self, [data_id])
        graph, delegating = graph.add_node(DelegatingOperator(), [est_node, source])
        graph, sink = graph.add_sink(delegating)
        return Pipeline(graph, source, sink)

    def to_pipeline(self) -> Pipeline:
        raise TypeError(
            "an Estimator is not directly chainable; use with_data(data) or "
            "and_then(est, data)"
        )

    def __call__(self, data: Any) -> Pipeline:
        return self.with_data(data)


class LabelEstimator(Chainable, EstimatorOperator):
    """Fits on (data, labels), producing a Transformer.

    Implement ``fit(data: Dataset, labels: Dataset) -> Transformer``.
    """

    def fit(self, data: Dataset, labels: Dataset) -> Transformer:
        raise NotImplementedError

    def with_data(self, data: Any, labels: Any = None) -> Pipeline:
        if labels is None:
            raise ValueError("LabelEstimator.with_data requires labels")
        graph = Graph()
        graph, source = graph.add_source()
        graph, data_id = attach_data(graph, data)
        graph, labels_id = attach_data(graph, labels)
        graph, est_node = graph.add_node(self, [data_id, labels_id])
        graph, delegating = graph.add_node(DelegatingOperator(), [est_node, source])
        graph, sink = graph.add_sink(delegating)
        return Pipeline(graph, source, sink)

    def to_pipeline(self) -> Pipeline:
        raise TypeError(
            "a LabelEstimator is not directly chainable; use with_data(data, labels)"
        )

    def __call__(self, data: Any, labels: Any = None) -> Pipeline:
        return self.with_data(data, labels)
