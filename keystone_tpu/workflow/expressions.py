"""Lazy, memoized execution results.

Parity target: ``workflow/Expression.scala`` in the reference. An ``Expression``
wraps a thunk evaluated at most once; laziness is what lets the optimizer
rewrite the graph before anything executes, and memoization is what makes the
pull-based executor cheap to re-enter.
"""

from __future__ import annotations

from typing import Any, Callable, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..data.dataset import Dataset
    from .operators import TransformerOperator

_UNSET = object()


class Expression:
    """A call-by-name, memoized value."""

    def __init__(self, thunk: Callable[[], Any]):
        self._thunk = thunk
        self._value: Any = _UNSET

    @property
    def computed(self) -> bool:
        return self._value is not _UNSET

    def get(self) -> Any:
        if self._value is _UNSET:
            self._value = self._thunk()
            self._thunk = None  # release captured state
        return self._value

    def map_thunk(self, wrap: Callable[[Callable[[], Any]], Callable[[], Any]]) -> None:
        """Replace the pending thunk with ``wrap(thunk)``; no-op once
        computed. This is how the tracing executor attributes wall-clock to
        the node that actually COMPUTES (evaluation is lazy — timing
        ``Operator.execute`` would only measure thunk construction)."""
        if self._value is _UNSET:
            self._thunk = wrap(self._thunk)

    @staticmethod
    def now(value: Any) -> "Expression":
        e = Expression(lambda: value)
        e.get()
        return e


class DatasetExpression(Expression):
    """Evaluates to a :class:`Dataset`."""

    def get(self) -> "Dataset":
        return super().get()

    @staticmethod
    def now(value: "Dataset") -> "DatasetExpression":
        e = DatasetExpression(lambda: value)
        e.get()
        return e


class DatumExpression(Expression):
    """Evaluates to a single datum."""

    @staticmethod
    def now(value: Any) -> "DatumExpression":
        e = DatumExpression(lambda: value)
        e.get()
        return e


class TransformerExpression(Expression):
    """Evaluates to a fitted :class:`TransformerOperator`."""

    def get(self) -> "TransformerOperator":
        return super().get()

    @staticmethod
    def now(value: "TransformerOperator") -> "TransformerExpression":
        e = TransformerExpression(lambda: value)
        e.get()
        return e
