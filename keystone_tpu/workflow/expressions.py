"""Lazy, memoized execution results.

Parity target: ``workflow/Expression.scala`` in the reference. An ``Expression``
wraps a thunk evaluated at most once; laziness is what lets the optimizer
rewrite the graph before anything executes, and memoization is what makes the
pull-based executor cheap to re-enter.

Forcing is thread-safe: the concurrent executor (``executor.py``) hands
independent branches of one pull to a worker pool, and a diamond dependency
means two workers can reach the same expression at once — the per-expression
once-latch guarantees the thunk still runs exactly once, with every other
thread blocking until the value exists. Lock order follows dependency order
(a thunk only forces its own dependencies), so the acyclic graph cannot
deadlock.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from ..data.dataset import Dataset
    from .operators import TransformerOperator

_UNSET = object()


class Expression:
    """A call-by-name, memoized value with a thread-safe once-latch."""

    def __init__(self, thunk: Callable[[], Any]):
        self._thunk = thunk
        self._value: Any = _UNSET
        self._latch = threading.Lock()

    @property
    def computed(self) -> bool:
        return self._value is not _UNSET

    def get(self) -> Any:
        # lock-free fast path: a computed value never un-computes, and the
        # CPython assignment under the latch publishes it atomically
        if self._value is _UNSET:
            with self._latch:
                if self._value is _UNSET:
                    self._value = self._thunk()
                    self._thunk = None  # release captured state
        return self._value

    def map_thunk(self, wrap: Callable[[Callable[[], Any]], Callable[[], Any]]) -> None:
        """Replace the pending thunk with ``wrap(thunk)``; no-op once
        computed. This is how the tracing executor attributes wall-clock to
        the node that actually COMPUTES (evaluation is lazy — timing
        ``Operator.execute`` would only measure thunk construction)."""
        with self._latch:
            if self._value is _UNSET:
                self._thunk = wrap(self._thunk)

    @staticmethod
    def now(value: Any) -> "Expression":
        e = Expression(lambda: value)
        e.get()
        return e

    # locks don't pickle; only computed expressions are serializable anyway
    # (pending thunks are closures), so drop and rebuild the latch
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_latch"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._latch = threading.Lock()


class DatasetExpression(Expression):
    """Evaluates to a :class:`Dataset`."""

    def get(self) -> "Dataset":
        return super().get()

    @staticmethod
    def now(value: "Dataset") -> "DatasetExpression":
        e = DatasetExpression(lambda: value)
        e.get()
        return e


class DatumExpression(Expression):
    """Evaluates to a single datum."""

    @staticmethod
    def now(value: Any) -> "DatumExpression":
        e = DatumExpression(lambda: value)
        e.get()
        return e


class TransformerExpression(Expression):
    """Evaluates to a fitted :class:`TransformerOperator`."""

    def get(self) -> "TransformerOperator":
        return super().get()

    @staticmethod
    def now(value: "TransformerOperator") -> "TransformerExpression":
        e = TransformerExpression(lambda: value)
        e.get()
        return e
