"""Logical identity of a node's upstream computation.

Parity target: ``workflow/Prefix.scala``. A prefix is the tree of operators
feeding a node — it is the *key* under which fit results are saved in
:class:`~keystone_tpu.workflow.env.PipelineEnv` so that repeated ``apply`` /
``fit`` calls never refit an estimator. Operator identity is object identity,
exactly as in the reference (the same estimator instance applied to the same
dataset instance hits the cache; a structurally-equal copy does not).

A prefix only exists for nodes whose ancestry contains no unbound sources.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from .graph import Graph, NodeId, NodeOrSourceId, SourceId
from .operators import Operator


@dataclass(frozen=True)
class Prefix:
    operator: Operator  # identity-hashed unless the operator overrides eq/hash
    children: Tuple["Prefix", ...]

    def __hash__(self) -> int:
        return hash((self.operator, self.children))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Prefix)
            and self.operator == other.operator
            and self.children == other.children
        )


def find_prefix(graph: Graph, gid: NodeOrSourceId) -> Optional[Prefix]:
    """The prefix tree rooted at ``gid``, or None if it depends on a source.

    Iterative with per-node memoization: shared subgraphs (diamonds, merged
    CSE nodes) are visited once, and deep chains don't hit the recursion limit.
    """
    memo: dict = {}
    UNRESOLVED = object()

    stack = [gid]
    while stack:
        cur = stack[-1]
        if cur in memo and memo[cur] is not UNRESOLVED:
            stack.pop()
            continue
        if isinstance(cur, SourceId):
            memo[cur] = None
            stack.pop()
            continue
        deps = graph.get_dependencies(cur)
        pending = [d for d in deps if d not in memo or memo[d] is UNRESOLVED]
        unvisited = [d for d in pending if d not in memo]
        if unvisited:
            memo[cur] = UNRESOLVED
            stack.extend(unvisited)
            continue
        children = [memo[d] for d in deps]
        if any(c is None or c is UNRESOLVED for c in children):
            memo[cur] = None
        else:
            memo[cur] = Prefix(graph.get_operator(cur), tuple(children))
        stack.pop()
    result = memo[gid]
    return None if result is UNRESOLVED else result
