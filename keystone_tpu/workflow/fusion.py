"""Whole-chain trace fusion: collapse traceable transformer subgraphs into
one jit-compiled operator.

The reference leans on Spark to pipeline narrow transformations within a
stage; the TPU-native equivalent is *compilation* — a chain of pure
``trace_batch`` nodes is one XLA program, not N eager dispatches. This rule
is where that happens for every execution path (fit-time featurization,
``Pipeline.apply``, ``FittedPipeline.apply``), not just the explicit
``FittedPipeline.compile`` front door.

Why it matters on real hardware: each eager op dispatch pays a first-call
XLA compile and each host→device hop pays tunnel latency; one fused program
pays ONE compile (persisted across processes via the jax compilation cache)
and keeps every intermediate in HBM. Measured on a v5e chip this takes the
MnistRandomFFT featurize+fit path from ~26 s to under a second warm.

No reference counterpart file: this rule exists because the execution
substrate is XLA; the closest analogue is Spark stage pipelining, which the
reference gets implicitly (SURVEY §2.7 "data parallelism").
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

from ..data.dataset import Dataset
from . import analysis
from .expressions import DatasetExpression, DatumExpression
from .graph import Graph, NodeId
from .operators import (
    GatherTransformerOperator,
    TransformerOperator,
)
from .rules import Annotations, Rule


#: process-global jitted-callable cache keyed by the fused chain's
#: structural content (see FusedTransformerOperator._jitted). Holds the
#: first instance's ops (and their params) alive — the price of executable
#: reuse, same order of memory as the fitted pipelines themselves. LRU:
#: a long-lived sweep process re-fitting many distinct pipelines gets a
#: fresh key per fit (param digests differ), so without a bound every
#: discarded pipeline's weights would stay pinned for the process life.
from collections import OrderedDict

_FUSED_JIT_CACHE: "OrderedDict" = OrderedDict()
_FUSED_JIT_CACHE_MAX = 64


class FusedTransformerOperator(TransformerOperator):
    """A linearized traceable sub-DAG executing as one jitted XLA program.

    ``steps`` is a topologically-ordered list of ``(op, dep_indices)``; value
    index space is ``[0, n_inputs)`` for the fused node's inputs followed by
    one slot per step. The last step is the output.
    """

    #: ``_jit`` is derived memo state — a warm operator must AOT-fingerprint
    #: identically to a fresh one (see ``compile/fingerprint.py``)
    aot_fingerprint_exclude = ("_jit",)

    def __init__(self, steps: Sequence[Tuple[TransformerOperator, Tuple[int, ...]]],
                 n_inputs: int):
        self.steps = list(steps)
        self.n_inputs = n_inputs
        self._jit = None

    @property
    def label(self) -> str:
        inner = " » ".join(op.label for op, _ in self.steps)
        return f"Fused[{inner}]"

    @property
    def batch_coupled(self) -> bool:
        return any(
            getattr(op, "batch_coupled", False) for op, _ in self.steps
        )

    # -- traced path ----------------------------------------------------

    def trace_batch(self, *xs):
        values: List = list(xs)
        for op, deps in self.steps:
            args = [values[i] for i in deps]
            if isinstance(op, GatherTransformerOperator):
                values.append(tuple(args))
            else:
                values.append(op.trace_batch(*args))
        return values[-1]

    def _jitted(self):
        if self._jit is None:
            import jax

            from .operators import structural_key

            # Share the jitted callable across STRUCTURALLY EQUAL fused
            # chains: every fresh Pipeline instance builds fresh
            # FusedTransformerOperators, and a per-instance jax.jit means a
            # re-trace + executable re-load per instance — measured ~12 s
            # for the 300-image SIFT prefix through the tunneled TPU vs
            # 0.4 s for the program itself. Content-keyed reuse makes the
            # Nth structurally-identical pipeline hit jax.jit's own
            # executable cache. Ops with uncanonicalizable state key by
            # object identity (safe: reuse only within the same instance).
            op_keys = [structural_key(op) for op, _ in self.steps]
            if any(k is op for k, (op, _) in zip(op_keys, self.steps)):
                # identity-fallback key (closure/uncanonicalizable state):
                # a global entry could never be hit by another instance and
                # would pin the chain forever — keep the jit per-instance
                key = None
            else:
                try:
                    key = (
                        self.n_inputs,
                        tuple(
                            (k, tuple(deps))
                            for k, (_, deps) in zip(op_keys, self.steps)
                        ),
                    )
                    hash(key)
                except TypeError:
                    key = None
            if key is None:
                self._jit = jax.jit(self.trace_batch)
            else:
                cached = _FUSED_JIT_CACHE.get(key)
                if cached is None:
                    cached = _FUSED_JIT_CACHE[key] = jax.jit(self.trace_batch)
                    while len(_FUSED_JIT_CACHE) > _FUSED_JIT_CACHE_MAX:
                        _FUSED_JIT_CACHE.popitem(last=False)
                else:
                    _FUSED_JIT_CACHE.move_to_end(key)
                self._jit = cached
        return self._jit

    # -- operator glue --------------------------------------------------

    def batch_transform(self, inputs: Sequence[DatasetExpression]) -> Dataset:
        from ..data.chunked import ChunkedDataset, align_and_zip

        datasets = [d.get() for d in inputs]
        if any(isinstance(ds, ChunkedDataset) for ds in datasets):
            # out-of-core inputs: the fused program runs chunk-by-chunk,
            # lazily — one compiled executable per chunk shape, intermediates
            # bounded by one chunk (the whole point of data/chunked.py)
            if self.batch_coupled:
                coupled = [
                    op.label
                    for op, _ in self.steps
                    if getattr(op, "batch_coupled", False)
                ]
                raise ValueError(
                    f"batch-coupled node(s) {coupled} cannot stream "
                    "per-chunk: batch statistics would be computed per "
                    "chunk — materialize the dataset first"
                )
            # shape-bucket ragged (tail) chunks: pad up to a small static
            # ladder derived from the lead chunk and slice the pad off the
            # result, so the fused program compiles once per bucket instead
            # of once per distinct chunk shape (serving/batching.py's trick
            # applied to out-of-core scans). The padder is captured by the
            # lazy factory, so lineage re-scans reuse the same compiles.
            # shard=True: on a >1-wide data axis the padder rounds every
            # bucket to a lane multiple and commits the padded chunk with
            # batch_sharding before the call, so the fused program computes
            # SPMD across the whole mesh per chunk — featurization spans
            # the chips, not just the solver (ROADMAP "shard the whole fit
            # end-to-end"). A 1-lane mesh keeps this inert.
            from ..data.pipeline_scan import ChunkPadder

            fn = self._jitted()
            if len(datasets) == 1:
                return datasets[0].map_batch(ChunkPadder(fn, shard=True))
            zipped = align_and_zip(datasets)
            return zipped.map_batch(
                ChunkPadder(lambda t: fn(*t), shard=True)
            )
        if all(ds.is_batched for ds in datasets):
            arrays = [ds.to_array() for ds in datasets]
            return Dataset(self._jitted()(*arrays), batched=True)
        # Ragged/item-list inputs: fall back to the per-op Dataset semantics
        # the unfused graph would have used (correct, just not one program).
        values = list(datasets)
        for op, deps in self.steps:
            args = [DatasetExpression.now(values[i]) for i in deps]
            values.append(op.batch_transform(args))
        return values[-1]

    def single_transform(self, inputs: Sequence[DatumExpression]):
        values = [d.get() for d in inputs]
        for op, deps in self.steps:
            args = [DatumExpression.now(values[i]) for i in deps]
            values.append(op.single_transform(args))
        return values[-1]

    def __getstate__(self):
        state = dict(self.__dict__)
        state["_jit"] = None  # jitted callables don't pickle
        return state


class TraceFusionRule(Rule):
    """Replace maximal traceable transformer subgraphs (≥2 nodes) with
    :class:`FusedTransformerOperator` nodes.

    A node joins a group only if every consumer of its result is inside the
    group (so no fused intermediate is needed elsewhere) and it carries no
    saveable-prefix annotation (those results must hit the state table).
    Cachers, estimators, and host-side nodes have no ``trace_batch`` and
    bound the groups naturally.
    """

    name = "TraceFusionRule"

    @staticmethod
    def _traceable(op) -> bool:
        if getattr(op, "no_fuse", False):
            return False
        if isinstance(op, GatherTransformerOperator):
            return True
        return (
            isinstance(op, TransformerOperator)
            and getattr(op, "trace_batch", None) is not None
        )

    def apply(self, graph: Graph, annotations: Annotations) -> Tuple[Graph, Annotations]:
        consumers = {}
        for node in graph.nodes:
            for d in graph.get_dependencies(node):
                if isinstance(d, NodeId):
                    consumers.setdefault(d, set()).add(node)
        sink_consumed = set()
        for sink in graph.sinks:
            d = graph.get_sink_dependency(sink)
            if isinstance(d, NodeId):
                sink_consumed.add(d)

        order = [n for n in analysis.linearize(graph) if isinstance(n, NodeId)]
        assigned = set()
        groups: List[Tuple[NodeId, set]] = []
        for out in reversed(order):
            if (
                out in assigned
                or out in annotations
                or not self._traceable(graph.get_operator(out))
            ):
                continue
            group = {out}
            changed = True
            while changed:
                changed = False
                for member in list(group):
                    for d in graph.get_dependencies(member):
                        if (
                            isinstance(d, NodeId)
                            and d not in group
                            and d not in assigned
                            and d not in annotations
                            and d not in sink_consumed
                            and self._traceable(graph.get_operator(d))
                            and consumers.get(d, set()) <= group
                        ):
                            group.add(d)
                            changed = True
            if len(group) >= 2:
                groups.append((out, group))
                assigned |= group

        for out, group in groups:
            inner_order = [n for n in order if n in group]
            pos = {n: i for i, n in enumerate(inner_order)}
            ext: List = []
            for n in inner_order:
                for d in graph.get_dependencies(n):
                    if (not isinstance(d, NodeId) or d not in group) and d not in ext:
                        ext.append(d)
            ext_index = {d: i for i, d in enumerate(ext)}
            steps = []
            for n in inner_order:
                dep_idx = tuple(
                    len(ext) + pos[d]
                    if isinstance(d, NodeId) and d in group
                    else ext_index[d]
                    for d in graph.get_dependencies(n)
                )
                steps.append((graph.get_operator(n), dep_idx))
            fused = FusedTransformerOperator(steps, len(ext))

            rep = Graph()
            src_ids = []
            for _ in ext:
                rep, s = rep.add_source()
                src_ids.append(s)
            rep, fused_node = rep.add_node(fused, src_ids)
            rep, rep_sink = rep.add_sink(fused_node)
            graph = graph.replace_nodes(
                frozenset(group),
                rep,
                dep_splice={s: d for s, d in zip(src_ids, ext)},
                out_splice={out: rep_sink},
            )

        ann = {n: p for n, p in annotations.items() if n in graph.operators}
        return graph, ann
