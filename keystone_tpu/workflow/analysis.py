"""Graph traversal helpers (parity: ``workflow/AnalysisUtils.scala``)."""

from __future__ import annotations

from typing import List, Set

from .graph import Graph, GraphId, NodeId, NodeOrSourceId, SinkId, SourceId


def get_parents(graph: Graph, gid: GraphId) -> List[NodeOrSourceId]:
    """Immediate dependencies of ``gid`` (ordered, possibly repeated)."""
    if isinstance(gid, SinkId):
        return [graph.get_sink_dependency(gid)]
    if isinstance(gid, NodeId):
        return list(graph.get_dependencies(gid))
    return []


def get_ancestors(graph: Graph, gid: GraphId) -> Set[NodeOrSourceId]:
    """All transitive dependencies of ``gid`` (not including itself)."""
    seen: Set[NodeOrSourceId] = set()
    stack = list(get_parents(graph, gid))
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(get_parents(graph, cur))
    return seen


def get_children(graph: Graph, gid: GraphId) -> Set[GraphId]:
    out: Set[GraphId] = set()
    for node, deps in graph.dependencies.items():
        if gid in deps:
            out.add(node)
    for sink, dep in graph.sink_dependencies.items():
        if dep == gid:
            out.add(sink)
    return out


def get_descendants(graph: Graph, gid: GraphId) -> Set[GraphId]:
    seen: Set[GraphId] = set()
    stack = list(get_children(graph, gid))
    while stack:
        cur = stack.pop()
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(get_children(graph, cur))
    return seen


def linearize(graph: Graph) -> List[GraphId]:
    """A topological order over sources, nodes, and sinks (dependencies first)."""
    order: List[GraphId] = []
    visited: Set[GraphId] = set()

    def visit(gid: GraphId) -> None:
        if gid in visited:
            return
        visited.add(gid)
        for p in get_parents(graph, gid):
            visit(p)
        order.append(gid)

    for sink in sorted(graph.sinks):
        visit(sink)
    # include disconnected nodes/sources too
    for node in sorted(graph.nodes):
        visit(node)
    for source in sorted(graph.sources):
        visit(source)
    return order
