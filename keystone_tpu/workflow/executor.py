"""Pull-based memoized graph executor.

Parity target: ``workflow/GraphExecutor.scala``. The executor optimizes its
graph lazily on first use, then ``execute(graph_id)`` recursively pulls
dependency expressions, memoizing one expression per graph id. Results of
saveable prefixes (annotated by the optimizer) are written into the global
:class:`PipelineEnv` state so later executions skip the work entirely.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..obs.tracer import current as _trace_current
from .env import PipelineEnv
from .expressions import Expression
from .graph import Graph, GraphId, NodeId, SinkId, SourceId
from .rules import Annotations


class GraphExecutor:
    def __init__(self, graph: Graph, optimize: bool = True):
        self._input_graph = graph
        self._optimize = optimize
        self._optimized: Optional[Graph] = None
        self._annotations: Annotations = {}
        self._state: Dict[GraphId, Expression] = {}

    @property
    def graph(self) -> Graph:
        """The optimized graph (optimization happens once, lazily)."""
        if self._optimized is None:
            if self._optimize:
                optimizer = PipelineEnv.get_or_create().optimizer
                self._optimized, self._annotations = optimizer.execute(self._input_graph)
            else:
                self._optimized = self._input_graph
        return self._optimized

    def _retain(self, graph: Graph, graph_id: NodeId) -> bool:
        """Whether this node's result stays resident across pulls.

        Default: everything (the HBM-memoizing fast path). After the
        AutoCacheRule has planned caching, only Cacher / estimator / source
        dataset results are retained — other intermediates recompute per
        pull, exactly like unpersisted RDDs in the reference, so the cache
        budget genuinely bounds resident bytes."""
        from .autocache import AUTOCACHE_ACTIVE

        if not self._annotations.get(AUTOCACHE_ACTIVE):
            return True
        from ..nodes.util.core import Cacher
        from .operators import (
            DatasetOperator,
            DatumOperator,
            EstimatorOperator,
            ExpressionOperator,
        )

        op = graph.get_operator(graph_id)
        return isinstance(
            op,
            (Cacher, DatasetOperator, DatumOperator, EstimatorOperator,
             ExpressionOperator),
        )

    def execute(self, graph_id: GraphId) -> Expression:
        return self._execute(graph_id, transient={})

    def _execute(self, graph_id: GraphId, transient: Dict) -> Expression:
        graph = self.graph  # force optimization before anything runs
        if isinstance(graph_id, SourceId):
            raise ValueError(f"cannot execute unconnected {graph_id}")
        if isinstance(graph_id, SinkId):
            return self._execute(graph.get_sink_dependency(graph_id), transient)
        # tracing is opt-in: disabled, the ONLY cost per pull is this None
        # check — no span allocation anywhere on the path
        tracer = _trace_current()
        if graph_id in self._state:
            if tracer is not None:
                self._trace_hit(tracer, graph, graph_id, store="state")
            return self._state[graph_id]
        if graph_id in transient:
            if tracer is not None:
                self._trace_hit(tracer, graph, graph_id, store="transient")
            return transient[graph_id]
        deps = [
            self._execute(d, transient) for d in graph.get_dependencies(graph_id)
        ]
        op = graph.get_operator(graph_id)
        retained = self._retain(graph, graph_id)
        if tracer is None:
            expr = op.execute(deps)
        else:
            expr = self._traced_execute(
                tracer, graph_id, op, deps, retained=retained
            )
        if retained:
            self._state[graph_id] = expr
        else:
            # shared within this pull (diamonds compute once), dropped after
            transient[graph_id] = expr
        prefix = self._annotations.get(graph_id)
        if prefix is not None:
            PipelineEnv.get_or_create().state[prefix] = expr
        return expr

    # -- tracing hooks (active only with an installed obs.Tracer) -------

    @staticmethod
    def _trace_hit(tracer, graph: Graph, graph_id: NodeId, store: str) -> None:
        """A memoized result was returned instead of recomputed — the
        Cacher/memo hit the span tree records against the recompute case."""
        op = graph.get_operator(graph_id)
        tracer.instant(
            f"node.{op.label}",
            node_id=str(graph_id.id),
            op_type=type(op).__name__,
            cache="hit",
            store=store,
        )

    @staticmethod
    def _traced_execute(tracer, graph_id: NodeId, op, deps, retained: bool):
        """Build the node's expression with its eventual EVALUATION wrapped
        in a span. Evaluation is lazy (``Expression`` thunks), so the span
        opens when ``.get()`` first forces this node — upstream thunks
        forced from inside it become child spans, giving the pull's true
        tree. Exit blocks on the result so async-dispatched device time is
        attributed here (recorded as ``sync_seconds``)."""
        from ..obs.span import Span, cheap_nbytes

        name = f"node.{op.label}"
        op_type = type(op).__name__
        node_id = str(graph_id.id)
        t0 = time.perf_counter()
        expr = op.execute(deps)
        if expr.computed:
            # eager operator (Dataset/Datum leaves, saved state): the work
            # happened inside op.execute — record it directly
            sp = Span(
                name=name,
                start=t0,
                end=time.perf_counter(),
                node_id=node_id,
                op_type=op_type,
                cache="miss",
                output_bytes=cheap_nbytes(expr.get()),
                attrs={"retained": retained, "eager": True},
            )
            tracer.record_complete(sp)
            return expr

        def _wrap(thunk):
            def traced_thunk():
                with tracer.span(
                    name,
                    node_id=node_id,
                    op_type=op_type,
                    cache="miss",
                    retained=retained,
                ) as sp:
                    value = thunk()
                    sp.sync_on(value)
                return value

            return traced_thunk

        expr.map_thunk(_wrap)
        return expr
